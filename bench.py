"""Benchmark: flagship-model training throughput on the local accelerator.

Prints ONE JSON line on stdout: {"metric", "value", "unit", "vs_baseline"}.
Everything else (per-stage progress) goes to stderr AND is persisted
incrementally to BENCH_STAGES.json so a partial run still leaves evidence.

Round-5 redesign (VERDICT r4 item 1): the round-3/4 failures were a wedged
axon tunnel eating the whole budget. Stage structure now:

  0. probe    (10 s)   import jax + jax.devices() + tiny matmul. BUDGET-
                       AWARE since round 7: BENCH_r03–r05 burned the old
                       120 s probe timeout on a wedged tunnel before the
                       auto-shrink could ever run. A probe that can't
                       answer in 10 s is treated as tunnel-down, BUT the
                       saved budget buys one blind shot at the SMALLEST
                       shrunken measure size (a slow first device init can
                       false-negative a 10 s probe) before the CPU
                       fallback — so some device metric always has a
                       chance to land.
  1. compile  (380 s)  flagship GBM on 20k rows — compile-dominated; its
                       wallclock separates slow-compile from slow-execute.
                       All device stages share a persistent XLA compilation
                       cache (JAX_COMPILATION_CACHE_DIR), so this stage
                       genuinely warms the measure stage across processes.
  2. measure  (500 s)  flagship GBM 1M rows x 20 trees (rows*trees/sec).
  3. drf-deep (150 s)  depth-20 DRF secondary metric.
  4. pallas   (150 s)  flagship with H2O_TPU_PALLAS_HIST=1 (XLA-vs-Pallas
                       on silicon; VERDICT r4 item 2).
  5. glm      (120 s)  GLM IRLS secondary metric.
  F. cpu-glm  (120 s)  tunnel-bypassed CPU fallback so a number ALWAYS lands.

Worst-case mandatory path = probe 120 + compile 380 + measure 500 + fallback
120 ≈ 1120 s. Secondary stages (drf/pallas/glm, 420 s combined) run only
after a successful measure AND only while the parent's DEADLINE (1380 s)
leaves room for them, so the final JSON line always prints inside the
driver's ~1500 s budget. Every stage is its own subprocess: the parent
NEVER imports jax (a wedged tunnel hangs jax import in any process that
touches it).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
STAGES_PATH = os.path.join(REPO, "BENCH_STAGES.json")

# first recorded values on real TPU hardware (v5 lite, 2026-07-29) — the
# baseline later rounds are measured against
RECORDED = {
    "gbm_rows_per_sec": 465943.8,
    "glm_irls_rows_per_sec": 371850175.7,
}

_STAGES: list = []


def _record(stage: str, **kw) -> None:
    entry = {"stage": stage, **kw}
    _STAGES.append(entry)
    print(f"BENCH_STAGE {json.dumps(entry)}", file=sys.stderr, flush=True)
    try:
        with open(STAGES_PATH, "w") as f:
            json.dump(_STAGES, f, indent=1)
    except OSError:
        pass


def bench_glm(n_rows: int = 1_000_000, p: int = 32, iters: int = 20) -> float:
    # single source of truth for the IRLS benchmark lives in the package
    # (h2o3_tpu/bench.py run_glm); this wrapper keeps the fallback stages'
    # `import bench` entry working from the repo root
    from h2o3_tpu.bench import run_glm

    return run_glm(n_rows=n_rows, p=p, iters=iters)[0]


# keep in sync with h2o3_tpu/obs/phases.py DEADLINE_EXIT_RC — this file
# must stay importable without h2o3_tpu (whose import pulls jax)
PHASE_DEADLINE_RC = 97


def _phase_deadline(name: str) -> float:
    """Stdlib parse of the H2O_TPU_PHASE_DEADLINE_S map (one number for
    every phase, or name=secs pairs) — the probe child must read it
    before anything heavier than os.environ exists."""
    raw = os.environ.get("H2O_TPU_PHASE_DEADLINE_S", "").strip()
    if not raw:
        return 0.0
    if "=" not in raw:
        try:
            return max(float(raw), 0.0)
        except ValueError:
            return 0.0
    for part in raw.replace(";", ",").split(","):
        k, _, v = part.partition("=")
        if k.strip() == name:
            try:
                return max(float(v), 0.0)
            except ValueError:
                return 0.0
    return 0.0


def _arm_probe_autopsy() -> None:
    """STDLIB-ONLY flight-dump timers for the probe stage: the probe's
    failure mode is `import jax` / backend init wedging, so the arming
    must not touch h2o3_tpu (whose import pulls jax). Two timers:

    - the classic stage autopsy a few seconds short of the parent's
      SIGKILL — thread stacks + newest imported modules, i.e. exactly
      WHERE the wedge sits;
    - the PHASE deadline (ISSUE 12): the whole probe IS backend_init, so
      at ``H2O_TPU_PHASE_DEADLINE_S``'s backend_init deadline the child
      dumps a corpse NAMING the phase and — under
      ``H2O_TPU_PHASE_DEADLINE_EXIT=1`` — exits with
      ``PHASE_DEADLINE_RC`` so the parent hands the saved budget to the
      CPU chain instead of waiting out the stage timeout."""
    import threading
    import traceback

    try:
        t = float(os.environ.get("H2O3_BENCH_STAGE_TIMEOUT_S") or 0)
    except ValueError:
        return
    if t <= 6:
        return

    def dump(reason="bench_probe_timeout", phase=None, hard_exit=False):
        try:
            frames = {str(tid): traceback.format_stack(frame)[-8:]
                      for tid, frame in sys._current_frames().items()}
            d = os.environ.get("H2O_TPU_OBS_FLIGHT_DIR") or os.path.join(
                os.environ.get("H2O_TPU_ICE_ROOT", "/tmp/h2o3_tpu"),
                "flight")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{time.strftime('%Y%m%d_%H%M%S')}"
                   f"_{reason}_{os.getpid()}.json")
            tmp = f"{path}.part"
            with open(tmp, "w") as f:
                json.dump({"reason": reason,
                           "ts": time.time(), "pid": os.getpid(),
                           **({"phase": phase} if phase else {}),
                           "thread_stacks": frames,
                           "modules_tail": list(sys.modules)[-40:]}, f)
            os.replace(tmp, path)
            print("H2O3_FLIGHT_JSON " + json.dumps(
                {"flight_record": path, "timeline_tail": [],
                 **({"phase": phase} if phase else {})}),
                file=sys.stderr, flush=True)
        except Exception:   # noqa: BLE001 — the autopsy must never be
            pass            # the thing that kills a healthy probe
        if hard_exit:
            try:
                sys.stderr.flush()
            except Exception:   # noqa: BLE001
                pass
            os._exit(PHASE_DEADLINE_RC)

    tm = threading.Timer(max(t - 5.0, 1.0), dump)
    tm.daemon = True
    tm.start()
    dl = _phase_deadline("backend_init")
    if 0 < dl < t:
        exit_fast = os.environ.get(
            "H2O_TPU_PHASE_DEADLINE_EXIT", "").lower() in ("1", "true",
                                                           "on")
        pm = threading.Timer(
            dl, dump, kwargs={"reason": "phase_deadline_backend_init",
                              "phase": "backend_init",
                              "hard_exit": exit_fast})
        pm.daemon = True
        pm.start()


def bench_probe() -> float:
    """Stage 0: is the accelerator reachable at all? Prints platform info."""
    _arm_probe_autopsy()       # leave a corpse if the tunnel wedges here
    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    x = jnp.ones((256, 256))
    (x @ x).block_until_ready()
    dt = time.perf_counter() - t0
    print(f"H2O3_PROBE platform={devs[0].platform} n={len(devs)}",
          file=sys.stderr, flush=True)
    return dt


def _parse_result(stdout: str):
    """All H2O3_BENCH lines, in print order. A stage's PRIMARY metric is
    its final line (the __main__ print); earlier lines are auxiliary
    metrics (e.g. the artifact stage's cold-start seconds)."""
    out = []
    for ln in stdout.splitlines():
        if ln.startswith("H2O3_BENCH "):
            try:
                _, metric, value = ln.split()
                out.append((float(value), metric))
            except ValueError:
                print(f"malformed bench line: {ln!r}", file=sys.stderr)
    return out or None


def _autopsy(stderr) -> dict:
    """Bench autopsy (ISSUE 8): a dying stage's child arms a timer that
    dumps a flight record and prints ONE ``H2O3_FLIGHT_JSON {...}`` line
    to stderr just before the parent's kill lands. Parse it into the
    BENCH_STAGE tail — the flight-record path plus the last 20 timeline
    events — so a dark round says WHY the device stage died instead of
    just "timeout"."""
    if isinstance(stderr, bytes):
        stderr = stderr.decode(errors="replace")
    for ln in reversed((stderr or "").splitlines()):
        if ln.startswith("H2O3_FLIGHT_JSON "):
            try:
                rec = json.loads(ln[len("H2O3_FLIGHT_JSON "):])
            except ValueError:
                break
            out = {"flight_record": rec.get("flight_record"),
                   "timeline_tail": (rec.get("timeline_tail") or [])[-20:]}
            # ISSUE 12: the corpse names the lifecycle phase that never
            # completed + the durations of the ones that did — fold them
            # into the BENCH_STAGE record next to the timeline tail
            if rec.get("phase"):
                out["phase"] = rec["phase"]
            if rec.get("phase_report"):
                out["phase_report"] = rec["phase_report"]
            return out
    return {}


def _stage(name, cmd, timeout_s, env_extra=None):
    """Run one bench stage in a subprocess with a hard timeout. Returns
    (value, metric) or None on timeout / crash / missing result line.
    Records the outcome — auxiliary metrics and any flight-record autopsy
    included — to BENCH_STAGES.json either way."""
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    # the child arms its own flight-dump timer a few seconds short of this
    # deadline (h2o3_tpu/bench.py _arm_stage_autopsy) — subprocess.run's
    # timeout kill is SIGKILL, so the corpse must be written BEFORE it
    env["H2O3_BENCH_STAGE_TIMEOUT_S"] = str(timeout_s)
    # ISSUE 12: deadline-supervised lifecycle phases in every child. A
    # wedged backend init / first tiny compile (the r03-r05 wedge) now
    # dumps a flight record naming the phase and EXITS fast with
    # PHASE_DEADLINE_RC instead of burning the whole stage budget — the
    # parent's chain then reaches the CPU fallback with budget to spare
    env.setdefault("H2O_TPU_PHASE_DEADLINE_S",
                   "backend_init=45,device_discovery=20,mesh_init=20,"
                   "first_compile=90,compile_cache_load=60")
    env.setdefault("H2O_TPU_PHASE_DEADLINE_EXIT", "1")
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=timeout_s,
                              text=True, cwd=REPO, env=env)
    except subprocess.TimeoutExpired as te:
        _record(name, ok=False, error=f"timeout after {timeout_s}s",
                secs=round(time.perf_counter() - t0, 1),
                **_autopsy(te.stderr))
        return None
    secs = round(time.perf_counter() - t0, 1)
    got = _parse_result(proc.stdout)
    if got is None:
        err = (f"phase deadline expired (rc {PHASE_DEADLINE_RC}): wedged "
               f"init phase, fell back to the CPU chain fast"
               if proc.returncode == PHASE_DEADLINE_RC
               else (proc.stderr or "")[-1500:])
        _record(name, ok=False, rc=proc.returncode, secs=secs,
                error=err, **_autopsy(proc.stderr))
        return None
    value, metric = got[-1]
    extras = {m: round(v, 3) for v, m in got[:-1]}
    _record(name, ok=True, metric=metric, value=round(value, 1), secs=secs,
            **({"extras": extras} if extras else {}))
    return value, metric


_GLM_SNIPPET = ("import bench; "
                "print('H2O3_BENCH glm_irls_rows_per_sec', bench.bench_glm())")
_PROBE_SNIPPET = ("import bench; "
                  "print('H2O3_BENCH probe_secs', bench.bench_probe())")


def main():
    py = sys.executable
    t_start = time.perf_counter()
    deadline = 1380.0          # leave ~2 min of the driver budget as margin

    def remaining():
        return deadline - (time.perf_counter() - t_start)

    # persistent XLA compilation cache: the compile stage's work carries
    # into the measure stage even though they are separate processes
    cache = {"JAX_COMPILATION_CACHE_DIR":
             os.environ.get("JAX_COMPILATION_CACHE_DIR",
                            os.path.join(REPO, ".jax_cache"))}
    # the 10 s probe gets a tighter backend_init deadline than the
    # default map: a wedged import jax leaves a corpse naming the phase
    # (and exits with PHASE_DEADLINE_RC) ~3 s before the SIGKILL would land
    probe = _stage("probe", [py, "-c", _PROBE_SNIPPET], 10,
                   env_extra={"H2O_TPU_PHASE_DEADLINE_S": "backend_init=7",
                              "H2O_TPU_PHASE_DEADLINE_EXIT": "1"})
    got = None
    unit = "rows/sec/chip"
    if probe is None and remaining() > 500:
        # fail-fast probe said tunnel-down: spend a bounded slice of the
        # saved 110 s on the smallest shrunken flagship size anyway — a
        # slow first device init looks identical to a dead tunnel inside
        # 10 s, and this is the only way a device metric can still land
        # laxer init deadlines than the default map: this shot EXISTS for
        # the slow-but-healthy first device init the 10 s probe cannot
        # distinguish from a dead tunnel
        got = _stage("measure-50k-blind", [py, "-m", "h2o3_tpu.bench"], 240,
                     env_extra={"H2O3_BENCH_ROWS": "50000",
                                "H2O3_BENCH_TREES": "5",
                                "H2O_TPU_PHASE_DEADLINE_S":
                                "backend_init=150,first_compile=60",
                                **cache})
    if probe is not None:
        # tunnel is up: compile-only stage first, then the measured run.
        # The measure stage AUTO-SHRINKS on failure/timeout (1M -> 200k ->
        # 50k rows) so SOME device number always lands — since BENCH_r03
        # the full-size stage has timed out on this platform and the
        # flagship metric went dark (ROADMAP open item 2).
        _stage("compile", [py, "-m", "h2o3_tpu.bench"], 380,
               env_extra={"H2O3_BENCH_ONLY": "compile", **cache})
        for sname, rows, trees, budget in (
                ("measure", None, None, 500),
                ("measure-200k", "200000", "10", 260),
                ("measure-50k", "50000", "5", 150)):
            if remaining() < 150:
                _record(sname, ok=False, error="skipped: deadline")
                break
            env_extra = dict(cache)
            if rows:
                env_extra["H2O3_BENCH_ROWS"] = rows
                env_extra["H2O3_BENCH_TREES"] = trees
            got = _stage(sname, [py, "-m", "h2o3_tpu.bench"],
                         min(budget, max(remaining() - 130, 60)),
                         env_extra=env_extra)
            if got is not None:
                break
        if got is not None:
            for sname, env in (("score", {"H2O3_BENCH_ONLY": "score"}),
                               ("rapids", {"H2O3_BENCH_ONLY": "rapids"}),
                               ("pipeline", {"H2O3_BENCH_ONLY": "pipeline"}),
                               ("parse", {"H2O3_BENCH_ONLY": "parse"}),
                               ("artifact", {"H2O3_BENCH_ONLY": "artifact"}),
                               ("drf-deep", {"H2O3_BENCH_ONLY": "drf"}),
                               ("pallas", {"H2O3_BENCH_ONLY": "pallas"}),
                               ("glm", {"H2O3_BENCH_ONLY": "glm"}),
                               # kill->elect->HEALTHY drill: control-plane
                               # only, so it bypasses the accelerator tunnel
                               # pinned-budget OOM ladder drill: chunked
                               # streaming + injected-OOM recovery
                               # (mem_degrade_recover_secs +
                               # bigger_than_hbm_ok aux)
                               ("oom-degrade",
                                {"H2O3_BENCH_ONLY": "oom-degrade"}),
                               ("recover", {"H2O3_BENCH_ONLY": "recover",
                                            "JAX_PLATFORMS": "cpu"}),
                               # kill-mid-grid -> watchdog search resume ->
                               # leaderboard complete (search_recover_secs
                               # + the members-overlap concurrency aux)
                               ("search-recover",
                                {"H2O3_BENCH_ONLY": "search-recover",
                                 "JAX_PLATFORMS": "cpu"})):
                if remaining() < 180:
                    _record(sname, ok=False, error="skipped: deadline")
                    continue
                _stage(sname, [py, "-m", "h2o3_tpu.bench"], 150,
                       env_extra={**env, **cache})
        if got is None and remaining() > 160:
            # flagship failed but tunnel is up: GLM on chip
            got = _stage("glm-fallback", [py, "-c", _GLM_SNIPPET], 150)
    if got is None:  # tunnel wedged: CPU bypass so a number ALWAYS lands
        got = _stage("cpu-glm", [py, "-c", _GLM_SNIPPET], 120,
                     env_extra={"PALLAS_AXON_POOL_IPS": "",
                                "JAX_PLATFORMS": "cpu"})
        unit = "rows/sec/cpu-fallback"
        # round-5 gap: the fallback landed ONLY a GLM number, leaving
        # serving perf unmeasured — always record a scoring metric too
        # (small training set so the stage fits its CPU budget)
        if remaining() > 150:
            # 8 virtual CPU devices: the fused score metric is measured
            # from the SHARDED data plane (per-process packing + shard_map
            # margins) on a ≥2-device single-process mesh, and the stage's
            # auxiliary score_gathered_rows line must report 0
            # the score stage's coalesced-flush phase (aux lines
            # score_dispatches_per_flush / score_p99_ms) runs at reduced
            # concurrency on the CPU fallback so the stage fits its budget
            score = _stage("cpu-score", [py, "-m", "h2o3_tpu.bench"], 140,
                           env_extra={"PALLAS_AXON_POOL_IPS": "",
                                      "JAX_PLATFORMS": "cpu",
                                      "XLA_FLAGS":
                                      (os.environ.get("XLA_FLAGS", "") +
                                       " --xla_force_host_platform_"
                                       "device_count=8"),
                                      "H2O3_BENCH_ONLY": "score",
                                      "H2O3_BENCH_SCORE_CONCURRENCY": "8",
                                      "H2O3_BENCH_SCORE_TRAIN_ROWS": "5000"})
            if got is None:
                got = score
        else:
            _record("cpu-score", ok=False, error="skipped: deadline")
        if remaining() > 160:
            # rapids data-plane metrics: fused-vs-eager statement engine,
            # the lazy chained-session ratio (rapids_chained_vs_eager) and
            # the device sort (rapids_sort_rows_per_sec) — pure
            # CPU-measurable, so the trajectory gains data-plane numbers
            # even while the device tree stage is dark
            rap = _stage("cpu-rapids", [py, "-m", "h2o3_tpu.bench"], 150,
                         env_extra={"PALLAS_AXON_POOL_IPS": "",
                                    "JAX_PLATFORMS": "cpu",
                                    "XLA_FLAGS":
                                    (os.environ.get("XLA_FLAGS", "") +
                                     " --xla_force_host_platform_"
                                     "device_count=8"),
                                    "H2O3_BENCH_ONLY": "rapids",
                                    "H2O3_BENCH_RAPIDS_ROWS": "2000000"})
            if got is None:
                got = rap
        else:
            _record("cpu-rapids", ok=False, error="skipped: deadline")
        if remaining() > 160:
            # munge→score pipeline fusion (ISSUE 16): raw-row scoring
            # throughput with the pipeline_vs_staged ratio and the
            # zero-materialization counters as aux lines — CPU-measurable
            # on the same 8-virtual-device mesh
            pipe = _stage("cpu-pipeline", [py, "-m", "h2o3_tpu.bench"],
                          150,
                          env_extra={"PALLAS_AXON_POOL_IPS": "",
                                     "JAX_PLATFORMS": "cpu",
                                     "XLA_FLAGS":
                                     (os.environ.get("XLA_FLAGS", "") +
                                      " --xla_force_host_platform_"
                                      "device_count=8"),
                                     "H2O3_BENCH_ONLY": "pipeline",
                                     "H2O3_BENCH_PIPELINE_TRAIN_ROWS":
                                     "5000"})
            if got is None:
                got = pipe
        else:
            _record("cpu-pipeline", ok=False, error="skipped: deadline")
        if remaining() > 160:
            # chunked sharded ingest metric (ISSUE 15): parse_mb_per_sec
            # with the chunked-vs-monolithic speedup and the
            # coordinator-bytes-0 evidence as aux lines — CPU-measurable,
            # same 8-virtual-device mesh as the score/rapids stages
            par = _stage("cpu-parse", [py, "-m", "h2o3_tpu.bench"], 150,
                         env_extra={"PALLAS_AXON_POOL_IPS": "",
                                    "JAX_PLATFORMS": "cpu",
                                    "XLA_FLAGS":
                                    (os.environ.get("XLA_FLAGS", "") +
                                     " --xla_force_host_platform_"
                                     "device_count=8"),
                                    "H2O3_BENCH_ONLY": "parse"})
            if got is None:
                got = par
        else:
            _record("cpu-parse", ok=False, error="skipped: deadline")
        if remaining() > 170:
            # serving-tier artifact metrics land even on a dead tunnel
            _stage("cpu-artifact", [py, "-m", "h2o3_tpu.bench"], 160,
                   env_extra={"PALLAS_AXON_POOL_IPS": "",
                              "JAX_PLATFORMS": "cpu",
                              "H2O3_BENCH_ONLY": "artifact",
                              "H2O3_BENCH_ARTIFACT_TRAIN_ROWS": "5000"})
        else:
            _record("cpu-artifact", ok=False, error="skipped: deadline")
        if remaining() > 160:
            # memory-safety drill (ISSUE 20): pinned-budget chunk
            # streaming + injected-OOM ladder recovery — CPU-measurable
            # on the same 8-virtual-device mesh (mem_degrade_recover_secs
            # + the bigger_than_hbm_ok bitwise evidence)
            _stage("cpu-oom-degrade", [py, "-m", "h2o3_tpu.bench"], 150,
                   env_extra={"PALLAS_AXON_POOL_IPS": "",
                              "JAX_PLATFORMS": "cpu",
                              "XLA_FLAGS":
                              (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_"
                               "device_count=8"),
                              "H2O3_BENCH_ONLY": "oom-degrade",
                              "H2O3_BENCH_OOM_ROWS": "30000"})
        else:
            _record("cpu-oom-degrade", ok=False, error="skipped: deadline")
        if remaining() > 90:
            # recovery drill is pure control plane: always measurable
            _stage("recover", [py, "-m", "h2o3_tpu.bench"], 80,
                   env_extra={"PALLAS_AXON_POOL_IPS": "",
                              "JAX_PLATFORMS": "cpu",
                              "H2O3_BENCH_ONLY": "recover"})
        else:
            _record("recover", ok=False, error="skipped: deadline")
    if got is None:
        print(json.dumps({"metric": "bench_failed", "value": 0.0,
                          "unit": "none", "vs_baseline": 0.0}))
        return
    value, metric = got
    rec = RECORDED.get(metric)
    vs = value / rec if (rec and unit == "rows/sec/chip") else 0.0
    print(json.dumps({"metric": metric, "value": round(value, 1),
                      "unit": unit, "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    main()
