"""Benchmark: flagship-model training throughput on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no in-tree numbers (BASELINE.md) — vs_baseline is
relative to the first recorded run of this implementation (RECORDED below);
1.0 until a baseline exists.

Watchdog design (round-4 fix): the driver runs `python bench.py` under its
own ~1500 s timeout. Every stage that touches jax runs in a SUBPROCESS with
its own hard timeout, and the stage budgets sum to ~1100 s so the parent
always gets to print its JSON line before the driver's outer timeout:
  1. flagship GBM bench (default env, real chip if tunnel is up) .. 650 s
  1b. depth-20 DRF secondary metric (own stage, only after 1 OK) .. 180 s
  2. GLM IRLS fallback (default env) ............................. 200 s
  3. GLM IRLS on CPU, bypassing the axon tunnel entirely ......... 180 s
The parent NEVER imports jax: a wedged accelerator tunnel hangs jax import
in any process that touches it, so all jax work is quarantined in children.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))

# first recorded values on real TPU hardware (v5 lite, 2026-07-29) — the
# baseline later rounds are measured against
RECORDED = {
    "gbm_rows_per_sec": 465943.8,
    "glm_irls_rows_per_sec": 371850175.7,
}


def bench_glm(n_rows: int = 1_000_000, p: int = 32, iters: int = 20) -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((n_rows, p)), jnp.float32)
    true_b = jnp.asarray(rng.standard_normal(p), jnp.float32)
    y = (jax.nn.sigmoid(X @ true_b) > 0.5).astype(jnp.float32)

    @jax.jit
    def irls_step(beta, _):
        eta = X @ beta[:-1] + beta[-1]
        mu = jax.nn.sigmoid(eta)
        w = jnp.maximum(mu * (1 - mu), 1e-6)
        z = eta + (y - mu) / w
        Xa = jnp.concatenate([X, jnp.ones((X.shape[0], 1), X.dtype)], axis=1)
        gram = (Xa * w[:, None]).T @ Xa + 1e-6 * jnp.eye(p + 1, dtype=X.dtype)
        rhs = Xa.T @ (w * z)
        return jnp.linalg.solve(gram, rhs), 0.0

    import jax.lax as lax

    @jax.jit
    def run(beta):
        beta, _ = lax.scan(irls_step, beta, None, length=iters)
        return beta

    beta0 = jnp.zeros(p + 1, jnp.float32)
    run(beta0).block_until_ready()  # compile
    t0 = time.perf_counter()
    run(beta0).block_until_ready()
    dt = time.perf_counter() - t0
    return n_rows * iters / dt


def _parse_result(stdout: str):
    for ln in stdout.splitlines():
        if ln.startswith("H2O3_BENCH "):
            try:
                _, metric, value = ln.split()
                return float(value), metric
            except ValueError:
                print(f"malformed bench line: {ln!r}", file=sys.stderr)
    return None


def _stage(cmd, timeout_s, env_extra=None):
    """Run one bench stage in a subprocess with a hard timeout. Returns
    (value, metric) or None on timeout / crash / missing result line."""
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=timeout_s,
                              text=True, cwd=REPO, env=env)
    except subprocess.TimeoutExpired:
        print(f"bench stage timed out after {timeout_s}s: {cmd}",
              file=sys.stderr)
        return None
    got = _parse_result(proc.stdout)
    if got is None:
        print(f"bench stage rc={proc.returncode} produced no result: "
              f"{proc.stderr[-2000:]}", file=sys.stderr)
    return got


_GLM_SNIPPET = ("import bench; "
                "print('H2O3_BENCH glm_irls_rows_per_sec', bench.bench_glm())")


def main():
    got = _stage([sys.executable, "-m", "h2o3_tpu.bench"], 650)
    if got is not None:
        # secondary metric in its OWN stage so a slow/hung DRF bench can
        # never take the flagship result down with it
        extra = _stage([sys.executable, "-m", "h2o3_tpu.bench"], 180,
                       env_extra={"H2O3_BENCH_ONLY": "drf"})
        if extra is not None:
            print(json.dumps({"metric": extra[1], "value": round(extra[0], 1),
                              "unit": "rows/sec/chip", "secondary": True}),
                  file=sys.stderr)
    if got is None:  # flagship failed/hung: GLM fallback, still default env
        got = _stage([sys.executable, "-c", _GLM_SNIPPET], 200)
    unit = "rows/sec/chip"
    if got is None:  # tunnel wedged: CPU bypass so a number ALWAYS lands
        got = _stage([sys.executable, "-c", _GLM_SNIPPET], 180,
                     env_extra={"PALLAS_AXON_POOL_IPS": "",
                                "JAX_PLATFORMS": "cpu"})
        unit = "rows/sec/cpu-fallback"
    if got is None:
        print(json.dumps({"metric": "bench_failed", "value": 0.0,
                          "unit": "none", "vs_baseline": 0.0}))
        return
    value, metric = got
    rec = RECORDED.get(metric)
    vs = value / rec if (rec and unit == "rows/sec/chip") else 0.0
    print(json.dumps({"metric": metric, "value": round(value, 1),
                      "unit": unit, "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    main()
