"""Benchmark: flagship-model training throughput on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no in-tree numbers (BASELINE.md) — vs_baseline is
relative to the first recorded run of this implementation (RECORDED below);
1.0 until a baseline exists.
"""

from __future__ import annotations

import json
import time

import numpy as np

# first recorded values on real TPU hardware (v5 lite, 2026-07-29) — the
# baseline later rounds are measured against
RECORDED = {
    "gbm_rows_per_sec": 465943.8,
    "glm_irls_rows_per_sec": 371850175.7,
}
METRIC = "glm_irls_rows_per_sec"


def bench_glm(n_rows: int = 1_000_000, p: int = 32, iters: int = 20) -> float:
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((n_rows, p)), jnp.float32)
    true_b = jnp.asarray(rng.standard_normal(p), jnp.float32)
    y = (jax.nn.sigmoid(X @ true_b) > 0.5).astype(jnp.float32)

    @jax.jit
    def irls_step(beta, _):
        eta = X @ beta[:-1] + beta[-1]
        mu = jax.nn.sigmoid(eta)
        w = jnp.maximum(mu * (1 - mu), 1e-6)
        z = eta + (y - mu) / w
        Xa = jnp.concatenate([X, jnp.ones((X.shape[0], 1), X.dtype)], axis=1)
        gram = (Xa * w[:, None]).T @ Xa + 1e-6 * jnp.eye(p + 1, dtype=X.dtype)
        rhs = Xa.T @ (w * z)
        return jnp.linalg.solve(gram, rhs), 0.0

    import jax.lax as lax

    @jax.jit
    def run(beta):
        beta, _ = lax.scan(irls_step, beta, None, length=iters)
        return beta

    beta0 = jnp.zeros(p + 1, jnp.float32)
    run(beta0).block_until_ready()  # compile
    t0 = time.perf_counter()
    run(beta0).block_until_ready()
    dt = time.perf_counter() - t0
    return n_rows * iters / dt


def _flagship_watchdog(timeout_s: int = 1500):
    """Run the flagship bench in a SUBPROCESS with a hard timeout: a wedged
    accelerator tunnel or a pathological compile must degrade to the GLM
    fallback metric, not hang the driver's bench step."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "h2o3_tpu.bench"],
        capture_output=True, timeout=timeout_s, text=True,
        cwd=__import__("os").path.dirname(__import__("os").path.abspath(__file__)))
    for ln in proc.stdout.splitlines():
        if ln.startswith("H2O3_BENCH "):
            _, metric, value = ln.split()
            return float(value), metric
    raise RuntimeError(f"flagship bench produced no result "
                       f"(rc={proc.returncode}): {proc.stderr[-2000:]}")


def main():
    try:
        value, metric = _flagship_watchdog()
    except Exception:
        # keep the one-JSON-line contract, but surface the flagship failure
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)
        value, metric = bench_glm(), METRIC
    rec = RECORDED.get(metric)
    vs = value / rec if rec else 1.0
    print(json.dumps({"metric": metric, "value": round(value, 1),
                      "unit": "rows/sec/chip", "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    main()
