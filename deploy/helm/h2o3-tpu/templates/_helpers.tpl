{{- define "h2o3-tpu.name" -}}
{{- .Chart.Name -}}
{{- end -}}

{{- define "h2o3-tpu.fullname" -}}
{{- printf "%s-%s" .Release.Name .Chart.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "h2o3-tpu.labels" -}}
app.kubernetes.io/name: {{ include "h2o3-tpu.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion }}
{{- end -}}
