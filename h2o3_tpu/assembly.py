"""Assembly — fit/apply munging pipelines.

Reference: h2o-core/src/main/java/water/rapids/Assembly.java + h2o-py's
h2o/assembly.py (H2OAssembly) and h2o/transforms/preprocessing.py
(H2OColSelect / H2OColOp / H2OScaler / H2OBinaryOp): an ordered list of
named frame transforms that fits once, applies to any frame, and persists
as a scoring artifact (the reference compiles it to a munging POJO).

TPU mapping: every step runs the normal device column ops (each transform
is one fused XLA program over the sharded frame); the fitted pipeline
pickles with the same versioned header models use, so it ships alongside
model artifacts for end-to-end scoring pipelines."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.core.frame import Column, Frame, T_NUM


class H2OColSelect:
    """Keep only the named columns (h2o-py H2OColSelect)."""

    def __init__(self, cols: Sequence[str]):
        self.cols = list(cols)

    def fit_transform(self, fr: Frame) -> Frame:
        return self.transform(fr)

    def transform(self, fr: Frame) -> Frame:
        missing = [c for c in self.cols if c not in fr]
        if missing:
            raise ValueError(f"H2OColSelect: missing columns {missing}")
        return fr.subframe(self.cols)


class H2OColOp:
    """Apply a unary device op to one column (h2o-py H2OColOp): op is a
    callable on jax arrays (e.g. jnp.cos) or the name of one."""

    def __init__(self, op, col: str, new_col_name: Optional[str] = None,
                 inplace: bool = True):
        # callables normalize to their NAME at construction: the pipeline
        # must pickle (jax ufunc objects do not) and derived column names
        # must be stable across processes
        if callable(op):
            op = getattr(op, "__name__", None) or str(op)
        self.op = str(op)
        import jax.numpy as jnp

        if not callable(getattr(jnp, self.op, None)):
            raise ValueError(f"H2OColOp: unknown op {self.op!r} "
                             "(must name a jax.numpy function)")
        self.col = col
        self.new_col_name = new_col_name
        self.inplace = bool(inplace)

    def _fn(self) -> Callable:
        import jax.numpy as jnp

        return getattr(jnp, self.op)

    def fit_transform(self, fr: Frame) -> Frame:
        return self.transform(fr)

    def transform(self, fr: Frame) -> Frame:
        import jax

        c = fr.col(self.col)
        out_data = jax.jit(self._fn())(c.data)
        name = self.new_col_name or (self.col if self.inplace
                                     else f"{self.op}_{self.col}")
        out = Frame()
        for nm in fr.names:
            if nm == self.col and self.inplace:
                out.add(name, Column(out_data, T_NUM, c.nrows))
            else:
                out.add(nm, fr.col(nm))
        if not self.inplace:
            out.add(name, Column(out_data, T_NUM, c.nrows))
        return out


class H2OScaler:
    """Standardize numeric columns with TRAINING means/sds (h2o-py
    H2OScaler): statistics fit once, reused at apply time."""

    def __init__(self, center: bool = True, scale: bool = True):
        self.center = bool(center)
        self.scale = bool(scale)
        self.means: Dict[str, float] = {}
        self.sds: Dict[str, float] = {}

    def fit_transform(self, fr: Frame) -> Frame:
        for nm in fr.names:
            c = fr.col(nm)
            if c.is_numeric:
                vals = c.to_numpy()
                self.means[nm] = float(np.nanmean(vals))
                sd = float(np.nanstd(vals))
                self.sds[nm] = sd if sd > 0 else 1.0
        return self.transform(fr)

    def transform(self, fr: Frame) -> Frame:
        out = Frame()
        for nm in fr.names:
            c = fr.col(nm)
            if nm in self.means:
                d = c.data
                if self.center:
                    d = d - self.means[nm]
                if self.scale:
                    d = d / self.sds[nm]
                out.add(nm, Column(d, T_NUM, c.nrows))
            else:
                out.add(nm, c)
        return out


class H2OBinaryOp:
    """colA <op> colB -> new column (h2o-py H2OBinaryOp)."""

    _OPS = {"+": "add", "-": "subtract", "*": "multiply", "/": "divide"}

    def __init__(self, op: str, left: str, right: str,
                 new_col_name: Optional[str] = None):
        if op not in self._OPS:
            raise ValueError(f"H2OBinaryOp: op must be one of {list(self._OPS)}")
        self.op = op
        self.left = left
        self.right = right
        self.new_col_name = new_col_name or f"{left}{op}{right}"

    def fit_transform(self, fr: Frame) -> Frame:
        return self.transform(fr)

    def transform(self, fr: Frame) -> Frame:
        import jax.numpy as jnp

        a, b = fr.col(self.left).data, fr.col(self.right).data
        v = getattr(jnp, self._OPS[self.op])(a, b)
        out = Frame()
        for nm in fr.names:
            out.add(nm, fr.col(nm))
        out.add(self.new_col_name, Column(v, T_NUM, fr.nrows))
        return out


class H2OAssembly:
    """Ordered named steps; fit() runs fit_transform through the chain,
    transform() replays with frozen statistics (water/rapids/Assembly.java
    fit + the munging-artifact replay)."""

    _SAVE_MAGIC = b"H2O3TPUA"
    _SAVE_VERSION = 1

    def __init__(self, steps: Sequence[Tuple[str, Any]]):
        self.steps = list(steps)
        self.fitted = False

    def fit(self, frame: Frame) -> Frame:
        out = frame
        for _name, step in self.steps:
            out = step.fit_transform(out)
        self.fitted = True
        return out

    def transform(self, frame: Frame) -> Frame:
        if not self.fitted:
            raise RuntimeError("assembly not fitted — call fit() first")
        out = frame
        for _name, step in self.steps:
            out = step.transform(out)
        return out

    @property
    def names(self) -> List[str]:
        return [n for n, _s in self.steps]

    # -- persistence (the munging-POJO analog: a replayable artifact) -----
    def save(self, path: str) -> str:
        import pickle
        import struct

        with open(path, "wb") as f:
            f.write(self._SAVE_MAGIC)
            f.write(struct.pack("<H", self._SAVE_VERSION))
            pickle.dump(self, f)
        return path

    @staticmethod
    def load(path: str) -> "H2OAssembly":
        # restricted unpickler: an assembly artifact is untrusted input
        # like any model artifact — framework types only (ISSUE-11
        # serialization invariant)
        import struct

        from h2o3_tpu.utils.unpickle import restricted_load

        with open(path, "rb") as f:
            if f.read(8) != H2OAssembly._SAVE_MAGIC:
                raise ValueError(f"{path!r} is not an assembly artifact")
            (ver,) = struct.unpack("<H", f.read(2))
            if ver > H2OAssembly._SAVE_VERSION:
                raise ValueError(f"assembly artifact version {ver} too new")
            return restricted_load(f, what="assembly artifact")

    # -- REST wire format (h2o-py transform_base.to_rest) ----------------
    @staticmethod
    def from_steps(step_strings: Sequence[str]) -> "H2OAssembly":
        """Decode the POST /99/Assembly `steps` payload: each entry is
        `name__ClassName__(rapids ast over 'dummy')__inplace__newcols`
        (h2o-py transforms/transform_base.py to_rest; server counterpart
        water/rapids/transforms/H2OColOp.java:28)."""
        steps: List[Tuple[str, Any]] = []
        for raw in step_strings:
            s = str(raw).strip().strip('"').strip("'")
            parts = s.split("__")
            if len(parts) < 5:
                raise ValueError(f"bad assembly step {s!r}")
            name, klass, ast, inplace, newcols = (
                parts[0], parts[1], "__".join(parts[2:-2]),
                parts[-2], parts[-1])
            new_names = [c for c in newcols.split("|") if c]
            steps.append((name, RestStep(
                klass, ast, inplace.strip().lower() == "true", new_names)))
        return H2OAssembly(steps)

    def describe(self) -> List[str]:
        return [f"{n}: {getattr(s, 'describe', lambda: type(s).__name__)()}"
                for n, s in self.steps]

    # -- munge→score pipeline artifact (artifact/pipeline.py) -------------
    def export_pipeline(self, model, frame: Frame, out_dir: str,
                        buckets: Optional[Sequence[int]] = None):
        """Fuse this assembly's munge with `model`'s scoring core into ONE
        standalone program and write a *pipeline artifact*: the steps
        replay LAZILY through a private Rapids session so every engineered
        column stays a pending expression node, and the exporter splices
        those nodes into the model's fused scoring program —
        h2o3_genmodel.aot then scores RAW rows in `frame`'s schema with no
        munge replay at serve time, bitwise-identical to in-process.

        Only Rapids-backed steps (the REST wire format) can stay lazy;
        assemblies whose steps touch column data directly (H2OScaler and
        friends) materialize their outputs and the export refuses with
        the reason. Returns the written manifest."""
        import uuid as _uuid

        from h2o3_tpu.artifact.pipeline import export_pipeline as _export
        from h2o3_tpu.rapids import Session
        from h2o3_tpu.rapids import planner as lazy_planner

        sess = Session(f"assembly_pipe_{_uuid.uuid4().hex[:8]}")
        try:
            with lazy_planner.force(True):
                out = frame
                for _name, step in self.steps:
                    if isinstance(step, RestStep):
                        out = step.transform(out, session=sess)
                    elif self.fitted:
                        out = step.transform(out)
                    else:
                        out = step.fit_transform(out)
                return _export(model, out, out_dir, buckets=buckets)
        finally:
            sess.end()

    def to_source(self, name: str = "MungePipeline") -> str:
        """Self-contained replay source (the reference emits a Java munging
        POJO via GET /99/Assembly.java; we emit the equivalent pipeline as
        commented Rapids so any client of this server can replay it)."""
        lines = [f"// {name} — munging pipeline export (h2o3_tpu)",
                 "// Replay: POST each Rapids expression below with the",
                 "// target frame id substituted for 'dummy'."]
        for n, s in self.steps:
            lines.append(f"// step {n}")
            lines.append(getattr(s, "ast", f"(noop {type(s).__name__})"))
        return "\n".join(lines) + "\n"


class RestStep:
    """One wire-decoded munging step, with the reference's column-splice
    semantics (water/rapids/transforms/H2OColOp.java transformImpl:
    substitute the frame, exec the ast, then replace/append columns)."""

    def __init__(self, klass: str, ast: str, inplace: bool,
                 new_names: List[str]):
        self.klass = klass
        self.ast = ast
        self.inplace = inplace
        self.new_names = new_names

    def describe(self) -> str:
        return f"{self.klass}(inplace={self.inplace}) {self.ast}"

    def _old_col(self) -> Optional[str]:
        import re

        m = re.search(r"\(cols(?:_py)?\s+dummy\s+'([^']+)'\)", self.ast) or \
            re.search(r'\(cols(?:_py)?\s+dummy\s+"([^"]+)"\)', self.ast)
        return m.group(1) if m else None

    def _exec(self, fr: Frame, session=None):
        import re

        from h2o3_tpu.core.dkv import Key
        from h2o3_tpu.rapids import exec_rapids

        expr = re.sub(r"\bdummy\b", str(fr.key), self.ast)
        if session is not None:
            # bind through a session temp: assignment statements are what
            # the lazy planner defers, so the step's expression stays a
            # pending DAG node (the pipeline-artifact export path)
            expr = f"(tmp= {Key.make('assembly_t')} {expr})"
        return exec_rapids(expr, session)

    def fit_transform(self, fr: Frame) -> Frame:
        return self.transform(fr)

    def transform(self, fr: Frame, session=None) -> Frame:
        fr.install()
        res = self._exec(fr, session)
        if self.klass == "H2OColSelect":
            return res if isinstance(res, Frame) else fr
        old = self._old_col()
        out = Frame()
        res_cols = (list(res.names) if isinstance(res, Frame) else [None])
        if isinstance(res, Frame) and len(res_cols) == 1:
            new_col = res.col(res_cols[0])
            if self.inplace and old is not None:
                for nm in fr.names:
                    out.add(nm, new_col if nm == old else fr.col(nm))
            else:
                for nm in fr.names:
                    out.add(nm, fr.col(nm))
                nm = self.new_names[0] if self.new_names else \
                    f"{old or 'col'}0"
                out.add(nm, new_col)
            return out
        if isinstance(res, Frame):       # multi-column result
            for nm in fr.names:
                if self.inplace and nm == old:
                    continue
                out.add(nm, fr.col(nm))
            for i, rn in enumerate(res_cols):
                nm = (self.new_names[i] if i < len(self.new_names)
                      else f"{old or 'col'}{i}")
                out.add(nm, res.col(rn))
            return out
        return fr
