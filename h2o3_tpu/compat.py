"""JAX version compatibility shims.

The framework targets the promoted `jax.shard_map` API; older jax releases
(< 0.5) only ship it as `jax.experimental.shard_map.shard_map`. Every
shard_map call site routes through :func:`shard_map` so the framework runs
on both without scattering version checks."""

from __future__ import annotations


def shard_map(f=None, **kw):
    """`jax.shard_map` where available, else the experimental spelling.
    Translates the renamed replication-check kwarg (check_vma, jax>=0.6)
    to the older check_rep when falling back."""
    import inspect

    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    params = inspect.signature(sm).parameters
    if "check_vma" in kw and "check_vma" not in params:
        kw["check_rep"] = kw.pop("check_vma")
    elif "check_rep" in kw and "check_rep" not in params:
        kw["check_vma"] = kw.pop("check_rep")
    return sm(f, **kw) if f is not None else lambda g: sm(g, **kw)


def pcast(x, axes, to="varying"):
    """`jax.lax.pcast` (jax>=0.7 varying-mesh-axis annotation) with an
    identity fallback: older shard_map has no vma system, so replicated→
    varying casts are no-ops there."""
    import jax

    pc = getattr(jax.lax, "pcast", None)
    if pc is not None:
        return pc(x, axes, to=to)
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None and to == "varying":
        return pv(x, axes)
    return x
