"""JAX version compatibility shims.

The framework targets the promoted `jax.shard_map` API; older jax releases
(< 0.5) only ship it as `jax.experimental.shard_map.shard_map`. Every
shard_map call site routes through :func:`shard_map` so the framework runs
on both without scattering version checks."""

from __future__ import annotations


def shard_map(f=None, **kw):
    """`jax.shard_map` where available, else the experimental spelling.
    Translates the renamed replication-check kwarg (check_vma, jax>=0.6)
    to the older check_rep when falling back."""
    import inspect

    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    params = inspect.signature(sm).parameters
    if "check_vma" in kw and "check_vma" not in params:
        kw["check_rep"] = kw.pop("check_vma")
    elif "check_rep" in kw and "check_rep" not in params:
        kw["check_vma"] = kw.pop("check_rep")
    return sm(f, **kw) if f is not None else lambda g: sm(g, **kw)


def pcast(x, axes, to="varying"):
    """`jax.lax.pcast` (jax>=0.7 varying-mesh-axis annotation) with an
    identity fallback: older shard_map has no vma system, so replicated→
    varying casts are no-ops there."""
    import jax

    pc = getattr(jax.lax, "pcast", None)
    if pc is not None:
        return pc(x, axes, to=to)
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None and to == "varying":
        return pv(x, axes)
    return x


# ---------------------------------------------------------------------------
# AOT executable (de)serialization — the artifact/compile-cache substrate.
# jax.experimental.serialize_executable has moved/changed signature across
# releases; every artifact/cache call site routes through these three shims
# so a jax without the API degrades to the StableHLO / recompile fallbacks
# instead of crashing the exporter or the loader.
# ---------------------------------------------------------------------------

def serialize_compiled(compiled):
    """Serialize an AOT-compiled executable (``jit(f).lower(...).compile()``)
    to ``(payload_bytes, in_tree, out_tree)``, or None when this jax/backend
    cannot serialize executables (the caller falls back to StableHLO)."""
    try:
        from jax.experimental import serialize_executable as se

        return se.serialize(compiled)
    except Exception:   # noqa: BLE001 — capability probe by contract
        return None


def deserialize_compiled(payload, in_tree, out_tree):
    """Load a serialized executable back into a callable. Raises when the
    payload targets a different backend/topology or the API is missing —
    callers treat any raise as 'unavailable on this target' and fall back."""
    from jax.experimental import serialize_executable as se

    return se.deserialize_and_load(payload, in_tree, out_tree)


def profiler_start(log_dir: str) -> None:
    """``jax.profiler.start_trace`` across jax releases (the API predates
    0.4 but its kwargs have shifted): positional log_dir only, which every
    supported release accepts. Raises when a capture is already running —
    the REST layer maps that to a clean 409."""
    import jax

    jax.profiler.start_trace(log_dir)


def profiler_stop() -> None:
    """``jax.profiler.stop_trace`` — raises when no capture is running
    (mapped to a clean 400 at the REST layer)."""
    import jax

    jax.profiler.stop_trace()


def profiler_annotation(name: str):
    """``jax.profiler.TraceAnnotation`` — a named region inside a capture
    (the API spelling has been stable, but it lives on the same
    version-mobile module as start/stop_trace, so it routes here too)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def pallas_modules():
    """``(pallas, pallas.tpu)`` — the TPU kernel surface. Pallas is a
    device-only lowering that has moved within jax.experimental across
    releases; importing it at call time through this shim keeps CPU-only
    deployments importable (callers already guard execution behind
    ``H2O_TPU_PALLAS_HIST`` / interpret mode). The tpu submodule is None
    when this jax does not ship it — callers fall back to default memory
    spaces."""
    from jax.experimental import pallas as pl

    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:         # pragma: no cover — very old jax
        pltpu = None
    return pl, pltpu


def memory_analysis(compiled):
    """Byte-level memory estimate of an AOT-compiled executable
    (``compiled.memory_analysis()`` — the API and its field names are
    version-mobile, and some backends return None). Normalized to
    ``{argument_bytes, output_bytes, temp_bytes, generated_code_bytes}``
    (missing fields omitted), or None when this jax/backend cannot say —
    the compile ledger records it as the program's HBM estimate
    ("Memory Safe Computations with XLA Compiler", PAPERS.md)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:   # noqa: BLE001 — capability probe by contract
        return None
    if ma is None:
        return None
    out = {}
    for key, attrs in (
            ("argument_bytes", ("argument_size_in_bytes",)),
            ("output_bytes", ("output_size_in_bytes",)),
            ("temp_bytes", ("temp_size_in_bytes",)),
            ("generated_code_bytes", ("generated_code_size_in_bytes",))):
        for a in attrs:
            v = getattr(ma, a, None)
            if v is not None:
                try:
                    out[key] = int(v)
                except (TypeError, ValueError):
                    pass
                break
    return out or None


def compile_stablehlo(text: str):
    """Portable lowering fallback: compile StableHLO module text through the
    local XLA client. Returns an executable whose ``.execute([arrays])``
    runs the program on the default device — the exact program the exporter
    lowered, so results stay bitwise-identical to the source process."""
    import jax

    return jax.devices()[0].client.compile(text)
