"""Serving fast path: compile-once, device-resident scoring sessions.

Reference: H2O-3 solves high-QPS serving with standalone MOJO scorers
(genmodel) that keep the tree bytes resident and score without touching
the training stack. The TPU-native equivalent is a per-model
:class:`ScoringSession` that keeps the CompressedForest arrays
device-resident and dispatches ONE fused XLA program (bin + traverse +
init margin) per request batch.

Two properties make this a serving engine rather than a batch scorer
(cf. "Memory Safe Computations with XLA Compiler" / Podracer, PAPERS.md):

- **Shape buckets**: incoming batches are padded to power-of-two row
  buckets (env ``H2O_TPU_SCORE_BUCKETS``, default 256/1k/4k/16k), so the
  traversal compiles once per (bucket, forest-shape) instead of once per
  request row count. Requests above the largest bucket are chunked at it,
  keeping the trace count bounded. Padded rows are zero-filled and sliced
  off before anything reads them.
- **Micro-batching**: concurrent requests against the SAME model coalesce
  into one dispatch inside a time-boxed window
  (``H2O_TPU_SCORE_BATCH_WINDOW_MS``, default 2 ms); each request gets its
  exact row-slice back. Requests against different models never block
  each other (per-model queues). On a multi-process cloud the whole batch
  ships as ONE oplog op ("score_batch") that followers replay once.

Per-model serving metrics (requests, batch sizes, latency percentiles,
traversal compile count) land in the timeline ring and are snapshotted by
``GET /3/ScoringMetrics``.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.obs import tracing

_DEFAULT_BUCKETS = (256, 1024, 4096, 16384)

# -- per-process fused-dispatch accounting ----------------------------------
# one increment per fused program execution on the serving/explainability
# paths, by path label (sharded | host | local | leaf_sharded | leaf_host).
# /3/ScoringMetrics serves these under ``dispatches`` and /3/Metrics as
# ``h2o3_score_dispatches_total``; the consistency suite asserts a
# multi-entry sharded flush records exactly one dispatch per row bucket.

_DISP_LOCK = threading.Lock()
_DISPATCHES: Dict[str, int] = {}


def note_dispatch(path: str, n: int = 1) -> None:
    with _DISP_LOCK:
        _DISPATCHES[path] = _DISPATCHES.get(path, 0) + int(n)


def dispatch_counters() -> Dict[str, int]:
    with _DISP_LOCK:
        return dict(_DISPATCHES)


def reset_dispatch_counters() -> None:
    with _DISP_LOCK:
        _DISPATCHES.clear()


def _shard_owners(arr) -> list:
    """Process indices (other than ours) owning shards of a device array —
    the processes a degraded cloud would need to reach to score it."""
    import jax

    try:
        me = jax.process_index()
        return sorted({d.process_index for d in arr.sharding.device_set}
                      - {me})
    except Exception:   # noqa: BLE001 — sharding introspection best-effort
        return []


def _env_buckets() -> Tuple[int, ...]:
    raw = os.environ.get("H2O_TPU_SCORE_BUCKETS", "")
    if not raw.strip():
        return _DEFAULT_BUCKETS
    try:
        vals = sorted({int(v) for v in raw.replace(";", ",").split(",")
                       if v.strip()})
    except ValueError:
        return _DEFAULT_BUCKETS
    return tuple(v for v in vals if v > 0) or _DEFAULT_BUCKETS


def _window_s() -> float:
    try:
        ms = float(os.environ.get("H2O_TPU_SCORE_BATCH_WINDOW_MS", "2"))
    except ValueError:
        ms = 2.0
    return max(ms, 0.0) / 1000.0


def enabled() -> bool:
    return os.environ.get("H2O_TPU_SCORE_FAST", "1").lower() not in (
        "0", "false", "off")


def supports(model) -> bool:
    """True when `model` can ride the fused bucketed path: a SharedTree
    forest whose raw-prediction semantics are pure margin post-processing
    (subclasses overriding _predict_raw — e.g. IsolationForest's
    mean-length output — stay on the generic path)."""
    if not enabled():
        return False
    from h2o3_tpu.models.tree.shared_tree import SharedTreeModel

    return (isinstance(model, SharedTreeModel)
            and model.forest is not None and model.spec is not None
            and type(model)._predict_raw is SharedTreeModel._predict_raw)


class SessionStats:
    """Per-model serving counters behind a small lock; p50/p99 computed at
    read time over a bounded latency ring."""

    def __init__(self):
        self._lock = threading.Lock()
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.dispatches = 0          # fused program executions (all paths)
        self.max_batch_requests = 0
        self._lat_ms: collections.deque = collections.deque(maxlen=512)

    def record_batch(self, n_requests: int, n_rows: int, ms: float,
                     dispatches: int = 0) -> None:
        with self._lock:
            self.requests += n_requests
            self.batches += 1
            self.rows += n_rows
            self.dispatches += int(dispatches)
            self.max_batch_requests = max(self.max_batch_requests, n_requests)
            self._lat_ms.append(float(ms))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            lat = np.asarray(self._lat_ms, np.float64)
            out = {"requests": self.requests, "batches": self.batches,
                   "rows": self.rows, "dispatches": self.dispatches,
                   "max_batch_requests": self.max_batch_requests}
            if self.batches:
                out["dispatches_per_flush"] = round(
                    self.dispatches / self.batches, 3)
        if lat.size:
            out["p50_ms"] = round(float(np.percentile(lat, 50)), 3)
            out["p99_ms"] = round(float(np.percentile(lat, 99)), 3)
        return out


class ScoringSession:
    """Device-resident scorer for ONE trained forest.

    Holds the forest arrays + BinSpec tables on device and a fused
    bin+traverse program compiled per row bucket. All margins it returns
    are bitwise-identical to spec.bin_columns + forest.predict_binned."""

    def __init__(self, model):
        import jax.numpy as jnp

        from h2o3_tpu.core.runtime import cluster
        from h2o3_tpu.models.tree.compressed import _fused_score_fn

        self.model = model
        self.forest = model.forest
        self.spec = model.spec
        self._cl = cluster()
        self._arrays = self.forest.arrays()          # device-resident
        self._edges = jnp.asarray(self.spec.padded_edges())
        self._is_cat = jnp.asarray(np.asarray(self.spec.is_cat, bool))
        if self.forest.init_class is not None:
            self._init = jnp.asarray(np.asarray(self.forest.init_class,
                                                np.float32))
        else:
            self._init = jnp.float32(self.forest.init_f)
        # buckets rounded up to shard-divisible sizes so row sharding holds
        self.buckets = tuple(sorted({self._cl.pad_rows(b)
                                     for b in _env_buckets()}))
        self._fn = _fused_score_fn(self.forest.max_depth,
                                   self.forest.nclasses,
                                   self.forest.per_class_trees)
        self._fn_sharded = None          # lazy shard_map'd twin (sharded plane)
        self._fn_leaf = None             # lazy fused bin+leaf twin (explain)
        self._fn_leaf_sharded = None     # ... and its shard_map'd variant
        self._traced: set = set()        # buckets activated so far
        # AOT executables per (bucket, local): dispatched explicitly so
        # compilation is observable (fused-compile counter) and cacheable
        # across server restarts (artifact/compile_cache.py)
        self._exec: Dict[tuple, Any] = {}
        self._model_ck: Optional[str] = None
        self.fused_compiles = 0          # actual XLA compiles this session
        self.cache_hits = 0              # executables served from disk
        self._local_cache = None         # degraded-mode forest array copies
        self.stats = SessionStats()

    # -- feature packing ---------------------------------------------------
    def _features(self, adapted, n: int) -> np.ndarray:
        """(n, F) float32 host matrix in training-column order: numerics
        as-is (NaN = NA), categoricals as their (already remapped) integer
        codes — NA_CAT stays negative and bins to the NA bin.

        This is the HOST-GATHER fallback (degraded-local serving, ragged
        layouts): every column round-trips through this process's host, so
        the rows count as ``gathered`` on the data-plane counters. The
        default serving path packs shard-locally via _sharded_view /
        _margins_sharded_batch and never lands here."""
        from h2o3_tpu.core import sharded_frame

        sharded_frame.note_gathered(n)
        with tracing.span("pack", rows=n, path="host"):
            X = np.empty((n, self.spec.F), np.float32)
            for i, name in enumerate(self.spec.names):
                X[:, i] = np.asarray(adapted.col(name).data)[:n]
        return X

    def _sharded_view(self, adapted):
        """ShardedFrame view of an adapted frame over the training feature
        columns, or None when shard-local packing cannot hold (plane off,
        host-resident column, ragged layout)."""
        from h2o3_tpu.core.sharded_frame import ShardedFrame

        return ShardedFrame.of(adapted, self.spec.names)

    def _bucket_for(self, m: int) -> int:
        for b in self.buckets:
            if b >= m:
                return b
        return self.buckets[-1]

    def _window_snap(self, w: int) -> int:
        """Snap a planner-chosen window DOWN onto the bucket ladder so
        chunk streaming reuses the compiled bucket programs (below the
        smallest bucket the window stays as-is and pads up into it)."""
        for b in reversed(self.buckets):
            if b <= w:
                return b
        return max(w, 1)

    def _row_bytes_hint(self) -> float:
        """Static working-set bytes/row for one fused dispatch: packed
        features in and out of the pack program plus the margin lanes —
        the planner takes the max of this and the ledger-seeded
        estimate."""
        F = max(len(self.spec.names), 1)
        return 4.0 * (2 * F + self._out_k() + 2)

    # -- bucketed dispatch -------------------------------------------------
    def _local_arrays(self):
        """Coordinator-local copies of the device-resident forest arrays
        for degraded-cloud serving: the training-time originals may be laid
        out over the GLOBAL mesh, and any dispatch against that mesh is an
        SPMD program a dead follower will never join. Host-roundtripped
        once per session and cached; raises when the arrays themselves need
        the dead peer."""
        if self._local_cache is None:
            import jax.numpy as jnp

            from h2o3_tpu.core.failure import ShardUnavailableError

            for a in self._arrays:
                if not getattr(a, "is_fully_addressable", True):
                    raise ShardUnavailableError(
                        f"cloud degraded and model {self.model.key}'s "
                        "forest arrays are not fully addressable here",
                        owners=_shard_owners(a))
            self._local_cache = tuple(jnp.asarray(np.asarray(a))
                                      for a in self._arrays)
        return self._local_cache

    def _model_checksum(self) -> str:
        if self._model_ck is None:
            from h2o3_tpu.artifact import packer

            self._model_ck = packer.model_checksum(self.forest, self.spec)
        return self._model_ck

    def _sharded_score_fn(self):
        """Lazy shard_map'd twin of the fused program (compressed.py
        _fused_score_sharded_fn) — same per-row core, margins computed per
        addressable row shard under the named 'rows' axis."""
        if self._fn_sharded is None:
            from h2o3_tpu.models.tree.compressed import \
                _fused_score_sharded_fn

            self._fn_sharded = _fused_score_sharded_fn(
                self.forest.max_depth, self.forest.nclasses,
                self.forest.per_class_trees, self._cl.mesh)
        return self._fn_sharded

    def _leaf_score_fn(self, sharded: bool):
        """Lazy fused bin+leaf programs (compressed.py _fused_leaf_fn /
        _fused_leaf_sharded_fn) — the explainability twins of the scoring
        programs, sharing the binning and walk cores bitwise."""
        if sharded:
            if self._fn_leaf_sharded is None:
                from h2o3_tpu.models.tree.compressed import \
                    _fused_leaf_sharded_fn

                self._fn_leaf_sharded = _fused_leaf_sharded_fn(
                    self.forest.max_depth, self._cl.mesh)
            return self._fn_leaf_sharded
        if self._fn_leaf is None:
            from h2o3_tpu.models.tree.compressed import _fused_leaf_fn

            self._fn_leaf = _fused_leaf_fn(self.forest.max_depth)
        return self._fn_leaf

    def _executable_for(self, bucket: int, local: bool, call_args: tuple,
                        sharded: bool = False, kind: str = "score"):
        """AOT executable for one (kind, bucket, placement) — in-memory
        first, then the persistent compile cache
        ($H2O_TPU_COMPILE_CACHE_DIR, keyed by model checksum + bucket +
        variant + backend fingerprint), and only then an actual XLA
        compile (counted, and stored back for the next process/restart).
        A warm restart therefore compiles zero fused programs. `sharded`
        selects the shard_map'd program family (the sharded data plane's
        serving path); `kind` is ``score`` (fused bin+traverse margins,
        ledger family "scoring") or ``leaf`` (fused bin+leaf walk for the
        explainability outputs, ledger family "explain")."""
        key = (kind, bucket, bool(local), bool(sharded))
        family = "scoring" if kind == "score" else "explain"
        exe = self._exec.get(key)
        if exe is not None:
            # warm path: a counter bump only (no ring row, no hashing) —
            # /3/Runtime's scoring hit ratio must reflect the dominant
            # in-memory tier, not just the disk tier
            from h2o3_tpu.obs import compiles

            compiles.record_hit(family, tier="memory")
            return exe
        from h2o3_tpu.artifact import compile_cache
        from h2o3_tpu.obs import compiles

        variant = "local" if local else "sharded" if sharded else "mesh"
        if kind != "score":
            variant = f"{kind}_{variant}"
        progname = f"fused_score_{variant}" if kind == "score" \
            else f"fused_{variant}"
        sig = (str(getattr(self.model, "key", id(self))), bucket, variant)
        ckey = None
        if compile_cache.enabled():
            # checksum + key work only when a persistent tier exists —
            # with the cache off the first dispatch must not pay a
            # whole-forest hash for a key nobody will read
            ckey = compile_cache.cache_key(
                self._model_checksum(), bucket, variant=variant)
            exe = compile_cache.load(ckey)
        if exe is None:
            if kind == "score":
                fn = self._sharded_score_fn() if sharded else self._fn
            else:
                fn = self._leaf_score_fn(sharded)
            # the ledger chokepoint lowers, compiles, times, records the
            # row AND feeds the legacy note_compile counter — callers no
            # longer self-report durations that could drift
            exe = compiles.compile_jit(family, fn, call_args,
                                       signature=sig, program=progname)
            self.fused_compiles += 1
            if ckey is not None:
                compile_cache.store(ckey, exe)
        else:
            self.cache_hits += 1
            compiles.record_hit(family, sig, "disk", program=progname)
        # seed the memory planner's bytes/row estimate from the real
        # lowered program (compat.memory_analysis via the ledger's shim)
        from h2o3_tpu.memory import budget as membudget

        membudget.note_compiled(family, bucket, exe)
        self._exec[key] = exe
        if kind == "score":
            self._traced.add(bucket)
        return exe

    def _margin_x(self, X: np.ndarray, local: bool = False,
                  dispatched: Optional[list] = None) -> np.ndarray:
        """Margins for an (n, F) feature matrix via bucketed fused
        dispatch; returns host (n,) or (n, K) float32, exact per row.
        Rows beyond the largest bucket are chunked at it, so the set of
        compiled traversal programs never exceeds len(self.buckets).
        `local=True` (degraded-cloud serving on a real multi-process cloud)
        dispatches on this process's default device with NO mesh sharding —
        the global row sharding would be a collective the dead peer never
        runs. `dispatched` (a mutable list) receives one bucket entry per
        fused dispatch, so per-model stats count exactly what ran instead
        of re-deriving the chunking arithmetic."""
        import jax

        from h2o3_tpu.memory import stream

        n = X.shape[0]
        maxb = self.buckets[-1]
        sharding = None if local else self._cl.row_sharding()
        arrays = self._local_arrays() if local else self._arrays

        def dispatch(pos: int, m: int):
            bucket = self._bucket_for(m)
            buf = np.zeros((bucket, X.shape[1]), np.float32)
            buf[:m] = X[pos: pos + m]
            xd = jax.device_put(buf) if local else jax.device_put(buf,
                                                                  sharding)
            call_args = (xd, self._edges, self._is_cat, self._init) + \
                tuple(arrays)
            exe = self._executable_for(bucket, local, call_args)
            with tracing.span("dispatch", bucket=bucket, rows=m,
                              path="host"):
                out = exe(*call_args)
            note_dispatch("local" if local else "host")
            if dispatched is not None:
                dispatched.append(bucket)
            return out

        def fetch(out, m: int):
            with tracing.span("fetch", rows=m, path="host"):
                return np.asarray(out)[:m]   # the one blocking transfer

        # chunk-streamed under the memory planner: window i+1 ships while
        # window i's output transfers; an OOM walks the halving ladder
        outs: List[np.ndarray] = stream.run_windows(
            "scoring", n, dispatch, maxb, fetch=fetch,
            row_bytes=self._row_bytes_hint(),
            window_sizer=self._window_snap)
        if not outs:
            K = (self.forest.nclasses if (self.forest.nclasses > 2
                                          or self.forest.per_class_trees)
                 else 1)
            return np.zeros((0,) if K == 1 else (0, K), np.float32)
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def _out_k(self) -> int:
        return (self.forest.nclasses if (self.forest.nclasses > 2
                                         or self.forest.per_class_trees)
                else 1)

    def _reshard_bucket(self, x):
        """Re-lay a device (bucket, F) matrix out as P('rows', None) — the
        EXACT input sharding the shard_map'd fused programs are lowered
        with (ShardedFrame.pack_features' out_shardings), so a coalesced
        chunk and a directly-packed matrix hit the same AOT executable.
        Device-to-device only; jit identity on multi-process (cross-host
        resharding goes through XLA)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from h2o3_tpu.core.sharded_frame import ROW_AXIS

        sh = NamedSharding(self._cl.mesh, P(ROW_AXIS, None))
        if jax.process_count() > 1:
            return jax.jit(lambda a: a, out_shardings=sh)(x)
        return jax.device_put(x, sh)

    def _margins_sharded_batch(self, items) -> Tuple[Any, int]:
        """Fused margins for ALL sharded-eligible entries of one flush:
        ``items`` is ``[(sf, n)]`` in flush order; returns (margins,
        dispatches) where margins is ONE device array holding the flush's
        exact logical rows back to back — (ΣN,) or (ΣN, K) — and
        dispatches counts fused program executions.

        A multi-entry flush device-concatenates the per-entry
        shard-packed matrices (each already built from addressable shards
        — zero gathers) and dispatches ONE fused program per row-bucket
        chunk of the concatenation: the host path's
        one-dispatch-per-bucket batching, now with no host round-trip.
        This deletes the recorded PR-7 trade-off (one fused dispatch PER
        ENTRY per flush). A single-entry flush keeps the direct per-chunk
        dispatch — no concat/reshard detour on the latency path.

        Bitwise contract: the fused program is row-local (bin + walk per
        row), so every logical row's margin is independent of which
        bucket chunk carried it — rows [0, n_i) equal the host-packed
        path's margins per entry; pad lanes are zero-filled and sliced
        off before anything reads them."""
        import jax.numpy as jnp

        from h2o3_tpu.memory import stream

        maxb = self.buckets[-1]
        n_disp = 0

        def dispatch(Xd, bucket: int, rows: int):
            nonlocal n_disp
            call_args = (Xd, self._edges, self._is_cat, self._init) + \
                tuple(self._arrays)
            exe = self._executable_for(bucket, False, call_args,
                                       sharded=True)
            # host-side dispatch wall time only — the program is async and
            # NO block_until_ready is added here (the fused-path counters
            # assert the path is unchanged when profiling is off)
            with tracing.span("dispatch", bucket=bucket, rows=rows,
                              path="sharded"):
                out = exe(*call_args)
            n_disp += 1
            note_dispatch("sharded")
            return out

        outs: List[Any] = []
        if len(items) == 1:
            sf, n = items[0]

            def window(pos: int, m: int):
                bucket = self._bucket_for(m)
                Xd = sf.pack_features(pos, n, bucket)
                return dispatch(Xd, bucket, m)[:m]

            outs = stream.run_windows(
                "scoring", n, window, maxb,
                row_bytes=self._row_bytes_hint(),
                window_sizer=self._window_snap)
        else:
            parts: List[Any] = []
            for sf, n in items:
                pos = 0
                while pos < n:
                    m = min(maxb, n - pos)
                    bucket = self._bucket_for(m)
                    Xd = sf.pack_features(pos, n, bucket)
                    parts.append(Xd if m == bucket else Xd[:m])
                    pos += m
            if parts:
                total = sum(n for _, n in items)
                # the device-side concat of per-entry shard-packed
                # matrices — slices/concat/pad are cheap elementwise
                # device ops, never a host staging
                with tracing.span("pack", rows=total, path="coalesce"):
                    X = parts[0] if len(parts) == 1 else \
                        jnp.concatenate(parts)
                N = int(X.shape[0])

                def window(pos: int, m: int):
                    bucket = self._bucket_for(m)
                    chunk = X[pos: pos + m]
                    if m < bucket:
                        chunk = jnp.pad(chunk, ((0, bucket - m), (0, 0)))
                    chunk = self._reshard_bucket(chunk)
                    return dispatch(chunk, bucket, m)[:m]

                outs = stream.run_windows(
                    "scoring", N, window, maxb,
                    row_bytes=self._row_bytes_hint(),
                    window_sizer=self._window_snap)
        K = self._out_k()
        if not outs:
            return jnp.zeros((0,) if K == 1 else (0, K), jnp.float32), 0
        return (outs[0] if len(outs) == 1
                else jnp.concatenate(outs)), n_disp

    def _lift_entry_margins(self, mg, n: int, padded_rows: int):
        """Pad one entry's exact (n, …) device margins out to its frame's
        padded row count and reshard over the named rows axis (the single
        gather of the serving path — device-to-device, never through the
        coordinator host). Pad rows are exactly 0.0, like
        _raw_for_slice's pad — so the downstream margin→raw→frame math is
        byte-identical between the sharded and host paths."""
        import jax.numpy as jnp

        if padded_rows > n:
            pad = ((0, padded_rows - n),) + ((0, 0),) * (mg.ndim - 1)
            mg = jnp.pad(mg, pad)
        return self._cl.reshard_rows(mg)

    # -- fused explainability (leaf walks) ---------------------------------
    def leaf_matrix(self, adapted, n: int) -> np.ndarray:
        """(n, T) int32 leaf node ids through the fused bucketed bin+leaf
        programs — bitwise-identical to ``spec.bin_columns(adapted)`` +
        ``forest.leaf_index(binned)`` (shared binning/walk cores), but
        compiled once per row bucket instead of once per request shape.
        Leaf assignment, staged probabilities and RuleFit-style path
        consumers ride the same compiled-program discipline as serving
        (recorded PR-2 follow-up). Sharded-eligible frames pack from
        addressable shards; others take the host-packed fallback."""
        import jax
        import jax.numpy as jnp

        if n <= 0:
            return np.zeros((0, self.forest.n_trees), np.int32)
        maxb = self.buckets[-1]
        a = self._arrays
        tail = (a[0], a[1], a[2], a[3], a[4], a[6], a[7], a[9])
        outs: List[Any] = []
        sf = self._sharded_view(adapted)
        if sf is None and jax.process_count() > 1:
            # ineligible frame on a multi-process cloud: the host-gather
            # fallback below would pull non-addressable columns. Keep the
            # eager device-side pass (the pre-fused path) — it runs in
            # lockstep inside the mirrored op, like predict_batch's
            # generic fallback, and is the bitwise reference anyway.
            binned = self.spec.bin_columns(adapted)
            leaves = self.forest.leaf_index(binned)
            if not getattr(leaves, "is_fully_addressable", True):
                from jax.experimental import multihost_utils

                leaves = multihost_utils.process_allgather(leaves,
                                                           tiled=True)
            return np.asarray(leaves)[:n]
        from h2o3_tpu.memory import stream

        # leaf walks stream T int32 lanes per row instead of K margins
        leaf_row_bytes = 4.0 * (2 * max(len(self.spec.names), 1)
                                + self.forest.n_trees)
        if sf is not None:
            def window(pos: int, m: int):
                bucket = self._bucket_for(m)
                Xd = sf.pack_features(pos, n, bucket)
                call_args = (Xd, self._edges, self._is_cat) + tail
                exe = self._executable_for(bucket, False, call_args,
                                           sharded=True, kind="leaf")
                with tracing.span("dispatch", bucket=bucket, rows=m,
                                  path="leaf_sharded"):
                    out = exe(*call_args)
                note_dispatch("leaf_sharded")
                return out[:m]

            outs = stream.run_windows(
                "explain", n, window, maxb, row_bytes=leaf_row_bytes,
                window_sizer=self._window_snap)
            from h2o3_tpu.core import sharded_frame

            sharded_frame.note_packed(n)
        else:
            X = self._features(adapted, n)
            sharding = self._cl.row_sharding()

            def window(pos: int, m: int):
                bucket = self._bucket_for(m)
                buf = np.zeros((bucket, X.shape[1]), np.float32)
                buf[:m] = X[pos: pos + m]
                xd = jax.device_put(buf, sharding)
                call_args = (xd, self._edges, self._is_cat) + tail
                exe = self._executable_for(bucket, False, call_args,
                                           kind="leaf")
                with tracing.span("dispatch", bucket=bucket, rows=m,
                                  path="leaf_host"):
                    out = exe(*call_args)
                note_dispatch("leaf_host")
                return out[:m]

            outs = stream.run_windows(
                "explain", n, window, maxb, row_bytes=leaf_row_bytes,
                window_sizer=self._window_snap)
        cat = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
        if not getattr(cat, "is_fully_addressable", True):
            # multi-process cloud: every process reaches this inside its
            # mirrored op (REST turn / follower replay), so the allgather
            # is in lockstep
            from jax.experimental import multihost_utils

            cat = multihost_utils.process_allgather(cat, tiled=True)
        return np.asarray(cat)[:n]

    @property
    def traversal_compiles(self) -> int:
        return len(self._traced)

    # -- request-level API -------------------------------------------------
    def _raw_for_slice(self, margin: np.ndarray, n: int,
                       local: bool = False):
        """Pad an exact (n,)/(n, K) margin slice back out to the cluster's
        padded row count and lift to a row-sharded device array, then run
        the model's margin→raw post-processing. Pad rows carry zeros; they
        are weight-masked out of metrics and sliced off of frames, exactly
        like the generic path's NA-binned pad rows. `local=True` keeps the
        identical padded shape but stays on this process's devices (no
        cluster `put_rows` — that is a global-mesh materialization)."""
        import jax.numpy as jnp

        padded = self._cl.pad_rows(n)
        buf = np.zeros((padded,) + margin.shape[1:], np.float32)
        buf[:n] = margin
        f = buf if local else self._cl.put_rows(buf)
        return self.model._margin_to_raw(jnp.asarray(f))

    def predict_batch(self, entries: List[Tuple[Any, Optional[str], bool]],
                      local_only: bool = False):
        """Score a coalesced batch: entries = [(frame, dest_key,
        with_metrics)]. Returns [(prediction_frame, metrics_or_None)] in
        entry order; prediction frames are installed under dest_key.

        Default path (sharded data plane, single- AND multi-process):
        per entry, ShardedFrame packs the feature matrix from this
        process's addressable row shards, margins run under shard_map over
        the named 'rows' axis, and one device-side reshard assembles the
        prediction frame — no column ever stages on the coordinator host.
        On a multi-process cloud every process executes the identical SPMD
        program sequence inside the mirrored op (followers replay), so the
        fused path no longer falls back to the generic predict there.
        Entries the view cannot hold (host-resident columns, ragged
        layouts, plane off) take the legacy host-packed dispatch —
        coalesced into one bucketed program — or, multi-process, the
        generic predict path.

        Coalesced dispatch (the PR-7 trade-off, removed): ALL
        sharded-eligible entries of a flush are scored by ONE fused
        dispatch per row-bucket chunk — their shard-packed matrices are
        concatenated device-side (zero gathers) and the concatenation is
        chunked at the bucket ladder exactly like the host path's
        concatenated batches. A flush of many small entries therefore
        costs ~one fused program execution per bucket, not one per entry;
        the per-entry work that remains (adapt, margin→raw, frame
        install, metrics) was per-entry on both paths. Dispatch counts
        land on /3/ScoringMetrics (``dispatches``) and
        ``h2o3_score_dispatches_total``.

        `local_only=True` is degraded-cloud serving: the followers are
        dead or stale, so no cross-process program may run. The fused
        host-packed path serves from this process alone — local-device
        dispatch, never the global mesh (the sharded path IS a mesh
        program, so it is skipped) — when every column is addressable
        here; non-addressable shards raise ShardUnavailableError (scoring
        them NEEDS the dead peer). That raise is the exceptional path:
        coordinator-addressable sharded frames serve."""
        import jax

        t0 = time.perf_counter()
        local_mp = local_only and jax.process_count() > 1
        if local_mp:
            from h2o3_tpu.core.failure import ShardUnavailableError

            for frame, _, _ in entries:
                for nm in frame.names:
                    data = frame.col(nm).data
                    if not getattr(data, "is_fully_addressable", True):
                        raise ShardUnavailableError(
                            f"cloud degraded and frame {frame.key} has "
                            f"non-coordinator shards (column {nm!r})",
                            owners=_shard_owners(data))
        mp = jax.process_count() > 1
        results: List[Any] = [None] * len(entries)
        host_entries = []          # (idx, frame, adapted, n, dest, wm)
        sharded_entries = []       # (idx, frame, n, dest, wm, sf)
        pipe_entries = []          # (idx, frame, n, dest, wm, capture)
        n_dispatches = 0
        for i, (frame, dest, with_metrics) in enumerate(entries):
            n = frame.nrows
            # pipeline splice FIRST: capture must see the frame BEFORE
            # adapt_test touches column data (a lazy-column fault is an
            # observation point and would flush the pending feature DAG)
            if not mp and not local_only:
                from h2o3_tpu import pipeline

                if pipeline.enabled():
                    try:
                        cap = pipeline.try_capture(self, frame)
                    except Exception:   # noqa: BLE001 — staged is the
                        cap = None      # contract for anything capture
                    if cap is not None:  # cannot hold
                        pipe_entries.append((i, frame, n, dest,
                                             with_metrics, cap))
                        continue
            adapted = self.model.adapt_test(frame)
            sf = None if local_mp else self._sharded_view(adapted)
            if sf is not None:
                sharded_entries.append((i, frame, n, dest, with_metrics,
                                        sf))
            elif mp and not local_only:
                # ineligible entry on a multi-process cloud: the generic
                # path (device-side binning + traversal) keeps the program
                # sequence mirrored without host packing. Reuse the one
                # adaptation above — predict()/model_performance() would
                # each re-adapt the frame (2-3x column transfers per
                # request, on every process)
                raw = self.model._predict_raw(adapted)
                pred = self.model._raw_to_frame(raw, n, key=dest)
                pred.install()
                mm = self.model._make_metrics(frame, raw) if with_metrics \
                    else None
                results[i] = (pred, mm)
            else:
                host_entries.append((i, frame, adapted, n, dest,
                                     with_metrics))
        if pipe_entries:
            from h2o3_tpu import pipeline
            from h2o3_tpu.core import sharded_frame

            for i, frame, n, dest, with_metrics, cap in pipe_entries:
                # munge→score as ONE program per bucket: the captured
                # feature DAG and the forest core dispatch together; no
                # engineered Column ever materializes
                try:
                    mg, nd = pipeline.execute_margins(self, cap)
                except Exception:   # noqa: BLE001 — abandon to staged
                    pipeline.note_fallback(cap)
                    adapted = self.model.adapt_test(frame)
                    sf = None if local_mp else self._sharded_view(adapted)
                    if sf is not None:
                        sharded_entries.append((i, frame, n, dest,
                                                with_metrics, sf))
                    else:
                        host_entries.append((i, frame, adapted, n, dest,
                                             with_metrics))
                    continue
                n_dispatches += nd
                sharded_frame.note_packed(n)
                raw = self.model._margin_to_raw(
                    self._lift_entry_margins(mg, n, cap.padded))
                with tracing.span("fetch", rows=n, path="pipeline"):
                    pred = self.model._raw_to_frame(raw, n, key=dest)
                    pred.install()
                    mm = self.model._make_metrics(frame, raw) \
                        if with_metrics else None
                results[i] = (pred, mm)
        if sharded_entries:
            from h2o3_tpu.core import sharded_frame

            margins, nd = self._margins_sharded_batch(
                [(sf, n) for _i, _f, n, _d, _w, sf in sharded_entries])
            n_dispatches += nd
            off = 0
            for i, frame, n, dest, with_metrics, sf in sharded_entries:
                mg = margins[off: off + n]
                off += n
                sharded_frame.note_packed(n)
                raw = self.model._margin_to_raw(
                    self._lift_entry_margins(mg, n, sf.padded_rows))
                # result assembly is where this path first blocks on the
                # device (frame install / metrics read host values) — the
                # "fetch" phase of the request's span tree. No sync is
                # ADDED: these calls block with or without tracing.
                with tracing.span("fetch", rows=n, path="sharded"):
                    pred = self.model._raw_to_frame(raw, n, key=dest)
                    pred.install()
                    mm = self.model._make_metrics(frame, raw) \
                        if with_metrics else None
                results[i] = (pred, mm)
        if host_entries:
            X = np.concatenate([self._features(a, n)
                                for _, _, a, n, _, _ in host_entries])
            # the host path coalesces into one margin dispatch per bucket
            # chunk of the concatenated rows (the pre-PR-7 batching);
            # _margin_x reports what actually ran
            host_disp: list = []
            margins = self._margin_x(X, local=local_mp,
                                     dispatched=host_disp)
            n_dispatches += len(host_disp)
            off = 0
            for i, frame, _a, n, dest, with_metrics in host_entries:
                raw = self._raw_for_slice(margins[off: off + n], n,
                                          local=local_mp)
                off += n
                pred = self.model._raw_to_frame(raw, n, key=dest)
                pred.install()
                mm = self.model._make_metrics(frame, raw) if with_metrics \
                    else None
                results[i] = (pred, mm)
        total_rows = sum(frame.nrows for frame, _, _ in entries)
        ms = (time.perf_counter() - t0) * 1000
        self.stats.record_batch(len(entries), total_rows, ms,
                                dispatches=n_dispatches)
        from h2o3_tpu.obs import metrics as obs_metrics
        from h2o3_tpu.utils import timeline

        obs_metrics.observe("h2o3_score_flush_requests",
                            float(len(entries)))
        timeline.record("scoring", str(self.model.key), ms=ms,
                        requests=len(entries), rows=total_rows,
                        dispatches=n_dispatches,
                        compiles=self.traversal_compiles)
        return results

    def predict(self, frame, key: Optional[str] = None):
        """Single-request convenience (no micro-batching, no oplog)."""
        return self.predict_batch([(frame, key, False)])[0][0]


# ---------------------------------------------------------------------------
# session registry (bounded; a retrain under the same key gets a fresh
# session because the CompressedForest identity changes)
# ---------------------------------------------------------------------------

_REG_LOCK = threading.Lock()
_REGISTRY: "collections.OrderedDict[tuple, ScoringSession]" = \
    collections.OrderedDict()
_REGISTRY_CAP = 16


def session_for(model) -> ScoringSession:
    key = (str(model.key), id(model.forest))
    with _REG_LOCK:
        sess = _REGISTRY.get(key)
        if sess is not None:
            _REGISTRY.move_to_end(key)
            return sess
    sess = ScoringSession(model)
    with _REG_LOCK:
        cur = _REGISTRY.setdefault(key, sess)
        _REGISTRY.move_to_end(key)
        while len(_REGISTRY) > _REGISTRY_CAP:
            _REGISTRY.popitem(last=False)
        return cur


def purge(model_key: Optional[str] = None) -> None:
    """Drop sessions for a deleted model (all sessions when key is None)."""
    with _REG_LOCK:
        if model_key is None:
            _REGISTRY.clear()
            return
        for k in [k for k in _REGISTRY if k[0] == str(model_key)]:
            del _REGISTRY[k]


def metrics_snapshot() -> List[Dict[str, Any]]:
    with _REG_LOCK:
        items = [(k[0], s) for k, s in _REGISTRY.items()]
    out = []
    for mk, sess in items:
        entry = {"model": mk, "buckets": list(sess.buckets),
                 "traversal_compiles": sess.traversal_compiles,
                 "fused_compiles": sess.fused_compiles,
                 "compile_cache_hits": sess.cache_hits}
        entry.update(sess.stats.snapshot())
        out.append(entry)
    return out


# ---------------------------------------------------------------------------
# micro-batcher
# ---------------------------------------------------------------------------

class _Pending:
    __slots__ = ("frame", "dest", "with_metrics", "event", "pred", "mm",
                 "error", "promoted", "trace_ctx", "enq_ms")

    def __init__(self, frame, dest, with_metrics):
        self.frame = frame
        self.dest = dest
        self.with_metrics = with_metrics
        self.event = threading.Event()
        self.pred = None
        self.mm = None
        self.error: Optional[BaseException] = None
        self.promoted = False      # woken to take over flush leadership
        # submitter's trace context + enqueue wall time: the flush leader
        # (a different thread) records each request's queue-wait span into
        # ITS trace, and adopts the lead context for the batch phases
        self.trace_ctx = tracing.context()
        self.enq_ms = time.time() * 1000.0


def execute_batch(model, entries: List[Tuple[Any, Optional[str], bool]],
                  local_only: bool = False):
    """Run one coalesced batch (shared by the coordinator's flush and the
    follower's oplog replay, so both sides execute the identical device
    program sequence). `local_only` is the degraded-cloud serving mode:
    no cross-process program, coordinator-addressable data only."""
    return session_for(model).predict_batch(entries, local_only=local_only)


class ScoreBatcher:
    """Coalesces concurrent scoring requests per model key.

    The first request for a model becomes the flush leader: it sleeps the
    batch window, drains everything queued for that model, broadcasts ONE
    'score_batch' oplog op, and dispatches the whole batch inside the
    op's execution turn. Followers of the request (other handler threads)
    block on their entry's event and get their exact slice back. Per-model
    queues mean requests against different models proceed independently."""

    def __init__(self):
        self._lock = threading.Lock()
        self._queues: Dict[str, List[_Pending]] = {}
        self._leaders: set = set()

    def submit(self, model, frame, dest: Optional[str] = None,
               with_metrics: bool = False, timeout_s: float = 600.0):
        mk = str(model.key)
        ent = _Pending(frame, dest, with_metrics)
        with self._lock:
            self._queues.setdefault(mk, []).append(ent)
            lead = mk not in self._leaders
            if lead:
                self._leaders.add(mk)
        if lead:
            self._lead(model, mk)
        else:
            if not ent.event.wait(timeout=timeout_s):
                # withdraw BEFORE erroring: a still-queued entry must not
                # be scored later (its client already got the failure) —
                # if it is mid-flush, give that dispatch a grace period
                with self._lock:
                    q = self._queues.get(mk)
                    if q and ent in q:
                        q.remove(ent)
                        if ent.promoted:
                            # leadership was handed to us in the same
                            # instant we gave up — pass it on, don't let
                            # the queue stall behind a departed leader
                            if q:
                                q[0].promoted = True
                                q[0].event.set()
                            else:
                                self._queues.pop(mk, None)
                                self._leaders.discard(mk)
                        raise TimeoutError(
                            f"scoring batch for model {mk!r} did not "
                            f"flush within {timeout_s}s")
                if not ent.event.wait(timeout=60.0):
                    raise TimeoutError(
                        f"scoring dispatch for model {mk!r} wedged "
                        f"mid-batch")
            if ent.promoted and not (ent.pred or ent.error):
                # the previous leader finished its batch with us still
                # queued and handed leadership over: our flush (which
                # includes our own entry) runs on THIS thread
                self._lead(model, mk)
        if ent.error is not None:
            raise ent.error
        return ent.pred, ent.mm

    def _lead(self, model, mk: str) -> None:
        """Flush ONE batch (window sleep → drain → dispatch), then either
        release leadership or hand it to the first still-queued waiter —
        the leader's own request is never delayed past its batch, even
        under a sustained request stream."""
        try:
            w = _window_s()
            if w > 0:
                time.sleep(w)
            with self._lock:
                batch = self._queues.get(mk) or []
                self._queues[mk] = []
            if batch:
                self._flush(model, batch)
            with self._lock:
                rest = self._queues.get(mk)
                if rest:
                    # leadership stays marked; the promoted waiter's
                    # thread continues the flush loop
                    rest[0].promoted = True
                    rest[0].event.set()
                    return
                self._queues.pop(mk, None)
                self._leaders.discard(mk)
        except BaseException as ex:   # noqa: BLE001 — never strand waiters
            with self._lock:
                stranded = self._queues.pop(mk, [])
                self._leaders.discard(mk)
            for e in stranded:
                if e.error is None and not e.event.is_set():
                    e.error = ex
                    e.event.set()
            raise

    @staticmethod
    def _flush(model, batch: List[_Pending]) -> None:
        from h2o3_tpu.parallel import oplog, retry, supervisor

        # queue-wait: submit -> flush start, one span per request in that
        # request's OWN trace; the batch's shared phases (publish, pack,
        # dispatch, fetch) then run under the lead (oldest) context
        now_ms = time.time() * 1000.0
        for e in batch:
            tracing.record_span("queue_wait", e.trace_ctx, e.enq_ms, now_ms,
                                batched_with=len(batch) - 1)
        lead_ctx = next((e.trace_ctx for e in batch if e.trace_ctx), None)
        try:
            # broadcast ONE op for the whole batch; followers replay it
            # once. Existence/compat validation already happened
            # pre-broadcast in the REST handler, so coordinator and
            # follower fail symmetrically. The broadcast sits INSIDE the
            # try: a KV failure must error the waiters, not strand them.
            # A transiently-lost publish is retried with backoff (publish
            # rolled its sequence slot back, so the re-claim is gapless);
            # on a DEGRADED/FAILED cloud scoring skips the broadcast and
            # serves coordinator-locally — the one surface that stays up.
            with tracing.activate(lead_ctx):
                local_only = (oplog.active()
                              and supervisor.state() != supervisor.HEALTHY)
                op_seq = None
                if not local_only:
                    from h2o3_tpu.core.failure import CloudUnhealthyError

                    try:
                        op_seq = retry.retry_call(
                            oplog.broadcast, "score_batch", {
                                "model": str(model.key),
                                "requests": [{"frame": str(e.frame.key),
                                              "destination_frame": e.dest,
                                              "with_metrics":
                                              bool(e.with_metrics)}
                                             for e in batch]},
                            retry_on=(oplog.OplogPublishError,),
                            describe="score_batch broadcast")
                    except CloudUnhealthyError:
                        # the cloud degraded between the state snapshot and
                        # the broadcast's own fail-fast check: scoring is
                        # the surface that keeps serving — fall back to
                        # local
                        local_only = True
                if local_only:
                    # local serving installs prediction frames only in the
                    # COORDINATOR's DKV (no oplog record): follower key
                    # state is now behind, so the degraded verdict must
                    # never auto-recover — only a cloud restart re-syncs
                    supervisor.degrade(
                        "coordinator-local scoring served while degraded: "
                        "follower DKV state is behind; restart the cloud "
                        "to re-sync", hold_s=float("inf"))
                with oplog.turn(op_seq):
                    results = execute_batch(
                        model, [(e.frame, e.dest, e.with_metrics)
                                for e in batch],
                        local_only=local_only)
            for e, (pred, mm) in zip(batch, results):
                e.pred, e.mm = pred, mm
        except BaseException as ex:   # noqa: BLE001 — propagate per-request
            for e in batch:
                e.error = ex
        finally:
            for e in batch:
                e.event.set()


BATCHER = ScoreBatcher()


def score_request(model, frame, dest: Optional[str] = None,
                  with_metrics: bool = False):
    """Entry point for the REST layer: admission-controlled, coalescing,
    bucketed, oplog-mirrored scoring of one request. Returns
    (prediction_frame, metrics_or_None). Over the per-model concurrency
    limit requests queue (bounded); overflow raises AdmissionRejected,
    which the REST layer maps to 429/503 + Retry-After — heavy traffic
    degrades by queueing, not collapse.

    Every served request's latency feeds the per-model admission ring:
    the SLO-adaptive controller (``H2O_TPU_SCORE_SLO_MS``) derives the
    inflight limit from the observed p99 against the target, and the
    Retry-After hints from the observed drain rate."""
    from h2o3_tpu import admission
    from h2o3_tpu.obs import metrics as obs_metrics

    mk = str(model.key)
    t0 = time.perf_counter()
    with admission.CONTROLLER.slot(mk):
        t1 = time.perf_counter()
        out = BATCHER.submit(model, frame, dest, with_metrics)
        admission.CONTROLLER.note_latency(
            mk, (time.perf_counter() - t1) * 1000.0)
    obs_metrics.observe("h2o3_score_request_seconds",
                        time.perf_counter() - t0, model=mk)
    return out
