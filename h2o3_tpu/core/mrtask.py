"""MRTask — the distributed compute harness.

Reference design: fork/join map/reduce over chunks with a binary-tree RPC
fan-out across nodes (water/MRTask.java:63; dfork :455, remote_compute :572,
compute2 :596, reduce3 :751) and user hooks map/reduce/setupLocal/postGlobal.

TPU-native design (SURVEY.md §7): a map over row shards is a
`shard_map`-decorated function on the mesh; the reduce is an XLA collective
(`psum`/`pmax`/...) over ICI — the binary node tree AND the lock-free local
CAS reductions both collapse into one compiler-scheduled all-reduce.
setupLocal/postGlobal become host code around the jitted region.

Two entry points:
- `map_reduce(fn, cols)`: fn(shard_arrays...) -> pytree of partials, psum'd
  across shards. Equivalent of `new MRTask(){map/reduce}.doAll(frame)`.
- `map_chunks(fn, cols)`: fn(shard_arrays...) -> same-length output
  shard(s); equivalent of doAll(outputTypes, frame) producing NewChunks
  (water/MRTask.java:224 outputFrame).

Both run inside one jit: XLA fuses the per-shard body and inserts the
collectives.
"""

from __future__ import annotations

from h2o3_tpu.compat import shard_map as _compat_shard_map
import functools
import time
from typing import Callable, Optional, Sequence

import jax
from jax.sharding import PartitionSpec as P

from h2o3_tpu.core.frame import Column


def _mesh():
    from h2o3_tpu.core.runtime import cluster

    return cluster().mesh


@functools.lru_cache(maxsize=512)
def _build_map_reduce(fn, n_in: int, mesh):
    @jax.jit
    def run(*arrays):
        def body(*chunks):
            partial = fn(*chunks)
            return jax.tree.map(lambda x: jax.lax.psum(x, "rows"), partial)

        shard = _compat_shard_map(
            body, mesh=mesh,
            in_specs=tuple(P("rows") for _ in range(n_in)),
            out_specs=P(),
        )
        return shard(*arrays)

    return run


def map_reduce(fn: Callable, cols: Sequence[Column]):
    """doAll-style map/reduce: fn sees this shard's slice of each column and
    returns a pytree of reduction partials; result is the psum over shards.
    Under H2O_TPU_PROFILE=1, per-phase timings land in the TimeLine ring
    (MRTask.profile analog; the sync phase forces a device wait)."""
    from h2o3_tpu.core.failure import faultpoint
    from h2o3_tpu.utils import timeline

    faultpoint("mrtask.map_reduce")     # chaos hook (core/failure.py)
    arrays = tuple(c.data for c in cols)
    if not timeline.profiling_enabled():
        return _build_map_reduce(fn, len(arrays), _mesh())(*arrays)
    prof = timeline.TaskProfile(getattr(fn, "__name__", "map_reduce"))
    t0 = time.perf_counter()
    run = _build_map_reduce(fn, len(arrays), _mesh())
    t1 = time.perf_counter()
    out = run(*arrays)
    t2 = time.perf_counter()
    jax.block_until_ready(out)
    t3 = time.perf_counter()
    prof.build_ms = (t1 - t0) * 1000
    prof.run_ms = (t2 - t1) * 1000
    prof.sync_ms = (t3 - t2) * 1000
    prof.emit()
    return out


@functools.lru_cache(maxsize=512)
def _build_map_chunks(fn, n_in: int, n_out: int, mesh):
    @jax.jit
    def run(*arrays):
        shard = _compat_shard_map(
            fn, mesh=mesh,
            in_specs=tuple(P("rows") for _ in range(n_in)),
            out_specs=tuple(P("rows") for _ in range(n_out)) if n_out > 1 else P("rows"),
        )
        return shard(*arrays)

    return run


def map_chunks(fn: Callable, cols: Sequence[Column], n_out: int = 1):
    """doAll(newtypes)-style: shard-local transform producing new row-aligned
    output arrays (the NewChunk path, MRTask.java:224-249)."""
    arrays = tuple(c.data for c in cols)
    return _build_map_chunks(fn, len(arrays), n_out, _mesh())(*arrays)


def new_column(fn: Callable, cols: Sequence[Column], ctype: Optional[str] = None) -> Column:
    """Build one output Column from input columns via a shard-local fn."""
    out = map_chunks(fn, cols, n_out=1)
    c0 = cols[0]
    return Column.from_device(out, ctype or c0.ctype, c0.nrows)


class LocalMR:
    """Node-local parallel loop (water/LocalMR.java). On TPU the analog is a
    vmapped/fused jit body; provided for API parity."""

    @staticmethod
    def run(fn: Callable, xs):
        return jax.vmap(fn)(xs)
