"""Cleaner — HBM pressure relief by LRU-evicting cold columns to host RAM.

Reference: water/Cleaner.java:12 — a background thread watching heap
pressure that ages and swaps cold Chunks to the ice root, with Vec access
faulting them back in.

TPU mapping: the scarce resource is HBM, not JVM heap. Every Column.data
access stamps a monotonic LRU clock; sweep() walks DKV frames coldest-first
and calls Column.evict() (device -> host numpy) until the requested bytes
are freed. Access after eviction faults the column back in through the
normal put_rows sharding path. A background thread mode watches the
device's own memory gauges when the backend exposes them."""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

_CLOCK = 0
# serializes evict vs fault-in swaps (NOT the hot read path)
SWAP_LOCK = threading.Lock()


def tick() -> int:
    """Monotonic-enough LRU stamp. Deliberately unlocked: this sits on the
    hottest read path (every Column.data access); the GIL makes the
    increment benign and approximate ordering is all an LRU needs."""
    global _CLOCK
    _CLOCK += 1
    return _CLOCK


def _all_columns():
    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.core.frame import Frame

    out: List[Tuple[int, object]] = []
    for k in list(DKV.keys()):
        fr = DKV.get(k)
        if isinstance(fr, Frame):
            for name in fr.names:
                c = fr._cols[name]             # no .col() — don't touch LRU
                out.append((c._touch, c))
    return out


def device_bytes_in_use() -> int:
    return sum(c.device_nbytes for _, c in _all_columns())


def sweep(target_free_bytes: int) -> int:
    """Evict coldest columns until target_free_bytes are freed (or nothing
    evictable remains). Returns bytes actually freed."""
    freed = 0
    for _, c in sorted(_all_columns(), key=lambda tc: tc[0]):
        if freed >= target_free_bytes:
            break
        freed += c.evict()
    return freed


def evicted_count() -> int:
    return sum(1 for _, c in _all_columns() if c.is_evicted)


class Cleaner:
    """Background sweeper: keeps framework device residency under
    limit_bytes (the LRU swap loop of water/Cleaner.java run())."""

    def __init__(self, limit_bytes: int, interval_s: float = 5.0):
        self.limit = int(limit_bytes)
        self.interval = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Cleaner":
        def run():
            while not self._stop.wait(self.interval):
                used = device_bytes_in_use()
                if used > self.limit:
                    sweep(used - self.limit)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="h2o3-cleaner")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self.interval + 1)
