"""Jobs: async work units with progress/cancel, resident in DKV.

Reference: water/Job.java:23 (progress :184-203), polled by clients via
GET /3/Jobs/{id}. Same lifecycle here: CREATED -> RUNNING -> DONE/FAILED/
CANCELLED, with a progress fraction and message, running on a host thread
(the device work inside is async XLA dispatch anyway)."""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Optional

from h2o3_tpu.core.dkv import DKV, Key, Keyed


class JobCancelled(Exception):
    pass


class Job(Keyed):
    CREATED, RUNNING, DONE, FAILED, CANCELLED = "CREATED", "RUNNING", "DONE", "FAILED", "CANCELLED"

    def __init__(self, description: str = "", dest: Optional[str] = None):
        super().__init__(Key.make("Job"))
        self.description = description
        self.dest = dest  # key of the result object
        self.status = Job.CREATED
        self.progress = 0.0
        self.progress_msg = ""
        self.exception: Optional[str] = None
        # True when the cloud supervisor failed this job from outside
        # (dead follower / cloud FAILED) rather than the worker crashing:
        # such a job stays FAILED across a later cloud recovery — clients
        # resubmit against the recovered cloud, nothing auto-reruns
        self.failed_externally = False
        self.start_time = 0.0
        self.end_time = 0.0
        self._cancel_requested = False
        self._thread: Optional[threading.Thread] = None
        # serializes terminal-status writes: the worker thread's DONE and
        # the cloud supervisor's external FAILED must not interleave
        self._status_lock = threading.Lock()
        self.result: Any = None
        self.install()

    # -- driver side ------------------------------------------------------
    def start(self, fn: Callable[["Job"], Any], background: bool = True) -> "Job":
        """Run fn(job) (the Driver.computeImpl analog, hex/ModelBuilder.java:224)."""

        def run():
            with self._status_lock:
                if self.status == Job.FAILED:
                    # the supervisor failed this job while still CREATED
                    # (cloud died between submit and thread start): honor
                    # the verdict, never run work against a dead cloud
                    return
                self.status = Job.RUNNING
            self.start_time = time.time()
            try:
                self.result = fn(self)
                with self._status_lock:
                    if self.status == Job.FAILED:
                        # the supervisor declared this job dead (cloud
                        # FAILED) while in flight: keep that verdict and
                        # do NOT install the result — it was built
                        # against a diverged cloud
                        return
                    if self.dest and self.result is not None:
                        DKV.put(self.dest, self.result)
                    self.status = Job.DONE
                    self.progress = 1.0
            except JobCancelled:
                with self._status_lock:
                    if self.status != Job.FAILED:
                        self.status = Job.CANCELLED
            except Exception:
                with self._status_lock:
                    if self.status != Job.FAILED:
                        # a supervisor verdict (remote traceback) already
                        # landed: keep it — the worker's own exception is
                        # a downstream symptom of the same cloud failure
                        self.exception = traceback.format_exc()
                        self.status = Job.FAILED
            finally:
                self.end_time = time.time()

        if background:
            self._thread = threading.Thread(target=run, daemon=True)
            self._thread.start()
        else:
            run()
        return self

    def update(self, progress: float, msg: str = "") -> None:
        """Progress tick; raises if a cancel was requested (cooperative)."""
        if self._cancel_requested:
            raise JobCancelled()
        self.progress = float(progress)
        if msg:
            self.progress_msg = msg

    def fail(self, exception_text: str) -> None:
        """Mark FAILED from OUTSIDE the worker thread (cloud supervisor,
        degraded mode): the worker may be wedged inside a dead collective
        and never unwind to record its own failure. No-op once terminal;
        the status lock keeps a worker unwinding at the same instant from
        overwriting the verdict with DONE."""
        with self._status_lock:
            if not self.is_running:
                return
            self.exception = exception_text
            self.failed_externally = True
            self.status = Job.FAILED
            self.end_time = time.time()

    # -- client side ------------------------------------------------------
    def cancel(self) -> None:
        self._cancel_requested = True

    def join(self, timeout: Optional[float] = None) -> "Job":
        if self._thread is not None:
            self._thread.join(timeout)
        if self.status == Job.FAILED:
            raise RuntimeError(f"Job {self.key} failed:\n{self.exception}")
        return self

    @property
    def is_running(self) -> bool:
        return self.status in (Job.CREATED, Job.RUNNING)

    def to_dict(self) -> dict:
        return {
            "key": str(self.key),
            "description": self.description,
            "status": self.status,
            "progress": self.progress,
            "progress_msg": self.progress_msg,
            "dest": self.dest,
            "exception": self.exception,
            "failed_externally": self.failed_externally,
            "start_time": self.start_time,
            "end_time": self.end_time,
        }
