"""Jobs: async work units with progress/cancel, resident in DKV.

Reference: water/Job.java:23 (progress :184-203), polled by clients via
GET /3/Jobs/{id}. Same lifecycle here: CREATED -> RUNNING -> DONE/FAILED/
CANCELLED, with a progress fraction and message, running on a host thread
(the device work inside is async XLA dispatch anyway).

Crash survivability (hex/Model._checkpoint spirit): a job the cloud
supervisor failed from OUTSIDE (``failed_externally``) is not necessarily
dead — when its trainer persisted durable per-iteration progress
(parallel/ckpt.py job-progress store), the recovery watchdog re-dispatches
it through the RESUMING state: FAILED -> RESUMING -> RUNNING -> DONE, with
``attempt`` counting the dispatches and ``resumed_from_iteration`` naming
where training picked back up (both on GET /3/Jobs). Jobs also survive
control-plane checkpoints: pickling drops the live thread and lock, so a
standby coordinator restores the job METADATA and the watchdog rebuilds
the rest from the progress file.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Optional

from h2o3_tpu.core.dkv import DKV, Key, Keyed


class JobCancelled(Exception):
    pass


class Job(Keyed):
    CREATED, RUNNING, DONE, FAILED, CANCELLED = "CREATED", "RUNNING", "DONE", "FAILED", "CANCELLED"
    # externally-failed job being re-dispatched from durable progress
    RESUMING = "RESUMING"

    def __init__(self, description: str = "", dest: Optional[str] = None):
        super().__init__(Key.make("Job"))
        self.description = description
        self.dest = dest  # key of the result object
        self.status = Job.CREATED
        self.progress = 0.0
        self.progress_msg = ""
        self.exception: Optional[str] = None
        # True when the cloud supervisor failed this job from outside
        # (dead follower / cloud FAILED) rather than the worker crashing:
        # such a job stays FAILED across a later cloud recovery UNLESS it
        # persisted durable training progress — then the watchdog resumes
        # it (restart() below); everything else is resubmitted by clients
        self.failed_externally = False
        # dispatch count (1 = original submit) and, on a resume, the
        # iteration training continued from — both on GET /3/Jobs
        self.attempt = 1
        self.resumed_from_iteration: Optional[int] = None
        # re-dispatch recipe (algo, wire params, frame keys, response,
        # destination) attached by the REST train handler when durable
        # progress is enabled; JSON-only so it survives pickling
        self.resume_spec: Optional[dict] = None
        self.start_time = 0.0
        self.end_time = 0.0
        self._cancel_requested = False
        self._thread: Optional[threading.Thread] = None
        # serializes terminal-status writes: the worker thread's DONE and
        # the cloud supervisor's external FAILED must not interleave
        self._status_lock = threading.Lock()
        self.result: Any = None
        self.install()

    # -- control-plane checkpoint survival --------------------------------
    # a Job rides the DKV, so it is pickled into oplog checkpoints; the
    # live thread and lock are process-local and must not sink the whole
    # per-key snapshot (they used to — jobs landed in the 'skipped' list
    # and a standby coordinator lost every job's metadata)
    def __getstate__(self):
        d = dict(self.__dict__)
        d["_thread"] = None
        d.pop("_status_lock", None)
        d["result"] = None          # results live under their own DKV key
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._thread = None
        self._status_lock = threading.Lock()
        if self.status in (Job.CREATED, Job.RUNNING, Job.RESUMING):
            # an unpickled job has NO worker thread by construction: it was
            # in flight when the snapshot was taken and that work died with
            # its process. Mark it externally failed so it either resumes
            # (durable progress) or reports honestly — a restored RUNNING
            # job with no thread would otherwise stay RUNNING forever.
            self.status = Job.FAILED
            self.failed_externally = True
            self.end_time = self.end_time or time.time()
            self.exception = self.exception or (
                "job was in flight when its process died; restored from a "
                "control-plane checkpoint (the recovery watchdog resumes "
                "it if durable training progress exists)")

    # -- driver side ------------------------------------------------------
    def start(self, fn: Callable[["Job"], Any], background: bool = True) -> "Job":
        """Run fn(job) (the Driver.computeImpl analog, hex/ModelBuilder.java:224)."""
        # dispatch generation: restart() bumps `attempt`, so a STALE worker
        # thread from a pre-restart dispatch (e.g. one that was wedged in a
        # dead collective when the supervisor failed the job) can never
        # write this job's verdict or result once a resume is in flight
        gen = self.attempt

        def run():
            with self._status_lock:
                if self.status == Job.FAILED or self.attempt != gen:
                    # the supervisor failed this job while still CREATED
                    # (cloud died between submit and thread start): honor
                    # the verdict, never run work against a dead cloud
                    return
                self.status = Job.RUNNING
            self.start_time = time.time()
            try:
                result = fn(self)
                with self._status_lock:
                    if self.status == Job.FAILED or self.attempt != gen:
                        # the supervisor declared this job dead (cloud
                        # FAILED) while in flight: keep that verdict and
                        # do NOT install the result — it was built
                        # against a diverged cloud
                        return
                    self.result = result
                    if self.dest and result is not None:
                        DKV.put(self.dest, result)
                    self.status = Job.DONE
                    self.progress = 1.0
                    # a completed resume supersedes the old verdict
                    self.failed_externally = False
            except JobCancelled:
                with self._status_lock:
                    if self.status != Job.FAILED and self.attempt == gen:
                        self.status = Job.CANCELLED
            except Exception:
                with self._status_lock:
                    if self.status != Job.FAILED and self.attempt == gen:
                        # a supervisor verdict (remote traceback) already
                        # landed: keep it — the worker's own exception is
                        # a downstream symptom of the same cloud failure
                        self.exception = traceback.format_exc()
                        self.status = Job.FAILED
            finally:
                if self.attempt == gen:
                    self.end_time = time.time()

        if background:
            self._thread = threading.Thread(target=run, daemon=True)
            self._thread.start()
        else:
            run()
        return self

    def update(self, progress: float, msg: str = "") -> None:
        """Progress tick; raises if a cancel was requested (cooperative)."""
        if self._cancel_requested:
            raise JobCancelled()
        self.progress = float(progress)
        if msg:
            self.progress_msg = msg

    def fail(self, exception_text: str) -> None:
        """Mark FAILED from OUTSIDE the worker thread (cloud supervisor,
        degraded mode): the worker may be wedged inside a dead collective
        and never unwind to record its own failure. No-op once terminal;
        the status lock keeps a worker unwinding at the same instant from
        overwriting the verdict with DONE."""
        with self._status_lock:
            if not self.is_running:
                return
            self.exception = exception_text
            self.failed_externally = True
            self.status = Job.FAILED
            self.end_time = time.time()

    # -- locked terminal transitions for SYNCHRONOUS drivers --------------
    # ModelBuilder.train() runs without Job.start's wrapper; these keep its
    # status writes under the same lock so its DONE can never land on top
    # of a supervisor's external FAILED (the fail()/completion race)
    def begin(self) -> bool:
        """CREATED/RESUMING -> RUNNING; False when the supervisor already
        failed the job (the caller must not run work against a dead cloud)."""
        with self._status_lock:
            if self.status == Job.FAILED:
                return False
            self.status = Job.RUNNING
            self.start_time = time.time()
            return True

    def complete(self) -> bool:
        """RUNNING -> DONE under the status lock; False (verdict kept) when
        an external FAILED already landed."""
        with self._status_lock:
            if self.status == Job.FAILED:
                return False
            self.status = Job.DONE
            self.progress = 1.0
            self.failed_externally = False
            self.end_time = time.time()
            return True

    def fail_local(self, exception_text: str) -> None:
        """Worker-side failure under the status lock; an earlier external
        verdict (with the remote traceback) is kept."""
        with self._status_lock:
            if self.status != Job.FAILED:
                self.exception = exception_text
                self.status = Job.FAILED
            self.end_time = time.time()

    def restart(self, resumed_from_iteration: Optional[int] = None) -> bool:
        """FAILED(externally) -> RESUMING for a re-dispatch from durable
        progress. Atomic under the status lock so two recovery passes can
        never double-dispatch one job; False when the job is not an
        externally-failed candidate."""
        with self._status_lock:
            if self.status != Job.FAILED or not self.failed_externally:
                return False
            self.status = Job.RESUMING
            self.attempt += 1
            self.failed_externally = False
            self.exception = None
            self.end_time = 0.0
            if resumed_from_iteration is not None:
                self.resumed_from_iteration = int(resumed_from_iteration)
            return True

    # -- client side ------------------------------------------------------
    def cancel(self) -> None:
        self._cancel_requested = True

    def join(self, timeout: Optional[float] = None) -> "Job":
        if self._thread is not None:
            self._thread.join(timeout)
        if self.status == Job.FAILED:
            raise RuntimeError(f"Job {self.key} failed:\n{self.exception}")
        return self

    @property
    def is_running(self) -> bool:
        return self.status in (Job.CREATED, Job.RUNNING, Job.RESUMING)

    def to_dict(self) -> dict:
        return {
            "key": str(self.key),
            "description": self.description,
            "status": self.status,
            "progress": self.progress,
            "progress_msg": self.progress_msg,
            "dest": self.dest,
            "exception": self.exception,
            "failed_externally": self.failed_externally,
            "attempt": self.attempt,
            "resumed_from_iteration": self.resumed_from_iteration,
            "start_time": self.start_time,
            "end_time": self.end_time,
        }
