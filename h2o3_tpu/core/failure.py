"""Failure detection + fault injection.

Reference: water/HeartBeatThread.java:16 — every node gossips a heartbeat;
peers that miss enough beats are declared dead and the cloud locks/fails
jobs against them. Fault injection in the reference lives in the test tree
(water/runner chaos flags) to exercise those paths.

TPU mapping: process liveness is ALREADY policed by the JAX coordination
service (a dead process fails collectives for everyone — there is no
half-alive cloud the way a UDP mesh allows). What this module adds:
- a heartbeat table over the coordination KV so OBSERVABILITY can show
  per-process liveness before a collective trips (`heartbeat()` /
  `cluster_health()`), surfaced in /3/Cloud's node listing;
- deterministic fault injection (`inject`, `faultpoint`) so tests can
  drive the error paths (Job FAILED propagation, per-segment capture,
  AutoML keep-going) without a real dead chip."""

from __future__ import annotations

import contextlib
import json
import time
from typing import Dict, List, Optional

_HB_PREFIX = "h2o3/heartbeat/"
HEARTBEAT_STALE_S = 30.0

# fault injection registry: name -> remaining trigger count
_FAULTS: Dict[str, int] = {}

# this process's incarnation number: bumped by rejoin() so a restarted
# follower's beats/acks are distinguishable from its dead predecessor's
# (water/H2ONode.java's _heartbeat "cloud hash" freshness analog)
_INCARNATION = 0


class CloudUnhealthyError(RuntimeError):
    """The cloud cannot complete multi-process work right now: a follower
    crashed mid-replay (its traceback rides along), stopped acknowledging
    ops, or went heartbeat-stale. The REST layer maps this to HTTP 503;
    the supervisor marks in-flight jobs FAILED with the same message."""

    def __init__(self, msg: str, remote_trace: str = ""):
        if remote_trace:
            msg = f"{msg}\n--- remote traceback ---\n{remote_trace}"
        super().__init__(msg)
        self.remote_trace = remote_trace


class ShardUnavailableError(CloudUnhealthyError):
    """Degraded-mode local scoring needs device shards homed on a dead or
    unreachable peer. Carries the owning process indices so the operator
    knows WHICH process to restart; the REST layer maps it to HTTP 503
    with the remediation hint embedded."""

    def __init__(self, what: str, owners: Optional[List[int]] = None):
        self.owners = sorted(owners or [])
        owner_s = (f"process(es) {self.owners}" if self.owners
                   else "a non-coordinator process")
        super().__init__(
            f"{what}: shards are homed on {owner_s}, which this degraded "
            "cloud cannot reach. Remediation: restart the dead process and "
            "let it rejoin() (FAILED -> RECOVERING -> HEALTHY), or restart "
            "the cloud and re-import the frame")


def heartbeat_stale_s() -> float:
    """Staleness threshold: beats older than this mark a process dead
    (env ``H2O_TPU_HEARTBEAT_STALE_S``, default 30 s)."""
    from h2o3_tpu.parallel.retry import env_float

    return env_float("H2O_TPU_HEARTBEAT_STALE_S", HEARTBEAT_STALE_S)


def election_grace_s() -> float:
    """How long past heartbeat-staleness the coordinator must stay silent
    before a standby follower may assume coordination
    (env ``H2O_TPU_ELECTION_GRACE_S``, default 2x the staleness window —
    an election is far more disruptive than a degrade, so the bar is
    higher)."""
    from h2o3_tpu.parallel.retry import env_float

    return env_float("H2O_TPU_ELECTION_GRACE_S", 2.0 * heartbeat_stale_s())


def incarnation() -> int:
    return _INCARNATION


def set_incarnation(inc: int) -> None:
    global _INCARNATION
    _INCARNATION = int(inc)


def bump_incarnation() -> int:
    """New life for this process (rejoin after a crash/restart): beats and
    acks from here on carry the fresh incarnation so the coordinator can
    reject anything the dead predecessor left behind."""
    global _INCARNATION
    _INCARNATION += 1
    return _INCARNATION


def heartbeat() -> bool:
    """Publish this process's liveness beat (HeartBeatThread analog).
    False in single-process mode (nothing to police)."""
    import jax

    from h2o3_tpu.parallel import distributed as D

    faultpoint("failure.heartbeat")
    return D.kv_put(_HB_PREFIX + str(jax.process_index()),
                    json.dumps({"ts": time.time(),
                                "proc": jax.process_index(),
                                "inc": _INCARNATION}))


def cluster_health(stale_after_s: Optional[float] = None) -> List[dict]:
    """Per-process liveness from the heartbeat table: one row per process
    that has ever beat, with age, incarnation and a healthy flag."""
    from h2o3_tpu.parallel import distributed as D

    if stale_after_s is None:
        stale_after_s = heartbeat_stale_s()
    now = time.time()
    out = []
    for key, val in D.kv_dir(_HB_PREFIX):
        try:
            rec = json.loads(val)
        except ValueError:
            continue
        age = now - float(rec.get("ts", 0))
        out.append({"process": rec.get("proc"), "age_s": round(age, 3),
                    "incarnation": int(rec.get("inc", 0)),
                    "healthy": age < stale_after_s})
    return sorted(out, key=lambda r: (r["process"] is None, r["process"]))


class HeartbeatThread:
    """Background beater (the reference runs one per node)."""

    def __init__(self, interval_s: float = 5.0):
        import threading

        self.interval = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HeartbeatThread":
        import threading

        def beat_once():
            try:
                heartbeat()
            except Exception:   # noqa: BLE001 — a transient coordination
                pass            # hiccup must not kill the beater for good

        def run():
            while not self._stop.wait(self.interval):
                beat_once()

        beat_once()
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="h2o3-heartbeat")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()


# ---------------------------------------------------------------------------
# fault injection (test-only chaos hooks)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def inject(name: str, times: int = 1):
    """Arm the named fault point for `times` triggers within the block."""
    _FAULTS[name] = int(times)
    try:
        yield
    finally:
        _FAULTS.pop(name, None)


class InjectedFault(RuntimeError):
    pass


def faultpoint(name: str) -> None:
    """Raise InjectedFault if the named fault is armed (cheap no-op dict
    lookup otherwise). Production code sprinkles these at the few places
    whose failure paths need deterministic coverage."""
    left = _FAULTS.get(name)
    if left:
        _FAULTS[name] = left - 1
        if _FAULTS[name] <= 0:
            _FAULTS.pop(name, None)
        raise InjectedFault(f"injected fault: {name}")
