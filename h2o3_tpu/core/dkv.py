"""DKV — the key/value control plane.

Reference design: H2O-3 stores ALL data (chunks, frames, models, jobs) in a
distributed hash map with keys homed by hash (water/DKV.java, water/Key.java:47,
water/Value.java) and atomic updates shipped to the home node
(water/Atomic.java).

TPU-native inversion (SURVEY.md §7): big data lives in HBM as sharded
jax.Arrays referenced BY Python objects; the DKV holds only metadata, frames
(which wrap device arrays), models and jobs. In a multi-host deployment every
process holds the same metadata (control-plane replication via the REST
leader); device data is sharded by XLA, not by key hash. Hence this store is
an in-process, thread-safe map with the same API verbs (get/put/remove) and
the same supporting cast: Scope (RAII key cleanup, water/Scope.java),
Lockable (read/write locks, water/Lockable.java) and atomic updates
(water/TAtomic.java)."""

from __future__ import annotations

import threading
import uuid
from typing import Any, Callable, Dict, Iterator, List, Optional


class Key(str):
    """A DKV key. H2O keys are ≤512-byte strings with embedded homing bytes
    (water/Key.java:47); here a key is just a unique name — homing is the
    mesh sharding rule, not the key."""

    __slots__ = ()

    @staticmethod
    def make(prefix: str = "key") -> "Key":
        return Key(f"{prefix}_{uuid.uuid4().hex[:12]}")


class _DKV:
    def __init__(self) -> None:
        self._store: Dict[str, Any] = {}
        self._lock = threading.RLock()
        self._rw: Dict[str, threading.RLock] = {}

    # H2O verbs: DKV.put / DKV.get / DKV.remove (water/DKV.java)
    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._store[str(key)] = value
            Scope._track(str(key))

    def get(self, key: str) -> Any:
        with self._lock:
            return self._store.get(str(key))

    def remove(self, key: str) -> None:
        with self._lock:
            self._store.pop(str(key), None)
            self._rw.pop(str(key), None)

    def contains(self, key: str) -> bool:
        with self._lock:
            return str(key) in self._store

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._store.keys())

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
            self._rw.clear()

    # -- cross-process control plane (water/DKV.java's distributed half) --
    # In a multi-process cloud, puts ANNOUNCE key metadata cloud-wide over
    # the coordination-service KV (parallel/distributed.py); small host
    # objects can opt into full payload replication so any process can
    # fetch_remote them. Device data never travels here — columns are
    # already globally-sharded jax.Arrays.
    _META_PREFIX = "h2o3/dkv/meta/"
    _BLOB_PREFIX = "h2o3/dkv/blob/"
    _MAX_BLOB = 8 * 1024 * 1024

    def publish(self, key: str, value: Any = None,
                replicate: bool = False) -> bool:
        """Announce a key cloud-wide; with replicate=True also ship the
        pickled payload (small host objects only). False in local mode."""
        import json as _json

        from h2o3_tpu.parallel import distributed as D

        blob_b64 = None
        if replicate and value is not None:
            # validate the payload BEFORE announcing the key — a meta entry
            # without its blob would be an unfetchable ghost cloud-wide
            import base64
            import pickle

            blob = pickle.dumps(value)
            if len(blob) > self._MAX_BLOB:
                raise ValueError(
                    f"object {key!r} is {len(blob)}B — too large for "
                    "control-plane replication (cap "
                    f"{self._MAX_BLOB}B); device data replicates via "
                    "sharded arrays, not the KV")
            blob_b64 = base64.b64encode(blob).decode()
        meta = {"type": type(value).__name__ if value is not None else "?",
                "proc": __import__("jax").process_index(),
                "replicated": blob_b64 is not None}
        if not D.kv_put(self._META_PREFIX + str(key), _json.dumps(meta)):
            return False
        if blob_b64 is not None:
            D.kv_put(self._BLOB_PREFIX + str(key), blob_b64)
        return True

    def global_keys(self) -> List[str]:
        """Cloud-wide announced keys merged with local ones."""
        from h2o3_tpu.parallel import distributed as D

        remote = [k[len(self._META_PREFIX):] if k.startswith(self._META_PREFIX)
                  else k
                  for k, _v in D.kv_dir(self._META_PREFIX)]
        return sorted(set(self.keys()) | set(remote))

    def fetch_remote(self, key: str, timeout_ms: int = 5000) -> Any:
        """Get a key from anywhere in the cloud: local store first, then the
        replicated control-plane payload (publish(..., replicate=True)).

        The blob read rides the shared backoff budget (water/RPC.java's
        resend schedule, parallel/retry.py) like kv_put/kv_get: a key whose
        metadata says it WAS replicated but whose blob read drops
        (transient coordination fault) is retried instead of failing the
        caller's job on the first blip — a recovery would have saved it
        anyway. Keys announced WITHOUT replication (the normal case for
        frames/models whose data lives on device) have no blob to find, so
        they return immediately instead of burning the backoff budget."""
        local = self.get(key)
        if local is not None:
            return local
        import json as _json

        from h2o3_tpu.parallel import distributed as D

        raw = D.kv_get(self._BLOB_PREFIX + str(key), timeout_ms)
        if raw is None:
            # only retry when the metadata says a blob SHOULD exist. (The
            # announcement check lives on the miss path only — the common
            # successful fetch stays one KV roundtrip.)
            meta_raw = D.kv_try_get(self._META_PREFIX + str(key))
            replicated = False
            if meta_raw is not None:
                try:
                    replicated = bool(_json.loads(meta_raw).get("replicated"))
                except (ValueError, TypeError):
                    replicated = False
            if replicated:
                # shared bounded retry budget + spill-retries counter with
                # the persist spill reloads (memory/stream.py): every read
                # standing between a dispatch and its data degrades
                # loudly, not behind its own bespoke loop
                from h2o3_tpu.memory import stream as _mstream

                raw = _mstream.bounded_remote_read(
                    lambda: D.kv_get(self._BLOB_PREFIX + str(key),
                                     timeout_ms),
                    what=f"DKV blob {key!r}")
        if raw is None:
            return None
        import base64

        # restricted unpickler: the blob came over the coordination KV —
        # another process (or whatever reached the KV) wrote it, so it is
        # untrusted input like any artifact (ISSUE-11 serialization
        # invariant); framework/numeric types only
        from h2o3_tpu.utils.unpickle import restricted_loads

        value = restricted_loads(base64.b64decode(raw), what="DKV blob")
        self.put(key, value)       # cache locally, like Value caching
        return value

    # -- checkpoint support (parallel/ckpt.py) ---------------------------
    def snapshot_control_plane(self) -> dict:
        """Serialize the control plane for an oplog checkpoint: every
        DKV-resident object that pickles (models, frames, metadata — a
        Job's live thread does not, and is listed in ``skipped``), plus
        the announced-key metadata and replicated blobs from the cloud
        KV. Values are pickled PER KEY so one unpicklable object cannot
        sink the whole checkpoint."""
        import pickle

        from h2o3_tpu.parallel import distributed as D

        objects: Dict[str, bytes] = {}
        skipped: List[str] = []
        with self._lock:
            items = list(self._store.items())
        for k, v in items:
            try:
                objects[k] = pickle.dumps(v)
            except Exception:   # noqa: BLE001 — per-key isolation
                skipped.append(k)
        kv: Dict[str, str] = {}
        for prefix in (self._META_PREFIX, self._BLOB_PREFIX):
            for kk, vv in D.kv_dir(prefix):
                kv[kk] = vv
        return {"objects": objects, "skipped": sorted(skipped), "kv": kv}

    def restore_control_plane(self, snap: dict, loads=None) -> List[str]:
        """Install a checkpoint snapshot into this process's store (rejoin
        / standby takeover). `loads` lets the caller supply its own
        restricted unpickler; the DEFAULT is the shared restricted loader
        — a snapshot blob came off shared storage and must never reach a
        raw unpickler (ISSUE-11 serialization invariant). Returns the
        keys restored; per-key failures are skipped (the object rebuilds
        from the oplog suffix or a re-import)."""
        from h2o3_tpu.parallel import distributed as D
        from h2o3_tpu.utils.unpickle import restricted_loads

        loads = loads or restricted_loads
        restored: List[str] = []
        for k, blob in (snap.get("objects") or {}).items():
            try:
                self.put(k, loads(blob))
                restored.append(k)
            except Exception:   # noqa: BLE001 — per-key isolation
                continue
        for kk, vv in (snap.get("kv") or {}).items():
            # put-if-absent: the live cloud kept publishing while this
            # process was down, so a key still present in the shared KV is
            # at least as new as the checkpoint's copy — overwriting it
            # would hand every OTHER process a stale blob (and their
            # fetch_remote caches never invalidate). Only resurrect keys
            # the KV actually lost.
            if D.kv_try_get(kk) is None:
                D.kv_put(kk, vv)
        return restored

    def atomic(self, key: str, fn: Callable[[Any], Any]) -> Any:
        """Compare-and-set style update on the stored value
        (water/TAtomic.java): fn runs under the store lock."""
        with self._lock:
            old = self._store.get(str(key))
            new = fn(old)
            self._store[str(key)] = new
            return new

    def write_lock(self, key: str) -> threading.RLock:
        """Per-key lock (water/Lockable.java write_lock)."""
        with self._lock:
            return self._rw.setdefault(str(key), threading.RLock())

    def unlock_all(self) -> int:
        """Drop every per-key lock object (water/api/UnlockTask: force-
        unlock all Lockables after a failed job). Returns count dropped."""
        with self._lock:
            n = len(self._rw)
            self._rw.clear()
            return n


DKV = _DKV()


def unlock_all() -> int:
    return DKV.unlock_all()


class Scope:
    """RAII key tracking (water/Scope.java): keys put while a scope is open
    are removed when it exits, unless untracked."""

    _stack: List[set] = []
    _slock = threading.RLock()

    def __init__(self) -> None:
        self._keys: set = set()

    def __enter__(self) -> "Scope":
        with Scope._slock:
            Scope._stack.append(self._keys)
        return self

    def __exit__(self, *exc) -> None:
        with Scope._slock:
            Scope._stack.remove(self._keys)
        for k in self._keys:
            DKV.remove(k)

    @classmethod
    def _track(cls, key: str) -> None:
        with cls._slock:
            if cls._stack:
                cls._stack[-1].add(key)

    def untrack(self, key: str) -> None:
        self._keys.discard(str(key))


class Keyed:
    """Base for DKV-resident objects (water/Keyed.java): has a _key, can
    install/remove itself."""

    def __init__(self, key: Optional[str] = None):
        self._key: Key = Key(key) if key else Key.make(type(self).__name__)

    @property
    def key(self) -> Key:
        return self._key

    def install(self) -> "Keyed":
        DKV.put(self._key, self)
        return self

    def delete(self) -> None:
        DKV.remove(self._key)
