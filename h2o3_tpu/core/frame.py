"""Columnar store: Frame / Column.

Reference design: Frame -> Vec -> Chunk with 19 compression codecs and
inflate-on-write (water/fvec/Frame.java:64, Vec.java:157, Chunk.java:113,
NewChunk.java:22), ragged ESPC row layout, lazily-computed RollupStats
(water/fvec/RollupStats.java:30).

TPU-native design (SURVEY.md §7):
- One dense device array per column, row-sharded over the mesh 'rows' axis
  (`NamedSharding(P('rows'))`) — chunk homing becomes the sharding rule.
- Static shapes: rows padded to a multiple of (shards * row_align); the pad
  sentinel doubles as the NA sentinel, so masked reductions skip both.
- NA encoding replaces the codec zoo + mask machinery: numeric = NaN,
  categorical/int = -1. XLA's fusion makes narrow-dtype compression moot in
  HBM terms for f32; categoricals are int32 codes with a host-side domain
  (strings NEVER go to device).
- Columns are immutable: Rapids assign becomes copy-on-write version chains
  instead of Chunk inflate-on-write (Chunk.java:427-451).
- RollupStats = one fused jitted reduction, cached on the (immutable) column.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from h2o3_tpu.core.dkv import DKV, Key, Keyed

# Column logical types (water/fvec/Vec.java:160 BAD/UUID/STR/NUM/CAT/TIME)
T_NUM = "real"
T_INT = "int"
T_CAT = "enum"
T_TIME = "time"
T_STR = "string"
T_UUID = "uuid"
T_BAD = "bad"

NA_CAT = np.int32(-1)

# monotonically increasing Column identity tokens (Column.token); CPython's
# GIL makes next() atomic, so no lock is needed
_COLUMN_TOKENS = itertools.count(1)


def code_dtype(n_levels: int):
    """Narrowest signed code dtype that fits the domain plus the -1 NA
    sentinel (SURVEY §7 narrow-dtype design — the replacement for the
    reference's 19-codec chunk zoo, water/fvec/NewChunk.java compress()).
    Ops upcast at their boundaries (binning/DataInfo cast to int32/f32).
    The ONE categorical storage rule — shared by from_numpy and the
    chunked sharded ingest assembly (ingest/chunked.py)."""
    if n_levels <= 126:
        return np.int8
    if n_levels <= 32766:
        return np.int16
    return np.int32


_code_dtype = code_dtype        # historical internal name


def numeric_store_dtype(ctype: str):
    """The ONE numeric storage rule (shared by pad_numeric_host and the
    chunked sharded ingest assembly): T_NUM honors the cluster's bf16
    opt-in; T_TIME/T_INT stay f32."""
    return _numeric_dtype() if ctype == T_NUM else np.dtype(np.float32)


def pad_numeric_host(arr, n: int, padded: int, ctype: str) -> np.ndarray:
    """The one place deciding numeric padded-buffer layout (shared by
    Column.from_numpy and file-backed loaders): dtype per
    numeric_store_dtype; pad tail is NaN."""
    dt = numeric_store_dtype(ctype)
    buf = np.full(padded, np.nan, dt)
    buf[:n] = np.asarray(arr, np.float64).astype(dt)
    return buf


def _numeric_dtype():
    """Device storage dtype for numeric columns: float32 default, bfloat16
    when the cluster opts in (halves HBM per column; compute still runs in
    f32 via the MXU's preferred_element_type / DataInfo's casts)."""
    from h2o3_tpu.core.runtime import cluster

    name = getattr(cluster().args, "numeric_dtype", "float32")
    if name in ("bfloat16", "bf16"):
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(np.float32)


def _cluster():
    from h2o3_tpu.core.runtime import cluster

    return cluster()


class Column:
    """A distributed column (Vec analog, water/fvec/Vec.java:157).

    data: jax.Array (padded_rows,) row-sharded; float32 for real/int/time
    (NaN = NA/pad) or int32 for enum (-1 = NA/pad). For string/uuid columns
    the data lives host-side in `host_data` (object ndarray) and `data` is
    None — TPUs never touch strings (SURVEY.md §7).
    """

    __slots__ = ("_data", "_evicted", "_loader", "_touch", "ctype", "domain",
                 "host_data", "nrows", "_rollups", "_chunks", "_token")

    def __init__(self, data, ctype: str, nrows: int,
                 domain: Optional[List[str]] = None,
                 host_data: Optional[np.ndarray] = None):
        self._data = data
        self._evicted = None       # host copy (or loader) while out of HBM
        self._loader = None        # file-backed source (FileVec analog)
        self._touch = 0            # LRU clock (core/cleaner.py)
        self.ctype = ctype
        self.domain = domain
        self.host_data = host_data
        self.nrows = int(nrows)
        self._rollups = None
        # minted eagerly: a lazy check-then-set would race under the
        # threaded REST server and hand two threads different tokens
        self._token = next(_COLUMN_TOKENS)

    # -- HBM residency (water/Cleaner.java analog: cold columns swap to
    #    host RAM; access faults them back in) ----------------------------
    @property
    def data(self):
        from h2o3_tpu.core import cleaner

        d = self._data
        while d is None:
            # `_evicted` is either a host buffer (Cleaner swap-out) or a
            # CALLABLE loader (file-backed Vec, water/fvec/FileVec.java
            # analog). The possibly-slow load/decode runs OUTSIDE the swap
            # lock so concurrent fault-ins of other columns don't serialize
            # behind a disk read; the install happens under the lock only
            # if _evicted is still the SAME source we materialized (a
            # racing evict/fault-in cycle retries with the fresh state).
            src = self._evicted
            if src is None:
                d = self._data      # plain data-less column, or raced-in
                break
            buf = src() if callable(src) else src
            with cleaner.SWAP_LOCK:
                if self._data is None and self._evicted is src:
                    self._data = _cluster().put_rows(buf)
                    self._evicted = None
                d = self._data
        self._touch = cleaner.tick()
        # returning the local binding keeps this safe against an evict()
        # landing between the check and the return: the caller's reference
        # pins the device buffer it already obtained
        return d

    @staticmethod
    def file_backed(loader, ctype: str, nrows: int,
                    domain: Optional[List[str]] = None) -> "Column":
        """A column whose device buffer materializes lazily from `loader()`
        (must return the PADDED host buffer) on first data access."""
        c = Column(None, ctype, nrows, domain=domain)
        c._evicted = loader
        c._loader = loader      # evictions revert to the source
        return c

    @data.setter
    def data(self, v):
        from h2o3_tpu.core import cleaner

        # under SWAP_LOCK so a concurrent evict() can't capture the old
        # loader mid-rebind; clearing _loader makes the rebound buffer
        # authoritative (evict falls back to a host copy, not stale disk)
        with cleaner.SWAP_LOCK:
            self._data = v
            self._evicted = None
            self._loader = None

    def evict(self) -> int:
        """Swap the device buffer to host RAM; returns bytes freed. No-op
        for multi-process shardings (remote shards are not addressable
        here) and for host-resident string columns."""
        from h2o3_tpu.core import cleaner

        with cleaner.SWAP_LOCK:
            if self._data is None or \
                    not getattr(self._data, "is_fully_addressable", True):
                return 0
            freed = int(self._data.nbytes)
            # file-backed columns revert to their DISK source — eviction
            # must free host RAM too, not pin a padded copy of the file
            self._evicted = (self._loader if self._loader is not None
                             else np.asarray(self._data))
            self._data = None
            return freed

    @property
    def is_evicted(self) -> bool:
        return self._data is None and self._evicted is not None

    @property
    def device_nbytes(self) -> int:
        return int(self._data.nbytes) if self._data is not None else 0

    # -- constructors -----------------------------------------------------
    @staticmethod
    def from_numpy(arr: np.ndarray, ctype: Optional[str] = None,
                   domain: Optional[List[str]] = None) -> "Column":
        """Build a device column from host data; pads + shards + pins to HBM."""
        import jax
        import jax.numpy as jnp

        cl = _cluster()
        n = len(arr)
        padded = cl.pad_rows(n)

        if ctype is None:
            if arr.dtype.kind in "OUS":
                return Column._from_strings(arr)
            elif arr.dtype.kind in "fiub":
                ctype = T_INT if arr.dtype.kind in "iub" else T_NUM
            elif arr.dtype.kind == "M":
                ctype = T_TIME
            else:
                raise TypeError(f"unsupported dtype {arr.dtype}")

        if ctype == T_CAT:
            a = np.asarray(arr)
            if a.dtype.kind in "OUS":
                dom, codes = _intern_domain(a)
                domain = dom
            else:
                codes = (np.where(np.isnan(a.astype(np.float64)), NA_CAT,
                                  a.astype(np.float64)).astype(np.int32)
                         if a.dtype.kind == "f" else a.astype(np.int32))
            card = len(domain) if domain is not None \
                else int(max(codes.max(initial=0) + 1, 1))
            buf = np.full(padded, NA_CAT, _code_dtype(card))
            buf[:n] = codes
        elif ctype in (T_TIME, T_INT, T_NUM):
            # dtype rules live in pad_numeric_host: T_NUM may opt into bf16;
            # times (epoch-millis precision) and integer keys stay f32
            buf = pad_numeric_host(arr, n, padded, ctype)
        else:
            raise TypeError(f"cannot device-store ctype {ctype}")

        data = cl.put_rows(buf)
        host = None
        if ctype == T_TIME and np.asarray(arr).dtype.kind in "Mi":
            host = np.asarray(arr)  # exact epoch-millis kept host-side
        return Column(data, ctype, n, domain=domain, host_data=host)

    @staticmethod
    def _from_strings(arr: np.ndarray) -> "Column":
        a = np.asarray(arr, dtype=object)
        return Column(None, T_STR, len(a), host_data=a)

    @staticmethod
    def from_device(data, ctype: str, nrows: int,
                    domain: Optional[List[str]] = None) -> "Column":
        return Column(data, ctype, nrows, domain=domain)

    # -- identity ---------------------------------------------------------
    @property
    def token(self) -> int:
        """Process-unique stable identity for this Column. Unlike ``id()``
        it is never reused after GC, so it is safe as a dictionary key
        that may outlive the object (Rapids Session refcounts, fusion
        leaf dedup)."""
        return self._token

    # -- introspection ----------------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self.ctype in (T_NUM, T_INT)

    @property
    def is_categorical(self) -> bool:
        return self.ctype == T_CAT

    @property
    def is_string(self) -> bool:
        return self.ctype == T_STR

    @property
    def cardinality(self) -> int:
        return len(self.domain) if self.domain else 0

    @property
    def padded_rows(self) -> int:
        return int(self.data.shape[0]) if self.data is not None else len(self.host_data)

    def to_numpy(self) -> np.ndarray:
        """Gather the logical (unpadded) rows back to host. On a
        multi-process cloud the column spans non-addressable devices —
        allgather the shards so any process sees the full column (the
        reference's as_data_frame works from any node: water/Frame fetch
        over RPC; here it rides the jax.distributed transport)."""
        if self.data is None:
            return self.host_data[: self.nrows]
        data = self.data
        if not getattr(data, "is_fully_addressable", True):
            from jax.experimental import multihost_utils

            from h2o3_tpu.parallel import oplog

            if oplog.unmirrored_collective_risk():
                # a REST handler outside its op turn must not enter a
                # collective the follower will never join — fail fast with
                # the actionable error instead of deadlocking the mesh
                raise RuntimeError(
                    "host fetch of a multi-process frame from a REST "
                    "handler requires an oplog-mirrored op (followers "
                    "replay broadcast ops only)")
            data = multihost_utils.process_allgather(data, tiled=True)
        arr = np.asarray(data)[: self.nrows]
        return arr

    def values(self) -> np.ndarray:
        """Decode to user-facing values (enum codes -> labels)."""
        arr = self.to_numpy()
        if self.ctype == T_CAT and self.domain is not None:
            dom = np.asarray(self.domain, dtype=object)
            out = np.empty(len(arr), dtype=object)
            valid = arr >= 0
            out[valid] = dom[arr[valid]]
            out[~valid] = None
            return out
        return arr

    # -- rollups ----------------------------------------------------------
    @property
    def rollups(self):
        """Lazy fused min/max/mean/sigma/naCnt/nzCnt (RollupStats.java:30)."""
        if self._rollups is None:
            from h2o3_tpu.ops.rollups import compute_rollups

            self._rollups = compute_rollups(self)
        return self._rollups

    def min(self):
        return self.rollups.min

    def max(self):
        return self.rollups.max

    def mean(self):
        return self.rollups.mean

    def sigma(self):
        return self.rollups.sigma

    def na_count(self):
        return self.rollups.na_count

    # -- transforms (copy-on-write) --------------------------------------
    def with_data(self, data, ctype: Optional[str] = None,
                  domain: Optional[List[str]] = None) -> "Column":
        return Column(data, ctype or self.ctype, self.nrows,
                      domain=domain if domain is not None else self.domain)

    def valid_mask(self):
        """Device bool mask of valid (non-NA, non-pad) rows."""
        import jax.numpy as jnp

        if self.ctype == T_CAT:
            return self.data >= 0
        return ~jnp.isnan(self.data)


def _intern_domain(a: np.ndarray) -> Tuple[List[str], np.ndarray]:
    """Global categorical interning (water/parser/Categorical.java): string
    labels -> dense int codes, domain sorted lexicographically (H2O sorts
    domains, water/parser/ParseDataset.java:518 GatherCategoricalDomainsTask)."""
    mask_na = np.array([x is None or (isinstance(x, float) and math.isnan(x)) or x == "" for x in a])
    vals = np.asarray([("" if m else str(x)) for x, m in zip(a, mask_na)])
    dom = sorted(set(vals[~mask_na].tolist()))
    lookup = {v: i for i, v in enumerate(dom)}
    codes = np.array([NA_CAT if m else lookup[v] for v, m in zip(vals, mask_na)], np.int32)
    return dom, codes


class Frame(Keyed):
    """Named, ordered collection of equal-length Columns
    (water/fvec/Frame.java:64). Lockable via DKV per-key locks."""

    def __init__(self, columns: Optional[Dict[str, Column]] = None,
                 key: Optional[str] = None):
        super().__init__(key or Key.make("Frame"))
        self._names: List[str] = []
        self._cols: Dict[str, Column] = {}
        if columns:
            for name, col in columns.items():
                self.add(name, col)

    # -- structure --------------------------------------------------------
    @property
    def names(self) -> List[str]:
        return list(self._names)

    @property
    def columns(self) -> List[Column]:
        return [self._cols[n] for n in self._names]

    @property
    def ncols(self) -> int:
        return len(self._names)

    @property
    def nrows(self) -> int:
        return self._cols[self._names[0]].nrows if self._names else 0

    nrow = nrows  # h2o-py alias
    ncol = ncols

    @property
    def types(self) -> Dict[str, str]:
        return {n: self._cols[n].ctype for n in self._names}

    def col(self, name_or_idx: Union[str, int]) -> Column:
        if isinstance(name_or_idx, int):
            return self._cols[self._names[name_or_idx]]
        return self._cols[name_or_idx]

    def __getitem__(self, sel):
        if isinstance(sel, (str, int)):
            return self.col(sel)
        if isinstance(sel, (list, tuple)):
            return self.subframe(sel)
        raise TypeError(f"bad frame selector {sel!r}")

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def add(self, name: str, col: Column) -> "Frame":
        if self._names and col.nrows != self.nrows:
            raise ValueError(f"column {name!r} has {col.nrows} rows, frame has {self.nrows}")
        if name in self._cols:
            raise ValueError(f"duplicate column {name!r}")
        self._names.append(name)
        self._cols[name] = col
        return self

    def replace(self, name: str, col: Column) -> "Frame":
        """Copy-on-write column replacement (vs H2O inflate-on-write)."""
        if name not in self._cols:
            return self.add(name, col)
        if col.nrows != self.nrows:
            raise ValueError("row mismatch")
        self._cols[name] = col
        return self

    def swap_columns(self, mapping: Dict[str, Column]) -> "Frame":
        """Atomically swap EVERY column for a same-length replacement —
        the streaming-append path (ingest/chunked.append_csv) grows all
        columns to the new row count in one step, which replace()'s
        per-column row guard would reject mid-swap. The mapping must
        cover exactly the frame's columns and agree on one row count."""
        if set(mapping) != set(self._names):
            raise ValueError("swap_columns must cover exactly the frame's "
                             "columns")
        rows = {c.nrows for c in mapping.values()}
        if len(rows) > 1:
            raise ValueError(f"swap_columns row counts disagree: {rows}")
        # ONE reference rebind (GIL-atomic). A reader calling col() per
        # column MAY observe mixed generations across calls, which is
        # benign by the append invariant: the new columns preserve rows
        # [0, old_n) bitwise (cat codes renumber WITH their domain inside
        # one Column, so label semantics hold), and a reader can only
        # target the appended rows after reading the new nrows — i.e.
        # after this rebind is visible, when every col() already returns
        # the new generation (attribute reads are monotonic under the
        # GIL). Appends that grow the PADDED capacity may transiently
        # hand a mixed-layout column set to a packed scorer — a per-
        # request retryable layout miss, not corruption.
        self._cols = {nm: mapping[nm] for nm in self._names}
        return self

    def drop(self, name: str) -> "Frame":
        self._names.remove(name)
        self._cols.pop(name)
        return self

    def rename(self, old: str, new: str) -> "Frame":
        i = self._names.index(old)
        self._names[i] = new
        self._cols[new] = self._cols.pop(old)
        return self

    def subframe(self, names: Sequence[Union[str, int]], key: Optional[str] = None) -> "Frame":
        fr = Frame(key=key)
        for n in names:
            nm = self._names[n] if isinstance(n, int) else n
            fr.add(nm, self._cols[nm])
        return fr

    def cbind(self, other: "Frame") -> "Frame":
        fr = Frame()
        for n in self._names:
            fr.add(n, self._cols[n])
        for n in other._names:
            nm = n
            while nm in fr._cols:
                nm = nm + "0"  # H2O dedup suffix behavior
            fr.add(nm, other._cols[n])
        return fr

    # -- sharded data plane -----------------------------------------------
    def sharded_view(self, names: Optional[Sequence[str]] = None):
        """Row-sharded data-plane view (core/sharded_frame.ShardedFrame):
        named row axis + NamedSharding over this frame's device columns,
        or None when a named column has no device data (strings) or the
        layouts disagree. The fused scoring and tree-input paths pack
        through it so full columns are never staged on the coordinator."""
        from h2o3_tpu.core.sharded_frame import ShardedFrame

        return ShardedFrame.of(self, names)

    # -- materialization --------------------------------------------------
    def to_pandas(self):
        import pandas as pd

        # python string storage, scoped: pandas-3's pyarrow-backed string
        # construction has crashed (SIGSEGV) under the threaded REST server
        # in this environment; keep the workaround out of global state
        with pd.option_context("mode.string_storage", "python"):
            return pd.DataFrame({n: self._cols[n].values()
                                 for n in self._names})

    def to_numpy(self) -> np.ndarray:
        return np.column_stack([self._cols[n].to_numpy() for n in self._names])

    @staticmethod
    def from_numpy(arr: np.ndarray, names: Optional[Sequence[str]] = None,
                   key: Optional[str] = None) -> "Frame":
        arr = np.atleast_2d(arr)
        names = list(names) if names else [f"C{i+1}" for i in range(arr.shape[1])]
        fr = Frame(key=key)
        for i, n in enumerate(names):
            fr.add(n, Column.from_numpy(arr[:, i]))
        return fr

    @staticmethod
    def from_pandas(df, key: Optional[str] = None,
                    column_types: Optional[Dict[str, str]] = None) -> "Frame":
        fr = Frame(key=key)
        for n in df.columns:
            s = df[n]
            ctype = (column_types or {}).get(n)
            if ctype is None and (s.dtype.name == "category" or s.dtype.kind in "OUS"):
                # strings with low-ish cardinality -> enum, like ParseSetup guessing
                ctype = T_CAT
            fr.add(str(n), Column.from_numpy(s.to_numpy(), ctype=ctype))
        return fr

    # -- stats ------------------------------------------------------------
    def summary(self) -> Dict[str, dict]:
        out = {}
        for n in self._names:
            c = self._cols[n]
            if c.is_numeric or c.ctype == T_TIME:
                r = c.rollups
                out[n] = {"type": c.ctype, "min": r.min, "max": r.max,
                          "mean": r.mean, "sigma": r.sigma, "na_count": r.na_count}
            elif c.is_categorical:
                r = c.rollups
                out[n] = {"type": c.ctype, "cardinality": c.cardinality,
                          "na_count": r.na_count}
            else:
                out[n] = {"type": c.ctype}
        return out

    def __repr__(self) -> str:
        return f"<Frame {self._key} {self.nrows}x{self.ncols} {self._names[:8]}>"
