"""Sharded data plane: per-process feature packing over addressable shards.

Reference: H2O-3's entire engine is "map/reduce over chunks that live where
they are" (water/fvec/Chunk.java homing + water/MRTask.java local maps) —
no node ever pulls another node's chunks to build a task's input. The
TPU-native analog (ROADMAP open item 1, the recorded blocker of PRs 2/3/4):
columns are row-sharded jax.Arrays over the mesh's named ``rows`` axis, so
"chunk locality" is the ``NamedSharding`` rule — and every input-building
step (serving feature packing, tree-training bin matrices) must consume
those shards WHERE THEY ARE instead of round-tripping whole columns
through the coordinator host.

:class:`ShardedFrame` is that contract as a view over ``core/frame.Frame``:

- **named row axis** — ``ROW_AXIS`` ("rows"), the mesh axis every column's
  ``NamedSharding`` partitions; the same axis the fused scorers
  ``shard_map`` over (compressed.py ``_fused_score_sharded_fn``, routed
  through ``compat.shard_map`` for this container's jax).
- **pack_features** — the serving fast path's (bucket, F) float32 feature
  matrix built by ONE compiled program whose output keeps the row
  sharding: each process materializes only its addressable shards
  (``jit`` + ``out_shardings``; the slice/cast/mask is elementwise over
  rows, so XLA keeps per-shard work local). Bitwise-identical to the
  host-packed path's matrix: same casts, same zero pad.
- **pack_binned** — the tree-training input build: the (N, F) integer bin
  matrix fused into one program with a ``P('rows', None)`` output, so
  training input pipelines never stage full columns on the coordinator
  (previously: eager per-column ops + a re-homing device_put).

Per-process counters make the no-gather property OBSERVABLE
(``GET /3/ScoringMetrics`` → ``data_plane``): ``packed_rows`` counts rows
packed shard-locally; ``gathered_rows`` counts rows whose columns WERE
pulled to this process's host inside the fused scoring / tree input paths
(the degraded-serving and ragged-layout fallbacks). tests/test_consistency
asserts ``gathered_rows`` stays 0 on the sharded path.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import List, Optional, Sequence

import numpy as np

ROW_AXIS = "rows"

# -- per-process data-plane counters ----------------------------------------

_LOCK = threading.Lock()
_PACKED = 0
_GATHERED = 0
_SORTED = 0


def note_packed(n: int) -> None:
    """Record `n` rows whose task input was built from addressable shards
    in place (no host round-trip)."""
    global _PACKED
    with _LOCK:
        _PACKED += int(n)


def note_gathered(n: int) -> None:
    """Record `n` rows whose columns were fetched to this process's host
    inside the fused scoring / tree input path (the exceptional path)."""
    global _GATHERED
    with _LOCK:
        _GATHERED += int(n)


def note_sorted(n: int) -> None:
    """Record `n` rows ordered by a device sort whose permutation never
    crossed to the host (ops/sort.py device paths — the lazy-session PR's
    'sort stops being the host-keyed path' observable)."""
    global _SORTED
    with _LOCK:
        _SORTED += int(n)


def counters() -> dict:
    with _LOCK:
        return {"packed_rows": _PACKED, "gathered_rows": _GATHERED,
                "device_sorted_rows": _SORTED}


def reset_counters() -> None:
    global _PACKED, _GATHERED, _SORTED
    with _LOCK:
        _PACKED = 0
        _GATHERED = 0
        _SORTED = 0


def enabled() -> bool:
    """Master switch for the sharded data plane (H2O_TPU_SHARDED_PLANE,
    default on). Off = the legacy host-packed / eager paths, kept for
    A/B bitwise verification and emergency rollback."""
    return os.environ.get("H2O_TPU_SHARDED_PLANE", "1").lower() not in (
        "0", "false", "off")


def shard_geometry(cl, padded: int):
    """(shard_rows, addressable shard indices) for a padded row count.
    The authority is the row sharding's OWN index map (what put_rows
    materializes), never process_index — the chunked sharded ingest
    (ingest/chunked.py) uses it to land each byte-range chunk's rows
    directly in their owning shard buffers."""
    shard_rows = padded // max(cl.row_shards, 1)
    sh = cl.row_sharding()
    idx_map = sh.addressable_devices_indices_map((padded,))
    return shard_rows, {(sl[0].start or 0) // shard_rows
                        for sl in idx_map.values()}


# -- compiled packers (cached per geometry, not per request) ----------------

@functools.lru_cache(maxsize=64)
def _pack_features_fn(bucket: int, padded: int, dtypes: tuple, mesh):
    """(pos, n, *cols) -> (bucket, F) float32, row-sharded.

    Matches ScoringSession._features + its zero pad bitwise: values pass
    through for logical rows [pos, min(pos+bucket, n)) — numerics as-is
    (NaN = NA, bf16 upcast exactly as numpy's), categorical codes cast to
    float (NA_CAT stays negative) — and every other row is exactly 0.0.
    pos/n are traced scalars, so one compile covers every request against
    this (bucket, layout). `padded`/`dtypes` are cache-key-only: they pin
    the jit wrapper to one column layout so its trace cache never aliases
    across layouts."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def pack(pos, n, *cols):
        idx = pos + jnp.arange(bucket, dtype=jnp.int32)
        valid = idx < n
        parts = []
        for c in cols:
            x = c.astype(jnp.float32)
            # pad THEN slice: a tail chunk's [pos, pos+bucket) window may
            # overrun the padded column, and dynamic_slice would clamp the
            # start (silently shifting rows); the zero tail keeps the
            # window in bounds and is masked off below anyway
            x = jnp.pad(x, (0, bucket))
            parts.append(jax.lax.dynamic_slice_in_dim(x, pos, bucket))
        X = jnp.stack(parts, axis=-1)
        return jnp.where(valid[:, None], X, jnp.float32(0))

    return jax.jit(pack, out_shardings=NamedSharding(mesh, P(ROW_AXIS, None)))


@functools.lru_cache(maxsize=64)
def _pack_binned_fn(padded: int, dtypes: tuple, nbins: tuple, is_cat: tuple,
                    out_dtype: str, mesh):
    """(edges, *cols) -> (padded, F) integer bin matrix, row-sharded.

    The fused replacement for BinSpec.bin_columns' eager per-column loop:
    same bin math (searchsorted side='left' over the real edges — the +inf
    pad lanes never count — NA/out-of-range to the per-feature NA bin),
    one XLA program, output sharding P('rows', None) so each process bins
    only its addressable row shards."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    dt = getattr(jnp, out_dtype)

    def pack(edges, *cols):
        parts = []
        for i, c in enumerate(cols):
            na_bin = int(nbins[i]) - 1
            if is_cat[i]:
                codes = c.astype(jnp.int32)
                b = jnp.where((codes < 0) | (codes >= na_bin), na_bin, codes)
            else:
                x = c
                b = jnp.searchsorted(edges[i], x,
                                     side="left").astype(jnp.int32)
                b = jnp.where(jnp.isnan(x), na_bin, b)
            parts.append(b.astype(dt))
        return jnp.stack(parts, axis=-1)

    return jax.jit(pack, out_shardings=NamedSharding(mesh, P(ROW_AXIS, None)))


@functools.lru_cache(maxsize=64)
def _pack_binned_window_fn(win: int, padded: int, dtypes: tuple,
                           nbins: tuple, is_cat: tuple, out_dtype: str,
                           mesh):
    """(pos, edges, *cols) -> (win, F) bin matrix for rows
    [pos, pos+win) — the chunk-streamed twin of _pack_binned_fn for
    frames whose full (padded, F) bin matrix exceeds the memory
    planner's budget. Same bin math on pad→dynamic-sliced column
    windows (identical values per covered row → bitwise-identical bins);
    the overrun lanes of a tail window are trimmed by the caller. Full
    columns stay in place as args — only the temporaries and the output
    shrink to the window, which is where the working set lives."""
    import jax
    import jax.numpy as jnp

    dt = getattr(jnp, out_dtype)

    def pack(pos, edges, *cols):
        parts = []
        for i, c in enumerate(cols):
            x = jax.lax.dynamic_slice_in_dim(jnp.pad(c, (0, win)), pos, win)
            na_bin = int(nbins[i]) - 1
            if is_cat[i]:
                codes = x.astype(jnp.int32)
                b = jnp.where((codes < 0) | (codes >= na_bin), na_bin, codes)
            else:
                b = jnp.searchsorted(edges[i], x,
                                     side="left").astype(jnp.int32)
                b = jnp.where(jnp.isnan(x), na_bin, b)
            parts.append(b.astype(dt))
        return jnp.stack(parts, axis=-1)

    return jax.jit(pack)


# packer executables, AOT-compiled through the compile ledger (family
# "pack") so the data plane's compiles land on /3/Runtime like every
# other program. Keyed by geometry + the concrete input shardings: a
# frame with a different layout gets its own recorded compile instead of
# a silent uncounted jit trace.
_EXE_LOCK = threading.Lock()
_EXE_CACHE: dict = {}
_EXE_CAP = 64


_EXE_MISS = object()


def _packer_exe(key: tuple, jfn, call_args, program: str,
                family: str = "pack", rows: int = 0):
    """Ledger-recorded AOT executable for one packer geometry (or None
    when AOT lowering/compilation itself fails on this layout/backend —
    cached so the failure is paid once and callers permanently use the
    jit twin, exactly the pre-ledger behavior). Lowered from the
    CONCRETE first-call args (jit-identical program, exact input
    shardings).

    Hot-path cost discipline: the warm lookup is a lock-free dict get
    (GIL-atomic); _EXE_LOCK is held only across the miss path, where the
    double-checked re-read makes concurrent first-touch threads pay ONE
    compile (and land one ledger row) instead of racing duplicates."""
    exe = _EXE_CACHE.get(key, _EXE_MISS)
    if exe is not _EXE_MISS:
        return exe
    with _EXE_LOCK:
        exe = _EXE_CACHE.get(key, _EXE_MISS)
        if exe is not _EXE_MISS:
            return exe
        try:
            from h2o3_tpu.obs import compiles

            exe = compiles.compile_jit(family, jfn, call_args,
                                       signature=key, program=program)
            if rows > 0:
                from h2o3_tpu.memory import budget as membudget

                membudget.note_compiled(family, int(rows), exe)
        except Exception:   # noqa: BLE001 — AOT unavailable for this
            exe = None      # layout: the jit twin still dispatches
        if len(_EXE_CACHE) >= _EXE_CAP:
            _EXE_CACHE.pop(next(iter(_EXE_CACHE)))
        _EXE_CACHE[key] = exe
    return exe


def _sharding_key(arrs) -> tuple:
    # the sharding OBJECTS, not their str(): jax shardings are hashable/
    # eq-comparable, and stringifying one per column per dispatch would
    # tax the data-plane hot path for nothing
    return tuple(getattr(a, "sharding", None) for a in arrs)


class ShardedFrame:
    """Row-sharded data-plane view over a Frame's device columns.

    Build with :meth:`of` (returns None when the view cannot hold: a named
    column is host-resident (strings), layouts disagree, or the plane is
    switched off) — callers fall back to their legacy host/eager path and
    count the rows as ``gathered``."""

    __slots__ = ("frame", "names", "_datas", "_cl", "padded_rows")

    def __init__(self, frame, names: List[str], datas: list, cl,
                 padded_rows: int):
        self.frame = frame
        self.names = names
        self._datas = datas
        self._cl = cl
        self.padded_rows = padded_rows

    @classmethod
    def of(cls, frame, names: Optional[Sequence[str]] = None
           ) -> Optional["ShardedFrame"]:
        if not enabled():
            return None
        from h2o3_tpu.core.runtime import cluster

        cl = cluster()
        use = list(names) if names is not None else list(frame.names)
        datas, padded = [], None
        for nm in use:
            c = frame.col(nm)
            if c.ctype not in ("real", "int", "enum", "time"):
                return None            # host-resident (string/uuid) column
            d = c.data                 # faults evicted columns back in
            if d is None:
                return None
            if padded is None:
                padded = int(d.shape[0])
            elif int(d.shape[0]) != padded:
                return None            # ragged layout: no shared row axis
            datas.append(d)
        if padded is None or padded % max(cl.row_shards, 1):
            return None
        return cls(frame, use, datas, cl, padded)

    @classmethod
    def for_key(cls, key, names: Optional[Sequence[str]] = None
                ) -> Optional["ShardedFrame"]:
        """DKV-resident variant: resolve `key` through the control plane
        (local store first, replicated payload second) and wrap it."""
        from h2o3_tpu.core.dkv import DKV

        fr = DKV.fetch_remote(key)
        return cls.of(fr, names) if fr is not None else None

    # -- layout -----------------------------------------------------------
    @property
    def row_axis(self) -> str:
        return ROW_AXIS

    @property
    def mesh(self):
        return self._cl.mesh

    def row_sharding(self, ncols: bool = False):
        """The view's NamedSharding: rows over the named axis (optionally
        with an unsharded trailing column axis)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(ROW_AXIS, None) if ncols else P(ROW_AXIS)
        return NamedSharding(self._cl.mesh, spec)

    # -- packers -----------------------------------------------------------
    def pack_features(self, pos: int, n: int, bucket: int):
        """(bucket, F) float32 scoring matrix for logical rows
        [pos, min(pos+bucket, n)), zero elsewhere — built on device from
        the columns' addressable shards; the host never sees a column."""
        import jax.numpy as jnp

        from h2o3_tpu.obs import tracing

        dtypes = tuple(str(d.dtype) for d in self._datas)
        fn = _pack_features_fn(int(bucket), self.padded_rows, dtypes,
                               self._cl.mesh)
        args = (jnp.int32(pos), jnp.int32(n)) + tuple(self._datas)
        exe = _packer_exe(
            ("features", int(bucket), self.padded_rows, dtypes,
             self._cl.mesh, _sharding_key(self._datas)),
            fn, args, program="pack_features", rows=int(bucket))
        # host-side dispatch wall time only — the packed matrix stays
        # device-resident and no sync is added (span is inert without an
        # active trace)
        with tracing.span("pack", bucket=int(bucket), rows=int(n),
                          path="sharded"):
            if exe is None:
                return fn(*args)
            try:
                return exe(*args)
            except Exception:   # noqa: BLE001 — AOT layout/placement
                return fn(*args)   # mismatch: the jit twin still fits

    def pack_binned(self, spec):
        """(padded_rows, F) integer bin matrix for tree training, fused
        and row-sharded (see _pack_binned_fn). Counts the frame's logical
        rows as packed."""
        import jax.numpy as jnp

        from h2o3_tpu.obs import tracing

        max_bins = int(spec.nbins.max()) if len(spec.nbins) else 1
        out_dtype = ("uint8" if max_bins <= 256
                     else "int16" if max_bins <= 32767 else "int32")
        dtypes = tuple(str(d.dtype) for d in self._datas)
        nbins = tuple(int(b) for b in spec.nbins)
        is_cat = tuple(bool(c) for c in spec.is_cat)
        fn = _pack_binned_fn(self.padded_rows, dtypes, nbins, is_cat,
                             out_dtype, self._cl.mesh)
        edges = jnp.asarray(spec.padded_edges())
        args = (edges,) + tuple(self._datas)
        exe = _packer_exe(
            ("binned", self.padded_rows, dtypes, nbins, is_cat, out_dtype,
             self._cl.mesh, _sharding_key(self._datas)),
            fn, args, program="pack_binned", family="binning",
            rows=self.padded_rows)
        note_packed(int(self.frame.nrows))

        from h2o3_tpu.memory import stream as mstream

        n_pad = self.padded_rows
        item = int(np.dtype(out_dtype).itemsize)
        # per window row: F float32 column lanes in flight + F output lanes
        row_bytes = float(len(self._datas)) * (4.0 + item)

        def window(pos, m):
            if pos == 0 and m == n_pad:
                # planned-full: the exact single-dispatch program
                if exe is None:
                    return fn(*args)
                try:
                    return exe(*args)
                except Exception as e:   # noqa: BLE001
                    if mstream.is_oom(e):
                        raise           # the ladder owns exhaustion
                    return fn(*args)    # AOT layout mismatch: jit twin
            w = 1 << max(int(m) - 1, 0).bit_length()
            wfn = _pack_binned_window_fn(w, n_pad, dtypes, nbins, is_cat,
                                         out_dtype, self._cl.mesh)
            out = wfn(jnp.int32(pos), *args)
            return out[:m] if m != w else out

        with tracing.span("pack", rows=int(self.frame.nrows),
                          path="binned"):
            pieces = mstream.run_windows("binning", n_pad, window,
                                         max_window=n_pad,
                                         row_bytes=row_bytes)
        return (pieces[0] if len(pieces) == 1
                else jnp.concatenate(pieces, axis=0))

    def __repr__(self) -> str:
        return (f"<ShardedFrame {getattr(self.frame, 'key', '?')} "
                f"{self.padded_rows}x{len(self.names)} axis={ROW_AXIS}>")
