"""Cluster runtime: mesh bootstrap, config, node info.

Replaces H2O-3's cloud-of-JVMs boot (reference: h2o-core/src/main/java/water/
H2O.java:1776 startLocalNode, :1811 startNetworkServices, water/Paxos.java:27
heartbeat-gossip membership). TPU-native design: membership is the set of JAX
processes/devices — static per job, which matches H2O's locked-cloud
semantics (water/Paxos.java:144 lockCloud: no elastic join after first job).
There is no Paxos to run: `jax.distributed.initialize()` (multi-host) or the
local device list (single-host) IS the cloud.
"""

from __future__ import annotations

from h2o3_tpu.compat import shard_map as _compat_shard_map
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np


@dataclass
class OptArgs:
    """Config/flag system (reference: water/H2O.java:316 OptArgs).

    Values may be overridden by environment variables H2O_TPU_<NAME>,
    mirroring H2O's -Dai.h2o.X=Y system-property pass-through
    (water/H2O.java:321 SYSTEM_PROP_PREFIX)."""

    name: str = "h2o3-tpu"
    # mesh shape: rows axis = data parallel over devices; model axis for TP.
    mesh_shape: Optional[Sequence[int]] = None
    mesh_axes: Sequence[str] = ("rows", "model")
    # row shard padding multiple (static shapes: ESPC replaced by padding,
    # SURVEY.md §7 "ESPC ragged chunks -> equal shard sizes with tail padding")
    row_align: int = 8
    # device storage dtype for numeric columns: "float32" (default) or
    # "bfloat16" (halves HBM; ops upcast at their boundaries)
    numeric_dtype: str = "float32"
    log_level: str = "INFO"
    ice_root: str = field(default_factory=lambda: os.environ.get("H2O_TPU_ICE_ROOT", "/tmp/h2o3_tpu"))
    # multi-host
    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0
    # explicit device list (dryrun/test harnesses pin a subset or a forced
    # CPU mesh); None = all of jax.devices()
    devices: Optional[Sequence] = None

    @staticmethod
    def from_env() -> "OptArgs":
        args = OptArgs()
        for f in ("name", "log_level", "ice_root", "coordinator_address",
                  "numeric_dtype"):
            v = os.environ.get("H2O_TPU_" + f.upper())
            if v is not None:
                setattr(args, f, v)
        for f in ("num_processes", "process_id", "row_align"):
            v = os.environ.get("H2O_TPU_" + f.upper())
            if v is not None:
                setattr(args, f, int(v))
        return args


class Cluster:
    """The booted runtime: device mesh + per-node info.

    H2O parity: `GET /3/Cloud` surface (water/api/CloudHandler.java) maps to
    :meth:`info`; the boot-time hardware probes (water/init/Linpack.java,
    MemoryBandwidth.java) map to :meth:`self_benchmark`."""

    def __init__(self, args: OptArgs):
        import jax

        from h2o3_tpu.obs import phases

        self.args = args
        self.start_time = time.time()
        self._jax = jax
        # the boot sequence below is the engine's historically-dark path
        # (ROADMAP item 1: every BENCH_r03-r05 device round wedged BEFORE
        # any stage body, in backend init / the first tiny compile) —
        # each step is now its own deadline-supervised lifecycle phase
        # with timeline events, so a wedge names itself
        if args.coordinator_address and args.num_processes > 1:
            with phases.enter("cloud_form", processes=args.num_processes):
                jax.distributed.initialize(
                    coordinator_address=args.coordinator_address,
                    num_processes=args.num_processes,
                    process_id=args.process_id,
                )
        with phases.enter("backend_init",
                          platforms=os.environ.get("JAX_PLATFORMS", "")):
            # first XLA client touch — THE wedge site of the r03 autopsy
            platform = jax.default_backend()
        with phases.enter("device_discovery", platform=platform):
            self.devices = (list(args.devices) if args.devices
                            else jax.devices())
        n = len(self.devices)
        with phases.enter("mesh_init", devices=n):
            if args.mesh_shape is None:
                shape = (n, 1)
            else:
                shape = tuple(args.mesh_shape)
            dev_grid = np.array(self.devices).reshape(shape)
            self.mesh = jax.sharding.Mesh(
                dev_grid, tuple(args.mesh_axes[: dev_grid.ndim]))
            self.n_devices = n
            self.locked = False  # parity flag; membership is static here
            # multi-process clouds run the liveness beater (HeartBeatThread
            # analog) so /3/Cloud's process_health stays fresh
            self._heartbeat = None
            if jax.process_count() > 1:
                from h2o3_tpu.core.failure import HeartbeatThread

                self._heartbeat = HeartbeatThread(interval_s=5.0).start()
        with phases.enter("first_compile"):
            # the supervised tiny boot compile: separates "backend up but
            # first compile wedges" from "backend init wedges" — exactly
            # the distinction the r03-r05 autopsies could not make
            import jax.numpy as jnp

            from h2o3_tpu.obs import compiles

            exe = compiles.compile_jit(
                "probe", jax.jit(lambda x: x + jnp.float32(1)),
                (jax.ShapeDtypeStruct((), jnp.float32),),
                signature="boot_first_compile", program="boot_probe")
            exe(jnp.float32(0)).block_until_ready()

    # -- sharding helpers -------------------------------------------------
    def row_sharding(self):
        """NamedSharding placing axis 0 over the 'rows' mesh axis — the
        TPU-native replacement for chunk homing by Key hash
        (water/Key.java:88-107)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P("rows"))

    def replicated_sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    @property
    def row_shards(self) -> int:
        return int(self.mesh.shape["rows"])

    def pad_rows(self, n: int) -> int:
        """Smallest padded length >= n divisible by (row_shards * row_align)."""
        m = self.row_shards * self.args.row_align
        return max(int(-(-n // m) * m), m)

    def put_rows(self, buf: np.ndarray):
        """Pin a padded host array into device memory row-sharded. In
        multi-process mode each process materializes only its addressable
        shards from its (replicated) host copy — the multi-host analog of
        H2O's parse-then-home-chunks ingestion (every node reads its share)."""
        import jax

        sh = self.row_sharding()
        if jax.process_count() > 1:
            return jax.make_array_from_callback(
                buf.shape, sh, lambda idx: buf[idx])
        return jax.device_put(buf, sh)

    def reshard_rows(self, x):
        """Re-lay an existing device array out over the rows axis. Eager
        device_put single-process; a compiled identity with out_shardings in
        multi-process mode (cross-host resharding must go through XLA)."""
        import jax

        sh = self.row_sharding()
        if jax.process_count() > 1:
            return jax.jit(lambda a: a, out_shardings=sh)(x)
        return jax.device_put(x, sh)

    # -- info / observability --------------------------------------------
    def info(self) -> dict:
        import jax

        from h2o3_tpu.core import failure
        from h2o3_tpu.parallel import distributed as D

        return {
            "cloud_name": self.args.name,
            "version": "h2o3_tpu",
            "cloud_size": self.n_devices,
            "cloud_uptime_millis": int((time.time() - self.start_time) * 1000),
            "cloud_healthy": True,
            "locked": self.locked,
            "platform": jax.default_backend(),
            # recovery-layer identity: which election epoch this cloud is
            # in, who leads it, and this process's incarnation (bumped by
            # every rejoin) — surfaced on /3/CloudStatus
            "epoch": D.epoch(),
            "leader": D.leader(),
            "incarnation": failure.incarnation(),
            "nodes": [
                {"name": str(d), "platform": d.platform, "id": d.id}
                for d in self.devices
            ],
        }

    def self_benchmark(self, size: int = 1024) -> dict:
        """Boot probes, the analogs of water/init/Linpack.java (matmul
        GFLOPs), water/init/MemoryBandwidth.java (HBM stream GB/s) and
        water/init/NetworkBench.java (collective latency over the mesh —
        ICI on real pods)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        x = jnp.ones((size, size), jnp.float32)
        f = jax.jit(lambda a: a @ a)
        f(x).block_until_ready()  # compile
        t0 = time.perf_counter()
        reps = 10
        y = x
        for _ in range(reps):
            y = f(y)
        y.block_until_ready()
        dt = time.perf_counter() - t0
        gflops = 2 * size**3 * reps / dt / 1e9

        # HBM stream: out = a + b reads 2 arrays and writes 1
        n = 4 * size * size
        a = jnp.ones(n, jnp.float32)
        b = jnp.ones(n, jnp.float32)
        g = jax.jit(lambda u, v: u + v)
        g(a, b).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(reps):
            c = g(a, b)
        c.block_until_ready()
        dt = time.perf_counter() - t0
        membw = 3 * n * 4 * reps / dt / 1e9

        # collective round: psum of a scalar-per-shard over the rows axis
        ps = jax.jit(_compat_shard_map(lambda v: jax.lax.psum(v, "rows"),
                                   mesh=self.mesh, in_specs=P("rows"),
                                   out_specs=P()))
        vec = jnp.ones(self.n_devices, jnp.float32)
        ps(vec).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(50):
            r = ps(vec)
        r.block_until_ready()
        psum_us = (time.perf_counter() - t0) / 50 * 1e6
        out = {"matmul_gflops": gflops, "membw_gbps": membw,
               "psum_latency_us": psum_us, "size": size}
        from h2o3_tpu.utils import timeline

        timeline.record("self_benchmark", "boot_probe", **{
            k: round(v, 2) for k, v in out.items() if k != "size"})
        return out


# reentrant: extension hooks run under the boot lock (so no other thread
# sees a cluster whose extensions haven't loaded) and may themselves call
# cluster()/init()
_LOCK = threading.RLock()
_CLUSTER: Optional[Cluster] = None


def init(args: Optional[OptArgs] = None, **kw) -> Cluster:
    """Boot (or return) the runtime. h2o.init() parity
    (reference: h2o-py/h2o/h2o.py h2o.init)."""
    global _CLUSTER
    with _LOCK:
        if _CLUSTER is None:
            a = args or OptArgs.from_env()
            for k, v in kw.items():
                setattr(a, k, v)
            _CLUSTER = Cluster(a)
            # extension SPI hooks (ExtensionManager.extensionsLoaded): after
            # _CLUSTER is assigned (hooks use the full public API through
            # the reentrant lock) but before any OTHER thread can observe
            # the cluster — failures are isolated inside the runner
            from h2o3_tpu import extensions as _ext

            _ext.run_extension_hooks(_CLUSTER)
        return _CLUSTER


def cluster() -> Cluster:
    return init()


def cluster_info() -> dict:
    return cluster().info()


def shutdown() -> None:
    """Drop the runtime and all stored keys (h2o.cluster().shutdown())."""
    global _CLUSTER
    from h2o3_tpu.core.dkv import DKV

    with _LOCK:
        if _CLUSTER is not None and getattr(_CLUSTER, "_heartbeat", None):
            _CLUSTER._heartbeat.stop()
        DKV.clear()
        _CLUSTER = None
    # registered extensions re-run their hooks against the next cluster
    from h2o3_tpu import extensions as _ext

    _ext._INITIALIZED.clear()
