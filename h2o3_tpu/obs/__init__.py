"""Cluster-wide observability plane (ISSUE 8).

Reference: H2O-3 ships node-level introspection as a first-class subsystem
(water/TimeLine.java ring, /3/Timeline, /3/Logs, WaterMeter, /3/Profiler);
Podracer-style fleets (PAPERS.md) roll per-learner health and throughput up
at the one controller. This package is that layer for the TPU cloud:

- :mod:`h2o3_tpu.obs.metrics` — a process-wide metrics registry
  (counters / gauges / histograms with bounded label sets). Per-process
  snapshots publish through the cloud KV so the coordinator serves
  CLUSTER-wide ``GET /3/Metrics`` in Prometheus text exposition and JSON.
- :mod:`h2o3_tpu.obs.tracing` — trace spans with context propagation: a
  span id minted at REST ingress rides the oplog op record, so
  coordinator publish → follower replay → ack land in ONE span tree
  (``GET /3/Trace/{id}``), and the scoring fast path emits child spans
  for queue-wait / pack / dispatch / blocking-fetch without adding any
  device sync.
- :mod:`h2o3_tpu.obs.flight` — the flight recorder: on a fatal signal, a
  watchdog recovery action, or a bench-stage timeout, the timeline ring +
  open spans + a metrics snapshot persist atomically to
  ``$H2O_TPU_ICE_ROOT/flight/`` (``GET /3/FlightRecords``), so a dark
  bench round leaves a corpse to autopsy instead of a bare timeout.
- :mod:`h2o3_tpu.obs.phases` — the runtime lifecycle phase tracker
  (ISSUE 12): ``backend_init`` … ``server_start`` as deadline-supervised
  timeline phases; a wedged phase dumps a flight record naming itself
  and, in bench/probe contexts, hands the budget to the CPU chain fast.
- :mod:`h2o3_tpu.obs.compiles` — the cluster-wide compile ledger: the
  ONE chokepoint every XLA compile routes through (family, signature,
  duration, cache disposition, HBM estimate), served on
  ``GET /3/Runtime`` and folded into ``/3/Metrics``.

Import cost: this package pulls in only the stdlib — jax and the heavy
framework modules load lazily inside callbacks, so the flight recorder
stays usable from a process whose accelerator tunnel is wedged."""

from h2o3_tpu.obs import (compiles, flight, metrics,  # noqa: F401
                          phases, tracing)
