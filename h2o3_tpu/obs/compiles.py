"""Cluster-wide compile ledger: the ONE chokepoint for XLA compilation.

"Automatic Full Compilation of Julia Programs and ML Models to Cloud
TPUs" (PAPERS.md) shows compile time is the dominant, attributable cost
of the XLA path; "Memory Safe Computations with XLA Compiler" motivates
recording each program's memory estimate next to its compile cost. Until
this module those costs were scattered: scoring, rapids fusion and the
artifact exporter each ran ``jit(...).lower(...).compile()`` themselves
and self-reported (or didn't) into ad-hoc counters that could drift.

Now EVERY explicit XLA compile in the repo routes through here
(:func:`compile_jit` / :func:`compile_lowered` / :func:`compile_stablehlo`
— an analysis pass bans direct ``.lower(...).compile(`` /
``compile_stablehlo`` calls outside this module), and each records one
ledger row: program family (closed :data:`FAMILIES` enumeration),
signature hash, wall duration ms, cache disposition
(compile | memory | disk), device kind, and the optional HBM estimate
from ``compiled.memory_analysis()`` (via the ``compat.py`` shim — the
API is version-mobile). Cache HITS are recorded by the same chokepoint
(:func:`record_hit`), so the per-family table on ``GET /3/Runtime``
tells hit ratios, not just compile counts.

The legacy ``artifact/compile_cache.note_compile()`` counter is now a
VIEW over this ledger: the ledger times the compile itself and feeds the
counter for the persistent-cache families (scoring/rapids), so
``compile_ms_total`` can never drift from the per-program rows.

Import cost: stdlib only (jax/compat imported per call — by the time
anything compiles, the backend is necessarily up)."""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

# closed program-family enumeration: scoring = fused bin+traverse serving
# programs, explain = fused bin+leaf explainability programs (leaf
# assignment / staged probabilities), binning = tree-training bin-matrix
# builds, rapids = statement fusion, pipeline = munge→score splices (the
# rapids feature graph + the model core in ONE program), artifact = AOT
# exporter lowerings, pack = sharded data-plane packers, probe = the
# supervised boot first-compile, tree = tree-grower programs (histogram
# builds, grow/apply steps, per-tree pre/post residual math, compressed
# forest traversal — everything a GBM/DRF train compiles)
FAMILIES = frozenset({"scoring", "explain", "binning", "rapids", "pipeline",
                      "artifact", "pack", "probe", "tree"})

# persistent-compile-cache families whose actual compiles feed the legacy
# note_compile() counter (the warm-restart zero-compile assertions)
_CACHED_FAMILIES = ("scoring", "explain", "rapids", "pipeline")

_KV_PREFIX = "obs/runtime/"

_LOCK = threading.Lock()
_ROWS: "collections.deque[dict]" = collections.deque(maxlen=512)
_AGG: Dict[str, Dict[str, float]] = {}
# (family, tier) -> hit count, bumped LOCK-FREE on the warm dispatch
# path and folded into family_table() at read time
_HIT_COUNTS: Dict[tuple, int] = {}


def _check(family: str) -> None:
    if family not in FAMILIES:
        raise ValueError(f"unknown compile family {family!r}; the "
                         f"enumeration is closed: {sorted(FAMILIES)}")


def _sig(signature: Any) -> str:
    """Stable short hash of whatever signature material the caller has
    (model checksum + bucket, an AST signature, a geometry tuple)."""
    raw = signature if isinstance(signature, str) else repr(signature)
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def _device_kind() -> Optional[str]:
    """Backend identity for the row; never triggers backend init (at
    compile time it is up by construction, but hit recording may run
    earlier)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        d = jax.devices()[0]
        return f"{d.platform}/{getattr(d, 'device_kind', '?')}"
    except Exception:   # noqa: BLE001
        return None


def _hbm_estimate(compiled) -> Optional[int]:
    try:
        from h2o3_tpu import compat

        ma = compat.memory_analysis(compiled)
    except Exception:   # noqa: BLE001
        return None
    if not ma:
        return None
    return int(sum(v for v in (ma.get("argument_bytes"),
                               ma.get("output_bytes"),
                               ma.get("temp_bytes"),
                               ma.get("generated_code_bytes")) if v))


def _agg_for(family: str) -> Dict[str, float]:
    a = _AGG.get(family)
    if a is None:
        a = _AGG[family] = {"compiles": 0, "hits_memory": 0, "hits_disk": 0,
                            "ms_total": 0.0, "ms_max": 0.0}
    return a


def _append(row: dict) -> None:
    with _LOCK:
        _ROWS.append(row)
        a = _agg_for(row["family"])
        a["compiles"] += 1
        a["ms_total"] += row["ms"]
        a["ms_max"] = max(a["ms_max"], row["ms"])


def record_compile(family: str, signature: Any, ms: float,
                   program: Optional[str] = None,
                   compiled: Any = None) -> dict:
    """One actual XLA compilation. Normally called by the compile_*
    wrappers below (which time the compile themselves); exposed for the
    one case where the compile happens inside an opaque API."""
    _check(family)
    row = {"ts": time.time(), "family": family, "signature": _sig(signature),
           "ms": round(float(ms), 3), "cache": "compile",
           "device_kind": _device_kind(), "program": program,
           "hbm_bytes": _hbm_estimate(compiled) if compiled is not None
           else None}
    _append(row)
    if family in _CACHED_FAMILIES:
        # the legacy counter becomes a view over the ledger: same ms, one
        # writer, zero drift (tests/test_artifact warm-restart assertions)
        from h2o3_tpu.artifact import compile_cache

        compile_cache.note_compile(row["ms"])
    return row


def record_hit(family: str, signature: Any = None, tier: str = "memory",
               program: Optional[str] = None) -> None:
    """A compile AVOIDED: `tier` is ``memory`` (in-process signature
    cache) or ``disk`` (persistent compile cache). Hits bump the
    per-family aggregate ONLY — they never consume the bounded
    compile-row ring (warm traffic would otherwise evict every
    ``cache="compile"`` row and empty /3/Runtime's slowest-N on exactly
    the long-lived clusters it exists for), and the warm path pays no
    signature hashing or device lookup. `signature`/`program` are
    accepted for call-site symmetry with the compile entries."""
    _check(family)
    if tier not in ("memory", "disk"):
        raise ValueError(f"unknown cache tier {tier!r}")
    # lock-free counter bump: this runs once per warm fused dispatch (the
    # hottest path in the engine), which must not serialize on the same
    # process-wide lock compile recording and /3/Runtime snapshots take.
    # A GIL-raced lost increment on an observability ratio is acceptable;
    # family_table() folds these in at read time.
    k = (family, tier)
    _HIT_COUNTS[k] = _HIT_COUNTS.get(k, 0) + 1


# ---------------------------------------------------------------------------
# the chokepoint entries (the ONLY legal spellings of an XLA compile —
# enforced by the `compile-ledger` analysis pass)
# ---------------------------------------------------------------------------

def compile_jit(family: str, jfn, args, signature: Any = None,
                program: Optional[str] = None):
    """Lower + compile a ``jax.jit`` wrapper over `args` (concrete arrays
    or ShapeDtypeStructs), timing the compile HERE so no caller
    self-reports a duration the ledger didn't measure."""
    _check(family)
    t0 = time.perf_counter()
    compiled = jfn.lower(*args).compile()
    ms = (time.perf_counter() - t0) * 1000
    record_compile(family, signature if signature is not None else program,
                   ms, program=program, compiled=compiled)
    return compiled


def compile_lowered(family: str, lowered, signature: Any = None,
                    program: Optional[str] = None):
    """Compile an already-lowered program (the artifact exporter keeps
    the lowering to also serialize its StableHLO text)."""
    _check(family)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    ms = (time.perf_counter() - t0) * 1000
    record_compile(family, signature if signature is not None else program,
                   ms, program=program, compiled=compiled)
    return compiled


def compile_stablehlo(family: str, text: str, signature: Any = None,
                      program: Optional[str] = None):
    """Compile StableHLO module text through the local XLA client
    (compat-shimmed), ledger-recorded like every other compile."""
    _check(family)
    from h2o3_tpu import compat

    t0 = time.perf_counter()
    exe = compat.compile_stablehlo(text)
    ms = (time.perf_counter() - t0) * 1000
    record_compile(family, signature if signature is not None else text[:256],
                   ms, program=program)
    return exe


# a key whose AOT lowering failed (or whose executable rejected a call):
# dispatch through the plain jit wrapper from then on. Distinct sentinel —
# None would be ambiguous with a missing key under dict.get.
_JIT_FALLBACK = object()


def _arg_key(args) -> str:
    """Shape/dtype signature of a call's arguments. Array leaves key by
    (shape, dtype); non-array leaves (python scalars, bools) key by TYPE
    only — jit treats them as weak-typed dynamic args, so keying their
    values would recompile per learning-rate/sample-rate value."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append((tuple(shape), str(dtype)))
        else:
            parts.append(type(leaf).__name__)
    return repr((parts, str(treedef)))


class _LedgeredJit:
    """A ``jax.jit`` wrapper whose every compile lands in the ledger.

    First call per argument shape class AOT-compiles through
    :func:`compile_jit` (one timed ledger row); subsequent calls hit the
    executable cache and bump :func:`record_hit` — so a warm re-train
    adds ZERO compile rows. Shapes the AOT path cannot serve (lowering
    failure, or an executable rejecting a call over sharding/weak-type
    drift) permanently fall back to the plain jit wrapper for that key.
    ``lower`` passes through, so callers that AOT-compile under their own
    family (scoring's executable cache over compressed-forest programs)
    keep working."""

    def __init__(self, family, fn, program=None, jit_kw=None):
        import jax

        _check(family)
        self._family = family
        self._program = program
        self._jfn = jax.jit(fn, **(jit_kw or {}))
        self._exe: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def lower(self, *args, **kw):
        return self._jfn.lower(*args, **kw)

    def __call__(self, *args):
        key = _arg_key(args)
        exe = self._exe.get(key)
        if exe is None:
            with self._lock:
                exe = self._exe.get(key)
                if exe is None:
                    try:
                        exe = compile_jit(self._family, self._jfn, args,
                                          signature=key,
                                          program=self._program)
                    except Exception:   # noqa: BLE001 — AOT-hostile shape
                        exe = _JIT_FALLBACK
                    self._exe[key] = exe
        else:
            record_hit(self._family, tier="memory")
        if exe is _JIT_FALLBACK:
            return self._jfn(*args)
        try:
            return exe(*args)
        except Exception:   # noqa: BLE001 — input layout the AOT
            # executable can't accept (sharding / weak-type drift):
            # this key dispatches through plain jit from now on
            self._exe[key] = _JIT_FALLBACK
            return self._jfn(*args)


def ledgered_jit(family: str, fn, program: Optional[str] = None, **jit_kw):
    """``jax.jit(fn)`` with ledger-visible compiles: the legal spelling
    of a jit under the ``jax.jit`` ban scopes (models/tree/). Keyword
    args pass through to ``jax.jit``."""
    return _LedgeredJit(family, fn, program=program, jit_kw=jit_kw)


# ---------------------------------------------------------------------------
# snapshots / cluster aggregation (GET /3/Runtime)
# ---------------------------------------------------------------------------

def ledger_rows(n: Optional[int] = None) -> List[dict]:
    with _LOCK:
        rows = list(_ROWS)
    return rows[-n:] if n else rows


def family_table() -> Dict[str, Dict[str, float]]:
    with _LOCK:
        out = {f: dict(a) for f, a in _AGG.items()}
    for (fam, tier), n in list(_HIT_COUNTS.items()):
        a = out.setdefault(fam, {"compiles": 0, "hits_memory": 0,
                                 "hits_disk": 0, "ms_total": 0.0,
                                 "ms_max": 0.0})
        a["hits_memory" if tier == "memory" else "hits_disk"] = n
    return out


def slowest(n: int = 10) -> List[dict]:
    rows = [r for r in ledger_rows() if r["cache"] == "compile"]
    return sorted(rows, key=lambda r: r["ms"], reverse=True)[:max(n, 0)]


def snapshot(slowest_n: int = 10) -> dict:
    return {"families": family_table(), "slowest": slowest(slowest_n),
            "rows_recorded": len(ledger_rows())}


def _proc_index() -> int:
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        return int(jax.process_index())
    except Exception:   # noqa: BLE001
        return 0


def runtime_snapshot(slowest_n: int = 10) -> dict:
    """This process's /3/Runtime contribution: phase summary + ledger.
    The full phase-history ring deliberately stays OUT of this payload —
    it is KV-published every ~2 s per process and nothing reads it from
    the merged snapshots (the coordinator serves its own history live);
    ``phase_report`` carries the per-phase durations that ARE consumed."""
    from h2o3_tpu.obs import phases

    return {"proc": _proc_index(), "ts": time.time(),
            "phase_report": phases.phase_report(),
            "compiles": snapshot(slowest_n)}


def publish_runtime() -> bool:
    """KV-publish this process's runtime snapshot (piggybacked on the
    metrics publish throttle) so the coordinator's /3/Runtime is
    cluster-wide."""
    import json

    from h2o3_tpu.parallel import distributed as D

    try:
        return D.kv_put(_KV_PREFIX + str(_proc_index()),
                        json.dumps(runtime_snapshot(), default=str))
    except Exception:   # noqa: BLE001 — best-effort by contract
        return False


def cluster_runtime(slowest_n: int = 10) -> List[dict]:
    """Own LIVE snapshot + every other process's KV-published one. The
    live snapshot honors `slowest_n`; remote rows carry their publish
    default (10)."""
    import json

    from h2o3_tpu.parallel import distributed as D

    me = _proc_index()
    out = [runtime_snapshot(slowest_n)]
    try:
        rows = list(D.kv_dir(_KV_PREFIX))
    except Exception:   # noqa: BLE001
        rows = []
    for _k, v in rows:
        try:
            rec = json.loads(v)
        except (ValueError, TypeError):
            continue
        if isinstance(rec, dict) and rec.get("proc") != me:
            out.append(rec)
    return out


def merge_family_tables(tables: List[Dict[str, dict]]) -> Dict[str, dict]:
    """Sum per-family aggregates across processes (ms_max takes max)."""
    merged: Dict[str, dict] = {}
    for table in tables:
        for fam, a in (table or {}).items():
            m = merged.setdefault(fam, {"compiles": 0, "hits_memory": 0,
                                        "hits_disk": 0, "ms_total": 0.0,
                                        "ms_max": 0.0})
            for k in ("compiles", "hits_memory", "hits_disk", "ms_total"):
                m[k] += a.get(k, 0)
            m["ms_max"] = max(m["ms_max"], a.get("ms_max", 0.0))
    return merged


def reset_for_tests() -> None:
    with _LOCK:
        _ROWS.clear()
        _AGG.clear()
    _HIT_COUNTS.clear()
