"""Runtime lifecycle phases: timeline events + hard deadlines for the
engine's dark startup path.

The flagship device bench metric has been dark since BENCH_r03, and the
PR-11 autopsy pinned the wedge at backend initialization / first tiny
compile on the 'axon' platform — a span of the process lifetime that had
NO timeline events, no metrics and no deadline, so every wedged round
burned the whole bench budget blind (ROADMAP open item 1). H2O-3's Flow
timeline answers "which phase never completed" for its boot; this module
is that answer for the TPU engine:

- **Closed enumeration** (:data:`PHASES`): ``backend_init``,
  ``device_discovery``, ``mesh_init``, ``first_compile``,
  ``compile_cache_load``, ``server_start``, ``cloud_form``. Free-form
  phase names would make the history un-queryable, so :func:`enter`
  refuses anything else and the analysis timeline-kinds guard pins every
  call-site literal to this set.
- **Context manager** (:func:`enter`): records a ``phase`` timeline event
  at entry (a wedged phase leaves its begin event as the ring's last
  word), a completion event with wall ms, a trace span when a trace is
  active, and the ``h2o3_phase_*`` metrics.
- **Hard deadlines** (``H2O_TPU_PHASE_DEADLINE_S``, a map like
  ``"backend_init=45,first_compile=90"`` or one number for every phase):
  a daemon timer dumps a flight record NAMING the wedged phase on expiry,
  emits the ``H2O3_FLIGHT_JSON`` corpse line in bench contexts, invokes
  the caller's ``fallback`` action, and — for ``backend_init`` /
  ``first_compile`` with ``H2O_TPU_PHASE_DEADLINE_EXIT=1`` (bench/probe
  children) — hard-exits with :data:`DEADLINE_EXIT_RC` so the parent
  bench driver falls back to the CPU chain fast instead of burning the
  stage budget.

Import cost: stdlib only — this module instruments the exact window where
jax itself may be wedged, so it must never pull the heavy stack
(``obs/flight.py`` has the same contract).
"""

from __future__ import annotations

import collections
import contextlib
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# the closed lifecycle enumeration (analysis `timeline-kinds` guard pins
# every enter() call-site literal to this set, mirroring timeline.KINDS)
PHASES = frozenset({
    "backend_init",         # first XLA backend/client touch (the r03 wedge)
    "device_discovery",     # jax.devices() enumeration
    "mesh_init",            # device mesh construction + liveness beater
    "first_compile",        # the supervised tiny boot compile
    "compile_cache_load",   # persistent-cache executable load/deserialize
    "server_start",         # REST server + supervision bring-up
    "cloud_form",           # jax.distributed.initialize (multi-host)
})

# display / report order (lifecycle order, not set order)
ORDER = ("cloud_form", "backend_init", "device_discovery", "mesh_init",
         "first_compile", "compile_cache_load", "server_start")

# child processes exit with this code when a backend_init/first_compile
# deadline expires under H2O_TPU_PHASE_DEADLINE_EXIT=1 — the bench parent
# treats it as "tunnel wedged, go to the CPU chain NOW" (bench.py keeps
# the same literal: it must stay importable without h2o3_tpu)
DEADLINE_EXIT_RC = 97

_EXIT_PHASES = ("backend_init", "first_compile")

_LOCK = threading.Lock()
_HISTORY: "collections.deque[dict]" = collections.deque(maxlen=256)
# most recent COMPLETED record per phase, outside the bounded ring: the
# boot durations (backend_init .. first_compile) must survive however
# many later server_start / compile_cache_load entries the ring churns
_LATEST: Dict[str, dict] = {}


def deadlines() -> Dict[str, float]:
    """Per-phase hard deadlines from ``H2O_TPU_PHASE_DEADLINE_S`` — either
    one number (every phase) or a ``name=secs`` comma map. Unset/0 =
    unsupervised (library mode default; the bench driver arms the map in
    every child)."""
    raw = os.environ.get("H2O_TPU_PHASE_DEADLINE_S", "").strip()
    if not raw:
        return {}
    out: Dict[str, float] = {}
    if "=" not in raw:
        try:
            d = float(raw)
        except ValueError:
            return {}
        return {p: d for p in PHASES} if d > 0 else {}
    for part in raw.replace(";", ",").split(","):
        if "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            d = float(val)
        except ValueError:
            continue
        if name.strip() in PHASES and d > 0:
            out[name.strip()] = d
    return out


def deadline_exit_enabled() -> bool:
    """``H2O_TPU_PHASE_DEADLINE_EXIT=1``: a backend_init/first_compile
    expiry hard-exits the process with :data:`DEADLINE_EXIT_RC` (set by
    the bench driver for its children; never on in library mode)."""
    return os.environ.get("H2O_TPU_PHASE_DEADLINE_EXIT", "").lower() in (
        "1", "true", "on")


# ---------------------------------------------------------------------------
# recording helpers (lazy imports; everything best-effort — phase
# bookkeeping must never be what kills a healthy boot)
# ---------------------------------------------------------------------------

def _timeline(what: str, ms: Optional[float] = None, **meta) -> None:
    try:
        from h2o3_tpu.utils import timeline

        timeline.record("phase", what, ms=ms, **meta)
    except Exception:   # noqa: BLE001
        pass


def _metric(kind: str, name: str, *args, **labels) -> None:
    try:
        from h2o3_tpu.obs import metrics

        getattr(metrics, kind)(name, *args, **labels)
    except Exception:   # noqa: BLE001
        pass


def _bench_corpse(rec: dict, flight_path: Optional[str]) -> None:
    """One ``H2O3_FLIGHT_JSON`` line to stderr in bench contexts so the
    parent folds the wedged phase into the failing BENCH_STAGE record."""
    if not os.environ.get("H2O3_BENCH_STAGE_TIMEOUT_S"):
        return
    try:
        import json

        tail: List[dict] = []
        try:
            from h2o3_tpu.utils import timeline

            tail = timeline.events(20)
        except Exception:   # noqa: BLE001
            pass
        print("H2O3_FLIGHT_JSON " + json.dumps(
            {"flight_record": flight_path, "timeline_tail": tail,
             "phase": rec["phase"], "phase_report": phase_report()},
            default=str), file=sys.stderr, flush=True)
    except Exception:   # noqa: BLE001
        pass


def _on_deadline(rec: dict, fallback: Optional[Callable]) -> None:
    """Deadline expiry (timer thread): flight record naming the phase,
    metrics, the bench corpse line, the caller's fallback action, and —
    bench children only — the fast process exit that hands the budget to
    the CPU chain."""
    with _LOCK:
        if rec.get("status") != "running":
            return                      # phase won the race: completed
        rec["status"] = "deadline"
    name = rec["phase"]
    _timeline(name, status="deadline", deadline_s=rec.get("deadline_s"))
    _metric("inc", "h2o3_phase_deadline_exceeded_total", phase=name)
    path = None
    try:
        from h2o3_tpu.obs import flight

        path = flight.record_flight(
            f"phase_deadline_{name}",
            extra={"phase": name, "deadline_s": rec.get("deadline_s"),
                   "phase_history": history()})
        rec["flight_record"] = path
    except Exception:   # noqa: BLE001
        pass
    _bench_corpse(rec, path)
    if fallback is not None:
        try:
            _metric("inc", "h2o3_phase_cpu_fallbacks_total", phase=name)
            fallback(name)
        except Exception:   # noqa: BLE001 — the escape hatch must not
            pass            # add its own crash to the postmortem
    elif deadline_exit_enabled() and name in _EXIT_PHASES:
        _metric("inc", "h2o3_phase_cpu_fallbacks_total", phase=name)
        try:
            sys.stderr.flush()
            sys.stdout.flush()
        except Exception:   # noqa: BLE001
            pass
        os._exit(DEADLINE_EXIT_RC)


@contextlib.contextmanager
def enter(name: str, fallback: Optional[Callable] = None, **meta):
    """Enter a lifecycle phase. `name` must be one of :data:`PHASES`.
    `fallback(name)` runs on deadline expiry (tests pass the CPU-chain
    engagement; bench children instead use the process-exit escape).
    The ``phases.deadline`` faultpoint fakes a wedged phase body —
    sleeping past the configured deadline — so the expiry machinery is
    deterministically drivable without a real dead tunnel."""
    if name not in PHASES:
        raise ValueError(f"unknown phase {name!r}; the enumeration is "
                         f"closed: {sorted(PHASES)}")
    dl = deadlines().get(name)
    rec: Dict[str, Any] = {"phase": name, "start_ts": time.time(),
                           "status": "running", "ms": None,
                           "deadline_s": dl, "pid": os.getpid()}
    if meta:
        rec["meta"] = {str(k): v for k, v in meta.items()}
    with _LOCK:
        _HISTORY.append(rec)
    _timeline(name, status="begin", deadline_s=dl)
    _metric("set_gauge", "h2o3_phase_active", 1.0, phase=name)
    timer = None
    if dl:
        timer = threading.Timer(dl, _on_deadline, args=(rec, fallback))
        timer.daemon = True
        timer.start()
    wedged = False
    try:
        from h2o3_tpu.core import failure

        failure.faultpoint("phases.deadline")
    except Exception as e:   # noqa: BLE001 — InjectedFault == fake wedge
        wedged = type(e).__name__ == "InjectedFault"
    if wedged and dl:
        # simulate the wedge: hold the phase open until the deadline
        # machinery has demonstrably fired (flight record + fallback)
        time.sleep(dl + 0.25)
    t0 = time.perf_counter()
    try:
        from h2o3_tpu.obs import tracing

        span_cm = tracing.span("phase", phase=name)
    except Exception:   # noqa: BLE001
        span_cm = contextlib.nullcontext()
    try:
        with span_cm:
            yield rec
    except BaseException:
        with _LOCK:
            if rec["status"] == "running":
                rec["status"] = "error"
            rec["ms"] = round((time.perf_counter() - t0) * 1000, 3)
            _LATEST[name] = dict(rec)
        _timeline(name, ms=rec["ms"], status=rec["status"])
        _metric("set_gauge", "h2o3_phase_active", 0.0, phase=name)
        raise
    finally:
        if timer is not None:
            timer.cancel()
    with _LOCK:
        expired = rec["status"] == "deadline"
        if not expired:
            rec["status"] = "ok"
        rec["ms"] = round((time.perf_counter() - t0) * 1000, 3)
        _LATEST[name] = dict(rec)
    _timeline(name, ms=rec["ms"], status=rec["status"])
    _metric("set_gauge", "h2o3_phase_active", 0.0, phase=name)
    _metric("observe", "h2o3_phase_duration_seconds", rec["ms"] / 1000.0,
            phase=name)
    if not expired:
        _metric("inc", "h2o3_phase_completed_total", phase=name)


def history() -> List[dict]:
    """The phase record ring, oldest first (each: phase, start_ts, ms,
    status running|ok|deadline|error, deadline_s)."""
    with _LOCK:
        return [dict(r) for r in _HISTORY]


def phase_report() -> Dict[str, float]:
    """{phase: wall ms} of the most recent COMPLETED entry per phase, in
    lifecycle order — the bench aux-line / flight-record summary shape.
    Read from the per-phase latest store (not the bounded ring), so the
    boot durations survive long-lived processes."""
    with _LOCK:
        latest = {p: r["ms"] for p, r in _LATEST.items()
                  if r.get("ms") is not None}
    return {p: latest[p] for p in ORDER if p in latest}


def wedged_phase(grace_s: float = 120.0) -> Optional[str]:
    """Name of the oldest phase that never completed — deadline-expired
    with no completion time, or running PAST its deadline (or past
    `grace_s` when unsupervised). What a bench autopsy names as 'the
    phase that never completed'. A phase that is merely in progress is
    NOT wedged: a live /3/Runtime query racing a healthy boot must not
    report a wedge, so the unsupervised grace sits beyond the slowest
    healthy boot step (the bench deadline map tops out at
    first_compile=90 s); and one that blew its deadline but DID
    eventually finish keeps its 'deadline' verdict in history without
    reading as wedged forever."""
    now = time.time()
    for r in history():
        st = r.get("status")
        if st == "deadline" and r.get("ms") is None:
            return r["phase"]
        if st == "running":
            age = now - float(r.get("start_ts") or now)
            if age > float(r.get("deadline_s") or grace_s):
                return r["phase"]
    return None


def reset_for_tests() -> None:
    with _LOCK:
        _HISTORY.clear()
        _LATEST.clear()
