"""Flight recorder: crash/timeout postmortems that survive the process.

The flagship device bench metric has been dark since BENCH_r03 with
nothing to autopsy — a stage dies and all we keep is "timeout after 120s"
(ROADMAP open item 2). This module makes every abnormal exit leave a
corpse: on a fatal signal, a watchdog recovery action, a cloud FAILURE, or
a bench-stage timeout, the timeline ring + this thread's open spans + a
metrics snapshot persist ATOMICALLY (tmp + rename) to
``$H2O_TPU_OBS_FLIGHT_DIR`` (default ``$H2O_TPU_ICE_ROOT/flight``),
size-capped and self-GCing (``H2O_TPU_OBS_FLIGHT_KEEP`` newest kept).
``GET /3/FlightRecords`` lists and fetches them.

Import cost: stdlib only — a process whose accelerator tunnel is wedged
can still dump (the bench autopsy path depends on this)."""

from __future__ import annotations

import json
import os
import re
import signal
import threading
import time
from typing import Any, Dict, List, Optional

_NAME_RE = re.compile(r"^flight_[\w.\-]+\.json$")
_TIMELINE_CAP = 1000            # newest timeline events kept in a record
_MAX_BYTES = 2_000_000          # hard cap per record (events trimmed to fit)
_LOCK = threading.Lock()
_SIGNAL_HOOKS_INSTALLED = False


def flight_dir() -> str:
    d = os.environ.get("H2O_TPU_OBS_FLIGHT_DIR", "").strip()
    if not d:
        ice = os.environ.get("H2O_TPU_ICE_ROOT", "/tmp/h2o3_tpu")
        d = os.path.join(ice, "flight")
    return d


def keep_records() -> int:
    try:
        return max(int(os.environ.get("H2O_TPU_OBS_FLIGHT_KEEP", "20")), 1)
    except ValueError:
        return 20


def _safe_process_index() -> Optional[int]:
    """Process index WITHOUT ever triggering (or blocking on) jax backend
    init: the recorder's primary scenario is a process wedged exactly
    there, and ``jax.process_index()`` would hang on the init lock rather
    than raise. Only consult jax when a backend is ALREADY up; fall back
    to the bootstrap env."""
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            from jax._src import xla_bridge as xb

            if getattr(xb, "_backends", None):
                return int(jax.process_index())
        except Exception:   # noqa: BLE001 — private-API drift = fall back
            pass
    try:
        return int(os.environ.get("H2O_TPU_PROCESS_ID", "") or 0) \
            if os.environ.get("H2O_TPU_PROCESS_ID") else None
    except ValueError:
        return None


def _payload(reason: str, extra: Optional[Dict[str, Any]]) -> dict:
    """Assemble the record; every section is individually best-effort so a
    half-broken process still dumps what it can. Nothing here may trigger
    jax backend init (see _safe_process_index)."""
    out: Dict[str, Any] = {"reason": str(reason), "ts": time.time(),
                           "pid": os.getpid(),
                           "process_index": _safe_process_index()}
    try:
        from h2o3_tpu.utils import timeline

        out["timeline"] = timeline.events(_TIMELINE_CAP)
    except Exception:   # noqa: BLE001
        out["timeline"] = []
    try:
        from h2o3_tpu.obs import tracing

        out["open_spans"] = tracing.open_spans()
        out["recent_traces"] = tracing.recent_traces(10)
    except Exception:   # noqa: BLE001
        out["open_spans"] = []
    try:
        from h2o3_tpu.obs import metrics

        out["metrics"] = metrics.REGISTRY.snapshot()
    except Exception:   # noqa: BLE001
        out["metrics"] = []
    if extra:
        out["extra"] = extra
    return out


def record_flight(reason: str,
                  extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Persist one flight record; returns its path (None when even the
    dump failed — the recorder never raises)."""
    try:
        payload = _payload(reason, extra)
        body = json.dumps(payload, default=str)
        while len(body) > _MAX_BYTES and payload["timeline"]:
            # trim oldest events until the record fits the size cap
            payload["timeline"] = payload["timeline"][
                len(payload["timeline"]) // 2:]
            payload["truncated"] = True
            body = json.dumps(payload, default=str)
        d = flight_dir()
        os.makedirs(d, exist_ok=True)
        safe = re.sub(r"[^\w.\-]", "_", str(reason))[:64]
        name = (f"flight_{time.strftime('%Y%m%d_%H%M%S')}"
                f"_{safe}_{os.getpid()}.json")
        path = os.path.join(d, name)
        tmp = f"{path}.{os.getpid()}.part"
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, path)
        _gc(d)
    except Exception:   # noqa: BLE001 — postmortem must not crash the
        return None     # process it is autopsying
    try:
        from h2o3_tpu.obs import metrics
        from h2o3_tpu.utils import timeline

        metrics.inc("h2o3_flight_records_total")
        timeline.record("flight", str(reason), path=path)
    except Exception:   # noqa: BLE001
        pass
    return path


def _gc(d: str) -> None:
    with _LOCK:
        try:
            names = sorted(n for n in os.listdir(d) if _NAME_RE.match(n))
        except OSError:
            return
        for n in names[: max(len(names) - keep_records(), 0)]:
            try:
                os.remove(os.path.join(d, n))
            except OSError:
                pass


def list_records() -> List[dict]:
    d = flight_dir()
    out = []
    try:
        names = [n for n in os.listdir(d) if _NAME_RE.match(n)]
    except OSError:
        return []
    for n in sorted(names, reverse=True):
        p = os.path.join(d, n)
        try:
            st = os.stat(p)
        except OSError:
            continue
        # flight_{YYYYmmdd_HHMMSS}_{reason}_{pid}.json
        m = re.match(r"^flight_\d{8}_\d{6}_(.+)_(\d+)\.json$", n)
        out.append({"name": n, "bytes": st.st_size,
                    "mtime": st.st_mtime,
                    "reason": m.group(1) if m else None,
                    "pid": int(m.group(2)) if m else None})
    return out


def read_record(name: str) -> Optional[bytes]:
    """Raw JSON bytes of one record; None for unknown/unsafe names (the
    pattern check is the path-traversal gate)."""
    if not _NAME_RE.match(name or ""):
        return None
    try:
        with open(os.path.join(flight_dir(), name), "rb") as f:
            return f.read()
    except OSError:
        return None


# ---------------------------------------------------------------------------
# fatal-signal hooks (main thread only; H2O_TPU_OBS_SIGNALS=0 disables)
# ---------------------------------------------------------------------------

def signals_enabled() -> bool:
    return os.environ.get("H2O_TPU_OBS_SIGNALS", "1").lower() not in (
        "0", "false", "off")


def install_signal_hooks() -> bool:
    """Chain a flight dump in front of SIGTERM/SIGQUIT, then re-deliver
    the default action — so an external kill (k8s eviction, a driver
    timeout that TERMs before KILLing) leaves a record. Idempotent;
    False when disabled or not callable from this (non-main) thread.

    Deadlock discipline: the interrupted main-thread frame may hold any
    of the locks the dump needs (timeline/metric/span stores), so the
    handler must not run record_flight inline. It restores SIG_DFL
    FIRST (a second signal always kills), runs the dump on a side thread
    with a bounded join, then re-raises — worst case a wedged dump
    delays death by the join timeout, never forever."""
    global _SIGNAL_HOOKS_INSTALLED
    if not signals_enabled() or _SIGNAL_HOOKS_INSTALLED:
        return _SIGNAL_HOOKS_INSTALLED

    def handler(signum, frame):
        signal.signal(signum, signal.SIG_DFL)
        t = threading.Thread(
            target=record_flight,
            args=(f"signal_{signal.Signals(signum).name}",), daemon=True)
        t.start()
        t.join(timeout=5.0)
        signal.raise_signal(signum)

    try:
        for sig in (signal.SIGTERM, signal.SIGQUIT):
            signal.signal(sig, handler)
    except (ValueError, OSError):       # not the main thread / no signals
        return False
    _SIGNAL_HOOKS_INSTALLED = True
    return True
