"""Trace spans with cross-process context propagation.

Reference: H2O-3's TimeLine ring records per-node wire events but has no
request identity — you cannot follow one REST call through the cloud. Here
a trace id is minted at REST ingress (api/server.py wraps every handler in
a root span), rides the oplog op record (``parallel/oplog.py`` attaches
``{"trace": {trace_id, span_id}}`` to ``publish``), and the follower's
replay + ack land as children of the coordinator's publish span — so
coordinator publish → follower replay → ack form ONE span tree,
retrievable from ``GET /3/Trace/{trace_id}``.

The scoring fast path emits child spans for queue-wait / pack / dispatch /
blocking-fetch. None of them adds a device synchronization: span timing is
host wall-clock around calls the path already makes (the fused-path
``gathered_rows``/compile counters assert the path itself is unchanged —
see tests).

Cost model: ``span()`` is a no-op (no allocation, no store write) unless
the calling thread has an ACTIVE trace — library-mode predict() pays one
thread-local read. The store is bounded (``H2O_TPU_OBS_TRACE_CAP`` traces
× ``_SPAN_CAP`` spans, oldest trace evicted) and follower-side spans from
replayed ops additionally publish to the cloud KV (bounded, self-GCing)
so the coordinator can serve the full tree."""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_SPAN_CAP = 512                 # spans kept per trace
_KV_PREFIX = "obs/span/"
_KV_KEEP = 512                  # remote-published span keys kept in the KV

_TLS = threading.local()        # .stack: list of active span dicts
_LOCK = threading.Lock()
# trace_id -> list of finished span dicts (insertion-ordered eviction)
_STORE: "collections.OrderedDict[str, List[dict]]" = collections.OrderedDict()
_PUBLISHED: "collections.deque[str]" = collections.deque()


def trace_cap() -> int:
    try:
        return max(int(os.environ.get("H2O_TPU_OBS_TRACE_CAP", "256")), 1)
    except ValueError:
        return 256


def _now_ms() -> float:
    return time.time() * 1000.0


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def current() -> Optional[dict]:
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


def current_trace_id() -> Optional[str]:
    cur = current()
    return cur["trace_id"] if cur else None


def context() -> Optional[Dict[str, str]]:
    """The active span as a propagation context ({trace_id, span_id}) —
    what rides the oplog op record and the micro-batcher's entries."""
    cur = current()
    if cur is None:
        return None
    return {"trace_id": cur["trace_id"], "span_id": cur["span_id"]}


def _proc_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:   # noqa: BLE001 — pre-init
        return 0


def _store(span: dict) -> None:
    """Bounded-store insert (oldest trace evicted) + the span counter —
    the single copy both the context-manager finish path and the
    explicitly-timed record_span path go through."""
    tid = span["trace_id"]
    with _LOCK:
        spans = _STORE.get(tid)
        if spans is None:
            spans = _STORE[tid] = []
            while len(_STORE) > trace_cap():
                _STORE.popitem(last=False)
        if len(spans) < _SPAN_CAP:
            spans.append(span)
    from h2o3_tpu.obs import metrics

    metrics.inc("h2o3_trace_spans_total")


def _finish(span: dict) -> None:
    span["end_ms"] = round(_now_ms(), 3)
    span["ms"] = round(span["end_ms"] - span["start_ms"], 3)
    _store(span)


def _kv_publish(span: dict) -> None:
    """Ship a finished follower-side span to the cloud KV so the
    coordinator's ``/3/Trace/{id}`` can merge it; bounded self-GC."""
    from h2o3_tpu.parallel import distributed as D

    key = f"{_KV_PREFIX}{span['trace_id']}/{span['proc']}_{span['span_id']}"
    try:
        if not D.kv_put(key, json.dumps(span)):
            return
    except Exception:   # noqa: BLE001 — best-effort by contract
        return
    expired = []
    with _LOCK:
        _PUBLISHED.append(key)
        while len(_PUBLISHED) > _KV_KEEP:
            expired.append(_PUBLISHED.popleft())
    # KV round-trips stay OUTSIDE the span-store lock: a slow delete must
    # not stall span recording on every other thread
    for old in expired:
        try:
            D.kv_delete(old)
        except Exception:   # noqa: BLE001
            pass


def _new_span(name: str, trace_id: str, parent_id: Optional[str],
              attrs: Dict[str, Any]) -> dict:
    return {"trace_id": trace_id, "span_id": uuid.uuid4().hex[:12],
            "parent_id": parent_id, "name": name,
            "proc": _proc_index(), "start_ms": round(_now_ms(), 3),
            "status": "ok",
            "attrs": {k: v for k, v in attrs.items() if v is not None}}


class _SpanCtx:
    """Context manager over one span; ``None``-like when tracing is
    inactive (``bool(span_cm)`` is False and ``ctx()`` returns None)."""

    __slots__ = ("span",)

    def __init__(self, span: Optional[dict]):
        self.span = span

    def __bool__(self):
        return self.span is not None

    def ctx(self) -> Optional[Dict[str, str]]:
        if self.span is None:
            return None
        return {"trace_id": self.span["trace_id"],
                "span_id": self.span["span_id"]}

    def set(self, **attrs) -> None:
        if self.span is not None:
            self.span["attrs"].update(attrs)

    def __enter__(self):
        if self.span is not None:
            _stack().append(self.span)
        return self

    def __exit__(self, et, ev, tb):
        if self.span is None:
            return False
        st = _stack()
        if st and st[-1] is self.span:
            st.pop()
        if et is not None:
            self.span["status"] = "error"
            self.span["attrs"]["error"] = f"{et.__name__}: {ev}"[:500]
        _finish(self.span)
        return False


def root_span(name: str, **attrs) -> _SpanCtx:
    """Mint a new trace (REST ingress). Always records."""
    return _SpanCtx(_new_span(name, uuid.uuid4().hex[:16], None, attrs))


def span(name: str, **attrs) -> _SpanCtx:
    """Child of the calling thread's active span; inert no-op when no
    trace is active (the library-mode fast path pays one TLS read)."""
    cur = current()
    if cur is None:
        return _SpanCtx(None)
    return _SpanCtx(_new_span(name, cur["trace_id"], cur["span_id"], attrs))


class activate:
    """Adopt a propagation context on THIS thread (the micro-batcher's
    flush leader runs on a different thread than the submitting request):
    nested ``span()`` calls attach under `ctx`. No-op for a None ctx."""

    def __init__(self, ctx: Optional[Dict[str, str]]):
        self._ok = isinstance(ctx, dict) and bool(ctx.get("trace_id"))
        self._frame = ({"trace_id": str(ctx["trace_id"]),
                        "span_id": ctx.get("span_id")} if self._ok else None)

    def __enter__(self):
        if self._ok:
            _stack().append(self._frame)
        return self

    def __exit__(self, et, ev, tb):
        if self._ok:
            st = _stack()
            if st and st[-1] is self._frame:
                st.pop()
        return False


def record_span(name: str, ctx: Optional[Dict[str, str]], start_ms: float,
                end_ms: Optional[float] = None, publish: bool = False,
                status: str = "ok", **attrs) -> Optional[dict]:
    """Append an already-timed span (explicit wall-clock ms timestamps)
    under `ctx`, returning it — the queue-wait span is recorded by the
    flush leader on behalf of each waiting request's trace, and the
    follower's replay/ack spans are recorded AFTER the ack (with
    `publish=True` so they cross the KV to the trace's home process)."""
    if not isinstance(ctx, dict) or not ctx.get("trace_id"):
        return None
    sp = _new_span(name, str(ctx["trace_id"]), ctx.get("span_id"), attrs)
    sp["status"] = status
    sp["start_ms"] = round(float(start_ms), 3)
    sp["end_ms"] = round(float(end_ms if end_ms is not None
                               else _now_ms()), 3)
    sp["ms"] = round(sp["end_ms"] - sp["start_ms"], 3)
    _store(sp)
    if publish:
        _kv_publish(sp)
    return sp


def get_trace(trace_id: str, include_remote: bool = True) -> List[dict]:
    """Every finished span recorded for `trace_id`: local store + (on a
    cloud) the KV-published follower spans, start-ordered."""
    with _LOCK:
        spans = list(_STORE.get(trace_id, ()))
    if include_remote:
        from h2o3_tpu.parallel import distributed as D

        seen = {s["span_id"] for s in spans}
        for _k, v in D.kv_dir(f"{_KV_PREFIX}{trace_id}/"):
            try:
                sp = json.loads(v)
            except (ValueError, TypeError):
                continue
            if isinstance(sp, dict) and sp.get("span_id") not in seen:
                spans.append(sp)
    return sorted(spans, key=lambda s: s.get("start_ms", 0.0))


def span_tree(spans: List[dict]) -> List[dict]:
    """Nest spans by parent_id: [{**span, children: [...]}] roots. Spans
    whose parent never finished (open at dump time) surface as roots."""
    nodes = {s["span_id"]: dict(s, children=[]) for s in spans}
    roots = []
    for s in spans:
        node = nodes[s["span_id"]]
        parent = nodes.get(s.get("parent_id"))
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots


def recent_traces(n: int = 50) -> List[dict]:
    """Newest trace ids with their root span names (for GET /3/Trace)."""
    with _LOCK:
        items = list(_STORE.items())[-n:]
    out = []
    for tid, spans in reversed(items):
        root = next((s for s in spans if not s.get("parent_id")), None)
        out.append({"trace_id": tid, "spans": len(spans),
                    "root": (root or {}).get("name"),
                    "start_ms": min((s.get("start_ms", 0.0) for s in spans),
                                    default=0.0)})
    return out


def open_spans() -> List[dict]:
    """The calling thread's active (unfinished) spans — flight-recorder
    fodder. Cross-thread open spans are not visible by design (no global
    registry of live stacks; the store holds everything finished)."""
    return [dict(s) for s in getattr(_TLS, "stack", [])]


def clear() -> None:
    """Drop the span store (tests)."""
    with _LOCK:
        _STORE.clear()
