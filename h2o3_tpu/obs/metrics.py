"""Process-wide metrics registry + cluster-wide aggregation.

Reference: H2O-3's WaterMeter family (water/util/WaterMeterCpuTicks etc.)
exposes per-node counters over REST; the Gemma-on-TPU serving comparison
(PAPERS.md) makes the case that serving-tier decisions stand or fall on
these series. This module gives the reproduction one registry every
subsystem's ad-hoc counters re-register onto, and one cluster-wide
``GET /3/Metrics`` the coordinator serves in both Prometheus text
exposition (``text/plain; version=0.0.4``) and JSON.

Design:

- **One registration site.** Every metric is registered exactly once, in
  :func:`_install_default_metrics` below — names must match
  ``^h2o3_[a-z0-9_]+$`` (tests/test_consistency.py guards both
  properties). Producers either increment by name (:func:`inc`,
  :func:`observe`) or are read at snapshot time through a collector
  callback (the existing counters in scoring.py, admission.py,
  artifact/compile_cache.py, core/sharded_frame.py, parallel/oplog.py
  stay the source of truth; the callbacks lazily import them so this
  module never pulls the heavy stack at import).
- **Bounded label sets.** A metric stores at most ``_LABEL_CAP`` distinct
  label-value tuples; overflow lands on a single ``{"overflow": "true"}``
  sample so a cardinality bug degrades one series, not the scrape.
- **Cluster aggregation through the KV.** Every process publishes its
  snapshot under ``obs/metrics/{proc}`` (follower replay loop + watchdog
  ticks keep it fresh, throttled by ``H2O_TPU_OBS_PUBLISH_S``); the
  coordinator merges its own LIVE snapshot with the other processes'
  published ones — counters and histograms sum, gauges aggregate by
  their declared ``agg`` ("sum" default, "max" for e.g. uptime).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

NAME_RE = re.compile(r"^h2o3_[a-z0-9_]+$")

_LABEL_CAP = 32           # distinct label tuples per metric
_OVERFLOW_LABELS = (("overflow", "true"),)

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0)


def _publish_interval_s() -> float:
    try:
        return max(float(os.environ.get("H2O_TPU_OBS_PUBLISH_S", "2")), 0.0)
    except ValueError:
        return 2.0


class Metric:
    """One registered series: a direct counter/gauge (incremented /set by
    name), a histogram, or a callback-collected series whose values are
    read from their owning module at snapshot time."""

    __slots__ = ("name", "mtype", "help", "agg", "labels", "buckets",
                 "_values", "_hist", "_fn", "_lock")

    def __init__(self, name: str, mtype: str, help_: str, agg: str = "sum",
                 fn: Optional[Callable] = None,
                 buckets: Tuple[float, ...] = _DEFAULT_BUCKETS):
        if not NAME_RE.match(name):
            raise ValueError(f"metric name {name!r} must match "
                             f"{NAME_RE.pattern}")
        self.name = name
        self.mtype = mtype           # counter | gauge | histogram
        self.help = help_
        self.agg = agg               # gauges: sum | max
        self.buckets = tuple(sorted(buckets))
        self._values: Dict[tuple, float] = {}
        self._hist: Dict[tuple, List] = {}   # labels -> [counts..., sum, n]
        self._fn = fn
        self._lock = threading.Lock()

    def _label_key(self, labels: Dict[str, str], store) -> tuple:
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        if key not in store and len(store) >= _LABEL_CAP:
            return _OVERFLOW_LABELS
        return key

    def inc(self, n: float = 1.0, **labels) -> None:
        with self._lock:
            key = self._label_key(labels, self._values)
            self._values[key] = self._values.get(key, 0.0) + float(n)

    def set(self, v: float, **labels) -> None:
        with self._lock:
            key = self._label_key(labels, self._values)
            self._values[key] = float(v)

    def observe(self, v: float, **labels) -> None:
        v = float(v)
        with self._lock:
            key = self._label_key(labels, self._hist)
            h = self._hist.get(key)
            if h is None:
                h = self._hist[key] = [0] * len(self.buckets) + [0.0, 0]
            for i, le in enumerate(self.buckets):
                if v <= le:
                    h[i] += 1
            h[-2] += v
            h[-1] += 1

    def snapshot(self) -> dict:
        out = {"name": self.name, "type": self.mtype, "help": self.help,
               "agg": self.agg}
        if self.mtype == "histogram":
            with self._lock:
                out["buckets"] = list(self.buckets)
                out["samples"] = [
                    {"labels": dict(k), "bucket_counts": list(h[:-2]),
                     "sum": h[-2], "count": h[-1]}
                    for k, h in self._hist.items()]
            return out
        samples: List[dict] = []
        if self._fn is not None:
            try:
                got = self._fn()
            except Exception:   # noqa: BLE001 — one broken collector must
                got = None      # never break the whole scrape
            if isinstance(got, dict):
                samples = [{"labels": dict(k) if isinstance(k, tuple) else {},
                            "value": float(v)} for k, v in got.items()]
            elif got is not None:
                samples = [{"labels": {}, "value": float(got)}]
        else:
            with self._lock:
                samples = [{"labels": dict(k), "value": v}
                           for k, v in self._values.items()]
            if not samples and self.mtype in ("counter", "gauge"):
                samples = [{"labels": {}, "value": 0.0}]
        out["samples"] = samples
        return out


class Registry:
    """Named metrics; registering the same name twice raises (the
    consistency suite additionally guards the source for drift)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _register(self, m: Metric) -> Metric:
        with self._lock:
            if m.name in self._metrics:
                raise ValueError(f"metric {m.name!r} is already registered")
            self._metrics[m.name] = m
        return m

    def counter(self, name: str, help_: str) -> Metric:
        return self._register(Metric(name, "counter", help_))

    def gauge(self, name: str, help_: str, agg: str = "sum") -> Metric:
        return self._register(Metric(name, "gauge", help_, agg=agg))

    def histogram(self, name: str, help_: str,
                  buckets: Tuple[float, ...] = _DEFAULT_BUCKETS) -> Metric:
        return self._register(Metric(name, "histogram", help_,
                                     buckets=buckets))

    def counter_fn(self, name: str, help_: str, fn: Callable) -> Metric:
        return self._register(Metric(name, "counter", help_, fn=fn))

    def gauge_fn(self, name: str, help_: str, fn: Callable,
                 agg: str = "sum") -> Metric:
        return self._register(Metric(name, "gauge", help_, agg=agg, fn=fn))

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> List[dict]:
        with self._lock:
            ms = list(self._metrics.values())
        return [m.snapshot() for m in ms]


REGISTRY = Registry()


# -- producer-facing helpers (never raise: observability must not take the
#    serving path down) ------------------------------------------------------

def inc(name: str, n: float = 1.0, **labels) -> None:
    m = REGISTRY.get(name)
    if m is not None:
        m.inc(n, **labels)


def set_gauge(name: str, v: float, **labels) -> None:
    m = REGISTRY.get(name)
    if m is not None:
        m.set(v, **labels)


def observe(name: str, v: float, **labels) -> None:
    m = REGISTRY.get(name)
    if m is not None:
        m.observe(v, **labels)


# ---------------------------------------------------------------------------
# cluster aggregation (per-process snapshots through the cloud KV)
# ---------------------------------------------------------------------------

_KV_PREFIX = "obs/metrics/"
_PUB_LOCK = threading.Lock()
_LAST_PUBLISH = 0.0


def _proc_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:   # noqa: BLE001 — pre-init / wedged backend
        return 0


def publish_snapshot(proc: Optional[int] = None) -> bool:
    """Publish this process's snapshot under ``obs/metrics/{proc}`` (the
    coordinator merges them into the cluster view). False when there is no
    cloud KV to publish into."""
    from h2o3_tpu.parallel import distributed as D

    p = _proc_index() if proc is None else int(proc)
    try:
        return D.kv_put(_KV_PREFIX + str(p),
                        json.dumps({"proc": p, "ts": time.time(),
                                    "metrics": REGISTRY.snapshot()}))
    except Exception:   # noqa: BLE001 — best-effort by contract
        return False


def maybe_publish() -> None:
    """Throttled publish (``H2O_TPU_OBS_PUBLISH_S`` between writes) —
    called from the hot-ish paths that keep follower snapshots fresh
    (op replay, watchdog ticks). The /3/Runtime contribution (phase
    history + compile ledger) rides the same throttle."""
    global _LAST_PUBLISH
    now = time.monotonic()
    with _PUB_LOCK:
        if now - _LAST_PUBLISH < _publish_interval_s():
            return
        _LAST_PUBLISH = now
    publish_snapshot()
    try:
        from h2o3_tpu.obs import compiles

        compiles.publish_runtime()
    except Exception:   # noqa: BLE001 — best-effort by contract
        pass


def cluster_snapshots() -> List[dict]:
    """This process's LIVE snapshot + every OTHER process's KV-published
    one, as [{proc, ts, metrics}]."""
    from h2o3_tpu.parallel import distributed as D

    me = _proc_index()
    out = [{"proc": me, "ts": time.time(), "metrics": REGISTRY.snapshot()}]
    for _k, v in D.kv_dir(_KV_PREFIX):
        try:
            rec = json.loads(v)
        except (ValueError, TypeError):
            continue
        if not isinstance(rec, dict) or rec.get("proc") == me:
            continue
        out.append(rec)
    return out


def aggregate(snaps: List[dict]) -> List[dict]:
    """Merge per-process snapshots into cluster series: counters and
    histograms sum; gauges follow their declared agg (sum/max)."""
    merged: Dict[str, dict] = {}
    for snap in snaps:
        for m in snap.get("metrics", []):
            name = m.get("name")
            if not name:
                continue
            agg = merged.get(name)
            if agg is None:
                agg = merged[name] = {"name": name, "type": m.get("type"),
                                      "help": m.get("help", ""),
                                      "agg": m.get("agg", "sum"),
                                      "buckets": m.get("buckets"),
                                      "_samples": {}}
            for s in m.get("samples", []):
                key = tuple(sorted((str(k), str(v))
                            for k, v in (s.get("labels") or {}).items()))
                cur = agg["_samples"].get(key)
                if agg["type"] == "histogram":
                    if cur is None:
                        agg["_samples"][key] = {
                            "labels": dict(key),
                            "bucket_counts": list(s.get("bucket_counts", [])),
                            "sum": float(s.get("sum", 0.0)),
                            "count": int(s.get("count", 0))}
                    else:
                        bc = s.get("bucket_counts", [])
                        cur["bucket_counts"] = [
                            a + b for a, b in zip(cur["bucket_counts"], bc)
                        ] if cur["bucket_counts"] else list(bc)
                        cur["sum"] += float(s.get("sum", 0.0))
                        cur["count"] += int(s.get("count", 0))
                else:
                    v = float(s.get("value", 0.0))
                    if cur is None:
                        agg["_samples"][key] = {"labels": dict(key),
                                                "value": v}
                    elif agg["type"] == "gauge" and agg["agg"] == "max":
                        cur["value"] = max(cur["value"], v)
                    else:
                        cur["value"] += v
    out = []
    for name in sorted(merged):
        m = merged[name]
        m["samples"] = list(m.pop("_samples").values())
        out.append(m)
    return out


def cluster_aggregate() -> List[dict]:
    return aggregate(cluster_snapshots())


def histogram_quantiles(buckets: List[float], bucket_counts: List[int],
                        count: int,
                        qs: Tuple[float, ...] = (0.5, 0.95, 0.99)
                        ) -> Dict[str, Optional[float]]:
    """Estimated quantiles from cumulative bucket counts (the standard
    histogram_quantile linear interpolation within the owning bucket;
    targets past the last finite bucket report that bucket's bound, the
    Prometheus convention). ``/3/Metrics?format=json`` attaches these so
    JSON consumers get p50/p95/p99 without re-deriving them from raw
    bucket counts."""
    out: Dict[str, Optional[float]] = {}
    total = int(count)
    for q in qs:
        key = f"p{int(q * 100)}"
        if total <= 0 or not buckets:
            out[key] = None
            continue
        target = q * total
        val: Optional[float] = None
        prev_cum = 0
        for i, (le, cum) in enumerate(zip(buckets, bucket_counts)):
            if cum >= target:
                lo = buckets[i - 1] if i > 0 else 0.0
                in_bucket = cum - prev_cum
                frac = ((target - prev_cum) / in_bucket) if in_bucket else 1.0
                val = lo + (le - lo) * frac
                break
            prev_cum = cum
        if val is None:
            # target lands in the +Inf bucket
            val = float(buckets[-1])
        out[key] = round(val, 6)
    return out


# ---------------------------------------------------------------------------
# Prometheus text exposition (format 0.0.4)
# ---------------------------------------------------------------------------

def _esc_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n",
                                                                   r"\n")


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_esc_label(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text(series: List[dict]) -> str:
    lines: List[str] = []
    for m in series:
        name, mtype = m["name"], m.get("type", "gauge")
        lines.append(f"# HELP {name} {m.get('help', '')}")
        lines.append(f"# TYPE {name} {mtype}")
        for s in m.get("samples", []):
            labels = s.get("labels") or {}
            if mtype == "histogram":
                for le, c in zip(m.get("buckets") or [],
                                 s.get("bucket_counts", [])):
                    # bucket counts are already cumulative
                    le_lab = 'le="%s"' % le
                    lines.append(f"{name}_bucket"
                                 f"{_label_str(labels, le_lab)} {_fmt(c)}")
                inf_lab = 'le="+Inf"'
                lines.append(f"{name}_bucket{_label_str(labels, inf_lab)} "
                             f"{_fmt(s.get('count', 0))}")
                lines.append(f"{name}_sum{_label_str(labels)} "
                             f"{_fmt(s.get('sum', 0.0))}")
                lines.append(f"{name}_count{_label_str(labels)} "
                             f"{_fmt(s.get('count', 0))}")
            else:
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_fmt(s.get('value', 0.0))}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# default metric set — THE single registration site (consistency-guarded):
# the ad-hoc counters that predate this registry (scoring, admission,
# compile cache, data plane, oplog, supervisor, watchdog) re-register here
# as collector callbacks; their modules stay the source of truth and are
# imported lazily at snapshot time.
# ---------------------------------------------------------------------------

_START_TS = time.time()


def _scoring_field(field: str) -> float:
    from h2o3_tpu import scoring

    return float(sum(e.get(field, 0) for e in scoring.metrics_snapshot()))


def _install_default_metrics() -> None:
    r = REGISTRY

    # -- direct counters/histograms (incremented by name at the source) --
    r.counter("h2o3_rest_requests_total",
              "REST requests served, by status class")
    r.histogram("h2o3_rest_request_seconds",
                "REST request wall time (seconds)")
    r.counter("h2o3_trace_spans_total", "trace spans recorded")
    r.counter("h2o3_flight_records_total", "flight records written")
    r.counter("h2o3_oplog_ops_published_total",
              "oplog ops published by this coordinator")
    r.counter("h2o3_oplog_ops_replayed_total",
              "oplog ops replayed by this follower")
    r.counter("h2o3_oplog_errors_total",
              "follower-side oplog error records written")
    r.counter("h2o3_oplog_rejoins_total", "successful rejoin() readmissions")
    r.counter("h2o3_cloud_transitions_total",
              "cloud health state transitions, by target state")
    r.counter("h2o3_tree_trees_built_total",
              "trees built across all forest trainers")
    r.counter("h2o3_log_messages_total",
              "framework log records, by level (warning and up)")

    # -- lifecycle phase tracker (obs/phases.py) --
    r.gauge("h2o3_phase_active",
            "1 while the labeled lifecycle phase is in progress")
    r.histogram("h2o3_phase_duration_seconds",
                "lifecycle phase wall time (backend_init .. server_start)")
    r.counter("h2o3_phase_completed_total",
              "lifecycle phases completed inside their deadline, by phase")
    r.counter("h2o3_phase_deadline_exceeded_total",
              "lifecycle phase hard-deadline expiries, by phase")
    r.counter("h2o3_phase_cpu_fallbacks_total",
              "deadline expiries that engaged the CPU-chain fallback")

    # -- collector-backed series (existing ad-hoc counters re-registered) --
    def _dp(field):
        def fn():
            from h2o3_tpu.core import sharded_frame

            return float(sharded_frame.counters()[field])
        return fn

    r.counter_fn("h2o3_data_plane_packed_rows_total",
                 "rows packed shard-locally (no host round-trip)",
                 _dp("packed_rows"))
    r.counter_fn("h2o3_data_plane_device_sorted_rows_total",
                 "rows ordered by device sorts whose permutation never "
                 "crossed to the host", _dp("device_sorted_rows"))
    r.counter_fn("h2o3_data_plane_gathered_rows_total",
                 "rows whose columns were gathered to this host "
                 "(exceptional path)", _dp("gathered_rows"))

    # -- chunked sharded ingest (ingest/chunked.py, ISSUE 15): the
    #    coordinator-bytes counter is the ingest-side gathered_rows analog --
    def _ing(field):
        def fn():
            from h2o3_tpu.ingest import chunked

            return float(chunked.counters()[field])
        return fn

    r.counter_fn("h2o3_ingest_chunks_total",
                 "byte-range chunks parsed by this process", _ing("chunks"))
    r.counter_fn("h2o3_ingest_chunk_rows_total",
                 "rows ingested through the chunked sharded parse path",
                 _ing("chunk_rows"))
    r.counter_fn("h2o3_ingest_coordinator_bytes_total",
                 "ingest bytes staged as whole-column host buffers: the "
                 "legacy/fallback paths, plus T_TIME columns (column-wide "
                 "datetime inference) — 0 on the chunked path otherwise",
                 _ing("coordinator_ingest_bytes"))
    r.counter_fn("h2o3_ingest_stream_appends_total",
                 "streaming micro-batch appends (POST /3/ParseStream)",
                 _ing("stream_appends"))
    r.counter_fn("h2o3_ingest_stream_rows_total",
                 "rows appended through the streaming shard-tail path",
                 _ing("stream_rows"))
    r.gauge_fn("h2o3_ingest_overlap_ratio",
               "fraction of aggregate split/parse/resolve/ship seconds "
               "hidden by pipelining (multi-core parse + async H2D) in "
               "the last chunked parse", _ing("overlap_ratio"), agg="max")
    r.histogram("h2o3_ingest_parse_seconds",
                "per-chunk parse wall time (seconds)")

    r.counter_fn("h2o3_scoring_requests_total",
                 "fused-path scoring requests",
                 lambda: _scoring_field("requests"))
    r.counter_fn("h2o3_scoring_batches_total",
                 "coalesced scoring batches dispatched",
                 lambda: _scoring_field("batches"))
    r.counter_fn("h2o3_scoring_rows_total", "rows scored on the fused path",
                 lambda: _scoring_field("rows"))
    r.counter_fn("h2o3_scoring_fused_compiles_total",
                 "fused traversal XLA compiles across live sessions",
                 lambda: _scoring_field("fused_compiles"))
    r.counter_fn("h2o3_scoring_compile_cache_hits_total",
                 "fused executables served from the persistent cache",
                 lambda: _scoring_field("compile_cache_hits"))

    # -- per-flush dispatch accounting (ISSUE 13): the one-fused-dispatch-
    #    per-flush contract is observable, by path label --
    def _score_dispatches():
        from h2o3_tpu import scoring

        return {(("path", p),): float(n)
                for p, n in scoring.dispatch_counters().items()}

    r.counter_fn("h2o3_score_dispatches_total",
                 "fused program executions on the serving/explainability "
                 "paths, by path", _score_dispatches)
    r.histogram("h2o3_score_flush_requests",
                "requests coalesced per micro-batch flush",
                buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
    r.histogram("h2o3_score_request_seconds",
                "fused-path request latency (admission + batching + "
                "dispatch), by model — the SLO-adaptive admission signal")

    def _rapids(field):
        def fn():
            from h2o3_tpu.rapids import fusion

            return float(fusion.counters()[field])
        return fn

    r.counter_fn("h2o3_rapids_statements_total",
                 "rapids statements executed", _rapids("statements"))
    r.counter_fn("h2o3_rapids_fused_statements_total",
                 "statements that ran at least one fused program",
                 _rapids("fused_statements"))
    r.counter_fn("h2o3_rapids_fused_programs_total",
                 "fused rapids program executions", _rapids("fused_programs"))
    r.counter_fn("h2o3_rapids_fused_programs_compiled_total",
                 "fused rapids programs actually XLA-compiled",
                 _rapids("fused_programs_compiled"))
    r.counter_fn("h2o3_rapids_compile_cache_hits_total",
                 "fused rapids programs served warm (signature or disk "
                 "tier)", _rapids("compile_cache_hits"))
    r.counter_fn("h2o3_rapids_barrier_fallbacks_total",
                 "host-fallback prim executions (the exceptional path)",
                 _rapids("barrier_fallbacks"))
    r.counter_fn("h2o3_rapids_host_materialized_cells_total",
                 "cells staged on host by host-fallback prims",
                 _rapids("host_materialized_cells"))
    r.counter_fn("h2o3_rapids_fused_rows_total",
                 "logical rows through fused rapids programs",
                 _rapids("fused_rows"))
    r.histogram("h2o3_rapids_statement_seconds",
                "rapids statement wall time over POST /99/Rapids (seconds)")

    # -- lazy-session planner (cross-statement DAG, rapids/planner.py) --
    def _lazy(field):
        def fn():
            from h2o3_tpu.rapids import planner

            return float(planner.counters()[field])
        return fn

    r.counter_fn("h2o3_rapids_deferred_statements_total",
                 "statements deferred into session DAGs", _lazy("deferred_statements"))
    r.counter_fn("h2o3_rapids_flushes_total",
                 "lazy-session DAG flushes", _lazy("flushes"))
    r.counter_fn("h2o3_rapids_cse_hits_total",
                 "deferred statements served from an identical node "
                 "(common-subexpression elimination)", _lazy("cse_hits"))
    r.counter_fn("h2o3_rapids_dead_temps_eliminated_total",
                 "deferred statements never computed (output overwritten "
                 "or removed before any observation)",
                 _lazy("dead_temps_eliminated"))
    r.counter_fn("h2o3_rapids_inlined_intermediates_total",
                 "deferred intermediates spliced into a consumer's fused "
                 "program without materializing a Column",
                 _lazy("inlined_intermediates"))
    r.counter_fn("h2o3_rapids_fused_sort_selections_total",
                 "sort+row-slice pairs executed as one windowed gather",
                 _lazy("fused_sort_selections"))
    r.gauge_fn("h2o3_rapids_deferred_pending",
               "deferred statements awaiting flush",
               _lazy("deferred_pending"))

    # -- munge→score pipeline fusion (h2o3_tpu/pipeline.py) --------------
    def _pipe(field):
        def fn():
            from h2o3_tpu import pipeline

            return float(pipeline.counters()[field])
        return fn

    r.counter_fn("h2o3_pipeline_captures_total",
                 "predict calls spliced onto a pending feature DAG",
                 _pipe("captures"))
    r.counter_fn("h2o3_pipeline_fused_dispatches_total",
                 "fused munge→score program executions",
                 _pipe("fused_dispatches"))
    r.counter_fn("h2o3_pipeline_spliced_nodes_total",
                 "pending DAG nodes spliced into fused scoring programs",
                 _pipe("spliced_nodes"))
    r.counter_fn("h2o3_pipeline_materialized_columns_total",
                 "engineered Columns materialized on the pipeline path "
                 "(the zero-materialization contract's observable)",
                 _pipe("materialized_columns"))
    r.counter_fn("h2o3_pipeline_fused_rows_total",
                 "logical rows scored through fused pipeline programs",
                 _pipe("fused_rows"))
    r.counter_fn("h2o3_pipeline_programs_compiled_total",
                 "pipeline programs actually XLA-compiled",
                 _pipe("programs_compiled"))
    r.counter_fn("h2o3_pipeline_compile_cache_hits_total",
                 "pipeline programs served warm (signature or disk tier)",
                 _pipe("compile_cache_hits"))
    r.counter_fn("h2o3_pipeline_fallbacks_total",
                 "captured pipelines that fell back to the staged path",
                 _pipe("fallbacks"))

    def _parse_cache_size():
        from h2o3_tpu.rapids import parser as rapids_parser

        return float(rapids_parser.parse_cache_stats()["size"])

    r.gauge_fn("h2o3_rapids_parse_cache_entries",
               "entries in the bounded statement-parse memo "
               "(H2O_TPU_RAPIDS_PARSE_CACHE)", _parse_cache_size)

    def _adm(field):
        def fn():
            from h2o3_tpu import admission

            return float(admission.CONTROLLER.snapshot()[field])
        return fn

    r.counter_fn("h2o3_admission_admitted_total",
                 "requests admitted to the fused path", _adm("admitted"))
    r.counter_fn("h2o3_admission_queued_total",
                 "requests that waited in the admission queue",
                 _adm("queued"))
    r.counter_fn("h2o3_admission_rejected_total",
                 "requests rejected 429 at the admission gate",
                 _adm("rejected"))
    r.counter_fn("h2o3_admission_timed_out_total",
                 "queued requests expired 503 before a slot freed",
                 _adm("timed_out"))
    r.counter_fn("h2o3_admission_shed_slo_total",
                 "requests shed 429 by the SLO queue-time gate",
                 _adm("shed_slo"))

    r.counter_fn("h2o3_admission_shed_mem_total",
                 "requests shed 503 under device memory pressure",
                 _adm("shed_mem"))

    def _adm_limits():
        from h2o3_tpu import admission

        return {(("model", k),): float(v)
                for k, v in admission.CONTROLLER.derived_limits().items()}

    r.gauge_fn("h2o3_admission_limit",
               "effective per-model inflight limit (static knob or "
               "SLO-derived)", _adm_limits, agg="max")

    # -- memory planner / OOM degradation ladder (h2o3_tpu/memory) -------
    def _mem(field):
        def fn():
            from h2o3_tpu.memory import stream

            return float(stream.counters()[field])
        return fn

    r.counter_fn("h2o3_mem_chunked_runs_total",
                 "fused dispatches the budget planner chunk-streamed",
                 _mem("chunked_runs"))
    r.counter_fn("h2o3_mem_windows_total",
                 "row-chunk windows dispatched by the stream driver",
                 _mem("windows"))
    r.counter_fn("h2o3_mem_ladder_halvings_total",
                 "OOM-triggered window halvings (degradation ladder)",
                 _mem("ladder_halvings"))
    r.counter_fn("h2o3_mem_ladder_recoveries_total",
                 "dispatches that hit device OOM and still completed",
                 _mem("ladder_recoveries"))
    r.counter_fn("h2o3_mem_pressure_failures_total",
                 "exhausted degradation ladders (MemoryPressureError)",
                 _mem("pressure_failures"))
    r.counter_fn("h2o3_mem_spill_retries_total",
                 "bounded remote-read retries (DKV fetches + persist "
                 "spill reloads)", _mem("spill_retries"))

    def _mem_budget(field):
        def fn():
            from h2o3_tpu.memory import budget as membudget

            v = getattr(membudget, field)()
            return float(v) if v is not None else 0.0
        return fn

    r.gauge_fn("h2o3_mem_budget_bytes",
               "effective per-device HBM budget (0 = unbudgeted)",
               _mem_budget("budget_bytes"), agg="max")
    r.gauge_fn("h2o3_mem_free_bytes",
               "budget minus headroom minus live column residency",
               _mem_budget("free_bytes"), agg="min")
    r.gauge_fn("h2o3_mem_live_bytes",
               "device bytes committed to frame columns",
               _mem_budget("live_bytes"), agg="max")

    def _mem_spilled():
        from h2o3_tpu.core import cleaner

        return float(cleaner.evicted_count())

    r.gauge_fn("h2o3_mem_spilled_columns",
               "columns currently evicted device→host/disk", _mem_spilled,
               agg="max")

    def _cc(field):
        def fn():
            from h2o3_tpu.artifact import compile_cache

            return float(compile_cache.stats()[field])
        return fn

    r.counter_fn("h2o3_compile_cache_compiles_total",
                 "actual fused-program XLA compilations", _cc("compiles"))

    def _compile_secs():
        from h2o3_tpu.artifact import compile_cache

        return float(compile_cache.stats()["compile_ms_total"]) / 1000.0

    r.counter_fn("h2o3_compile_cache_compile_seconds_total",
                 "wall seconds spent in fused-program XLA compilation",
                 _compile_secs)
    r.counter_fn("h2o3_compile_cache_disk_hits_total",
                 "persistent compile-cache hits", _cc("disk_hits"))
    r.counter_fn("h2o3_compile_cache_disk_misses_total",
                 "persistent compile-cache misses", _cc("disk_misses"))
    r.counter_fn("h2o3_compile_cache_stores_total",
                 "executables stored to the persistent cache", _cc("stores"))

    # -- compile-ledger views (obs/compiles.py is the ONE chokepoint
    #    every XLA compile routes through; these fold it into /3/Metrics
    #    so the cluster aggregation machinery carries it too) --
    def _ledger(field):
        def fn():
            from h2o3_tpu.obs import compiles

            return {(("family", fam),): float(a.get(field, 0))
                    for fam, a in compiles.family_table().items()}
        return fn

    r.counter_fn("h2o3_compile_ledger_compiles_total",
                 "ledger-recorded XLA compiles, by program family",
                 _ledger("compiles"))
    r.counter_fn("h2o3_compile_ledger_ms_total",
                 "wall milliseconds of ledger-recorded XLA compiles, "
                 "by program family", _ledger("ms_total"))
    r.counter_fn("h2o3_compile_ledger_memory_hits_total",
                 "in-process signature-cache hits, by program family",
                 _ledger("hits_memory"))
    r.counter_fn("h2o3_compile_ledger_disk_hits_total",
                 "persistent compile-cache hits, by program family",
                 _ledger("hits_disk"))

    def _wd(field):
        def fn():
            from h2o3_tpu.parallel import watchdog

            return float(watchdog.status().get(field, 0))
        return fn

    r.counter_fn("h2o3_watchdog_ticks_total", "recovery watchdog ticks",
                 _wd("ticks"))
    r.counter_fn("h2o3_watchdog_elections_total",
                 "standby elections won by this process", _wd("elections"))
    r.counter_fn("h2o3_watchdog_rejoins_total",
                 "watchdog-driven rejoins", _wd("rejoins"))
    r.counter_fn("h2o3_watchdog_jobs_resumed_total",
                 "externally-failed jobs re-dispatched from durable "
                 "progress", _wd("jobs_resumed"))
    r.counter_fn("h2o3_watchdog_searches_resumed_total",
                 "orphaned AutoML/grid searches re-dispatched from durable "
                 "search state", _wd("searches_resumed"))

    def _srch(field):
        def fn():
            from h2o3_tpu.automl import search

            return float(search.stats().get(field, 0))
        return fn

    r.counter_fn("h2o3_search_members_done_total",
                 "AutoML/grid search members trained to completion",
                 _srch("members_done"))
    r.counter_fn("h2o3_search_members_failed_total",
                 "search member attempts that crashed or timed out",
                 _srch("members_failed"))
    r.counter_fn("h2o3_search_members_parked_total",
                 "search members quarantine-parked after MAX_ATTEMPTS or a "
                 "deterministic config error", _srch("members_parked"))
    r.counter_fn("h2o3_search_member_attempts_total",
                 "search member training attempts started",
                 _srch("attempts"))
    r.counter_fn("h2o3_search_resumed_total",
                 "searches resumed from durable state after coordinator "
                 "loss", _srch("searches_resumed"))
    r.counter_fn("h2o3_search_state_saves_total",
                 "durable search-state snapshots written",
                 _srch("state_saves"))
    r.gauge_fn("h2o3_search_members_running",
               "search members currently training", _srch("running"),
               agg="max")
    r.gauge_fn("h2o3_search_members_overlap",
               "high-water mark of concurrently-training search members",
               _srch("overlap"), agg="max")

    def _cloud_state():
        from h2o3_tpu.parallel import supervisor

        order = {supervisor.HEALTHY: 0, supervisor.DEGRADED: 1,
                 supervisor.RECOVERING: 2, supervisor.FAILED: 3}
        return float(order.get(supervisor.state(), -1))

    r.gauge_fn("h2o3_cloud_state",
               "health state (0 HEALTHY, 1 DEGRADED, 2 RECOVERING, "
               "3 FAILED)", _cloud_state, agg="max")

    def _oplog_seq():
        from h2o3_tpu.parallel import oplog

        return float(oplog.current_seq())

    r.gauge_fn("h2o3_oplog_current_seq",
               "next oplog sequence to be claimed", _oplog_seq, agg="max")

    def _timeline_events():
        from h2o3_tpu.utils import timeline

        return float(len(timeline.events()))

    r.gauge_fn("h2o3_timeline_events", "events in the timeline ring",
               _timeline_events, agg="max")
    r.gauge_fn("h2o3_process_uptime_seconds",
               "seconds since this process registered its metrics",
               lambda: time.time() - _START_TS, agg="max")

    def _devices():
        # only consult jax when a backend is ALREADY initialized: this
        # collector runs inside flight-recorder dumps, whose primary
        # scenario is a process wedged in backend init — calling
        # local_devices() there would hang the dump, not raise
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return 0.0
        try:
            from jax._src import xla_bridge as xb

            if not getattr(xb, "_backends", None):
                return 0.0
            return float(len(jax.local_devices()))
        except Exception:   # noqa: BLE001 — private-API drift / wedged
            return 0.0

    r.gauge_fn("h2o3_local_device_count",
               "accelerator devices addressable by this process", _devices)


_install_default_metrics()


def reset_for_tests() -> None:
    """Zero every direct counter/histogram (collector-backed series follow
    their sources). Tests only."""
    for name in REGISTRY.names():
        m = REGISTRY.get(name)
        with m._lock:
            if m._fn is None:
                m._values.clear()
            m._hist.clear()
