"""Persist backends: URI scheme registry for remote data sources.

Reference: water/persist/PersistManager.java — a scheme-keyed registry of
Persist implementations (PersistNFS, PersistHTTP, PersistS3, PersistGCS,
PersistHdfs) behind one importFiles/open facade; every ingest path resolves
URIs through it.

TPU-native design: schemes resolve to LOCAL file paths (remote objects are
fetched once into a process-local cache dir, then the normal parse path —
including the native C++ CSV parser and pyarrow columnar readers — runs on
the local copy). The registry is open: `register_scheme` installs new
backends at runtime (the Extension SPI analog for storage). Cloud schemes
whose SDKs are not installed raise actionable errors instead of importing
dead weight."""

from __future__ import annotations

import os
import shutil
import tempfile
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional

_CACHE_DIR: Optional[str] = None

# scheme -> fetch(uri) -> local path
_SCHEMES: Dict[str, Callable[[str], str]] = {}


def cache_dir() -> str:
    global _CACHE_DIR
    if _CACHE_DIR is None:
        _CACHE_DIR = tempfile.mkdtemp(prefix="h2o3_tpu_persist_")
    return _CACHE_DIR


def register_scheme(scheme: str, fetch: Callable[[str], str]) -> None:
    """Install a storage backend: fetch(uri) must return a local file path."""
    _SCHEMES[scheme.lower()] = fetch


def _local_name(uri: str) -> str:
    """Stable cache filename keeping the remote basename (extension drives
    format dispatch in the parser)."""
    import hashlib

    base = os.path.basename(urllib.parse.urlparse(uri).path) or "download"
    h = hashlib.sha1(uri.encode()).hexdigest()[:12]
    return os.path.join(cache_dir(), f"{h}_{base}")


def _fetch_http(uri: str) -> str:
    """PersistHTTP analog: stream the object to the local cache once."""
    dest = _local_name(uri)
    if os.path.exists(dest):
        return dest
    tmp = dest + ".part"
    with urllib.request.urlopen(uri, timeout=60) as r, open(tmp, "wb") as f:
        shutil.copyfileobj(r, f)
    os.replace(tmp, dest)
    return dest


def _fetch_file(uri: str) -> str:
    p = urllib.parse.urlparse(uri)
    return urllib.request.url2pathname(p.path)


def _gated(scheme: str, pkg: str, ref: str):
    def fetch(uri: str) -> str:
        from h2o3_tpu.errors import CapabilityGate

        raise CapabilityGate(
            f"{scheme}:// URIs need the {pkg} SDK, which is not installed in "
            f"this environment. Fetch the object to a local path (or an "
            f"http(s) endpoint) and import that instead. Reference analog: "
            f"{ref}.")

    return fetch


register_scheme("http", _fetch_http)
register_scheme("https", _fetch_http)
register_scheme("file", _fetch_file)
from h2o3_tpu.persist.s3 import fetch_s3  # noqa: E402

register_scheme("s3", fetch_s3)
register_scheme("gs", _gated("gs", "google-cloud-storage",
                             "h2o-persist-gcs/PersistGcs.java"))
register_scheme("hdfs", _gated("hdfs", "pyarrow HadoopFileSystem",
                               "h2o-persist-hdfs/PersistHdfs.java"))


def is_remote(path: str) -> bool:
    return "://" in path


def resolve(path: str) -> str:
    """URI -> local path (identity for plain paths)."""
    if not is_remote(path):
        return path
    scheme = path.split("://", 1)[0].lower()
    fetch = _SCHEMES.get(scheme)
    if fetch is None:
        raise ValueError(f"no persist backend registered for scheme "
                         f"{scheme!r} (have: {sorted(_SCHEMES)})")
    return fetch(path)


def resolve_all(paths: List[str]) -> List[str]:
    return [resolve(p) for p in paths]
