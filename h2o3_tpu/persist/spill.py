"""Checksum-gated column spill: device → host → the persist cache tier.

The LRU cleaner (core/cleaner.py) swaps cold columns device → host RAM;
this module is the next rung down for frames several times bigger than
the HBM budget (h2o3_tpu/memory): a spilled column's host buffer lands
as an ``.npy`` file in the persist cache directory (remote-backed
deployments mount that dir on S3/NFS — persist/__init__.py is the
scheme registry the ingest side already resolves through), and the
Column reverts to a file-backed loader, freeing host RAM too.

Two disciplines make the round trip safe:

- **sha256 gate** — the digest is taken at spill time over the exact
  buffer bytes and re-verified at every reload; a torn write, a stale
  cache object or plain bit rot surfaces as :class:`SpillCorrupt`
  instead of silently wrong predictions.
- **bounded reads** — reloads go through
  ``memory/stream.bounded_remote_read``: the SAME bounded backoff
  budget (and ``h2o3_mem_spill_retries_total`` counter) as DKV
  replicated-blob fetches, so a flaky backing store degrades loudly.
"""

from __future__ import annotations

import hashlib
import io
import os
from typing import Optional

import numpy as np

from h2o3_tpu import persist


class SpillCorrupt(Exception):
    """A spilled column failed its checksum gate (or vanished) on
    reload — the backing store returned different bytes than were
    written."""


def spill_dir() -> str:
    d = os.path.join(persist.cache_dir(), "spill")
    os.makedirs(d, exist_ok=True)
    return d


def spill_array(arr: np.ndarray, name: str) -> tuple:
    """Write one host buffer to the spill tier; returns (path, sha256).
    Content-addressed by digest, written atomically (tmp + rename), so
    a crashed spill never leaves a half-file a reload could trust."""
    buf = np.ascontiguousarray(arr)
    digest = hashlib.sha256(buf.tobytes()).hexdigest()
    path = os.path.join(spill_dir(), f"{name}_{digest[:16]}.npy")
    if not os.path.exists(path):
        tmp = f"{path}.part.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.save(f, buf, allow_pickle=False)
        os.replace(tmp, path)
    return path, digest


def loader_for(path: str, digest: str, what: str):
    """A Column loader (file_backed contract: returns the PADDED host
    buffer) that reads through the shared bounded retry budget and the
    checksum gate."""
    from h2o3_tpu.memory import stream

    def _read() -> Optional[bytes]:
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            return None

    def load() -> np.ndarray:
        raw = stream.bounded_remote_read(_read, what=what)
        if raw is None:
            raise SpillCorrupt(
                f"spilled column {what} missing at {path} after the "
                f"bounded retry budget")
        buf = np.load(io.BytesIO(raw), allow_pickle=False)
        got = hashlib.sha256(np.ascontiguousarray(buf).tobytes()).hexdigest()
        if got != digest:
            raise SpillCorrupt(
                f"spilled column {what} failed its checksum gate at "
                f"{path}: wrote sha256 {digest[:16]}…, read {got[:16]}…")
        return buf

    return load


def spill_column(col, name: Optional[str] = None) -> int:
    """Evict `col` off the device AND push its host copy down to the
    spill tier; returns device bytes freed. Columns already file-backed
    (their eviction reverts to the original source) and non-addressable
    shardings are left alone."""
    from h2o3_tpu.core import cleaner

    freed = int(col.evict())
    src = col._evicted
    if src is None or callable(src):
        return freed
    what = name or f"col{col._token}"
    path, digest = spill_array(np.asarray(src), what)
    loader = loader_for(path, digest, what)
    with cleaner.SWAP_LOCK:
        # only install the disk loader if the column still holds the
        # host buffer we spilled — a racing fault-in keeps its device copy
        if col._data is None and col._evicted is src:
            col._evicted = loader
            col._loader = loader
    return freed
