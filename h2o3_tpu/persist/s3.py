"""PersistS3 — native S3 object fetch, no boto3.

Reference: h2o-persist-s3/src/main/java/water/persist/PersistS3.java:1.
S3's GET-object API is plain HTTPS + (optionally) an AWS Signature V4
Authorization header, both of which the stdlib covers (urllib + hmac/
hashlib) — the SDK buys retries/multipart we don't need for ingest.

Credentials: AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY (+ AWS_SESSION_TOKEN,
AWS_REGION) env vars, the same chain the reference's default provider reads
first. Without credentials the request goes out unsigned (public buckets).
H2O_TPU_S3_ENDPOINT overrides the endpoint with path-style addressing —
minio/localstack and the mocked-persist test tier ride this."""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import shutil
import urllib.parse
import urllib.request
from typing import Dict, Optional, Tuple


def _split(uri: str) -> Tuple[str, str]:
    p = urllib.parse.urlparse(uri)
    bucket = p.netloc
    key = p.path.lstrip("/")
    if not bucket or not key:
        raise ValueError(f"malformed s3 uri {uri!r} (want s3://bucket/key)")
    return bucket, key


def _sign_v4(method: str, url: str, region: str, access_key: str,
             secret_key: str, session_token: Optional[str]) -> Dict[str, str]:
    """AWS Signature Version 4 for an empty-body request."""
    p = urllib.parse.urlparse(url)
    host = p.netloc
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(b"").hexdigest()

    headers = {"host": host, "x-amz-content-sha256": payload_hash,
               "x-amz-date": amz_date}
    if session_token:
        headers["x-amz-security-token"] = session_token
    signed = ";".join(sorted(headers))
    canonical = "\n".join([
        # the path is ALREADY percent-encoded by object_url — re-quoting
        # would double-encode and break the signature for keys with
        # spaces/unicode; AWS canonicalizes the path exactly as sent
        method, p.path or "/",
        p.query,
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
        signed, payload_hash])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(canonical.encode()).hexdigest()])

    def _h(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _h(("AWS4" + secret_key).encode(), datestamp)
    k = _h(k, region)
    k = _h(k, "s3")
    k = _h(k, "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = {k_: v for k_, v in headers.items() if k_ != "host"}
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    return out


def object_url(uri: str) -> str:
    bucket, key = _split(uri)
    endpoint = os.environ.get("H2O_TPU_S3_ENDPOINT")
    if endpoint:
        # path-style for custom endpoints (minio/localstack/mock)
        return f"{endpoint.rstrip('/')}/{bucket}/{urllib.parse.quote(key)}"
    region = os.environ.get("AWS_REGION", "us-east-1")
    host = (f"{bucket}.s3.amazonaws.com" if region == "us-east-1"
            else f"{bucket}.s3.{region}.amazonaws.com")
    return f"https://{host}/{urllib.parse.quote(key)}"


def fetch_s3(uri: str) -> str:
    """s3://bucket/key → local cache path (PersistS3.importFiles analog)."""
    from h2o3_tpu.persist import _local_name

    dest = _local_name(uri)
    if os.path.exists(dest):
        return dest
    url = object_url(uri)
    headers: Dict[str, str] = {}
    ak = os.environ.get("AWS_ACCESS_KEY_ID")
    sk = os.environ.get("AWS_SECRET_ACCESS_KEY")
    if ak and sk:
        headers = _sign_v4("GET", url, os.environ.get("AWS_REGION",
                                                      "us-east-1"),
                           ak, sk, os.environ.get("AWS_SESSION_TOKEN"))
    req = urllib.request.Request(url, headers=headers)
    tmp = dest + ".part"
    with urllib.request.urlopen(req, timeout=120) as r, open(tmp, "wb") as f:
        shutil.copyfileobj(r, f)
    os.replace(tmp, dest)
    return dest
