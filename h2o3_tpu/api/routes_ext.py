"""Extended REST routes — the RegisterV3Api.java surface beyond the core.

Closes the round-3 route gap (VERDICT r3 #3): Frames column/summary/export,
binary model & frame save/load, the ModelMetrics cache family, POJO export,
NodePersistentStorage, admin/diagnostic routes, and the /99 utility tier
(Assembly, DCTTransformer, Tabulate, Sample, Rapids/help).

Handlers follow the server.py conventions: fn(ctx) -> dict | RawReply,
ApiError for failures. Reference route list: water/api/RegisterV3Api.java:23.
"""

from __future__ import annotations

import gc
import glob as _glob
import io
import json
import os
import pickle
import sys
import threading
import time
import traceback
import uuid

import numpy as np

from h2o3_tpu.api import schemas as S
from h2o3_tpu.api.server import (ApiError, Ctx, RawReply, _frame_or_404,
                                 _model_or_404, _parse_list)
from h2o3_tpu.core.dkv import DKV
from h2o3_tpu.core.frame import Column, Frame, T_CAT, T_NUM
from h2o3_tpu.core.job import Job
from h2o3_tpu.models.model import Model


class _ArtifactUnpickler(pickle.Unpickler):
    """Unpickler restricted to framework/numeric types — binary artifacts
    must not be able to smuggle arbitrary callables (pickle RCE). Applied
    to every load path, including the network-facing upload route."""

    _PREFIXES = ("h2o3_tpu.", "numpy.", "jax.", "jaxlib.", "collections.")
    _MODULES = {"numpy", "jax", "jaxlib", "collections"}
    _EXACT = {("functools", "partial")}
    _BUILTINS = {"set", "frozenset", "slice", "complex", "range",
                 "bytearray", "object"}

    def find_class(self, module, name):
        if module == "builtins" and name in self._BUILTINS:
            return super().find_class(module, name)
        if (module, name) in self._EXACT:
            return super().find_class(module, name)
        if module in self._MODULES or \
                any(module.startswith(pfx) for pfx in self._PREFIXES):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"artifact references disallowed type {module}.{name}")


def _artifact_loads(data: bytes):
    return _ArtifactUnpickler(io.BytesIO(data)).load()


def _artifact_load_file(path: str):
    with open(path, "rb") as f:
        return _ArtifactUnpickler(f).load()


def _done_job(description: str, dest_key=None, dest_type=None) -> Job:
    job = Job(description=description)
    if dest_key:
        job.dest_key = str(dest_key)
    if dest_type:
        job.dest_type = dest_type
    job.status = Job.DONE
    job.progress = 1.0
    job.start_time = job.end_time = time.time()
    return job

# ---------------------------------------------------------------------------
# Capabilities (water/api/CapabilitiesHandler)
# ---------------------------------------------------------------------------

_CORE_CAPABILITIES = [
    {"name": "h2o3_tpu", "description": "TPU-native H2O-3 runtime (jax/XLA)"},
    {"name": "MOJO", "description": "MOJO export/import + standalone "
                                    "h2o3_genmodel scoring runtime"},
    {"name": "POJO", "description": "Java scoring class export (tree/GLM)"},
    {"name": "AutoML", "description": "automatic model search"},
    {"name": "Grid", "description": "cartesian + random hyperparameter search"},
    {"name": "Sharding", "description": "jax.sharding data parallelism over "
                                        "the device mesh"},
]


def h_capabilities(ctx: Ctx):
    return {"__meta": S.meta("CapabilitiesV3"),
            "capabilities": list(_CORE_CAPABILITIES)}


def h_capabilities_core(ctx: Ctx):
    return h_capabilities(ctx)


def h_capabilities_api(ctx: Ctx):
    from h2o3_tpu.api.server import ROUTES

    out = [{"name": f"{m} {p}", "description": s}
           for m, p, _h, s in ROUTES]
    return {"__meta": S.meta("CapabilitiesV3"), "capabilities": out}


def h_metadata_endpoint(ctx: Ctx):
    """GET /3/Metadata/endpoints/{path} — one endpoint by number or name
    (water/api/MetadataHandler.fetchRoute)."""
    from h2o3_tpu.api.server import ROUTES

    want = ctx.params["path"]
    for i, (m, p, h, summ) in enumerate(ROUTES):
        if want == str(i) or want == p or want == h.__name__.lstrip("h_"):
            return {"__meta": S.meta("EndpointsListV4"), "endpoints": [{
                "num": i, "http_method": m, "url_pattern": p,
                "summary": summ, "api_name": h.__name__.lstrip("h_")}]}
    raise ApiError(f"endpoint {want!r} not found", 404)


def h_metadata_schemaclass(ctx: Ctx):
    """GET /3/Metadata/schemaclasses/{classname} — schema detail by java
    class name (maps onto our schema registry)."""
    from h2o3_tpu.api.server import _SCHEMA_REGISTRY

    name = ctx.params["classname"].rsplit(".", 1)[-1]
    if name not in _SCHEMA_REGISTRY:
        raise ApiError(f"unknown schema class {name!r}", 404)
    return {"__meta": S.meta("SchemaMetadataV3"),
            "schemas": [{"name": name, "version": 3,
                         "type": name.rstrip("V3"), "fields": []}]}


# ---------------------------------------------------------------------------
# Frames: columns / summaries / chunks / export / binary save-load
# ---------------------------------------------------------------------------

def _col_or_404(fr: Frame, name: str) -> Column:
    if name not in fr:
        raise ApiError(f"Column '{name}' not found in frame {fr.key}", 404)
    return fr.col(name)


def h_frame_columns(ctx: Ctx):
    fr = _frame_or_404(ctx.params["frame_id"])
    off = int(ctx.arg("offset", 0) or 0)
    cnt = int(ctx.arg("column_count", -1) or -1)
    names = fr.names[off:] if cnt < 0 else fr.names[off:off + cnt]
    return {"__meta": S.meta("FramesV3"),
            "frames": [{"frame_id": S.key_ref(str(fr.key)),
                        "column_names": names, "total_column_count": fr.ncols,
                        "columns": [S.col_v3(n, fr.col(n), 0, 10)
                                    for n in names]}]}


def h_frame_column(ctx: Ctx):
    fr = _frame_or_404(ctx.params["frame_id"])
    col = _col_or_404(fr, ctx.params["column"])
    return {"__meta": S.meta("FramesV3"),
            "frames": [{"frame_id": S.key_ref(str(fr.key)),
                        "columns": [S.col_v3(ctx.params["column"], col, 0, 10)]}]}


def h_frame_column_domain(ctx: Ctx):
    fr = _frame_or_404(ctx.params["frame_id"])
    col = _col_or_404(fr, ctx.params["column"])
    return {"__meta": S.meta("FrameV3"),
            "domain": [list(col.domain or [])],
            "map_keys": {"string": list(col.domain or [])}}


def h_frame_column_summary(ctx: Ctx):
    fr = _frame_or_404(ctx.params["frame_id"])
    name = ctx.params["column"]
    col = _col_or_404(fr, name)
    cj = S.col_v3(name, col, 0, 10)
    if col.is_numeric:
        from h2o3_tpu.ops.quantile import quantile_column

        probs = [0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9, 0.99]
        try:
            cj["percentiles"] = [float(v) for v in quantile_column(col, probs)]
            cj["default_percentiles"] = probs
        except Exception:   # noqa: BLE001 — summary stays best-effort
            pass
    return {"__meta": S.meta("FramesV3"),
            "frames": [{"frame_id": S.key_ref(str(fr.key)), "columns": [cj]}]}


def h_frame_chunks(ctx: Ctx):
    """GET /3/FrameChunks/{frame_id} — per-shard layout (the reference's
    per-chunk distribution table, water/api/FrameChunksHandler)."""
    fr = _frame_or_404(ctx.params["frame_id"])
    from h2o3_tpu.core.runtime import cluster

    cl = cluster()
    n_dev = max(len(cl.devices), 1)
    per = -(-fr.nrows // n_dev)
    chunks = [{"chunk_id": i, "row_count": max(min(per, fr.nrows - i * per), 0),
               "node_idx": i} for i in range(n_dev)]
    return {"__meta": S.meta("FrameChunksV3"),
            "frame_id": S.key_ref(str(fr.key)), "chunks": chunks}


def _export_frame(fr: Frame, path: str, force: bool, fmt: str = "csv") -> str:
    if os.path.exists(path) and not force:
        raise ApiError(f"File {path} already exists (force=false)", 400)
    if fmt in ("parquet",):
        fr.to_pandas().to_parquet(path)
    else:
        fr.to_pandas().to_csv(path, index=False)
    return path


def h_frame_export(ctx: Ctx):
    """POST /3/Frames/{frame_id}/export and the GET
    /3/Frames/{frame_id}/export/{path}/overwrite/{force} legacy spelling —
    write the frame to a server-side file as a Job (FramesHandler.export)."""
    fr = _frame_or_404(ctx.params["frame_id"])
    path = ctx.params.get("path") or str(ctx.arg("path", "") or "").strip('"')
    if not path:
        raise ApiError("path required", 400)
    force_raw = ctx.params.get("force", ctx.arg("force", "true"))
    force = str(force_raw).lower() in ("1", "true")
    fmt = str(ctx.arg("format", "csv") or "csv").strip('"').lower()
    job = Job(description=f"Export frame {fr.key}")

    def run(j: Job):
        _export_frame(fr, path, force, fmt)
        return None

    job.start(run, background=False)        # small metadata op: sync
    return {"__meta": S.meta("FramesV3"), "job": S.job_v3(job)}


def h_frame_save(ctx: Ctx):
    """POST /3/Frames/{frame_id}/save — binary frame artifact
    (water/api/FramesHandler.save; reference writes its Iced binary form,
    we write a self-contained pickle of host-materialized columns)."""
    fr = _frame_or_404(ctx.params["frame_id"])
    d = str(ctx.arg("dir", "") or "").strip('"')
    if not d:
        raise ApiError("dir required", 400)
    force = str(ctx.arg("force", "true")).lower() in ("1", "true")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, str(fr.key))
    if os.path.exists(path) and not force:
        raise ApiError(f"{path} exists (force=false)", 400)
    with open(path, "wb") as f:
        pickle.dump(fr, f)
    job = _done_job(f"Save frame {fr.key}")
    return {"__meta": S.meta("FramesV3"), "job": S.job_v3(job)}


def h_frame_load(ctx: Ctx):
    """POST /3/Frames/load — restore a frame saved by /save."""
    d = str(ctx.arg("dir", "") or "").strip('"')
    fid = str(ctx.arg("frame_id", "") or "").strip('"')
    path = os.path.join(d, fid) if (d and fid) else (d or fid)
    if not os.path.exists(path):
        raise ApiError(f"no saved frame at {path}", 404)
    fr = _artifact_load_file(path)
    if not isinstance(fr, Frame):
        raise ApiError(f"{path} is not a saved frame", 400)
    fr.install()
    job = _done_job(f"Load frame {fr.key}", str(fr.key), "Key<Frame>")
    return {"__meta": S.meta("FramesV3"), "job": S.job_v3(job)}


# ---------------------------------------------------------------------------
# Models: binary save/load/upload, POJO, v99 aliases
# ---------------------------------------------------------------------------

def h_model_fetch_bin(ctx: Ctx):
    """GET /3/Models.fetch.bin/{model_id} (+ /99/Models.bin alias) — the
    model's binary artifact (reference: Iced serialization; here a pickle
    that restores the full model incl. metrics — same-version contract as
    the reference's .bin)."""
    m = _model_or_404(ctx.params["model_id"])
    data = pickle.dumps(m)
    return RawReply(data, "application/octet-stream",
                    headers={"Content-Disposition":
                             f'attachment; filename="{m.key}.bin"'})


def h_model_save_bin(ctx: Ctx):
    """POST /99/Models.bin/{model_id}?dir=... — h2o.save_model."""
    m = _model_or_404(ctx.params["model_id"])
    d = str(ctx.arg("dir", "") or "").strip('"')
    if not d:
        raise ApiError("dir required", 400)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, str(m.key))
    force = str(ctx.arg("force", "true")).lower() in ("1", "true")
    if os.path.exists(path) and not force:
        raise ApiError(f"{path} exists (force=false)", 400)
    with open(path, "wb") as f:
        pickle.dump(m, f)
    return {"__meta": S.meta("ModelsV3"), "dir": d,
            "models": [{"model_id": S.key_ref(str(m.key), "Key<Model>")}]}


def h_model_load_bin(ctx: Ctx):
    """POST /99/Models.bin/ with dir=path — h2o.load_model."""
    d = str(ctx.arg("dir", "") or "").strip('"')
    if not d or not os.path.exists(d):
        raise ApiError(f"no saved model at {d!r}", 404)
    m = _artifact_load_file(d)
    if not isinstance(m, Model):
        raise ApiError(f"{d} is not a saved model", 400)
    m.install()
    return {"__meta": S.meta("ModelsV3"),
            "models": [S.model_v3(m)]}


def h_model_upload_bin(ctx: Ctx):
    """POST /99/Models.upload.bin/{model_id} — raw model bytes upload."""
    raw = ctx.body.get("__raw__") or ctx.body.get("__file__")
    if not raw:
        raise ApiError("no model bytes uploaded", 400)
    try:
        m = _artifact_loads(raw)
    except pickle.UnpicklingError as e:
        raise ApiError(f"rejected model upload: {e}", 400) from None
    if not isinstance(m, Model):
        raise ApiError("uploaded bytes are not a model", 400)
    mid = ctx.params.get("model_id", "").strip()
    if mid:
        from h2o3_tpu.core.dkv import Key

        m._key = Key(mid)
    m.install()
    return {"__meta": S.meta("ModelsV3"),
            "models": [{"model_id": S.key_ref(str(m.key), "Key<Model>")}]}


def h_model_java(ctx: Ctx):
    """GET /3/Models.java/{model_id} — POJO source (toJava analog)."""
    from h2o3_tpu.models import pojo

    m = _model_or_404(ctx.params["model_id"])
    try:
        src = pojo.pojo_source(m)
    except ValueError as e:
        raise ApiError(str(e), 400) from None
    return RawReply(src.encode(), "text/x-java-source",
                    headers={"Content-Disposition":
                             f'attachment; filename="{m.key}.java"'})


def h_model_java_preview(ctx: Ctx):
    from h2o3_tpu.models import pojo

    m = _model_or_404(ctx.params["model_id"])
    try:
        src = pojo.pojo_source(m)
    except ValueError as e:
        raise ApiError(str(e), 400) from None
    lines = src.splitlines()[:1000]
    return RawReply(("\n".join(lines) + "\n").encode(), "text/plain")


def h_model_json(ctx: Ctx):
    m = _model_or_404(ctx.params["model_id"])
    return {"__meta": S.meta("ModelsV3"), "models": [S.model_v3(m)]}


def h_models_delete_all(ctx: Ctx):
    from h2o3_tpu import scoring

    for k in list(DKV.keys()):
        if isinstance(DKV.get(k), Model):
            DKV.remove(k)
            purge_metrics(model_key=k)
    scoring.purge()
    return {"__meta": S.meta("ModelsV3")}


def h_frames_delete_all(ctx: Ctx):
    for k in list(DKV.keys()):
        if isinstance(DKV.get(k), Frame):
            DKV.remove(k)
            purge_metrics(frame_key=k)
    return {"__meta": S.meta("FramesV3")}


# ---------------------------------------------------------------------------
# ModelMetrics cache family (water/api/ModelMetricsHandler)
# ---------------------------------------------------------------------------

_MM_STORE: list = []        # {"model": str, "frame": str, "mm": ModelMetrics}


_MM_CAP = 512      # FIFO bound — reference stores metrics in the DKV


def record_metrics(model_key: str, frame_key: str, mm) -> None:
    _MM_STORE[:] = [e for e in _MM_STORE
                    if not (e["model"] == model_key and e["frame"] == frame_key)]
    _MM_STORE.append({"model": model_key, "frame": frame_key, "mm": mm})
    if len(_MM_STORE) > _MM_CAP:
        del _MM_STORE[: len(_MM_STORE) - _MM_CAP]


def purge_metrics(model_key=None, frame_key=None) -> None:
    """Drop cached metrics tied to a deleted model/frame (DKV-removal
    parity: the reference reclaims metrics with their key)."""
    _MM_STORE[:] = [e for e in _MM_STORE
                    if not ((model_key and e["model"] == model_key)
                            or (frame_key and e["frame"] == frame_key))]


def _mm_entries(model=None, frame=None):
    out = []
    for e in _MM_STORE:
        if model and e["model"] != model:
            continue
        if frame and e["frame"] != frame:
            continue
        out.append(e)
    # training metrics of live models count as cached metrics too
    if not frame:
        for k in DKV.keys():
            m = DKV.get(k)
            if isinstance(m, Model) and (not model or str(m.key) == model):
                tm = m._output.training_metrics
                if tm is not None and not any(
                        e["model"] == str(m.key) and e["frame"] is None
                        for e in out):
                    out.append({"model": str(m.key), "frame": None, "mm": tm})
    return out


def h_modelmetrics_list(ctx: Ctx):
    model = ctx.params.get("model") or None
    frame = ctx.params.get("frame") or None
    if model:
        _model_or_404(model)
    if frame:
        _frame_or_404(frame)
    ents = _mm_entries(model, frame)
    return {"__meta": S.meta("ModelMetricsListSchemaV3"),
            "model_metrics": [S.metrics_v3(e["mm"], e["model"], e["frame"])
                              for e in ents]}


def h_modelmetrics_delete(ctx: Ctx):
    model = ctx.params.get("model") or None
    frame = ctx.params.get("frame") or None
    before = len(_MM_STORE)
    _MM_STORE[:] = [e for e in _MM_STORE
                    if (model and e["model"] != model)
                    or (frame and e["frame"] != frame)]
    return {"__meta": S.meta("ModelMetricsListSchemaV3"),
            "deleted": before - len(_MM_STORE)}


def h_modelmetrics_predictions_vs_actuals(ctx: Ctx):
    """POST /3/ModelMetrics/predictions_frame/{pf}/actuals_frame/{af} —
    h2o.make_metrics: metrics straight from a predictions frame."""
    from h2o3_tpu.models import metrics as M

    pf = _frame_or_404(ctx.params["predictions_frame"])
    af = _frame_or_404(ctx.params["actuals_frame"])
    domain = _parse_list(ctx.arg("domain")) or None
    import jax.numpy as jnp

    act = af.col(af.names[0])
    n = af.nrows
    w = jnp.ones(act.data.shape[0], jnp.float32)
    if n < act.data.shape[0]:          # mask any sharding pad rows
        w = w.at[n:].set(0.0)
    if act.is_categorical or domain:
        dom = domain or list(act.domain)
        y = act.data.astype(jnp.int32)
        if len(dom) == 2:
            # predictions frame: predict, p0, p1 — use p1
            p = pf.col(pf.names[-1]).data
            mm = M.make_binomial_metrics(y.astype(jnp.float32), p, w, dom)
            schema = "ModelMetricsBinomialV3"
        else:
            probs = jnp.stack([pf.col(nm).data for nm in pf.names[-len(dom):]],
                              axis=-1)
            mm = M.make_multinomial_metrics(y, probs, w, dom)
            schema = "ModelMetricsMultinomialV3"
    else:
        f = pf.col(pf.names[0]).data
        mm = M.make_regression_metrics(act.data, f, w)
        schema = "ModelMetricsRegressionV3"
    del schema
    return {"__meta": S.meta("ModelMetricsListSchemaV3"),
            "model_metrics": [S.metrics_v3(mm, None, str(af.key))]}


# ---------------------------------------------------------------------------
# NodePersistentStorage (water/api/NodePersistentStorageHandler)
# ---------------------------------------------------------------------------

def _nps_root() -> str:
    root = os.environ.get("H2O_TPU_NPS_DIR") or os.path.join(
        os.path.expanduser("~"), ".h2o3_tpu", "nps")
    os.makedirs(root, exist_ok=True)
    return root


def _nps_path(category: str, name: str = "") -> str:
    safe = lambda s: "".join(c for c in s if c.isalnum() or c in "-_.")
    p = os.path.join(_nps_root(), safe(category))
    return os.path.join(p, safe(name)) if name else p


def h_nps_configured(ctx: Ctx):
    return {"__meta": S.meta("NodePersistentStorageV3"), "configured": True}


def h_nps_category_exists(ctx: Ctx):
    return {"__meta": S.meta("NodePersistentStorageV3"),
            "exists": os.path.isdir(_nps_path(ctx.params["category"]))}


def h_nps_name_exists(ctx: Ctx):
    return {"__meta": S.meta("NodePersistentStorageV3"),
            "exists": os.path.isfile(_nps_path(ctx.params["category"],
                                               ctx.params["name"]))}


def h_nps_list(ctx: Ctx):
    d = _nps_path(ctx.params["category"])
    entries = []
    if os.path.isdir(d):
        for nm in sorted(os.listdir(d)):
            st = os.stat(os.path.join(d, nm))
            entries.append({"name": nm, "size": st.st_size,
                            "timestamp_millis": int(st.st_mtime * 1000)})
    return {"__meta": S.meta("NodePersistentStorageV3"),
            "category": ctx.params["category"], "entries": entries}


def h_nps_get(ctx: Ctx):
    p = _nps_path(ctx.params["category"], ctx.params["name"])
    if not os.path.isfile(p):
        raise ApiError(f"NPS entry {ctx.params['category']}/"
                       f"{ctx.params['name']} not found", 404)
    with open(p, "rb") as f:
        return RawReply(f.read(), "application/octet-stream")


def h_nps_put(ctx: Ctx):
    cat = ctx.params["category"]
    name = ctx.params.get("name") or f"{uuid.uuid4().hex[:12]}"
    value = ctx.body.get("__raw__", ctx.body.get("__file__"))
    if value is None:
        value = str(ctx.arg("value", "") or "").encode()
    os.makedirs(_nps_path(cat), exist_ok=True)
    with open(_nps_path(cat, name), "wb") as f:
        f.write(value)
    return {"__meta": S.meta("NodePersistentStorageV3"),
            "category": cat, "name": name}


def h_nps_delete(ctx: Ctx):
    p = _nps_path(ctx.params["category"], ctx.params["name"])
    if os.path.isfile(p):
        os.remove(p)
    return {"__meta": S.meta("NodePersistentStorageV3")}


# ---------------------------------------------------------------------------
# Admin / diagnostics
# ---------------------------------------------------------------------------

def h_jstack(ctx: Ctx):
    """GET /3/JStack — per-thread stack dump (water/api/JStackHandler;
    the JVM thread dump maps to Python thread frames here)."""
    frames = sys._current_frames()
    traces = []
    for t in threading.enumerate():
        try:
            frm = frames.get(t.ident)
            buf = traceback.format_stack(frm) if frm is not None else []
        except Exception:   # noqa: BLE001 — frame may die mid-walk
            buf = []
        traces.append({"thread_name": t.name,
                       "is_daemon": t.daemon,
                       "stack": "".join(buf)})
    node = {"node_name": "local", "thread_traces": traces}
    return {"__meta": S.meta("JStackV3"), "traces": [node],
            "nodes": [node]}


def h_kill_minus_3(ctx: Ctx):
    """GET /3/KillMinus3 — log a thread dump (reference sends SIGQUIT to
    itself so stacks land in the log)."""
    from h2o3_tpu.utils.log import get_logger

    dump = h_jstack(ctx)
    for tr in dump["traces"][0]["thread_traces"]:
        get_logger().info("thread %s daemon=%s\n%s", tr["thread_name"],
                          tr["is_daemon"], tr["stack"])
    return {"__meta": S.meta("KillMinus3V3")}


def h_log_and_echo(ctx: Ctx):
    from h2o3_tpu.utils.log import get_logger

    msg = str(ctx.arg("message", "") or "")
    get_logger().info("LogAndEcho: %s", msg)
    return {"__meta": S.meta("LogAndEchoV3"), "message": msg}


def h_logs_node_file(ctx: Ctx):
    """GET /3/Logs/nodes/{nodeidx}/files/{name} — reference per-node log
    fetch; single logical node here, every idx serves the local log."""
    from h2o3_tpu.api.server import h_logs

    out = h_logs(ctx)
    return {"__meta": S.meta("LogsV3"),
            "nodeidx": int(ctx.params.get("nodeidx", -1)),
            "name": ctx.params.get("name", "default"), "log": out["log"]}


def h_typeahead_files(ctx: Ctx):
    """GET /3/Typeahead/files — filesystem path completion
    (water/api/TypeaheadHandler)."""
    src = str(ctx.arg("src", "") or "").strip('"')
    limit = int(ctx.arg("limit", 100) or 100)
    pat = src + "*" if src else "*"
    matches = sorted(_glob.glob(os.path.expanduser(pat)))[:limit]
    return {"__meta": S.meta("TypeaheadV3"), "matches": matches}


def h_find(ctx: Ctx):
    """GET /3/Find?key=frame&column=c&row=N&match=v — next row >= N whose
    cell matches (water/api/FindHandler)."""
    fr = _frame_or_404(str(ctx.arg("key", "") or "").strip('"'))
    colname = str(ctx.arg("column", "") or "").strip('"')
    row = int(ctx.arg("row", 0) or 0)
    match = ctx.arg("match")
    cols = [colname] if colname else fr.names
    for nm in cols:
        col = fr.col(nm)
        vals = col.to_numpy()[row:]
        if col.domain:
            codes = np.asarray(vals, np.int64)
            labels = np.asarray(col.domain, object)[np.maximum(codes, 0)]
            # NA codes (-1) must never match a level
            hit = np.nonzero((codes >= 0)
                             & (labels.astype(str) == str(match)))[0]
        elif match in (None, "", "nan", "NaN"):
            hit = np.nonzero(np.isnan(np.asarray(vals, float)))[0]
        else:
            try:
                target = float(match)
            except (TypeError, ValueError):
                continue       # non-numeric needle, numeric column: no match
            hit = np.nonzero(np.asarray(vals, float) == target)[0]
        if hit.size:
            return {"__meta": S.meta("FindV3"), "prev": -1,
                    "next": row + int(hit[0])}
    return {"__meta": S.meta("FindV3"), "prev": -1, "next": -1}


def h_cloud_lock(ctx: Ctx):
    from h2o3_tpu.core.runtime import cluster

    cluster().locked = True
    return {"__meta": S.meta("CloudLockV3"), "reason":
            str(ctx.arg("reason", "") or "")}


def h_gc(ctx: Ctx):
    from h2o3_tpu.core import cleaner

    gc.collect()
    freed = 0
    try:
        freed = cleaner.sweep(0)
    except Exception:   # noqa: BLE001 — GC stays best-effort
        pass
    return {"__meta": S.meta("GarbageCollectV3"), "freed_bytes": int(freed or 0)}


def h_unlock_keys(ctx: Ctx):
    from h2o3_tpu.core import dkv as _dkv

    n = _dkv.unlock_all()
    return {"__meta": S.meta("UnlockKeysV3"), "unlocked": int(n or 0)}


def h_steam_metrics(ctx: Ctx):
    from h2o3_tpu.core.runtime import cluster_info

    info = cluster_info()
    jobs = [j for j in (DKV.get(k) for k in DKV.keys())
            if isinstance(j, Job)]
    return {"__meta": S.meta("SteamMetricsV3"),
            "idle": all(not j.is_running for j in jobs),
            "idle_millis": 0, "cloud_size": info["cloud_size"]}


def _search_stats() -> dict:
    """Engine counters for the CloudStatus search block (import kept out
    of module load so the API layer stays light)."""
    from h2o3_tpu.automl import search

    return search.stats()


def h_cloud_status(ctx: Ctx):
    """GET /3/CloudStatus — the supervised cloud health state machine
    (HEALTHY/DEGRADED/FAILED/RECOVERING) with its evidence: per-process
    heartbeat ages + incarnations + ack lag, follower replay failures
    (remote tracebacks), rejoin progress, checkpoint/epoch coordinates,
    and the recent transition history — the fields an operator needs to
    watch a recovery. The terse headline rides on /3/Cloud as
    ``cloud_status``; this route is the drill-down."""
    from h2o3_tpu.core.failure import cluster_health, heartbeat_stale_s
    from h2o3_tpu.parallel import ckpt, oplog, supervisor, watchdog
    from h2o3_tpu.parallel import distributed as D

    st = supervisor.status()
    # fold replay progress (last acked seq, ack lag, incarnation) into the
    # per-process heartbeat rows so one table tells the recovery story
    lag_by = {r["process"]: r for r in oplog.follower_lag()}
    health = []
    for row in cluster_health():
        lr = lag_by.pop(row["process"], None)
        if lr is not None:
            row = dict(row, last_acked_seq=lr["last_acked_seq"],
                       ack_lag=lr["ack_lag"])
        health.append(row)
    # followers with acks but no heartbeat row yet still show up
    for p, lr in sorted(lag_by.items()):
        health.append({"process": p, "age_s": None, "healthy": False,
                       "incarnation": lr["incarnation"],
                       "last_acked_seq": lr["last_acked_seq"],
                       "ack_lag": lr["ack_lag"]})
    return {"__meta": S.meta("CloudStatusV3"),
            "state": st["state"],
            "since": st["since"],
            "reason": st["reason"],
            "remote_trace": st["remote_trace"],
            "transitions": st["transitions"],
            "process_health": health,
            "heartbeat_stale_s": heartbeat_stale_s(),
            "expected_acks": oplog.expected_acks(),
            "current_seq": oplog.current_seq(),
            "checkpoint_seq": ckpt.latest_seq(),
            "checkpoint_interval_ops": ckpt.interval_ops(),
            "epoch": D.epoch(),
            "leader": D.leader(),
            # autonomous recovery watchdog: enabled/running, action
            # counters (elections, rejoins, jobs resumed), last action
            "watchdog": watchdog.status(),
            # durable AutoML/grid searches: engine counters plus every
            # search-state record still on disk/KV (a non-empty list during
            # a healthy cloud means a search is mid-flight; after a
            # coordinator loss it is the watchdog's resume worklist)
            "search": {"stats": _search_stats(),
                       "states": ckpt.search_state_records()},
            "job_progress": ckpt.job_progress_records(),
            "rejoins": oplog.rejoin_records(),
            "oplog_errors": [{"seq": seq, "kind": rec.get("kind"),
                              "trace": rec.get("trace")}
                             for seq, rec in oplog.error_records()]}


def h_scoring_metrics(ctx: Ctx):
    """GET /3/ScoringMetrics — per-model serving fast-path statistics
    (scoring.py ScoringSession): request/batch/row counts, micro-batch
    coalescing, latency percentiles, traversal/fused compile counts and
    the active row buckets; plus the admission-control counters, the
    persistent compile-cache stats, and the per-process sharded data-plane
    counters (``data_plane.packed_rows`` / ``data_plane.gathered_rows`` —
    "no coordinator column gather on the fused path" is asserted against
    gathered_rows staying 0), and the Rapids statement-fusion block
    (``rapids``: statements, fused programs/compiles/cache hits, barrier
    fallbacks, host-materialized cells). The per-dispatch events are also
    in /3/Timeline under kind='scoring'."""
    from h2o3_tpu import admission, pipeline, scoring
    from h2o3_tpu.artifact import compile_cache
    from h2o3_tpu.core import sharded_frame
    from h2o3_tpu.rapids import fusion

    return {"__meta": S.meta("ScoringMetricsV3"),
            "models": scoring.metrics_snapshot(),
            "admission": admission.CONTROLLER.snapshot(),
            "compile_cache": compile_cache.stats(),
            "data_plane": sharded_frame.counters(),
            # ISSUE-13 per-flush dispatch accounting: fused program
            # executions by path (sharded/host/local/leaf_*) — the
            # one-dispatch-per-flush contract's observable
            "dispatches": scoring.dispatch_counters(),
            "rapids": fusion.stats(),
            # munge→score splice: fused pipeline dispatches, spliced
            # plan nodes, and the materialized-column counter whose 0 is
            # the "no intermediate Column" contract's observable
            "pipeline": pipeline.stats()}


def h_metrics(ctx: Ctx):
    """GET /3/Metrics — CLUSTER-wide metrics: the coordinator merges its
    live registry snapshot with every other process's KV-published one
    (counters/histograms sum; gauges aggregate by their declared agg).
    Default body is Prometheus text exposition (format 0.0.4) so a stock
    Prometheus scrape config points straight at this route;
    ``?format=json`` returns the structured series instead."""
    from h2o3_tpu.obs import metrics as obs_metrics

    series = obs_metrics.cluster_aggregate()
    fmt = str(ctx.arg("format", "") or "").lower()
    if fmt == "json":
        # JSON consumers get computed p50/p95/p99 per histogram sample
        # (the Prometheus text path keeps raw cumulative buckets — that
        # is its contract; histogram_quantile runs server-side there)
        for m in series:
            if m.get("type") != "histogram":
                continue
            for s in m.get("samples", []):
                s["quantiles"] = obs_metrics.histogram_quantiles(
                    m.get("buckets") or [], s.get("bucket_counts") or [],
                    int(s.get("count", 0)))
        return {"__meta": S.meta("MetricsV3"), "series": series,
                "series_count": len(series)}
    return RawReply(obs_metrics.prometheus_text(series).encode(),
                    "text/plain; version=0.0.4; charset=utf-8")


def h_runtime(ctx: Ctx):
    """GET /3/Runtime — the engine's lifecycle + compile story in one
    page (ISSUE 12): this process's phase history (``backend_init`` …
    ``server_start``, each with wall ms, status and any deadline
    expiry), the cluster-wide compile-ledger table per program family
    (compiles / memory hits / disk hits / total+max ms), and the
    slowest-N compiled programs with signature hash, device kind and HBM
    estimate. Every process contributes its KV-published runtime
    snapshot (same throttle as the /3/Metrics publish). The response
    carries ``X-H2O3-Trace-Id`` like every traced route.

    The ``memory`` block is this process's HBM budget planner state
    (ISSUE 20): budget/free/live bytes, evicted-column count, per-family
    bytes-per-row estimates, streaming/ladder counters and the pressure
    flag admission sheds on."""
    from h2o3_tpu.memory import budget as membudget
    from h2o3_tpu.obs import compiles, phases

    try:
        n = int(ctx.arg("slowest", 10) or 10)
    except (TypeError, ValueError):
        n = 10
    n = max(min(n, 100), 1)
    snaps = compiles.cluster_runtime(slowest_n=n)
    families = compiles.merge_family_tables(
        [(s.get("compiles") or {}).get("families") or {} for s in snaps])
    slowest = sorted(
        (r for s in snaps
         for r in (s.get("compiles") or {}).get("slowest") or []),
        key=lambda r: float(r.get("ms") or 0.0), reverse=True)[:n]
    return {"__meta": S.meta("RuntimeV3"),
            "phases": phases.history(),
            "phase_report": phases.phase_report(),
            "wedged_phase": phases.wedged_phase(),
            "compile_families": families,
            "slowest_compiles": slowest,
            "memory": membudget.snapshot(),
            "processes": [{"proc": s.get("proc"), "ts": s.get("ts"),
                           "phase_report": s.get("phase_report"),
                           "rows_recorded":
                           (s.get("compiles") or {}).get("rows_recorded")}
                          for s in snaps]}


def h_trace_list(ctx: Ctx):
    """GET /3/Trace — newest trace ids with root span names."""
    from h2o3_tpu.obs import tracing

    n = int(ctx.arg("count", 50) or 50)
    return {"__meta": S.meta("TraceV3"),
            "traces": tracing.recent_traces(max(min(n, 500), 1))}


def h_trace_get(ctx: Ctx):
    """GET /3/Trace/{trace_id} — one request's span tree: local spans plus
    any follower-side replay/ack spans published through the cloud KV."""
    from h2o3_tpu.obs import tracing

    tid = ctx.params["trace_id"]
    spans = tracing.get_trace(tid)
    if not spans:
        raise ApiError(f"trace {tid!r} not found (bounded store — it may "
                       "have been evicted)", 404)
    return S.trace_v3(tid, spans, tracing.span_tree(spans))


def h_flight_list(ctx: Ctx):
    """GET /3/FlightRecords — newest-first postmortem records under the
    flight dir ($H2O_TPU_OBS_FLIGHT_DIR)."""
    from h2o3_tpu.obs import flight

    return S.flight_records_v3(flight.list_records())


def h_flight_get(ctx: Ctx):
    """GET /3/FlightRecords/{name} — one record's raw JSON (the name
    pattern check is the path-traversal gate)."""
    from h2o3_tpu.obs import flight

    data = flight.read_record(ctx.params["name"])
    if data is None:
        raise ApiError(f"flight record {ctx.params['name']!r} not found",
                       404)
    return RawReply(data, "application/json")


# XLA profiler capture state: one capture at a time per process
# (jax.profiler itself enforces this; the lock keeps our answer coherent)
_PROFILER_LOCK = threading.Lock()
_PROFILER = {"dir": None, "t0": None}


def h_profiler_start(ctx: Ctx):
    """POST /3/Profiler/start — begin an XLA profiler capture
    (``jax.profiler.start_trace`` through compat.py). The resulting trace
    dir is viewable with xprof/tensorboard. 409 when already capturing."""
    from h2o3_tpu import compat
    from h2o3_tpu.utils import timeline

    log_dir = str(ctx.arg("dir", "") or "").strip('"')
    if not log_dir:
        ice = os.environ.get("H2O_TPU_ICE_ROOT", "/tmp/h2o3_tpu")
        log_dir = os.path.join(ice, "profiler",
                               time.strftime("%Y%m%d_%H%M%S"))
    with _PROFILER_LOCK:
        if _PROFILER["dir"] is not None:
            raise ApiError(f"a profiler capture is already running "
                           f"(dir {_PROFILER['dir']!r}) — stop it first",
                           409)
        try:
            compat.profiler_start(log_dir)
        except Exception as e:   # noqa: BLE001 — backend refusal -> 400
            raise ApiError(f"profiler start failed: {e}", 400) from None
        _PROFILER["dir"] = log_dir
        _PROFILER["t0"] = time.perf_counter()
    timeline.record("profiler", "start", dir=log_dir)
    return {"__meta": S.meta("ProfilerV3"), "status": "capturing",
            "dir": log_dir}


def h_profiler_stop(ctx: Ctx):
    """POST /3/Profiler/stop — end the capture; returns the trace dir and
    capture duration. 400 when nothing is capturing."""
    from h2o3_tpu import compat
    from h2o3_tpu.utils import timeline

    with _PROFILER_LOCK:
        if _PROFILER["dir"] is None:
            raise ApiError("no profiler capture is running", 400)
        log_dir, t0 = _PROFILER["dir"], _PROFILER["t0"]
        try:
            compat.profiler_stop()
        except Exception as e:   # noqa: BLE001
            raise ApiError(f"profiler stop failed: {e}", 400) from None
        finally:
            _PROFILER["dir"] = _PROFILER["t0"] = None
    ms = (time.perf_counter() - t0) * 1000
    timeline.record("profiler", "stop", ms=ms, dir=log_dir)
    return {"__meta": S.meta("ProfilerV3"), "status": "stopped",
            "dir": log_dir, "captured_ms": round(ms, 3)}


def h_watermeter_cpu(ctx: Ctx):
    """GET /3/WaterMeterCpuTicks/{nodeidx} — per-node CPU ticks
    (water/util/WaterMeterCpuTicks); /proc-based on linux."""
    ticks = []
    try:
        with open("/proc/stat") as f:
            for ln in f:
                if ln.startswith("cpu") and ln[3:4].isdigit():
                    ticks.append([int(x) for x in ln.split()[1:5]])
    except OSError:
        pass
    return {"__meta": S.meta("WaterMeterCpuTicksV3"),
            "nodeidx": int(ctx.params.get("nodeidx", 0)),
            "cpu_ticks": ticks}


def h_watermeter_io(ctx: Ctx):
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        persist = [{"backend": "local", "store_count": 0,
                    "read_bytes": ru.ru_inblock * 512,
                    "write_bytes": ru.ru_oublock * 512}]
    except Exception:   # noqa: BLE001
        persist = []
    return {"__meta": S.meta("WaterMeterIoV3"),
            "nodeidx": int(ctx.params.get("nodeidx", -1)),
            "persist_stats": persist}


def h_rapids_help(ctx: Ctx):
    from h2o3_tpu.rapids.eval import PRIMS

    return {"__meta": S.meta("RapidsHelpV3"),
            "syntax": sorted(PRIMS.keys())}


def h_sample(ctx: Ctx):
    """GET /99/Sample?dataset=frame&rows=N — uniform row sample."""
    fr = _frame_or_404(str(ctx.arg("dataset", ctx.arg("frame_id", ""))
                           or "").strip('"'))
    rows = int(ctx.arg("rows", 100) or 100)
    seed = int(ctx.arg("seed", -1) or -1)
    rng = np.random.default_rng(None if seed < 0 else seed)
    idx = np.sort(rng.choice(fr.nrows, size=min(rows, fr.nrows),
                             replace=False))
    out = fr.take_rows(idx) if hasattr(fr, "take_rows") else _take(fr, idx)
    out.install()
    return {"__meta": S.meta("FramesV3"), "frames": [
        {"frame_id": S.key_ref(str(out.key)), "rows": out.nrows}]}


def _take(fr: Frame, idx: np.ndarray) -> Frame:
    import jax.numpy as jnp

    out = Frame()
    dev_idx = jnp.asarray(idx)
    for nm in fr.names:
        c = fr.col(nm)
        out.add(nm, Column(jnp.take(c.data, dev_idx, axis=0), c.ctype,
                           len(idx), domain=list(c.domain or []) or None))
    return out


# ---------------------------------------------------------------------------
# Frame utilities: MissingInserter / Interaction / ParseSVMLight / DCT /
# Tabulate
# ---------------------------------------------------------------------------

def h_missing_inserter(ctx: Ctx):
    """POST /3/MissingInserter — randomly NA-out a fraction of cells
    (water/api/MissingInserterHandler). In-place on the named frame."""
    import jax.numpy as jnp

    fr = _frame_or_404(str(ctx.arg("dataset", "") or "").strip('"'))
    frac = float(ctx.arg("fraction", 0.1) or 0.1)
    seed = int(ctx.arg("seed", 42) or 42)
    rng = np.random.default_rng(seed)
    for nm in fr.names:
        c = fr.col(nm)
        if not (c.is_numeric or c.is_categorical):
            continue
        mask = jnp.asarray(rng.random(c.data.shape[0]) < frac)
        if c.is_categorical:
            c.data = jnp.where(mask, -1, c.data)
        else:
            c.data = jnp.where(mask, jnp.nan, c.data)
    job = _done_job(f"MissingInserter {fr.key}", str(fr.key), "Key<Frame>")
    return {"__meta": S.meta("JobV3"), "job": S.job_v3(job),
            "key": S.key_ref(str(fr.key))}


def h_interaction(ctx: Ctx):
    """POST /3/Interaction — categorical interaction frame
    (hex/Interaction.java: pairwise or n-way combined factor columns)."""
    fr = _frame_or_404(str(ctx.arg("source_frame", "") or "").strip('"'))
    factors = _parse_list(ctx.arg("factor_columns")) or []
    if len(factors) < 2:
        raise ApiError("factor_columns needs >= 2 categorical columns", 400)
    pairwise = str(ctx.arg("pairwise", "false")).lower() in ("1", "true")
    max_factors = int(ctx.arg("max_factors", 100) or 100)
    dest = str(ctx.arg("dest", "") or "").strip('"') or \
        f"interaction_{uuid.uuid4().hex[:8]}"
    for nm in factors:
        if not _col_or_404(fr, nm).is_categorical:
            raise ApiError(f"column {nm!r} is not categorical", 400)

    def combine(cols):
        codes = [np.asarray(fr.col(nm).to_numpy(), np.int64) for nm in cols]
        doms = [list(fr.col(nm).domain) for nm in cols]
        combo = np.where(codes[0] < 0, 0, codes[0])   # NA -> level 0
        for c, d in zip(codes[1:], doms[1:]):
            combo = combo * len(d) + np.where(c < 0, 0, c)
        labels, combo = np.unique(combo, return_inverse=True)
        names = []
        for v in labels:
            parts = []
            for d in reversed(doms[1:]):
                parts.append(d[int(v % len(d))])
                v //= len(d)
            parts.append(doms[0][int(v)])
            names.append("_".join(reversed(parts)))
        if len(names) > max_factors:    # collapse tail to 'other'
            keep = set(range(max_factors - 1))
            combo = np.where(np.isin(combo, list(keep)), combo,
                             max_factors - 1)
            names = names[:max_factors - 1] + ["other"]
        return combo.astype(np.int32), names

    out = Frame(key=dest)
    if pairwise:
        for i in range(len(factors)):
            for j in range(i + 1, len(factors)):
                codes, names = combine([factors[i], factors[j]])
                out.add(f"{factors[i]}_{factors[j]}",
                        Column.from_numpy(codes, ctype="enum", domain=names))
    else:
        codes, names = combine(factors)
        out.add("_".join(factors),
                Column.from_numpy(codes, ctype="enum", domain=names))
    out.install()
    job = _done_job("Interaction", dest, "Key<Frame>")
    return {"__meta": S.meta("JobV3"), "job": S.job_v3(job)}


def h_parse_svmlight(ctx: Ctx):
    from h2o3_tpu.ingest.parser import import_file

    srcs = _parse_list(ctx.arg("source_frames")) or \
        _parse_list(ctx.arg("source_keys")) or []
    if not srcs:
        raise ApiError("source_frames required", 400)
    path = str(srcs[0]).strip('"')
    if path.startswith("nfs:/"):
        path = path[len("nfs:"):]
    dest = str(ctx.arg("destination_frame", "") or "").strip('"') or None
    fr = import_file(path, destination_frame=dest, parse_type="SVMLight")
    job = _done_job("ParseSVMLight", str(fr.key), "Key<Frame>")
    return {"__meta": S.meta("JobV3"), "job": S.job_v3(job)}


def h_dct_transformer(ctx: Ctx):
    """POST /99/DCTTransformer — orthonormal DCT-II over each row window
    (hex/util/DCTTransformer.java; device matmul with the cosine basis)."""
    import jax.numpy as jnp

    fr = _frame_or_404(str(ctx.arg("dataset", "") or "").strip('"'))
    dims = _parse_list(ctx.arg("dimensions")) or [fr.ncols, 1, 1]
    N = int(dims[0])
    if N <= 0 or N > fr.ncols:
        raise ApiError(f"dimensions[0]={N} out of range", 400)
    dest = str(ctx.arg("destination_frame", "") or "").strip('"') or \
        f"dct_{uuid.uuid4().hex[:8]}"
    X = jnp.stack([fr.col(nm).data for nm in fr.names[:N]], axis=-1)
    k = jnp.arange(N)[None, :]
    n = jnp.arange(N)[:, None]
    basis = jnp.cos(jnp.pi * (2 * n + 1) * k / (2 * N)) * \
        jnp.sqrt(2.0 / N)
    basis = basis.at[:, 0].multiply(1.0 / jnp.sqrt(2.0))
    Y = X @ basis
    out = Frame(key=dest)
    for j in range(N):
        out.add(f"DCT_{j}", Column(Y[:, j], T_NUM, fr.nrows))
    out.install()
    job = _done_job("DCTTransformer", dest, "Key<Frame>")
    return {"__meta": S.meta("JobV3"), "job": S.job_v3(job)}


def h_tabulate(ctx: Ctx):
    """POST /99/Tabulate — 2-D histogram/response table of predictor vs
    response (hex/Tabulate.java; drives h2o-py h2o.tabulate)."""
    fr = _frame_or_404(str(ctx.arg("dataset", "") or "").strip('"'))
    pred = str(ctx.arg("predictor", "") or "").strip('"')
    resp = str(ctx.arg("response", "") or "").strip('"')
    nbins_p = int(ctx.arg("nbins_predictor", 20) or 20)
    nbins_r = int(ctx.arg("nbins_response", 10) or 10)
    pc, rc = _col_or_404(fr, pred), _col_or_404(fr, resp)

    def bins(col, nb):
        v = np.asarray(col.to_numpy(), float)
        if col.domain:
            edges = None
            b = np.asarray(col.to_numpy(), np.int64)
            labels = list(col.domain)
            return b, labels
        lo, hi = np.nanmin(v), np.nanmax(v)
        edges = np.linspace(lo, hi, nb + 1)
        b = np.clip(np.searchsorted(edges, v, side="right") - 1, 0, nb - 1)
        labels = [f"{edges[i]:.4g}" for i in range(nb)]
        return b, labels

    pb, plabels = bins(pc, nbins_p)
    rb, rlabels = bins(rc, nbins_r)
    P, R = len(plabels), len(rlabels)
    pv_na = (np.asarray(pc.to_numpy(), float) != np.asarray(pc.to_numpy(), float)) \
        if not pc.domain else (np.asarray(pc.to_numpy(), np.int64) < 0)
    rv_all = np.asarray(rc.to_numpy(), float) if not rc.domain else None
    rv_na = np.isnan(rv_all) if rv_all is not None else \
        (np.asarray(rc.to_numpy(), np.int64) < 0)
    ok = ~(pv_na | rv_na)
    counts = np.zeros((P, R))
    np.add.at(counts, (np.clip(pb[ok], 0, P - 1), np.clip(rb[ok], 0, R - 1)), 1)
    count_table = S.twodim(
        f"Tabulate {pred} vs {resp}",
        [(pred, "string")] + [(str(rl), "double") for rl in rlabels],
        [list(plabels)] + [counts[:, j].tolist() for j in range(R)])
    rv = np.asarray(rc.to_numpy(), float)
    sums = np.zeros(P)
    np.add.at(sums, np.clip(pb[ok], 0, P - 1), np.nan_to_num(rv[ok]))
    denom = np.maximum(counts.sum(axis=1), 1)
    resp_table = S.twodim(
        f"Mean {resp} by {pred}",
        [(pred, "string"), ("mean_response", "double")],
        [list(plabels), (sums / denom).tolist()])
    return {"__meta": S.meta("TabulateV3"),
            "count_table": count_table, "response_table": resp_table}


# ---------------------------------------------------------------------------
# Grid import/export (water/api/GridImportExportHandler)
# ---------------------------------------------------------------------------

def h_grid_export(ctx: Ctx):
    from h2o3_tpu.grid import H2OGridSearch

    gid = ctx.params["grid_id"]
    grid = DKV.get(gid)
    if not isinstance(grid, H2OGridSearch):
        raise ApiError(f"grid {gid!r} not found", 404)
    d = str(ctx.arg("grid_directory", ctx.arg("dir", "")) or "").strip('"')
    if not d:
        raise ApiError("grid_directory required", 400)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, gid), "wb") as f:
        pickle.dump(grid, f)
    for m in grid.models:
        with open(os.path.join(d, str(m.key)), "wb") as f:
            pickle.dump(m, f)
    return {"__meta": S.meta("GridSearchV99"), "grid_id":
            S.key_ref(gid, "Key<Grid>")}


def h_grid_import(ctx: Ctx):
    from h2o3_tpu.grid import H2OGridSearch

    path = str(ctx.arg("grid_path", ctx.arg("dir", "")) or "").strip('"')
    if not path or not os.path.exists(path):
        raise ApiError(f"no grid at {path!r}", 404)
    grid = _artifact_load_file(path)
    if not isinstance(grid, H2OGridSearch):
        raise ApiError(f"{path} is not a saved grid", 400)
    d = os.path.dirname(path)
    for m in grid.models:
        mp = os.path.join(d, str(m.key))
        if os.path.exists(mp):
            _artifact_load_file(mp).install()
    grid.install()
    return {"__meta": S.meta("GridSearchV99"),
            "grid_id": S.key_ref(str(grid.key), "Key<Grid>")}


def h_grids_list(ctx: Ctx):
    from h2o3_tpu.grid import H2OGridSearch

    grids = [DKV.get(k) for k in DKV.keys()]
    grids = [g for g in grids if isinstance(g, H2OGridSearch)]
    return {"__meta": S.meta("GridsV99"),
            "grids": [{"grid_id": S.key_ref(str(g.key), "Key<Grid>"),
                       "model_count": len(g.models)} for g in grids]}


# ---------------------------------------------------------------------------
# Assembly (water/api/AssemblyV99)
# ---------------------------------------------------------------------------

def h_assembly_fit(ctx: Ctx):
    """POST /99/Assembly — run a munging pipeline on a frame (h2o-py
    H2OAssembly.fit); steps arrive as the stringified ast list."""
    from h2o3_tpu import assembly as A

    fr = _frame_or_404(str(ctx.arg("frame", "") or "").strip('"'))
    steps_raw = ctx.arg("steps")
    steps = _parse_list(steps_raw) or []
    aid = str(ctx.arg("assembly_id", "") or "").strip('"') or \
        f"assembly_{uuid.uuid4().hex[:8]}"
    try:
        pipe = A.H2OAssembly.from_steps(steps)
    except ValueError as e:
        raise ApiError(str(e), 400) from None
    out = pipe.fit(fr)
    out.install()
    DKV.put(aid, pipe)
    return {"__meta": S.meta("AssemblyV99"),
            "assembly": {"name": aid},
            "assembly_id": S.key_ref(aid, "Key<Assembly>"),
            "result": {"name": str(out.key)}}


def h_assembly_pipeline(ctx: Ctx):
    """POST /99/Assembly/{assembly_id}/pipeline — export the assembly's
    munge fused with a model as a standalone *pipeline artifact*
    (artifact/pipeline.py): one program from raw columns to prediction,
    scored by h2o3_genmodel.aot with no cluster and no munge replay.
    Coordinator-local like the model artifact export (no oplog op)."""
    from h2o3_tpu import artifact
    from h2o3_tpu import assembly as A

    pipe = DKV.get(ctx.params["assembly_id"])
    if not isinstance(pipe, A.H2OAssembly):
        raise ApiError(
            f"assembly {ctx.params['assembly_id']!r} not found", 404)
    fr = _frame_or_404(str(ctx.arg("frame", "") or "").strip('"'))
    m = _model_or_404(str(ctx.arg("model_id", "") or "").strip('"'))
    out_dir = str(ctx.arg("dir", "") or "").strip('"')
    if not out_dir:
        raise ApiError("dir required (server-side artifact directory)", 400)
    raw_buckets = _parse_list(ctx.arg("buckets")) or None
    try:
        buckets = [int(b) for b in raw_buckets] if raw_buckets else None
    except (TypeError, ValueError):
        raise ApiError(f"buckets must be integers, got {raw_buckets!r}",
                       400) from None
    try:
        man = pipe.export_pipeline(m, fr, out_dir, buckets=buckets)
    except artifact.ArtifactError as e:
        raise ApiError(str(e), 400) from None
    return {"__meta": S.meta("AssemblyPipelineV99"),
            "assembly_id": S.key_ref(ctx.params["assembly_id"],
                                     "Key<Assembly>"),
            "model_id": str(m.key),
            "dir": out_dir,
            "model_type": man.get("model_type"),
            "inner": (man.get("pipeline") or {}).get("inner"),
            "inputs": [i.get("name")
                       for i in (man.get("pipeline") or {}).get("inputs",
                                                                [])],
            "buckets": man.get("buckets"),
            "executables": len(man.get("executables") or [])}


def h_assembly_java(ctx: Ctx):
    """GET /99/Assembly.java/{assembly_id}/{pojo_name} — the munging
    pipeline as source (reference emits a Java MungeTransformer; we emit a
    self-contained numpy transform for the same steps)."""
    pipe = DKV.get(ctx.params["assembly_id"])
    if pipe is None:
        raise ApiError(f"assembly {ctx.params['assembly_id']!r} not found", 404)
    name = ctx.params.get("pojo_name", "MungePipeline")
    src = getattr(pipe, "to_source", lambda n: None)(name)
    if src is None:
        src = f"# assembly {ctx.params['assembly_id']}: " \
              f"steps={getattr(pipe, 'describe', lambda: [])()}\n"
    return RawReply(src.encode(), "text/plain",
                    headers={"Content-Disposition":
                             f'attachment; filename="{name}.java"'})


# ---------------------------------------------------------------------------
# Gated integrations (route exists, actionable error when SDK absent)
# ---------------------------------------------------------------------------

def h_import_hive(ctx: Ctx):
    from h2o3_tpu.ingest.sql import import_sql_table

    table = str(ctx.arg("table_name", "") or "").strip('"')
    url = str(ctx.arg("hive_jdbc_url", ctx.arg("database", "")) or "").strip('"')
    if not table:
        raise ApiError("table_name required", 400)
    try:
        fr = import_sql_table(url or "hive://", table)
    except Exception as e:   # noqa: BLE001 — map driver absence to 501
        raise ApiError(
            f"Hive import needs a DB-API Hive driver (pyhive/impyla) on the "
            f"server: {e}", 501) from None
    fr.install()
    job = _done_job("ImportHiveTable", str(fr.key), "Key<Frame>")
    return {"__meta": S.meta("JobV3"), "job": S.job_v3(job)}


def h_save_to_hive(ctx: Ctx):
    raise ApiError("SaveToHiveTable needs a DB-API Hive driver "
                   "(pyhive/impyla) on the server", 501)


def h_decryption_setup(ctx: Ctx):
    """POST /3/DecryptionSetup — register a decryption tool for parse
    (water/parser/DecryptionTool). The null tool (passthrough) is built in;
    AES-SPEC tools need the 'cryptography' package."""
    from h2o3_tpu.ingest import decrypt

    tool = str(ctx.arg("decrypt_tool", "") or "").strip('"') or \
        "water.parser.NullDecryptionTool"
    tool_id = str(ctx.arg("decrypt_impl", "") or "").strip('"') or \
        f"decrypt_{uuid.uuid4().hex[:8]}"
    params = {
        "keystore_type": str(ctx.arg("keystore_type", "") or "").strip('"'),
        "key_alias": str(ctx.arg("key_alias", "") or "").strip('"'),
        "password": str(ctx.arg("password", "") or "").strip('"'),
        "cipher_spec": str(ctx.arg("cipher_spec", "") or "").strip('"'),
    }
    decrypt.register_tool(tool_id, tool, params)
    return {"__meta": S.meta("DecryptionSetupV3"), "decrypt_tool_id":
            S.key_ref(tool_id, "Key<DecryptionTool>")}


# ---------------------------------------------------------------------------
# route table extension
# ---------------------------------------------------------------------------

EXTRA_ROUTES = [
    ("GET", "/3/Capabilities", h_capabilities, "All capabilities"),
    ("GET", "/3/Capabilities/API", h_capabilities_api, "REST capabilities"),
    ("GET", "/3/Capabilities/Core", h_capabilities_core, "Core capabilities"),
    ("GET", "/3/Frames/{frame_id}/columns", h_frame_columns, "Frame columns"),
    ("GET", "/3/Frames/{frame_id}/columns/{column}", h_frame_column,
     "One column"),
    ("GET", "/3/Frames/{frame_id}/columns/{column}/domain",
     h_frame_column_domain, "Column domain"),
    ("GET", "/3/Frames/{frame_id}/columns/{column}/summary",
     h_frame_column_summary, "Column summary"),
    ("GET", "/3/FrameChunks/{frame_id}", h_frame_chunks, "Frame chunk layout"),
    ("POST", "/3/Frames/{frame_id}/export", h_frame_export, "Export frame"),
    ("GET", "/3/Frames/{frame_id}/export/{path}/overwrite/{force}",
     h_frame_export, "Export frame (legacy)"),
    ("POST", "/3/Frames/{frame_id}/save", h_frame_save, "Save frame binary"),
    ("POST", "/3/Frames/load", h_frame_load, "Load saved frame"),
    ("DELETE", "/3/Frames", h_frames_delete_all, "Delete all frames"),
    ("DELETE", "/3/Models", h_models_delete_all, "Delete all models"),
    ("GET", "/3/Models.fetch.bin/{model_id}", h_model_fetch_bin,
     "Model binary artifact"),
    ("GET", "/99/Models.bin/{model_id}", h_model_fetch_bin,
     "Model binary artifact (v99)"),
    ("POST", "/99/Models.bin/{model_id}", h_model_save_bin,
     "Save model binary to dir"),
    ("POST", "/99/Models.bin/", h_model_load_bin, "Load model binary"),
    ("POST", "/99/Models.upload.bin/{model_id}", h_model_upload_bin,
     "Upload model binary"),
    ("GET", "/99/Models.mojo/{model_id}",
     None, "Export MOJO (v99 alias)"),                      # filled below
    ("GET", "/3/Models.java/{model_id}", h_model_java, "POJO source"),
    ("GET", "/3/Models.java/{model_id}/preview", h_model_java_preview,
     "POJO preview"),
    ("GET", "/99/Models/{model_id}/json", h_model_json, "Model JSON (v99)"),
    ("GET", "/3/ModelMetrics", h_modelmetrics_list, "All cached metrics"),
    ("GET", "/3/ModelMetrics/models/{model}", h_modelmetrics_list,
     "Metrics for model"),
    ("GET", "/3/ModelMetrics/frames/{frame}", h_modelmetrics_list,
     "Metrics on frame"),
    ("GET", "/3/ModelMetrics/models/{model}/frames/{frame}",
     h_modelmetrics_list, "Metrics for model on frame"),
    ("GET", "/3/ModelMetrics/frames/{frame}/models/{model}",
     h_modelmetrics_list, "Metrics for model on frame"),
    ("DELETE", "/3/ModelMetrics", h_modelmetrics_delete, "Drop cached metrics"),
    ("DELETE", "/3/ModelMetrics/models/{model}", h_modelmetrics_delete,
     "Drop metrics for model"),
    ("DELETE", "/3/ModelMetrics/frames/{frame}", h_modelmetrics_delete,
     "Drop metrics on frame"),
    ("DELETE", "/3/ModelMetrics/models/{model}/frames/{frame}",
     h_modelmetrics_delete, "Drop metrics"),
    ("DELETE", "/3/ModelMetrics/frames/{frame}/models/{model}",
     h_modelmetrics_delete, "Drop metrics"),
    ("POST", "/3/ModelMetrics/predictions_frame/{predictions_frame}"
             "/actuals_frame/{actuals_frame}",
     h_modelmetrics_predictions_vs_actuals, "Metrics from predictions"),
    ("GET", "/3/NodePersistentStorage/configured", h_nps_configured,
     "NPS configured?"),
    ("GET", "/3/NodePersistentStorage/categories/{category}/exists",
     h_nps_category_exists, "NPS category exists?"),
    ("GET", "/3/NodePersistentStorage/categories/{category}/names/{name}"
            "/exists", h_nps_name_exists, "NPS entry exists?"),
    ("GET", "/3/NodePersistentStorage/{category}", h_nps_list, "NPS list"),
    ("GET", "/3/NodePersistentStorage/{category}/{name}", h_nps_get,
     "NPS fetch"),
    ("POST", "/3/NodePersistentStorage/{category}", h_nps_put, "NPS store"),
    ("POST", "/3/NodePersistentStorage/{category}/{name}", h_nps_put,
     "NPS store named"),
    ("DELETE", "/3/NodePersistentStorage/{category}/{name}", h_nps_delete,
     "NPS delete"),
    ("GET", "/3/JStack", h_jstack, "Thread stack dump"),
    ("GET", "/3/KillMinus3", h_kill_minus_3, "Log thread dump"),
    ("POST", "/3/LogAndEcho", h_log_and_echo, "Log a message"),
    ("GET", "/3/Logs/nodes/{nodeidx}/files/{name}", h_logs_node_file,
     "Per-node log file"),
    ("GET", "/3/Typeahead/files", h_typeahead_files, "Path completion"),
    ("GET", "/3/Find", h_find, "Find value in frame"),
    ("POST", "/3/CloudLock", h_cloud_lock, "Lock the cloud"),
    ("POST", "/3/GarbageCollect", h_gc, "Run GC + cleaner sweep"),
    ("POST", "/3/UnlockKeys", h_unlock_keys, "Unlock all keys"),
    ("GET", "/3/SteamMetrics", h_steam_metrics, "Steam health metrics"),
    ("GET", "/3/CloudStatus", h_cloud_status,
     "Supervised cloud health state machine"),
    ("GET", "/3/ScoringMetrics", h_scoring_metrics,
     "Serving fast-path scoring metrics"),
    ("GET", "/3/Metrics", h_metrics,
     "Cluster-wide metrics (Prometheus text / JSON)"),
    ("GET", "/3/Runtime", h_runtime,
     "Lifecycle phase history + cluster compile ledger"),
    ("GET", "/3/Trace", h_trace_list, "Recent trace ids"),
    ("GET", "/3/Trace/{trace_id}", h_trace_get, "One request's span tree"),
    ("GET", "/3/FlightRecords", h_flight_list,
     "List flight-recorder postmortems"),
    ("GET", "/3/FlightRecords/{name}", h_flight_get,
     "Fetch one flight record"),
    ("POST", "/3/Profiler/start", h_profiler_start,
     "Start an XLA profiler capture"),
    ("POST", "/3/Profiler/stop", h_profiler_stop,
     "Stop the XLA profiler capture"),
    ("GET", "/3/WaterMeterCpuTicks/{nodeidx}", h_watermeter_cpu,
     "CPU tick counters"),
    ("GET", "/3/WaterMeterIo", h_watermeter_io, "IO counters"),
    ("GET", "/3/WaterMeterIo/{nodeidx}", h_watermeter_io,
     "IO counters (node)"),
    ("GET", "/99/Rapids/help", h_rapids_help, "Rapids primitive list"),
    ("GET", "/99/Sample", h_sample, "Sample rows from a frame"),
    ("POST", "/3/MissingInserter", h_missing_inserter, "Insert missing values"),
    ("POST", "/3/Interaction", h_interaction, "Categorical interactions"),
    ("POST", "/3/ParseSVMLight", h_parse_svmlight, "Parse SVMLight file"),
    ("POST", "/99/DCTTransformer", h_dct_transformer, "Row-window DCT"),
    ("POST", "/99/Tabulate", h_tabulate, "Predictor-response table"),
    ("POST", "/3/Grid.bin/{grid_id}/export", h_grid_export, "Export grid"),
    ("POST", "/3/Grid.bin/import", h_grid_import, "Import grid"),
    ("GET", "/99/Grids", h_grids_list, "List grids"),
    ("POST", "/99/Assembly", h_assembly_fit, "Fit a munging assembly"),
    ("POST", "/99/Assembly/{assembly_id}/pipeline", h_assembly_pipeline,
     "Export assembly+model as a standalone pipeline artifact"),
    ("GET", "/99/Assembly.java/{assembly_id}/{pojo_name}", h_assembly_java,
     "Assembly pipeline source"),
    ("POST", "/3/ImportHiveTable", h_import_hive, "Import a Hive table"),
    ("POST", "/3/SaveToHiveTable", h_save_to_hive, "Save to Hive table"),
    ("POST", "/3/DecryptionSetup", h_decryption_setup,
     "Register a parse decryption tool"),
    ("GET", "/3/Metadata/endpoints/{path}", h_metadata_endpoint,
     "One endpoint's metadata"),
    ("GET", "/3/Metadata/schemaclasses/{classname}", h_metadata_schemaclass,
     "Schema detail by class name"),
]


def register(routes: list, handlers: dict) -> None:
    """Append EXTRA_ROUTES onto the server ROUTES table; `handlers` maps
    names already defined in server.py reused by aliases. Idempotent —
    both server.py's bottom and _ensure_registered may call it."""
    if any(r[2] is h_capabilities for r in routes):
        return
    mojo = handlers["h_model_mojo"]
    importfiles = handlers["h_importfiles"]
    pdp_post = handlers["h_pdp_post"]
    pdp_get = handlers["h_pdp_get"]
    fixed = []
    for m, p, h, s in EXTRA_ROUTES:
        if h is None and "Models.mojo" in p:
            h = mojo
        fixed.append((m, p, h, s))
    fixed += [
        ("POST", "/3/ImportFiles", importfiles, "List importable files"),
        # reference singular spellings of PartialDependence
        ("POST", "/3/PartialDependence/", pdp_post, "Compute PDP"),
        ("GET", "/3/PartialDependence/{key}", pdp_get, "PDP result"),
        # train-with-model_id spelling (TrainModelV3 model_id path segment);
        # the train handler reads model_id from the body either way
        ("POST", "/3/ModelBuilders/{algo}/model_id", handlers.get(
            "h_modelbuilder_train", importfiles), "Train with model_id"),
        ("DELETE", "/3/InitID", handlers.get("h_session_end_legacy",
                                             importfiles), "End session"),
    ]
    routes.extend(fixed)


def _ensure_registered():
    """Import-order independence: when THIS module is imported before
    server.py finishes (server's bottom couldn't call register on the
    partial module), append + recompile here instead."""
    srv = sys.modules.get("h2o3_tpu.api.server")
    if srv is None or not hasattr(srv, "_COMPILED"):
        return      # server mid-import: its bottom registers us
    if any(r[2] is h_capabilities for r in srv.ROUTES):
        return      # already registered
    register(srv.ROUTES, {"h_model_mojo": srv.h_model_mojo,
                          "h_importfiles": srv.h_importfiles,
                          "h_pdp_post": srv.h_pdp_post,
                          "h_pdp_get": srv.h_pdp_get,
                          "h_modelbuilder_train": srv.h_modelbuilder_train,
                          "h_session_end_legacy": srv.h_session_end})
    srv._COMPILED = srv._compile_routes()


_ensure_registered()
