"""REST API server — the /3 (+/4, /99) HTTP surface stock h2o-py speaks.

Reference: water/api/RequestServer.java:56 with the RegisterV3Api.java route
table (~122 routes) and the water/api/schemas3 DTO layer. Serving stack is
jetty in the reference; here a stdlib ThreadingHTTPServer — the API layer
carries only JSON metadata, all heavy data stays device-side, so a native
web stack buys nothing on TPU.

Design: a declarative ROUTES table (method, pattern, handler, summary) —
the same shape as RequestServer's route registry — drives both dispatch and
the self-describing /3/Metadata/endpoints listing that h2o-bindings-style
codegen introspects (water/api/SchemaServer.java:20).

Contract notes (verified against h2o-py):
- every schema'd response carries __meta.schema_name; H2OResponse.__new__
  (h2o-py backend/connection.py:869) dispatches on it.
- jobs flow: POST returns {"job": JobV3}; client polls GET /3/Jobs/{key}.
- model builds are asynchronous background Jobs, like hex/ModelBuilder
  trainModel() (:359).
"""

from __future__ import annotations

import contextlib
import io
import json
import math
import os
import re
import threading
import time
import traceback
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

import numpy as np

from h2o3_tpu import admission
from h2o3_tpu.admission import AdmissionRejected
from h2o3_tpu.memory import MemoryPressureError
from h2o3_tpu.api import schemas as S
from h2o3_tpu.obs import metrics as obs_metrics
from h2o3_tpu.obs import tracing
from h2o3_tpu.core.dkv import DKV, Key
from h2o3_tpu.core.failure import CloudUnhealthyError
from h2o3_tpu.core.frame import Frame
from h2o3_tpu.core.job import Job
from h2o3_tpu.models.model import Model
from h2o3_tpu.parallel.oplog import OplogPublishError, OplogTurnTimeout
from h2o3_tpu.rapids import Session, exec_rapids

_SESSIONS: Dict[str, Session] = {}
_TIMELINE: List[dict] = []          # ring of recent requests (water/TimeLine.java:22)
_TIMELINE_MAX = 2048


def _timeline_record(method: str, path: str, status: int, ms: float):
    _TIMELINE.append({"time_ms": int(time.time() * 1000), "method": method,
                      "path": path, "status": status, "duration_ms": round(ms, 3)})
    if len(_TIMELINE) > _TIMELINE_MAX:
        del _TIMELINE[: len(_TIMELINE) - _TIMELINE_MAX]


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


# unquoted NaN/Infinity as json.dumps emits them: preceded by a structural
# character, never inside a quoted string (dumps escapes quotes, so a
# [,: or space before the token means it is a bare literal)
_BARE_NONFINITE = re.compile(rb"[\[,:\s](?:NaN|-?Infinity)[,\]\}\s]")


def _definite(o):
    """Recursively replace non-finite floats with None (the slow path of
    _reply_json, taken only when the fast serialization contains NaN)."""
    if isinstance(o, float):
        return o if math.isfinite(o) else None
    if isinstance(o, np.floating):
        f = float(o)
        return f if math.isfinite(f) else None
    if isinstance(o, dict):
        return {k: _definite(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_definite(v) for v in o]
    if isinstance(o, np.ndarray):
        return _definite(o.tolist())
    return o


def _parse_list(v) -> Optional[list]:
    """Tolerant list parse: accepts JSON, h2o-py stringify_list ('[a,b]' with
    optionally-quoted items), or an actual list."""
    if v is None:
        return None
    if isinstance(v, list):
        return v
    s = str(v).strip()
    if not s.startswith("["):
        return [s.strip('"')]
    try:
        return json.loads(s)
    except ValueError:
        inner = s[1:-1].strip()
        if not inner:
            return []
        return [p.strip().strip('"').strip("'") for p in inner.split(",")]


def _coerce(v: Any, template: Any) -> Any:
    """Coerce a form-encoded string to the type of a default value."""
    if not isinstance(v, str):
        return v
    s = v.strip()
    if s.startswith("[") or s.startswith("{"):
        try:
            return json.loads(s)
        except ValueError:
            return _parse_list(s)
    if isinstance(template, bool):
        return s.lower() in ("true", "1")
    if isinstance(template, int) and not isinstance(template, bool):
        try:
            return int(float(s))
        except ValueError:
            return s.strip('"')
    if isinstance(template, float):
        try:
            return float(s)
        except ValueError:
            return s.strip('"')
    if isinstance(template, (list, tuple)):
        return _parse_list(s)
    if s.lower() in ("true", "false"):
        return s.lower() == "true"
    return s.strip('"')


def _frame_or_404(fid: str) -> Frame:
    fr = DKV.get(fid)
    if not isinstance(fr, Frame):
        raise ApiError(f"Object '{fid}' not found for argument: frame", 404)
    return fr


def _model_or_404(mid: str) -> Model:
    m = DKV.get(mid)
    if not isinstance(m, Model):
        raise ApiError(f"Object '{mid}' not found for argument: model", 404)
    return m


class ApiError(Exception):
    def __init__(self, msg: str, status: int = 400, schema: str = "H2OErrorV3"):
        super().__init__(msg)
        self.status = status
        self.schema = schema


# ---------------------------------------------------------------------------
# handlers (each: fn(ctx) -> (obj, status)); ctx carries path/query/body
# ---------------------------------------------------------------------------

class Ctx:
    def __init__(self, params: Dict[str, str], query: Dict[str, str],
                 body: Dict[str, Any], server: "ApiServer"):
        self.params = params
        self.query = query
        self.body = body
        self.server = server

    def arg(self, name: str, default=None):
        # parse_qs already URL-decoded form/query values; JSON was never
        # encoded — do NOT unquote again (it corrupts literal '%xx').
        return self.body.get(name, self.query.get(name, default))


def h_cloud(ctx: Ctx):
    from h2o3_tpu.core.failure import cluster_health
    from h2o3_tpu.core.runtime import cluster_info
    from h2o3_tpu.parallel import supervisor

    out = S.cloud_v3(cluster_info())
    hb = cluster_health()
    if hb:          # multi-process cloud: liveness table per process
        out["process_health"] = hb
        out["cloud_healthy"] = bool(out.get("cloud_healthy", True)) and \
            all(r["healthy"] for r in hb)
    # supervised health state machine (HEALTHY/DEGRADED/FAILED); detail at
    # GET /3/CloudStatus
    out["cloud_status"] = supervisor.state()
    if out["cloud_status"] != supervisor.HEALTHY:
        out["cloud_healthy"] = False
    return out


def h_about(ctx: Ctx):
    return {"__meta": S.meta("AboutV3"), "entries": [
        {"name": "Build project", "value": "h2o3_tpu"},
        {"name": "Build version", "value": S.SERVER_VERSION},
        {"name": "Backend", "value": "jax/XLA (TPU-native)"}]}


def h_ping(ctx: Ctx):
    return {"__meta": S.meta("PingV3"), "status": "running"}


def h_session_new(ctx: Ctx):
    sid = f"_sid{uuid.uuid4().hex[:12]}"
    _SESSIONS[sid] = Session(sid)
    return {"__meta": S.meta("InitIDV3"), "session_key": sid}


def h_session_end(ctx: Ctx):
    sid = ctx.params.get("session_key", "")
    sess = _SESSIONS.pop(sid, None)
    if sess is not None:
        sess.end()
    return {"__meta": S.meta("InitIDV3"), "session_key": sid}


def h_shutdown(ctx: Ctx):
    threading.Thread(target=ctx.server.stop, daemon=True).start()
    return {"__meta": S.meta("ShutdownV3"), "result": "shutting down"}


def h_logs(ctx: Ctx):
    import logging

    lines: List[str] = []
    for h in logging.getLogger("h2o3_tpu").handlers:
        f = getattr(h, "baseFilename", None)
        if f:
            try:
                with open(f) as fh:
                    lines = fh.read().splitlines()[-500:]
            except OSError:
                pass
    return {"__meta": S.meta("LogsV3"), "log": "\n".join(lines)}


def h_timeline(ctx: Ctx):
    """REST request ring merged with the framework TimeLine (task profiles,
    XLA traces, boot probes) — water/TimeLine.java:22 + TimelineHandler."""
    from h2o3_tpu.utils import timeline

    evs = ([dict(e, kind="rest") for e in _TIMELINE] + timeline.events())
    evs.sort(key=lambda e: e.get("time_ms", 0))
    return {"__meta": S.meta("TimelineV3"), "events": evs}


def h_profiler(ctx: Ctx):
    """GET /3/Profiler — per-device HBM gauges (the reference's JVM stack
    profiles map to device memory pressure here)."""
    from h2o3_tpu.utils import timeline

    return {"__meta": S.meta("ProfilerV3"), "nodes": timeline.device_memory()}


def h_flow(ctx: Ctx):
    """Serve the Flow single-page app (api/flow.html): import → parse →
    train → leaderboard → predict over the existing REST routes. Falls back
    to the plain status dashboard if the packaged asset is missing.
    Reference: h2o-web/ Flow notebook packaging."""
    fpath = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "flow.html")
    if os.path.exists(fpath):
        with open(fpath, "rb") as f:
            return RawReply(f.read(), "text/html")
    return h_flow_status(ctx)


def h_flow_status(ctx: Ctx):
    """Plain status dashboard (pre-round-5 Flow landing)."""
    from h2o3_tpu.core.runtime import cluster_info

    import html as _html

    esc = _html.escape
    info = cluster_info()
    frames = [str(k) for k in DKV.keys() if isinstance(DKV.get(k), Frame)]
    models = [str(k) for k in DKV.keys() if isinstance(DKV.get(k), Model)]
    # keys are caller-controlled strings: escape everything interpolated
    rows_f = "".join(f"<li><code>{esc(f)}</code></li>" for f in frames[:50])
    rows_m = "".join(f"<li><code>{esc(m)}</code></li>" for m in models[:50])
    html = f"""<!doctype html><html><head><title>h2o3-tpu</title>
<style>body{{font-family:sans-serif;margin:2em}}code{{background:#eee}}</style>
</head><body>
<h1>h2o3-tpu</h1>
<p>cloud <b>{esc(str(info['cloud_name']))}</b> — {info['cloud_size']} devices on
<b>{esc(str(info['platform']))}</b>, healthy: {info['cloud_healthy']}</p>
<h2>Frames ({len(frames)})</h2><ul>{rows_f or '<li>none</li>'}</ul>
<h2>Models ({len(models)})</h2><ul>{rows_m or '<li>none</li>'}</ul>
<p>REST: <a href="/3/Cloud">/3/Cloud</a> ·
<a href="/3/Frames">/3/Frames</a> · <a href="/3/Models">/3/Models</a> ·
<a href="/3/Timeline">/3/Timeline</a> ·
<a href="/3/Metadata/endpoints">/3/Metadata/endpoints</a></p>
</body></html>"""
    return RawReply(html.encode(), "text/html")


# -- import / parse ---------------------------------------------------------

def _list_files(path: str) -> List[str]:
    import glob as _g
    import os

    if any(ch in path for ch in "*?"):
        return sorted(_g.glob(path))
    if os.path.isdir(path):
        return sorted(os.path.join(path, f) for f in os.listdir(path))
    return [path] if os.path.exists(path) or "://" in path else []


def h_importfiles(ctx: Ctx):
    path = ctx.arg("path", "")
    files = _list_files(path)
    return {"__meta": S.meta("ImportFilesV3"), "path": path,
            "files": files, "destination_frames": files,
            "fails": [] if files else [path], "dels": []}


def h_importfiles_multi(ctx: Ctx):
    paths = _parse_list(ctx.arg("paths")) or []
    files: List[str] = []
    fails: List[str] = []
    for p in paths:
        got = _list_files(p)
        files.extend(got)
        if not got:
            fails.append(p)
    return {"__meta": S.meta("ImportFilesMultiV3"), "paths": paths,
            "files": files, "destination_frames": files, "fails": fails,
            "dels": []}


def h_postfile(ctx: Ctx):
    """Multipart upload → raw file key (upload_file path)."""
    dest = ctx.query.get("destination_frame") or f"upload_{uuid.uuid4().hex[:8]}"
    data = ctx.body.get("__file__")
    if data is None:
        raise ApiError("no file payload", 400)
    import os
    import tempfile

    d = tempfile.mkdtemp(prefix="h2o3_upload_")
    fpath = os.path.join(d, dest.replace("/", "_"))
    with open(fpath, "wb") as f:
        f.write(data)
    DKV.put(dest, fpath)          # raw file key → local path
    return {"__meta": S.meta("PostFileV3"), "destination_frame": dest,
            "total_bytes": len(data)}


def _resolve_sources(paths: List[str]) -> List[str]:
    """Map source_frames entries (raw upload keys or literal paths) to paths."""
    out = []
    for p in paths:
        v = DKV.get(p)
        out.append(v if isinstance(v, str) else p)
    return out


def h_parsesetup(ctx: Ctx):
    from h2o3_tpu.ingest.parse_setup import guess_setup

    paths = [p.strip('"') for p in (_parse_list(ctx.arg("source_frames")) or [])]
    if not paths:
        raise ApiError("source_frames required", 400)
    real = _resolve_sources(paths)
    setup = guess_setup(real[0])
    col_names = ctx.arg("column_names")
    col_types = ctx.arg("column_types")
    sep = ctx.arg("separator")
    check_header = ctx.arg("check_header")
    names = _parse_list(col_names) if col_names else setup.column_names
    types = _parse_list(col_types) if col_types else setup.column_types
    return {
        "__meta": S.meta("ParseSetupV3"),
        "source_frames": [{"__meta": S.meta("FrameKeyV3"), "name": p} for p in paths],
        "parse_type": "CSV",
        "separator": int(sep) if sep else ord(setup.separator),
        "single_quotes": False,
        "check_header": int(check_header) if check_header is not None else setup.check_header,
        "column_names": names,
        "column_types": types,
        "na_strings": None,
        "number_columns": len(names or types or []),
        "skipped_columns": [],
        "custom_non_data_line_markers": None,
        "partition_by": None,
        "destination_frame": _default_dest(paths[0]),
        "header_lines": 0,
        "chunk_size": 1 << 22,
        "total_filtered_column_count": len(names or types or []),
        "warnings": [],
    }


def _default_dest(path: str) -> str:
    base = path.rstrip("/").split("/")[-1]
    base = re.sub(r"\.(csv|tsv|txt|dat|gz|zip)$", "", base, flags=re.I)
    key = re.sub(r"[^\w.]", "_", base) + ".hex"
    return key


def h_parse(ctx: Ctx):
    from h2o3_tpu.ingest.parser import import_file

    paths = [p.strip('"') for p in (_parse_list(ctx.arg("source_frames")) or [])]
    real = _resolve_sources(paths)
    dest = (str(ctx.arg("destination_frame") or "")).strip('"') or _default_dest(paths[0])
    col_names = [str(c).strip('"') for c in (_parse_list(ctx.arg("column_names")) or [])] or None
    col_types = [str(c).strip('"') for c in (_parse_list(ctx.arg("column_types")) or [])] or None
    check_header = ctx.arg("check_header")
    from h2o3_tpu.parallel import oplog

    if oplog.active() and len(real) > 1:
        # before Job() so a rejected request leaves no phantom CREATED job
        raise ApiError("multi-file parse over REST is not yet "
                       "supported on a multi-process cloud", 501)
    job = Job(description="Parse")
    job.dest_type = "Key<Frame>"
    job.dest_key = dest

    # followers must run the SAME parse so the sharded frame materializes
    # on every process of the cloud
    op_seq = oplog.broadcast("import_file", {
        "path": real[0], "destination_frame": dest,
        "col_names": col_names, "col_types": col_types,
        "header": int(check_header) if check_header is not None else None})

    def run(j: Job):
        from h2o3_tpu.parallel import oplog as _ol

        kw = dict(col_names=col_names, col_types=col_types,
                  header=int(check_header) if check_header is not None else 0)
        with _ol.turn(op_seq):
            fr = import_file(real[0], destination_frame=dest, **kw)
        if len(real) > 1:
            # multi-file import: parse each file and stack (reference
            # MultiFileParseTask parses all byte-chunks into ONE frame,
            # water/parser/ParseDataset.java:623)
            from h2o3_tpu.ops.filters import rbind

            parts = [fr]
            for i, p in enumerate(real[1:]):
                j.update(progress=(i + 1) / len(real), msg=f"parsing {p}")
                parts.append(import_file(p, destination_frame=f"{dest}_part{i+1}", **kw))
            fr = rbind(parts, key=dest)
            for part in parts:
                part.delete()
            fr.install()
        j.dest_key = str(fr.key)
        return fr

    job.start(run, background=True)
    return {"__meta": S.meta("ParseV3"), "job": S.job_v3(job),
            "destination_frame": {"name": dest}}


def h_parsestream(ctx: Ctx):
    """POST /3/ParseStream — stream-append a CSV micro-batch to an
    installed frame (ISSUE 15 streaming scenario: train-on-static +
    score-on-streaming). Body: ``destination_frame`` (existing frame),
    ``data`` (CSV rows, NO header, columns in frame order), optional
    ``separator``. Rows land as new shard-tail chunks through one fused
    device concat per column (ingest/chunked.append_csv) with rollups
    updated incrementally; on multi-process clouds the append rides the
    oplog so every process grows the same shards in lockstep."""
    dest = (str(ctx.arg("destination_frame") or "")).strip('"')
    fr = _frame_or_404(dest)
    data = ctx.arg("data")
    if not data:
        raise ApiError("data (CSV rows, no header) required", 400)
    from h2o3_tpu.ingest import chunked
    from h2o3_tpu.parallel import oplog

    # default to the separator the frame was IMPORTED with (a tab-separated
    # frame streams tab-separated rows without repeating it per request);
    # the broadcast carries the RESOLVED value so followers parse alike
    sep = chunked.stream_separator(fr, str(ctx.arg("separator") or "") or
                                   None)

    # preflight BEFORE the broadcast (the h_predict_v3 pattern): a batch
    # with a stray delimiter or a non-numeric token in a numeric column
    # must be a clean 400 here — raising inside every follower's mirrored
    # replay would fail the whole cloud. The batch deliberately parses
    # twice (preflight + append): micro-batches are small by design, and
    # threading the parsed result into only the coordinator's append would
    # fork its code path from the follower replay's
    try:
        chunked.validate_batch(fr, str(data), sep)
    except ValueError as e:
        raise ApiError(str(e), 400)
    op_seq = oplog.broadcast("parse_stream", {
        "frame": dest, "data": str(data), "separator": sep})
    with oplog.turn(op_seq):
        added = chunked.append_csv(fr, str(data), sep)
    return {"__meta": S.meta("ParseStreamV3"), "destination_frame": dest,
            "rows_appended": added, "total_rows": fr.nrows}


# -- jobs -------------------------------------------------------------------

def _find_job(key: str) -> Job:
    j = DKV.get(key)
    if not isinstance(j, Job):
        raise ApiError(f"Job {key} not found", 404)
    return j


def h_jobs_list(ctx: Ctx):
    jobs = [v for v in (DKV.get(k) for k in DKV.keys()) if isinstance(v, Job)]
    return {"__meta": S.meta("JobsV3"), "jobs": [S.job_v3(j) for j in jobs]}


def h_job_get(ctx: Ctx):
    return {"__meta": S.meta("JobsV3"), "jobs": [S.job_v3(_find_job(ctx.params["job_id"]))]}


def h_job_cancel(ctx: Ctx):
    _find_job(ctx.params["job_id"]).cancel()
    return {"__meta": S.meta("JobsV3"), "jobs": []}


# -- rapids -----------------------------------------------------------------

def h_rapids(ctx: Ctx):
    """POST /99/Rapids — execute (or defer) one statement.

    Lazy-session semantics (rapids/planner.py): a deferrable assignment
    returns immediately with the temp's key/nrows/ncols — its columns
    are lazy, so the reply costs no device work. The flush points are
    (a) any later statement the planner cannot defer, and (b) ANY data
    access on the temp — `GET /3/Frames/{key}` (the fetch h2o-py issues
    on frame refresh), CSV export/download, and model builds on the temp
    all materialize it transparently. `DELETE /4/sessions/{id}` retires
    the session's whole DAG without computing dead temps."""
    ast = ctx.arg("ast", "")
    sid = str(ctx.arg("session_id", "default"))
    sess = _SESSIONS.setdefault(sid, Session(sid))
    from h2o3_tpu.obs import metrics as obs_metrics
    from h2o3_tpu.parallel import oplog

    # munging runs device programs too: replay the same AST cloud-wide
    op_seq = oplog.broadcast("rapids", {"ast": str(ast), "session_id": sid})
    t0 = time.perf_counter()
    with oplog.turn(op_seq):
        # exec_rapids emits parse/plan/execute/fused_dispatch child spans
        # on the ingress trace (wall-clock only — no device syncs added)
        val = exec_rapids(ast, sess)
    obs_metrics.observe("h2o3_rapids_statement_seconds",
                        time.perf_counter() - t0)
    out: Dict[str, Any] = {"__meta": S.meta("RapidsFrameV3", "RapidsFrameV3")}
    if isinstance(val, Frame):
        if DKV.get(str(val.key)) is None:
            val.install()
        out.update({"key": {"name": str(val.key)},
                    "num_rows": val.nrows, "num_cols": val.ncols})
        return out
    if isinstance(val, (bool, np.bool_)):
        return {"__meta": S.meta("RapidsScalarV3"), "scalar": bool(val)}
    if isinstance(val, (int, float, np.integer, np.floating)):
        v = float(val)
        return {"__meta": S.meta("RapidsScalarV3"), "scalar": None if v != v else v}
    if isinstance(val, str):
        return {"__meta": S.meta("RapidsStringV3"), "string": val}
    if isinstance(val, (list, tuple, np.ndarray)):
        return {"__meta": S.meta("RapidsScalarV3"),
                "scalar": [None if (isinstance(x, float) and x != x) else x
                           for x in np.asarray(val).tolist()]}
    return {"__meta": S.meta("RapidsScalarV3"), "scalar": None}


# -- frames -----------------------------------------------------------------

def _frame_reply(fr: Frame, ctx: Ctx, with_data: bool = True):
    rc = int(ctx.arg("row_count", 10) or 10)
    ro = int(ctx.arg("row_offset", 0) or 0)
    cc = int(ctx.arg("column_count", -1) or -1)
    co = int(ctx.arg("column_offset", 0) or 0)
    fj = S.frame_v3(fr, row_count=rc, row_offset=ro, column_count=cc,
                    column_offset=co, with_data=with_data)
    fj["column_names"] = fr.names        # in-repo thin-client convenience
    return fj


def h_frames_list(ctx: Ctx):
    frames = [v for v in (DKV.get(k) for k in DKV.keys()) if isinstance(v, Frame)]
    return {"__meta": S.meta("FramesListV3"),
            "frames": [S.frame_v3(f, with_data=False) | {"column_names": f.names}
                       for f in frames]}


def h_frame_get(ctx: Ctx):
    fr = _frame_or_404(ctx.params["frame_id"])
    return {"__meta": S.meta("FramesV3"), "frames": [_frame_reply(fr, ctx)]}


def h_frame_light(ctx: Ctx):
    fr = _frame_or_404(ctx.params["frame_id"])
    return {"__meta": S.meta("FramesV3"), "frames": [_frame_reply(fr, ctx)]}


def h_frame_summary(ctx: Ctx):
    fr = _frame_or_404(ctx.params["frame_id"])
    fj = _frame_reply(fr, ctx)
    fj["summary"] = fr.summary()
    for cj in fj["columns"]:
        col = fr.col(cj["label"])
        if col.is_numeric:
            from h2o3_tpu.ops.quantile import quantile_column

            probs = [0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9, 0.99]
            try:
                cj["percentiles"] = [float(v) for v in quantile_column(col, probs)]
                cj["default_percentiles"] = probs
            except Exception:   # noqa: BLE001 — summary stays best-effort
                pass
    return {"__meta": S.meta("FramesV3"), "frames": [fj]}


def h_frame_delete(ctx: Ctx):
    DKV.remove(ctx.params["frame_id"])
    from h2o3_tpu.api import routes_ext

    routes_ext.purge_metrics(frame_key=ctx.params["frame_id"])
    return {"__meta": S.meta("FramesV3")}


def h_dkv_delete(ctx: Ctx):
    DKV.remove(ctx.params["key"])
    return {"__meta": S.meta("RemoveV3")}


def h_dkv_delete_all(ctx: Ctx):
    DKV.clear()
    return {"__meta": S.meta("RemoveAllV3")}


def h_download_dataset(ctx: Ctx):
    fr = _frame_or_404(str(ctx.arg("frame_id", "")))
    df = fr.to_pandas()
    buf = io.StringIO()
    df.to_csv(buf, index=False)
    return RawReply(buf.getvalue().encode(), "text/plain")


# -- model builders ---------------------------------------------------------

def _builders():
    from h2o3_tpu.models.model_builder import BUILDERS

    return BUILDERS


def _builder_schema(name: str, cls) -> dict:
    return {
        "__meta": S.meta("ModelBuilderSchema"),
        "algo": name, "algo_full_name": name.upper(),
        "can_build": ["Supervised" if cls.supervised else "Unsupervised"],
        "visibility": "Stable",
        "parameters": [S.model_parameter_v3(k, v, v)
                       for k, v in cls.default_params().items()],
        "messages": [], "error_count": 0,
    }


def h_modelbuilders_list(ctx: Ctx):
    return {"__meta": S.meta("ModelBuildersV3"),
            "model_builders": {name: _builder_schema(name, cls)
                               for name, cls in _builders().items()}}


def h_modelbuilder_get(ctx: Ctx):
    algo = ctx.params["algo"].lower()
    cls = _builders().get(algo)
    if cls is None:
        raise ApiError(f"unknown algo {algo!r}", 404)
    return {"__meta": S.meta("ModelBuildersV3"),
            "model_builders": {algo: _builder_schema(algo, cls)}}


def _pin_seed_and_wire(params: Dict[str, Any]) -> Dict[str, Any]:
    """Prepare builder params for an oplog broadcast: every process must
    draw the SAME host-side sampling masks, so a wildcard seed is pinned
    IN PLACE before the op ships; the returned copy keeps only
    JSON-serializable values (and drops model_id — the op carries the
    destination separately)."""
    if params.get("seed") in (None, -1):
        params["seed"] = int(uuid.uuid4().int % (2 ** 31))
    wire = {k: v for k, v in params.items()
            if isinstance(v, (int, float, str, bool, type(None), list))}
    wire.pop("model_id", None)
    return wire


def _clear_wallclock_budget(params: Dict[str, Any], what: str) -> bool:
    """Zero ``max_runtime_secs`` IN PLACE ahead of an oplog broadcast.

    The budget is wall-clock measured per process (``_out_of_time`` polls
    ``time.time()`` inside the fit loops): on a mirrored op each process
    would stop training at a DIFFERENT iteration, desynchronizing the
    per-iteration device collectives — the mirrored-program invariant the
    static analyzer pins (``h2o3_tpu/analysis``, mirrored pass). The
    AutoML handler has cleared it since PR 4; train and grid broadcasts
    shipped it until the analyzer surfaced the gap. Returns True when a
    non-zero budget was cleared (callers log the downgrade)."""
    if float(params.get("max_runtime_secs") or 0.0) <= 0:
        return False
    params["max_runtime_secs"] = 0.0
    import logging

    logging.getLogger("h2o3_tpu").warning(
        "%s: max_runtime_secs ignored on a multi-process cloud (per-"
        "process wall clock would desynchronize the mirrored device "
        "program sequence); bound the build by iterations/trees instead",
        what)
    return True


def _extract_train_params(cls, body: Dict[str, Any]):
    defaults = cls.default_params()
    params: Dict[str, Any] = {}
    ignored = []
    for k, v in body.items():
        kk = "lambda_" if k == "lambda" else k
        kk = cls.translate_param(kk)
        if kk not in defaults:
            ignored.append(k)
            continue
        params[kk] = _coerce(v, defaults[kk])
    return params, ignored


def _h_generic_train(cls, params: Dict[str, Any], model_id):
    """ModelBuilders path for Generic: load the MOJO named by `path` (or
    `model_key` pointing at an uploaded blob) and install it like any
    trained model."""
    params.pop("training_frame", None)
    params.pop("validation_frame", None)
    params.pop("response_column", None)
    dest = model_id or f"GENERIC_model_{uuid.uuid4().hex[:12]}"
    try:
        # validate SYNCHRONOUSLY so bad params surface as a 412 response,
        # not a FAILED background job with a raw traceback
        builder = cls(**{k: v for k, v in params.items() if v})
        path = builder.params.get("path") or builder.params.get("model_key")
        if not path:
            raise ValueError("Generic: 'path' to a MOJO file is required")
    except ValueError as e:
        raise ApiError(str(e), 412, "H2OModelBuilderErrorV3") from None
    job = Job(description="generic Model Build", dest=dest)
    job.dest_type = "Key<Model>"
    job.dest_key = dest

    from h2o3_tpu.parallel import oplog

    # followers must install the model under the SAME key (later predict
    # ops broadcast and resolve it by name); the MOJO path rides the
    # shared-filesystem contract like parse sources
    op_seq = oplog.broadcast("generic", {"path": str(path),
                                         "model_id": dest})

    def run(j: Job):
        with oplog.turn(op_seq):
            model = builder.train()
        model._key = Key(dest)
        DKV.put(dest, model)
        return model

    job.start(run, background=True)
    return {"__meta": S.meta("ModelBuilderJobV3", "ModelBuilderJob"),
            "job": S.job_v3(job), "messages": [], "error_count": 0,
            "parameters": [], "algo": "generic"}


def _pop_train_args(params: Dict[str, Any]):
    """Shared extraction of the frame/response/ignored args from a coerced
    param dict (used by the ModelBuilders and Grid build handlers — one
    place for the 404/412 shapes)."""
    train_key = str(params.pop("training_frame", "") or "").strip('"')
    valid_key = str(params.pop("validation_frame", "") or "").strip('"')
    y = str(params.pop("response_column", "") or "").strip('"') or None
    x_ignored = params.pop("ignored_columns", None)
    if not train_key:
        raise ApiError("training_frame required", 412, "H2OModelBuilderErrorV3")
    train = DKV.get(train_key)
    if not isinstance(train, Frame):
        raise ApiError(f"Object '{train_key}' not found for argument: "
                       "training_frame", 404, "H2OModelBuilderErrorV3")
    valid = DKV.get(valid_key) if valid_key else None
    if x_ignored:
        x_ignored = [str(c).strip('"') for c in x_ignored]
    return train, valid, y, x_ignored


def h_modelbuilder_train(ctx: Ctx):
    algo = ctx.params["algo"].lower()
    cls = _builders().get(algo)
    if cls is None:
        raise ApiError(f"unknown algo {algo!r}", 404)
    body = dict(ctx.body)
    params, _ignored = _extract_train_params(cls, body)
    model_id = str(params.pop("model_id", "") or "").strip('"') or None
    if algo == "generic":
        # Generic trains from a MOJO artifact, not a frame (h2o-py
        # H2OGenericEstimator.from_file → train() with no training_frame;
        # hex/generic/Generic.java)
        return _h_generic_train(cls, params, model_id)
    train, valid, y, x_ignored = _pop_train_args(params)

    try:
        builder = cls(**params)
        if x_ignored:
            builder.params["ignored_columns"] = x_ignored
        if model_id:
            builder.params["model_id"] = model_id
    except ValueError as e:
        raise ApiError(str(e), 412, "H2OModelBuilderErrorV3") from None

    dest = model_id or f"{algo.upper()}_model_{uuid.uuid4().hex[:12]}"
    job = Job(description=f"{algo} Model Build", dest=dest)
    job.dest_type = "Key<Model>"
    job.dest_key = dest

    from h2o3_tpu.parallel import ckpt, oplog

    op_seq = None
    wire_params = None
    if oplog.active():
        # cleared on the COORDINATOR'S builder too, not just the wire:
        # both sides must run the identical un-budgeted fit loop
        _clear_wallclock_budget(builder.params, f"{algo} train")
        wire_params = _pin_seed_and_wire(builder.params)
        op_seq = oplog.broadcast("train", {
            "algo": algo, "params": wire_params,
            "training_frame": str(train.key),
            "validation_frame": str(valid.key) if valid is not None else None,
            "y": y, "model_id": dest})
    if ckpt.job_ckpt_iters() > 0 and builder.supports_iteration_resume:
        # crash-survivable build: pin the wildcard seed NOW (a resumed
        # dispatch must re-derive the identical RNG streams) and record
        # the re-dispatch recipe on the job — the trainer's fit loop
        # persists durable progress against it every
        # H2O_TPU_JOB_CKPT_ITERS iterations
        if wire_params is None:
            wire_params = _pin_seed_and_wire(builder.params)
        job.resume_spec = {
            "algo": algo, "params": wire_params,
            "training_frame": str(train.key),
            "validation_frame": str(valid.key) if valid is not None else None,
            "y": y, "model_id": dest, "description": job.description}
        builder._progress_job = job

    def run(j: Job):
        with oplog.turn(op_seq):
            model = builder.train(y=y, training_frame=train,
                                  validation_frame=valid)
        if j.status == Job.FAILED:
            # supervisor failed this job from outside mid-train: don't
            # install the result at dest — Job.start's wrapper is about to
            # discard it, and a pre-installed model would outlive that
            return model
        # the client captured dest at submit time (h2o-py H2OJob.__init__
        # reads dest.name once) — re-home the model under the advertised key
        old = str(model.key)
        if old != dest:
            DKV.remove(old)
            model._key = Key(dest)
        DKV.put(dest, model)
        model._parms.setdefault("training_frame", str(train.key))
        return model

    job.start(run, background=True)
    return {"__meta": S.meta("ModelBuilderJobV3", "ModelBuilderJob"),
            "job": S.job_v3(job), "messages": [], "error_count": 0,
            "parameters": [S.model_parameter_v3(k, cls.default_params().get(k), v)
                           for k, v in params.items()],
            "algo": algo}


def h_modelbuilder_validate(ctx: Ctx):
    algo = ctx.params["algo"].lower()
    cls = _builders().get(algo)
    if cls is None:
        raise ApiError(f"unknown algo {algo!r}", 404)
    params, ignored = _extract_train_params(cls, dict(ctx.body))
    msgs = [{"__meta": S.meta("ValidationMessageV3"), "message_type": "WARN",
             "field_name": k, "message": f"unknown parameter {k}"} for k in ignored]
    return {"__meta": S.meta("ModelBuildersV3"), "messages": msgs,
            "error_count": 0, "parameters": []}


# -- models -----------------------------------------------------------------

def _model_json(m: Model) -> dict:
    cls = _builders().get(m.algo_name)
    return S.model_v3(m, builder_cls=cls)


def h_models_list(ctx: Ctx):
    models = [v for v in (DKV.get(k) for k in DKV.keys()) if isinstance(v, Model)]
    return {"__meta": S.meta("ModelsV3"), "models": [_model_json(m) for m in models]}


def h_model_get(ctx: Ctx):
    return {"__meta": S.meta("ModelsV3"),
            "models": [_model_json(_model_or_404(ctx.params["model_id"]))]}


def h_model_delete(ctx: Ctx):
    DKV.remove(ctx.params["model_id"])
    from h2o3_tpu import scoring
    from h2o3_tpu.api import routes_ext

    routes_ext.purge_metrics(model_key=ctx.params["model_id"])
    scoring.purge(ctx.params["model_id"])     # drop its device-resident session
    return {"__meta": S.meta("ModelsV3")}


def _wants_contributions(ctx: Ctx) -> bool:
    return str(ctx.arg("predict_contributions", "")).lower() in ("1", "true")


def _check_contributions_size(fr: Frame) -> None:
    if fr.nrows > 100_000:
        raise ApiError("predict_contributions over REST is capped at "
                       "100k rows (host-side TreeSHAP); subset the "
                       "frame first", 400)


def h_predict_v3(ctx: Ctx):
    m = _model_or_404(ctx.params["model_id"])
    fr = _frame_or_404(ctx.params["frame_id"])
    from h2o3_tpu.parallel import oplog

    # column-compatibility preflight BEFORE any oplog broadcast: an
    # adapt_test raise after the broadcast would kill every follower's
    # replay loop (the 137d938 pattern) — reject as a clean 400 instead
    err = m.check_test_compat(fr)
    if err:
        raise ApiError(err, 400)
    dest = str(ctx.arg("predictions_frame", "") or "").strip('"') or None
    if str(ctx.arg("leaf_node_assignment", "")).lower() in ("1", "true"):
        # ModelBase.predict_leaf_node_assignment (tree models only). The
        # bin+leaf_index pass is a DEVICE program over sharded columns, so
        # followers must replay it like any other predict op
        la_type = str(ctx.arg("leaf_node_assignment_type", "Path") or
                      "Path").strip('"') or "Path"
        if not hasattr(m, "predict_leaf_node_assignment"):
            raise ApiError(f"{m.algo_name} has no leaf node assignments "
                           "(tree models only)", 400)
        if la_type not in ("Path", "Node_ID"):
            # validate BEFORE the broadcast: a post-broadcast raise would
            # kill every follower's replay loop
            raise ApiError(f"leaf_node_assignment_type {la_type!r} "
                           "(Path or Node_ID)", 400)
        dest = dest or f"leaf_assignment_{m.key}_on_{fr.key}"
        # explainability rides the same admission gate as predictions
        # (ISSUE 13): an overloaded model sheds leaf-assignment traffic
        # with 429/Retry-After too, instead of queueing it past the SLO
        with admission.CONTROLLER.slot(str(m.key)):
            op_seq = oplog.broadcast("leaf_assignment", {
                "model": str(m.key), "frame": str(fr.key),
                "type": la_type, "destination_frame": dest})
            with oplog.turn(op_seq):
                pred = m.predict_leaf_node_assignment(fr, type=la_type,
                                                      key=dest)
                pred.install()
        return {"__meta": S.meta("ModelMetricsListSchemaV3"),
                "predictions_frame": {"name": str(pred.key)},
                "model_metrics": []}
    if str(ctx.arg("predict_staged_proba", "")).lower() in ("1", "true"):
        # ModelBase.staged_predict_proba (GBM only) — device leaf pass, so
        # mirrored like leaf assignment
        if not hasattr(m, "staged_predict_proba"):
            raise ApiError(f"{m.algo_name} has no staged probabilities "
                           "(GBM only)", 400)
        if m._output.model_category not in ("Binomial", "Multinomial"):
            # validate BEFORE the broadcast (post-broadcast raises are
            # follower-fatal); matches the model-side check
            raise ApiError("staged_predict_proba needs a classification "
                           "GBM", 400)
        dest = dest or f"staged_proba_{m.key}_on_{fr.key}"
        with admission.CONTROLLER.slot(str(m.key)):
            op_seq = oplog.broadcast("staged_proba", {
                "model": str(m.key), "frame": str(fr.key),
                "destination_frame": dest})
            with oplog.turn(op_seq):
                pred = m.staged_predict_proba(fr, key=dest)
                pred.install()
        return {"__meta": S.meta("ModelMetricsListSchemaV3"),
                "predictions_frame": {"name": str(pred.key)},
                "model_metrics": []}
    if _wants_contributions(ctx):
        # genmodel TreeSHAP surfaced over REST (h2o-py predict_contributions)
        _check_contributions_size(fr)
        dest = dest or f"contributions_{m.key}_on_{fr.key}"
        # contributions bin through the same fused pack program training
        # and serving use (ShardedFrame.pack_binned); the TreeSHAP walk
        # itself is host-side by design — admission-gate it so heavy
        # explainability traffic sheds instead of starving serving
        with admission.CONTROLLER.slot(str(m.key)):
            op_seq = oplog.broadcast("predict", {
                "model": str(m.key), "frame": str(fr.key),
                "destination_frame": dest, "contributions": True,
                "with_metrics": False})
            with oplog.turn(op_seq):
                pred = m.predict_contributions(fr, key=dest)
                pred.install()
        return {"__meta": S.meta("ModelMetricsListSchemaV3"),
                "predictions_frame": {"name": str(pred.key)},
                "model_metrics": []}
    # followers must mirror EVERY device program this handler runs —
    # predict AND the model_performance metrics pass below — and the
    # coordinator must run them inside its turnstile slot so they cannot
    # interleave out of broadcast order vs the follower's sequential replay.
    # The destination key ships explicitly (default included) so every
    # process installs the prediction frame under the SAME DKV name.
    dest = dest or f"prediction_{m.key}_on_{fr.key}"
    from h2o3_tpu import scoring

    if scoring.supports(m):
        # serving fast path: compile-once bucketed traversal; concurrent
        # requests for the same model coalesce into ONE dispatch (and ONE
        # "score_batch" oplog op on a multi-process cloud) inside the
        # micro-batcher's window. The scoring raw pass is reused for the
        # metrics too, so the whole request is a single forest traversal.
        pred, mm = scoring.score_request(m, fr, dest, with_metrics=True)
        return {"__meta": S.meta("ModelMetricsListSchemaV3"),
                "predictions_frame": {"name": str(pred.key)},
                "model_metrics": [S.metrics_v3(mm, str(m.key), str(fr.key))]
                if mm else []}
    op_seq = oplog.broadcast("predict", {"model": str(m.key),
                                         "frame": str(fr.key),
                                         "destination_frame": dest,
                                         "with_metrics": True})
    with oplog.turn(op_seq):
        pred = m.predict(fr, key=dest)
        pred.install()
        mm = m.model_performance(fr)
    return {"__meta": S.meta("ModelMetricsListSchemaV3"),
            "predictions_frame": {"name": str(pred.key)},
            "model_metrics": [S.metrics_v3(mm, str(m.key), str(fr.key))] if mm else []}


def h_predict_v4(ctx: Ctx):
    m = _model_or_404(ctx.params["model_id"])
    fr = _frame_or_404(ctx.params["frame_id"])
    # same pre-broadcast preflight as the v3 route: bad column types must
    # surface as a 400 BEFORE the op ships (post-broadcast raises are
    # follower-fatal)
    err = m.check_test_compat(fr)
    if err:
        raise ApiError(err, 400)
    contribs = str(ctx.arg("predict_contributions", "")).lower() in ("1", "true")
    if contribs:
        _check_contributions_size(fr)  # same 400 as the sync v3 route
    from h2o3_tpu import scoring

    use_fused = not contribs and scoring.supports(m)
    if use_fused:
        # surface saturation BEFORE detaching into a background job: a
        # request the gate would shed right now gets the synchronous 429
        # + Retry-After (a failed async job carries no backoff hint).
        # Non-consuming probe — the job's own slot() still gates.
        admission.CONTROLLER.check(str(m.key))
    job = Job(description=f"{m.algo_name} "
                          f"{'contributions' if contribs else 'prediction'}")
    job.dest_type = "Key<Frame>"
    pred_key = (f"contributions_{m.key}_on_{fr.key}" if contribs
                else f"prediction_{m.key}_on_{fr.key}")
    job.dest_key = pred_key

    from h2o3_tpu.parallel import oplog

    if use_fused:
        # fused /4 route (ISSUE 13): the async prediction rides the SAME
        # admission-controlled, coalescing, compile-once fast path as the
        # sync v3 route — score_request broadcasts its own coalesced
        # "score_batch" op from the job thread, so async clients no
        # longer fall off the fast path. Results are bitwise-identical
        # to the eager predict (the fused-path contract).
        def run_fused(j: Job):
            pred, _mm = scoring.score_request(m, fr, pred_key,
                                              with_metrics=False)
            return pred

        job.start(run_fused, background=True)
    else:
        op_seq = oplog.broadcast("predict", {
            "model": str(m.key), "frame": str(fr.key),
            "destination_frame": pred_key, "contributions": contribs,
            "with_metrics": False})

        def run(j: Job):
            with oplog.turn(op_seq):
                if contribs:
                    # genuine h2o-py predict_contributions rides this
                    # async route (model_base.py:199: POST /4/Predictions
                    # + flag)
                    pred = m.predict_contributions(fr, key=pred_key)
                else:
                    pred = m.predict(fr, key=pred_key)
            pred.install()
            return pred

        job.start(run, background=True)
    # h2o-r predict.H2OModel reads key/dest at the TOP level of the v4
    # response (models.R:679 res$key$name, res$dest$name); h2o-py reads
    # the nested job — serve both shapes
    jv = S.job_v3(job)
    return {"__meta": S.meta("JobV4"), "job": jv,
            "key": jv.get("key"), "dest": jv.get("dest")}


def _automl_tables(aml):
    """Leaderboard + event-log TwoDimTables in the shapes the genuine
    h2o-py AutoML client parses (autoh2o.py _fetch_state/_fetch_table:
    a leading index column the client strips with lb[1:], and an event log
    carrying name/value columns for _training_info)."""
    from h2o3_tpu.automl.automl import _leaderboard_metric
    from h2o3_tpu.utils.twodim import TwoDimTable

    metric = aml._metric_name
    lb = TwoDimTable("Leaderboard", ["", "model_id", metric],
                     ["string", "string", "double"])
    cache = getattr(aml, "_lb_cache", {})
    lbf = getattr(aml, "_leaderboard_frame", None)
    ranked = aml._ranked()
    for i, m in enumerate(ranked):
        # model_id must be the fetchable DKV key (h2o.get_model uses it)
        lb.add_row(str(i), str(m.key),
                   float(_leaderboard_metric(m, metric, lbf, cache)))
    el = TwoDimTable("Event Log",
                     ["", "timestamp", "level", "stage", "message",
                      "name", "value"],
                     ["string", "string", "string", "string", "string",
                      "string", "string"])
    for i, ev in enumerate(aml.event_log):
        # "Info" capitalization matters: the client filters levels against
        # ['Debug','Info','Warn'] (EventLogEntry.Level spellings)
        el.add_row(str(i), str(ev.get("timestamp", "")), "Info", "run",
                   str(ev.get("message", "")), "", "")
    el.add_row(str(len(aml.event_log)), "", "Info", "run", "",
               "project_name", aml.project_name)
    return lb, el, ranked


def h_automl_build(ctx: Ctx):
    """POST /99/AutoMLBuilder (ai.h2o.automl AutoMLBuildSpec; genuine
    h2o-py H2OAutoML.train posts build_control/build_models/input_spec)."""
    spec = ctx.body or {}
    input_spec = spec.get("input_spec") or {}
    build_control = spec.get("build_control") or {}
    build_models = spec.get("build_models") or {}
    sc = build_control.get("stopping_criteria") or {}
    train = _frame_or_404(str(input_spec.get("training_frame", "")))
    y = str(input_spec.get("response_column", "") or "")
    if not y:
        raise ApiError("response_column required", 412)
    valid_key = input_spec.get("validation_frame")
    lb_key = input_spec.get("leaderboard_frame")
    project = str(build_control.get("project_name", "") or "") or \
        f"AutoML_{uuid.uuid4().hex[:8]}"

    from h2o3_tpu.automl.automl import H2OAutoML

    nf = build_control.get("nfolds")
    mm = sc.get("max_models")
    aml = H2OAutoML(
        # explicit 0 is meaningful for both (no CV / no model cap) — only
        # ABSENT values take the defaults
        max_models=int(mm) if mm is not None else 10,
        max_runtime_secs=float(sc.get("max_runtime_secs") or 0.0),
        seed=int(sc.get("seed", -1) if sc.get("seed") is not None else -1),
        nfolds=int(nf) if nf is not None else 5,
        sort_metric=str(input_spec.get("sort_metric") or "AUTO"),
        include_algos=build_models.get("include_algos"),
        exclude_algos=build_models.get("exclude_algos"),
        project_name=project)
    ignored = set(input_spec.get("ignored_columns") or [])
    x = [c for c in train.names if c != y and c not in ignored] or None
    job = Job(description="AutoML", dest=project)
    job.dest_type = "Key<AutoML>"
    job.dest_key = project
    # durable search: the engine checkpoints member state under this Job's
    # key so a watchdog on a surviving node can resume the search in place
    aml._search_job = job

    from h2o3_tpu.parallel import oplog

    op_seq = None
    if oplog.active():
        # multi-process cloud: every process must walk the IDENTICAL model
        # sequence, so the seed is already pinned (H2OAutoML.__init__) and
        # the wall-clock budget — which would diverge across processes —
        # is cleared in favor of the max_models cap
        if aml.max_runtime_secs > 0:
            import logging

            logging.getLogger("h2o3_tpu").warning(
                "AutoML max_runtime_secs ignored on a multi-process cloud "
                "(nondeterministic across processes); bounded by "
                "max_models=%d instead", aml.max_models)
            aml.max_runtime_secs = 0.0
        op_seq = oplog.broadcast("automl", {
            "spec": {"max_models": aml.max_models, "max_runtime_secs": 0.0,
                     "seed": aml.seed, "nfolds": aml.nfolds,
                     "sort_metric": aml.sort_metric,
                     "include_algos": aml.include_algos,
                     "exclude_algos": aml.exclude_algos,
                     "project_name": aml.project_name,
                     "preprocessing": aml.preprocessing},
            "training_frame": str(train.key),
            "validation_frame": str(valid_key) if valid_key else None,
            "leaderboard_frame": str(lb_key) if lb_key else None,
            "x": x, "y": y})

    def run(j: Job):
        # Job.start installs the result under job.dest (= project) itself
        with oplog.turn(op_seq):
            aml.train(x=x, y=y, training_frame=train,
                      validation_frame=DKV.get(str(valid_key)) if valid_key else None,
                      leaderboard_frame=DKV.get(str(lb_key)) if lb_key else None)
        return aml

    job.start(run, background=True)
    return {"__meta": S.meta("AutoMLBuilderV99"), "job": S.job_v3(job),
            "build_control": {"project_name": project}}


def h_automl_get(ctx: Ctx):
    """GET /99/AutoML/{aml_id} — the AutoMLV99 state json h2o-py reads."""
    from h2o3_tpu.automl.automl import H2OAutoML

    aml = DKV.get(ctx.params["aml_id"])
    if not isinstance(aml, H2OAutoML):
        raise ApiError(f"AutoML {ctx.params['aml_id']!r} not found", 404)
    lb, el, ranked = _automl_tables(aml)
    return {"__meta": S.meta("AutoMLV99"),
            "project_name": aml.project_name,
            "leaderboard": {"models": [{"name": str(m.key)} for m in ranked]},
            "leaderboard_table": lb.to_v3(),
            "event_log_table": el.to_v3()}


def h_leaderboard_get(ctx: Ctx):
    """GET /99/Leaderboards/{aml_id} (h2o.automl.get_leaderboard)."""
    from h2o3_tpu.automl.automl import H2OAutoML

    aml = DKV.get(ctx.params["aml_id"])
    if not isinstance(aml, H2OAutoML):
        raise ApiError(f"AutoML {ctx.params['aml_id']!r} not found", 404)
    lb, _el, _ranked = _automl_tables(aml)
    return {"__meta": S.meta("LeaderboardV99"),
            "project_name": aml.project_name,
            "table": lb.to_v3()}


def h_grid_build(ctx: Ctx):
    """POST /99/Grid/{algo} — hyperparameter search job (water/api
    GridSearchHandler; genuine h2o-py H2OGridSearch.train rides this)."""
    algo = ctx.params["algo"].lower()
    cls = _builders().get(algo)
    if cls is None:
        raise ApiError(f"unknown algo {algo!r}", 404)
    body = dict(ctx.body)
    hp_raw = body.pop("hyper_parameters", None)
    if not hp_raw:
        raise ApiError("hyper_parameters required", 412)
    hyper = hp_raw if isinstance(hp_raw, dict) else json.loads(str(hp_raw))
    defaults = cls.default_params()
    hyper = {("lambda_" if k == "lambda" else cls.translate_param(k)):
             list(v) for k, v in hyper.items()}
    unknown = [k for k in hyper if k not in defaults]
    if unknown:
        raise ApiError(f"unknown hyper parameters {unknown}", 412)
    sc_raw = body.pop("search_criteria", None)
    criteria = (sc_raw if isinstance(sc_raw, dict)
                else json.loads(str(sc_raw)) if sc_raw else None)
    grid_id = str(body.pop("grid_id", "") or "").strip('"') or \
        f"Grid_{algo.upper()}_{uuid.uuid4().hex[:10]}"
    params, _ignored = _extract_train_params(cls, body)
    train, valid, y, x_ignored = _pop_train_args(params)
    if x_ignored:
        params["ignored_columns"] = x_ignored

    from h2o3_tpu.grid import H2OGridSearch

    parallelism = int(body.pop("parallelism", 1) or 1)
    recovery_dir = str(body.pop("recovery_dir", "") or "").strip('"') or None
    job = Job(description=f"{algo} Grid Build", dest=grid_id)
    job.dest_type = "Key<Grid>"

    from h2o3_tpu.parallel import oplog

    op_seq = None
    if oplog.active():
        # one deterministic op: every process walks the identical combo
        # sequence. Parallel building would interleave device programs
        # nondeterministically across processes — force sequential there.
        sc_seed = (criteria or {}).get("seed")
        if str((criteria or {}).get("strategy", "")).lower() == "randomdiscrete" \
                and (not isinstance(sc_seed, (int, float)) or int(sc_seed) < 0):
            criteria = dict(criteria or {})
            criteria["seed"] = int(uuid.uuid4().int % (2 ** 31))
        parallelism = 1
        # the walker's wall-clock budget break and each member build's
        # deadline are per-process time: zero BOTH before the op ships
        # (local run() and followers then walk the identical combo/model
        # sequence) — same mirrored-program invariant as train/automl
        if criteria and float(criteria.get("max_runtime_secs") or 0.0) > 0:
            criteria = dict(criteria)
            _clear_wallclock_budget(criteria, f"{algo} grid criteria")
        _clear_wallclock_budget(params, f"{algo} grid build")
        wire_params = _pin_seed_and_wire(params)
        op_seq = oplog.broadcast("grid", {
            "algo": algo, "params": wire_params, "hyper": hyper,
            "criteria": criteria, "grid_id": grid_id, "y": y,
            "training_frame": str(train.key),
            "validation_frame": str(valid.key) if valid is not None else None})

    def run(j: Job):
        base = cls(**params)
        grid = H2OGridSearch(base, hyper, grid_id=grid_id,
                             search_criteria=criteria)
        # durable search: member state checkpoints under this Job's key
        grid._search_job = j
        with oplog.turn(op_seq):
            grid.train(y=y, training_frame=train, validation_frame=valid,
                       parallelism=parallelism, recovery_dir=recovery_dir)
        return grid

    job.start(run, background=True)
    return {"__meta": S.meta("GridSearchV99"), "job": S.job_v3(job)}


def h_grid_get(ctx: Ctx):
    """GET /99/Grids/{grid_id} — the GridSchemaV99 fields h2o-py reads:
    model_ids (rank-ordered when sort_by given), hyper_names, failure
    lists, summary_table."""
    grid = DKV.get(ctx.params["grid_id"])
    from h2o3_tpu.grid import H2OGridSearch

    if not isinstance(grid, H2OGridSearch):
        raise ApiError(f"grid {ctx.params['grid_id']!r} not found", 404)
    sort_by = str(ctx.arg("sort_by", "") or "").strip('"') or None
    dec_raw = ctx.arg("decreasing")
    decreasing = None if dec_raw is None else \
        str(dec_raw).lower() in ("1", "true")
    g = grid.get_grid(sort_by=sort_by, decreasing=decreasing) \
        if grid.models else grid
    return {"__meta": S.meta("GridSchemaV99"),
            "grid_id": S.key_ref(str(grid.key), "Key<Grid>"),
            "model_ids": [{"name": str(m.key)} for m in g.models],
            "hyper_names": list(grid.hyper_params),
            "failure_details": [f["error"] for f in grid.failed],
            "failed_params": [f["params"] for f in grid.failed],
            "failure_stack_traces": [f["error"] for f in grid.failed],
            "export_checkpoints_dir": None,
            "summary_table": None}


def h_import_sql(ctx: Ctx):
    """POST /99/ImportSQLTable (water/jdbc SQLManager; h2o-py
    import_sql_table/import_sql_select)."""
    from h2o3_tpu.ingest.sql import import_sql_select, import_sql_table

    url = str(ctx.arg("connection_url", "") or "").strip('"')
    user = str(ctx.arg("username", "") or "").strip('"') or None
    pw = str(ctx.arg("password", "") or "").strip('"') or None
    select = str(ctx.arg("select_query", "") or "").strip('"')
    table = str(ctx.arg("table", "") or "").strip('"')
    if not url or not (select or table):
        raise ApiError("connection_url and table/select_query required", 400)
    if select:
        fr = import_sql_select(url, select, username=user, password=pw)
    else:
        cols = _parse_list(ctx.arg("columns")) or None
        fr = import_sql_table(url, table, columns=cols,
                              username=user, password=pw)
    fr.install()
    job = Job(description="ImportSQLTable")
    job.dest_key = str(fr.key)
    job.status = Job.DONE
    job.progress = 1.0
    return {"__meta": S.meta("JobV3"), "job": S.job_v3(job),
            "key": S.key_ref(str(fr.key))}


def h_network_test(ctx: Ctx):
    """GET /3/NetworkTest (water/api/NetworkTestHandler + NetworkBench):
    the mesh's boot probes — matmul GFLOPs, HBM stream, psum latency."""
    from h2o3_tpu.core.runtime import cluster

    b = cluster().self_benchmark(
        size=max(16, min(int(ctx.arg("size", 512) or 512), 4096)))
    return {"__meta": S.meta("NetworkTestV3"), "bench": b}


def h_create_frame(ctx: Ctx):
    """POST /3/CreateFrame (hex/createframe/CreateFrameHandler — synthetic
    frame generation; h2o.create_frame)."""
    from h2o3_tpu.frame_factory import create_frame

    kw = {}
    # templates drive _coerce's type parsing — fractions coerce as FLOATS
    # even though their unset default is None
    for name, template in (("rows", 100), ("cols", 4), ("randomize", True),
                           ("real_fraction", 0.0), ("categorical_fraction", 0.0),
                           ("integer_fraction", 0.0), ("binary_fraction", 0.0),
                           ("factors", 2), ("real_range", 100.0),
                           ("integer_range", 100), ("missing_fraction", 0.0),
                           ("has_response", False), ("response_factors", 2),
                           ("seed", -1)):
        v = ctx.arg(name)
        if v is not None:
            kw[name] = _coerce(v, template)
    if int(kw.get("seed", -1)) < 0:
        kw.pop("seed", None)     # h2o's -1 sentinel = pick a random seed
    if kw.pop("randomize", True) is False:
        # frame_factory's generator is always randomized; honor the contract
        # by rejecting rather than silently ignoring
        raise ApiError("randomize=false is not supported", 400)
    dest = str(ctx.arg("dest", "") or ctx.arg("destination_frame", "") or "")
    if dest.strip('"'):
        kw["key"] = dest.strip('"')
    fr = create_frame(**kw)
    job = Job(description="CreateFrame")
    job.dest_key = str(fr.key)
    job.status = Job.DONE
    job.progress = 1.0
    return {"__meta": S.meta("JobV3"), "job": S.job_v3(job),
            "key": S.key_ref(str(fr.key))}


def h_split_frame(ctx: Ctx):
    """POST /3/SplitFrame (hex/splitframe/SplitFrameHandler;
    h2o.split_frame non-rapids path)."""
    fr = _frame_or_404(str(ctx.arg("dataset", "")).strip('"'))
    ratios = [float(r) for r in (_parse_list(ctx.arg("ratios")) or [0.75])]
    dests = _parse_list(ctx.arg("destination_frames")) or None
    from h2o3_tpu.frame_factory import H2OFrame

    if not isinstance(fr, H2OFrame):
        fr = H2OFrame._wrap(fr)
    # split parts are installed by H2OFrame._wrap inside split_frame
    parts = fr.split_frame(ratios=ratios, destination_frames=dests)
    job = Job(description="SplitFrame")
    job.status = Job.DONE
    job.progress = 1.0
    return {"__meta": S.meta("SplitFrameV3"), "job": S.job_v3(job),
            "destination_frames": [S.key_ref(str(p.key)) for p in parts]}


def h_pdp_post(ctx: Ctx):
    """POST /3/PartialDependences (hex/PartialDependence.java; h2o-py
    partial_plot). Runs synchronously; results land in DKV under the
    destination key for the follow-up GET."""
    from h2o3_tpu import explain

    m = _model_or_404(str(ctx.arg("model_id", "")).strip('"'))
    fr = _frame_or_404(str(ctx.arg("frame_id", "")).strip('"'))
    cols = _parse_list(ctx.arg("cols")) or None
    nbins = int(ctx.arg("nbins", 20) or 20)
    ri = ctx.arg("row_index", -1)
    # explicit None/empty check: row_index=0 (ICE for the first row) is falsy
    row_index = int(ri) if ri not in (None, "") else -1
    wc = str(ctx.arg("weight_column", "") or "").strip('"') or None
    dest = (str(ctx.arg("destination_key", "") or "").strip('"')
            or f"pdp_{m.key}_{fr.key}")
    tables = explain.partial_dependence(m, fr, cols, nbins=nbins,
                                        weight_column=wc, row_index=row_index)
    DKV.put(dest, tables)
    job = Job(description="PartialDependence")
    job.dest_key = dest
    job.status = Job.DONE
    job.progress = 1.0
    return {"__meta": S.meta("PartialDependenceV3"), "job": S.job_v3(job),
            "destination_key": dest}


def h_pdp_get(ctx: Ctx):
    tables = DKV.get(ctx.params["key"])
    if tables is None:
        raise ApiError(f"no partial dependence result {ctx.params['key']!r}", 404)
    out = [{"name": t["column"],
            "columns": [{"name": t["column"]}, {"name": "mean_response"},
                        {"name": "stddev_response"}],
            "data": [t["values"], t["mean_response"], t["stddev_response"]]}
           for t in tables]
    return {"__meta": S.meta("PartialDependenceV3"),
            "partial_dependence_data": out}


def h_feature_interaction(ctx: Ctx):
    """POST /3/FeatureInteraction (hex/tree FeatureInteraction analog)."""
    from h2o3_tpu import explain

    m = _model_or_404(str(ctx.arg("model_id", "")).strip('"'))
    depth = int(ctx.arg("max_interaction_depth", 2) or 2)
    rows = explain.feature_interactions(m, max_interaction_depth=depth)
    return {"__meta": S.meta("FeatureInteractionV3"),
            "feature_interaction": rows}


def h_model_metrics(ctx: Ctx):
    m = _model_or_404(ctx.params["model_id"])
    fr = _frame_or_404(ctx.params["frame_id"])
    mm = m.model_performance(fr)
    out = []
    if mm is not None:
        from h2o3_tpu.api import routes_ext

        routes_ext.record_metrics(str(m.key), str(fr.key), mm)
        out.append(S.metrics_v3(mm, str(m.key), str(fr.key)))
    return {"__meta": S.meta("ModelMetricsListSchemaV3"), "model_metrics": out}


def h_model_mojo(ctx: Ctx):
    try:
        from h2o3_tpu.models import mojo
    except ImportError:
        raise ApiError("MOJO export not available in this build", 501) from None
    m = _model_or_404(ctx.params["model_id"])
    fmt = str(ctx.arg("format", "") or "").lower()
    if fmt in ("reference", "java"):
        # reference byte format (SharedTreeMojoModel v1.20): scoreable by
        # the stock dependency-free genmodel jar
        from h2o3_tpu.models.mojo_java import export_java_mojo_bytes

        try:
            data = export_java_mojo_bytes(m)
        except ValueError as e:
            raise ApiError(str(e), 400) from None
    else:
        data = mojo.export_mojo_bytes(m)
    return RawReply(data, "application/zip",
                    headers={"Content-Disposition":
                             f'attachment; filename="{m.key}.zip"'})


def h_te_transform(ctx: Ctx):
    """GET /3/TargetEncoderTransform (h2o-py targetencoder.transform)."""
    m = _model_or_404(str(ctx.arg("model", "")))
    fr = _frame_or_404(str(ctx.arg("frame", "")))
    if not hasattr(m, "transform"):
        raise ApiError(f"model {m.key} is not a TargetEncoder", 400)

    def _opt_f(name):
        v = ctx.arg(name)
        return None if v in (None, "", "null", "None") else float(v)

    blending = ctx.arg("blending")
    out = m.transform(
        fr,
        as_training=str(ctx.arg("as_training", "false")).lower() == "true",
        blending=None if blending in (None, "", "null") else
        str(blending).lower() == "true",
        inflection_point=_opt_f("inflection_point"),
        smoothing=_opt_f("smoothing"),
        noise=_opt_f("noise"))
    out.install()
    return {"__meta": S.meta("TargetEncoderTransformV3"),
            "name": str(out.key)}


# -- AOT scoring artifacts (the MOJO2-for-TPU deployment surface) -----------

def _artifact_summary(info: Dict[str, Any]) -> Dict[str, Any]:
    return S.artifact_v3(info)


def h_artifact_export(ctx: Ctx):
    """POST /3/Artifacts/models/{model_id} — export a trained forest model
    as a standalone AOT scoring artifact directory (manifest + packed
    constants + per-bucket serialized executables + StableHLO fallback).
    Coordinator-local: lowering runs no collectives, so no oplog op."""
    from h2o3_tpu import artifact

    m = _model_or_404(ctx.params["model_id"])
    out_dir = str(ctx.arg("dir", "") or "").strip('"')
    if not out_dir:
        raise ApiError("dir required (server-side artifact directory)", 400)
    raw_buckets = _parse_list(ctx.arg("buckets")) or None
    try:
        buckets = [int(b) for b in raw_buckets] if raw_buckets else None
    except (TypeError, ValueError):
        raise ApiError(f"buckets must be integers, got {raw_buckets!r}",
                       400) from None
    try:
        artifact.export_model(m, out_dir, buckets=buckets)
        info = artifact.describe(out_dir)
    except artifact.ArtifactError as e:
        raise ApiError(str(e), 400) from None
    return _artifact_summary(info | {"dir": out_dir,
                                     "model_id": str(m.key)})


def h_artifact_import(ctx: Ctx):
    """POST /3/Artifacts/import — load an artifact directory into a
    servable model under `model_id` (defaults to the exported key). On a
    multi-process cloud the load is mirrored as one oplog op so every
    process installs the model under the SAME key (the dir rides the
    shared-filesystem contract like parse sources)."""
    from h2o3_tpu import artifact
    from h2o3_tpu.parallel import oplog

    art_dir = str(ctx.arg("dir", "") or "").strip('"')
    if not art_dir:
        raise ApiError("dir required (artifact directory to load)", 400)
    model_id = str(ctx.arg("model_id", "") or "").strip('"') or None
    try:
        # FULL load-and-validate (manifest, checksums, packed forest,
        # algo) BEFORE the broadcast, without installing: a post-broadcast
        # raise would kill every follower's replay loop, so anything a
        # replayed load could reject must be rejected as a 400 right here
        artifact.load_model(art_dir, model_id, install=False)
    except artifact.ArtifactError as e:
        raise ApiError(str(e), 400) from None
    op_seq = oplog.broadcast("artifact_import", {"dir": art_dir,
                                                 "model_id": model_id})
    with oplog.turn(op_seq):
        try:
            model = artifact.load_model(art_dir, model_id)
        except artifact.ArtifactError as e:
            raise ApiError(str(e), 400) from None
    return _artifact_summary({"dir": art_dir, "model_id": str(model.key),
                              "algo": model.algo_name})


def h_artifact_info(ctx: Ctx):
    """GET /3/Artifacts?dir=... — validated manifest summary of an
    artifact directory (no payload loads)."""
    from h2o3_tpu import artifact

    art_dir = str(ctx.arg("dir", "") or "").strip('"')
    if not art_dir:
        raise ApiError("dir required", 400)
    try:
        info = artifact.describe(art_dir)
    except artifact.ArtifactError as e:
        raise ApiError(str(e), 400) from None
    return _artifact_summary(info | {"dir": art_dir})


# -- metadata (schema introspection, water/api/SchemaServer.java:20) --------

def h_metadata_endpoints(ctx: Ctx):
    routes = []
    for i, (method, pattern, handler, summary) in enumerate(ROUTES):
        routes.append({
            "__meta": S.meta("EndpointV4"),
            "num": i,
            "http_method": method,
            "url_pattern": pattern,
            "summary": summary,
            "api_name": handler.__name__.lstrip("h_"),
            "input_schema": "Iced", "output_schema": "Iced",
        })
    return {"__meta": S.meta("EndpointsListV4"), "endpoints": routes,
            "routes": routes}


_SCHEMA_REGISTRY = [
    "CloudV3", "JobV3", "JobsV3", "FrameV3", "FramesV3", "ColV3",
    "ParseSetupV3", "ParseV3", "ParseStreamV3", "ImportFilesV3", "InitIDV3",
    "RapidsFrameV3", "RapidsScalarV3", "RapidsStringV3",
    "ModelsV3", "ModelBuildersV3", "ModelParameterSchemaV3",
    "ModelMetricsBinomialV3", "ModelMetricsMultinomialV3",
    "ModelMetricsRegressionV3", "ModelMetricsClusteringV3",
    "TwoDimTableV3", "KeyV3", "H2OErrorV3", "H2OModelBuilderErrorV3",
    "TimelineV3", "LogsV3", "AboutV3", "ArtifactV3",
    "MetricsV3", "TraceV3", "FlightRecordsV3", "ProfilerV3",
]


def h_metadata_schemas(ctx: Ctx):
    return {"__meta": S.meta("SchemaMetadataV3"),
            "schemas": [{"__meta": S.meta("SchemaMetadataV3"),
                         "name": s, "version": 3, "type": s.rstrip("V3")}
                        for s in _SCHEMA_REGISTRY]}


def h_metadata_schema(ctx: Ctx):
    name = ctx.params["schema_name"]
    if name not in _SCHEMA_REGISTRY:
        raise ApiError(f"unknown schema {name!r}", 404)
    return {"__meta": S.meta("SchemaMetadataV3"),
            "schemas": [{"name": name, "version": 3, "type": name.rstrip("V3"),
                         "fields": []}]}


# ---------------------------------------------------------------------------
# route table (RegisterV3Api.java analog)
# ---------------------------------------------------------------------------

ROUTES: List[Tuple[str, str, Callable, str]] = [
    ("GET", "/3/Cloud", h_cloud, "Cluster status"),
    ("HEAD", "/3/Cloud", h_cloud, "Cluster status (head)"),
    ("GET", "/3/About", h_about, "Server build info"),
    ("GET", "/3/Ping", h_ping, "Liveness probe"),
    ("GET", "/4/sessions", h_session_new, "Open session (legacy GET)"),
    ("POST", "/4/sessions", h_session_new, "Open a new session"),
    ("DELETE", "/4/sessions/{session_key}", h_session_end, "End a session"),
    ("POST", "/3/InitID", h_session_new, "Open session (legacy)"),
    ("GET", "/3/InitID", h_session_new, "Open session (legacy)"),
    ("POST", "/3/Shutdown", h_shutdown, "Shut the server down"),
    ("GET", "/3/Logs", h_logs, "Server log tail"),
    ("GET", "/3/Timeline", h_timeline, "Recent request timeline"),
    ("GET", "/3/Profiler", h_profiler, "Per-device memory gauges"),
    ("GET", "/", h_flow, "Flow SPA (import-parse-train-predict)"),
    ("GET", "/flow/index.html", h_flow, "Flow SPA (import-parse-train-predict)"),
    ("GET", "/3/ImportFiles", h_importfiles, "List importable files"),
    ("POST", "/3/ImportFilesMulti", h_importfiles_multi, "List files for many paths"),
    ("POST", "/3/PostFile", h_postfile, "Upload a raw file"),
    ("POST", "/3/PostFile.bin", h_postfile, "Upload a raw file (binary)"),
    ("POST", "/3/ParseSetup", h_parsesetup, "Guess parse setup"),
    ("POST", "/3/Parse", h_parse, "Parse files into a Frame"),
    ("POST", "/3/ParseStream", h_parsestream,
     "Stream-append CSV micro-batch rows to a frame"),
    ("GET", "/3/Jobs", h_jobs_list, "List jobs"),
    ("GET", "/3/Jobs/{job_id}", h_job_get, "Job status"),
    ("POST", "/3/Jobs/{job_id}/cancel", h_job_cancel, "Cancel a job"),
    ("POST", "/99/Rapids", h_rapids, "Execute a Rapids AST"),
    ("GET", "/3/Frames", h_frames_list, "List frames"),
    ("GET", "/3/Frames/{frame_id}", h_frame_get, "Frame preview"),
    ("GET", "/3/Frames/{frame_id}/light", h_frame_light, "Frame preview (light)"),
    ("GET", "/3/Frames/{frame_id}/summary", h_frame_summary, "Frame summary"),
    ("DELETE", "/3/Frames/{frame_id}", h_frame_delete, "Delete a frame"),
    ("DELETE", "/3/DKV/{key}", h_dkv_delete, "Delete a DKV key"),
    ("DELETE", "/3/DKV", h_dkv_delete_all, "Delete all DKV keys"),
    ("GET", "/3/DownloadDataset", h_download_dataset, "Frame as CSV"),
    ("GET", "/3/DownloadDataset.bin", h_download_dataset, "Frame as CSV (binary)"),
    ("GET", "/3/ModelBuilders", h_modelbuilders_list, "List algorithms"),
    ("GET", "/3/ModelBuilders/{algo}", h_modelbuilder_get, "Algorithm parameters"),
    ("POST", "/3/ModelBuilders/{algo}", h_modelbuilder_train, "Train a model"),
    ("POST", "/3/ModelBuilders/{algo}/parameters", h_modelbuilder_validate,
     "Validate parameters"),
    ("GET", "/3/Models", h_models_list, "List models"),
    ("GET", "/3/Models/{model_id}", h_model_get, "Model details"),
    ("DELETE", "/3/Models/{model_id}", h_model_delete, "Delete a model"),
    ("GET", "/3/Models/{model_id}/mojo", h_model_mojo, "Export MOJO artifact"),
    ("POST", "/3/Artifacts/models/{model_id}", h_artifact_export,
     "Export a standalone AOT scoring artifact"),
    ("POST", "/3/Artifacts/import", h_artifact_import,
     "Import an AOT artifact as a servable model"),
    ("GET", "/3/Artifacts", h_artifact_info,
     "Inspect an AOT artifact directory"),
    ("POST", "/3/Predictions/models/{model_id}/frames/{frame_id}", h_predict_v3,
     "Score a frame (sync)"),
    ("POST", "/4/Predictions/models/{model_id}/frames/{frame_id}", h_predict_v4,
     "Score a frame (async job)"),
    ("POST", "/3/ModelMetrics/models/{model_id}/frames/{frame_id}", h_model_metrics,
     "Compute model metrics on a frame"),
    ("POST", "/99/AutoMLBuilder", h_automl_build, "Run AutoML"),
    ("GET", "/99/AutoML/{aml_id}", h_automl_get, "AutoML state"),
    ("GET", "/99/Leaderboards/{aml_id}", h_leaderboard_get, "AutoML leaderboard"),
    ("POST", "/99/Grid/{algo}", h_grid_build, "Hyperparameter grid search"),
    ("GET", "/99/Models/{model_id}", h_model_get, "Model details (v99 alias)"),
    ("GET", "/99/Grids/{grid_id}", h_grid_get, "Grid results"),
    ("POST", "/99/ImportSQLTable", h_import_sql, "Import a SQL table/query"),
    ("GET", "/3/NetworkTest", h_network_test, "Mesh compute/BW/latency probes"),
    ("POST", "/3/CreateFrame", h_create_frame, "Generate a synthetic frame"),
    ("POST", "/3/SplitFrame", h_split_frame, "Split a frame by ratios"),
    ("POST", "/3/PartialDependences", h_pdp_post, "Compute partial dependence"),
    ("GET", "/3/PartialDependences/{key}", h_pdp_get, "Partial dependence result"),
    ("POST", "/3/FeatureInteraction", h_feature_interaction,
     "Tree-path feature interaction statistics"),
    ("GET", "/3/TargetEncoderTransform", h_te_transform,
     "Apply a trained TargetEncoder to a frame"),
    ("GET", "/3/Metadata/endpoints", h_metadata_endpoints, "List REST endpoints"),
    ("GET", "/3/Metadata/schemas", h_metadata_schemas, "List schemas"),
    ("GET", "/3/Metadata/schemas/{schema_name}", h_metadata_schema, "Schema detail"),
]


def _compile_routes():
    compiled = []
    for method, pattern, handler, summary in ROUTES:
        parts = pattern.strip("/").split("/")
        compiled.append((method, parts, handler))
    return compiled


_COMPILED = _compile_routes()


def _match(method: str, path: str):
    parts = [unquote(p) for p in path.strip("/").split("/")]
    best = None
    for m, pat, handler in _COMPILED:
        if m != method or len(pat) != len(parts):
            continue
        params = {}
        ok = True
        for pp, vp in zip(pat, parts):
            if pp.startswith("{"):
                params[pp[1:-1]] = vp
            elif pp != vp:
                ok = False
                break
        if ok:
            # prefer literal-only matches over parameterized ones
            score = sum(1 for pp in pat if not pp.startswith("{"))
            if best is None or score > best[2]:
                best = (handler, params, score)
    if best is None:
        return None, None
    return best[0], best[1]


class RawReply:
    def __init__(self, data: bytes, content_type: str,
                 headers: Optional[Dict[str, str]] = None):
        self.data = data
        self.content_type = content_type
        self.headers = headers or {}


# ---------------------------------------------------------------------------
# HTTP plumbing
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # per-connection socket timeout: a silent client (or a TLS client that
    # never completes the deferred handshake) releases its handler thread
    # instead of pinning it forever. Generous enough that a keep-alive
    # client polling a long job never sees a surprise close mid-exchange.
    timeout = 300
    server_ref: "ApiServer" = None    # set by ApiServer

    def log_message(self, fmt, *args):    # quiet; reference logs to file
        pass

    # -- body parsing -----------------------------------------------------
    def _read_body(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        ctype = self.headers.get("Content-Type", "")
        if not raw:
            return {}
        if "multipart/form-data" in ctype:
            return self._parse_multipart(raw, ctype)
        if "json" in ctype:
            return json.loads(raw.decode())
        if "octet-stream" in ctype or "zip" in ctype:
            return {"__raw__": raw}
        out: Dict[str, Any] = {}
        for k, vs in parse_qs(raw.decode(), keep_blank_values=True).items():
            out[k] = vs[0]
        return out

    @staticmethod
    def _parse_multipart(raw: bytes, ctype: str) -> Dict[str, Any]:
        """RFC 2046 byte-exact parsing: each body part is delimited by
        CRLF--boundary; strip exactly the framing CRLFs, never content bytes."""
        m = re.search(r'boundary="?([^";]+)"?', ctype)
        if not m:
            return {}
        delim = b"--" + m.group(1).encode()
        out: Dict[str, Any] = {}
        chunks = raw.split(delim)
        # chunks[0] = preamble; last chunk starts with b"--" (close delimiter)
        for part in chunks[1:]:
            if part.startswith(b"--"):
                break
            if part.startswith(b"\r\n"):
                part = part[2:]
            if part.endswith(b"\r\n"):       # CRLF that precedes the next delimiter
                part = part[:-2]
            if b"\r\n\r\n" not in part:
                continue
            head, _, payload = part.partition(b"\r\n\r\n")
            headtext = head.decode(errors="replace")
            if "filename=" in headtext:
                out["__file__"] = payload
                fm = re.search(r'filename="([^"]*)"', headtext)
                if fm:
                    out["__filename__"] = fm.group(1)
            else:
                nm = re.search(r'name="([^"]*)"', headtext)
                if nm:
                    out[nm.group(1)] = payload.decode(errors="replace")
        return out

    # -- replies ----------------------------------------------------------
    def _send(self, code: int, body: bytes, content_type: str,
              extra: Optional[Dict[str, str]] = None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        tid = tracing.current_trace_id()
        if tid:
            # hand the client its span tree's address (GET /3/Trace/{id})
            self.send_header("X-H2O3-Trace-Id", tid)
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def _reply_json(self, obj: Any, code: int = 200,
                    headers: Optional[Dict[str, str]] = None):
        body = json.dumps(obj, default=_json_default).encode()
        # bare (UNQUOTED) NaN/Infinity tokens are NOT valid JSON: strict
        # parsers (simplejson>=3.19 as vendored by `requests` — i.e.
        # genuine h2o-py — and every browser JSON.parse) reject the whole
        # payload. The quoted "NaN" STRINGS in frame previews (ColV3
        # convention) are fine and must not trigger the slow path.
        if _BARE_NONFINITE.search(body):
            body = json.dumps(_definite(obj), default=_json_default,
                              allow_nan=False).encode()
        self._send(code, body, "application/json", headers)

    def _reply_error(self, msg: str, code: int, schema: str = "H2OErrorV3",
                     stack: Optional[List[str]] = None,
                     headers: Optional[Dict[str, str]] = None):
        self._reply_json(S.error_v3(msg, code, stacktrace=stack,
                                    schema=schema), code, headers)

    # -- auth (reference: hash-file basic auth, water.webserver
    #    BasicAuth/-hash_login; enabled via H2O_TPU_AUTH_FILE) -------------
    def _authorized(self) -> bool:
        auth = getattr(self.server_ref, "auth", None)
        login = getattr(self.server_ref, "login_module", None)
        if not auth and login is None:
            return True
        import base64
        import hashlib

        hdr = self.headers.get("Authorization", "")
        if not hdr.startswith("Basic "):
            return False
        try:
            user, _, pw = base64.b64decode(hdr[6:]).decode().partition(":")
        except Exception:   # noqa: BLE001 — malformed header
            return False
        if login is not None:
            # pluggable authenticator (reference: JAAS login modules —
            # h2o-security LDAP/PAM/Kerberos realms plug in the same way):
            # any callable(user, password) -> bool
            try:
                return bool(login(user, pw))
            except Exception:   # noqa: BLE001 — authenticator fault = deny
                return False
        import hmac

        want = auth.get(user)
        return bool(want) and hmac.compare_digest(
            hashlib.sha256(pw.encode()).hexdigest(), want)

    # -- dispatch ---------------------------------------------------------

    # routes that poll/scrape (metrics scrapers, job pollers, the
    # observability surfaces themselves): tracing them would evict the
    # interesting traces from the bounded store
    _UNTRACED = ("/3/Metrics", "/3/Trace", "/3/FlightRecords", "/3/Ping",
                 "/3/Timeline", "/3/Jobs", "/3/CloudStatus")

    def _handle(self):
        t0 = time.time()
        u = urlparse(self.path)
        traced = not any(u.path.startswith(p) for p in self._UNTRACED)
        span_cm = (tracing.root_span("ingress", method=self.command,
                                     path=u.path)
                   if traced else contextlib.nullcontext())
        try:
            with span_cm:
                try:
                    return self._dispatch(u)
                finally:
                    if traced:
                        span_cm.set(status=self._last_status)
        finally:
            dt = time.time() - t0
            _timeline_record(self.command, u.path, self._last_status, dt * 1000)
            obs_metrics.inc("h2o3_rest_requests_total",
                            status=f"{self._last_status // 100}xx")
            obs_metrics.observe("h2o3_rest_request_seconds", dt)

    _last_status = 200

    def _dispatch(self, u):
        status = 200
        try:
            # the body must ALWAYS be drained FIRST — before auth/route
            # early returns: h2o-py sends form bodies on GET too (e.g. GET
            # /99/Grids with sort_by), and any unread body bytes desync the
            # keep-alive stream so the NEXT request on the connection hangs
            body = self._read_body()
            if not self._authorized():
                status = 401
                return self._send(401, b'{"error":"unauthorized"}',
                                  "application/json",
                                  {"WWW-Authenticate": 'Basic realm="h2o3"'})
            handler, params = _match(self.command, u.path)
            if handler is None:
                status = 404
                return self._reply_error(f"unknown route {self.command} {u.path}", 404)
            query = {k: v[0] for k, v in parse_qs(u.query, keep_blank_values=True).items()}
            ctx = Ctx(params, query, body, self.server_ref)
            out = handler(ctx)
            if isinstance(out, RawReply):
                return self._send(200, out.data, out.content_type, out.headers)
            return self._reply_json(out)
        except ApiError as e:
            status = e.status
            return self._reply_error(str(e), e.status, e.schema)
        except AdmissionRejected as e:
            # serving-tier overload: refuse fast with the standard backoff
            # hint instead of letting the request pile onto a saturated
            # model (429 queue overflow / 503 queued-request timeout)
            status = e.status
            return self._reply_error(
                str(e), e.status,
                headers={"Retry-After":
                         str(int(math.ceil(e.retry_after_s)))})
        except MemoryPressureError as e:
            # exhausted OOM degradation ladder: the typed pressure error
            # carries its own cooldown-derived backoff hint — a clean 503
            # + Retry-After, never a raw RESOURCE_EXHAUSTED 500
            status = e.status
            return self._reply_error(
                str(e), e.status,
                headers={"Retry-After":
                         str(int(math.ceil(e.retry_after_s)))})
        except (CloudUnhealthyError, OplogPublishError,
                OplogTurnTimeout) as e:
            # supervised degraded-mode fail-fast: the cloud cannot complete
            # multi-process work (dead/stale/crashed follower, lost op
            # publish, wedged turnstile) — 503 with the diagnosis (incl.
            # any remote traceback) instead of a hang
            status = 503
            return self._reply_error(str(e), 503)
        except NotImplementedError as e:
            from h2o3_tpu.errors import CapabilityGate

            if isinstance(e, CapabilityGate):
                # deliberate capability gates (XLS/Avro parsers, cloud SDKs)
                status = 501
                return self._reply_error(str(e), 501)
            # abstract-hook NotImplementedError is a server bug, not a gate
            status = 500
            return self._reply_error(
                f"{type(e).__name__}: {e}", 500,
                stack=traceback.format_exc().splitlines()[-12:])
        except BrokenPipeError:
            status = 499
        except Exception as e:          # noqa: BLE001 — API boundary
            status = 500
            return self._reply_error(
                f"{type(e).__name__}: {e}", 500,
                stack=traceback.format_exc().splitlines()[-12:])
        finally:
            self._last_status = status

    do_GET = do_POST = do_DELETE = do_PUT = do_HEAD = _handle


class ApiServer:
    """Owns the HTTP thread (reference: water.webserver jetty adapters)."""

    def __init__(self, port: int = 54321,
                 auth_file: Optional[str] = None,
                 host: Optional[str] = None,
                 ssl_certfile: Optional[str] = None,
                 ssl_keyfile: Optional[str] = None):
        # bind address: loopback by default; containers/pods set
        # H2O_TPU_BIND=0.0.0.0 (deploy/ manifests do)
        self.host = host or os.environ.get("H2O_TPU_BIND", "127.0.0.1")
        self.port = port
        self.httpd: Optional[ThreadingHTTPServer] = None
        self.thread: Optional[threading.Thread] = None
        # cloud supervision (multi-process only; wired by start_server):
        # liveness beater + health state machine evaluator
        self.heartbeat_thread = None
        self.supervisor = None
        # TLS on the REST bind (reference: water/network/SSLProperties +
        # jetty h2o_ssl_jks options; here a PEM cert/key pair, the
        # standard python-stack equivalent)
        self.ssl_certfile = ssl_certfile or os.environ.get("H2O_TPU_SSL_CERT")
        self.ssl_keyfile = ssl_keyfile or os.environ.get("H2O_TPU_SSL_KEY")
        if bool(self.ssl_certfile) != bool(self.ssl_keyfile):
            raise ValueError("TLS needs BOTH H2O_TPU_SSL_CERT and "
                             "H2O_TPU_SSL_KEY (PEM paths)")
        # pluggable login module (reference: -login_conf JAAS realms —
        # LDAP/PAM/Kerberos): H2O_TPU_LOGIN_MODULE="pkg.mod:callable",
        # callable(user, password) -> bool. Takes precedence over the
        # hash-file table when both are configured.
        self.login_module = None
        spec = os.environ.get("H2O_TPU_LOGIN_MODULE", "")
        if spec:
            import importlib

            mod_name, _, fn_name = spec.partition(":")
            if not fn_name:
                raise ValueError("H2O_TPU_LOGIN_MODULE must be "
                                 "'module:callable'")
            self.login_module = getattr(importlib.import_module(mod_name),
                                        fn_name)
        # {user: sha256(password) hex} from "user:hash" lines
        self.auth: Optional[Dict[str, str]] = None
        path = auth_file or os.environ.get("H2O_TPU_AUTH_FILE")
        if path:
            self.auth = {}
            with open(path) as f:
                for ln in f:
                    ln = ln.strip()
                    if ln and not ln.startswith("#"):
                        user, _, h = ln.partition(":")
                        self.auth[user] = h.strip()
            if not self.auth:
                # fail CLOSED: a configured-but-empty hash file must not
                # silently disable auth (template files, bad parses)
                raise ValueError(f"auth file {path!r} contains no "
                                 "user:sha256hex entries")

    def start(self) -> "ApiServer":
        handler = type("_BoundHandler", (_Handler,), {"server_ref": self})
        self.httpd = ThreadingHTTPServer((self.host, self.port), handler)
        if self.ssl_certfile:
            import ssl

            sctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            sctx.minimum_version = ssl.TLSVersion.TLSv1_2
            sctx.load_cert_chain(self.ssl_certfile, self.ssl_keyfile)
            # handshake must happen in the per-connection handler thread,
            # NOT the accept loop: with on-connect handshakes one idle TCP
            # connection (port scan, health probe) wedges serve_forever and
            # the whole API with it
            self.httpd.socket = sctx.wrap_socket(
                self.httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self.thread.start()
        return self

    @property
    def scheme(self) -> str:
        return "https" if self.ssl_certfile else "http"

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()
            self.httpd = None
        if self.heartbeat_thread is not None:
            self.heartbeat_thread.stop()
            self.heartbeat_thread = None
        if self.supervisor is not None:
            self.supervisor.stop()
            self.supervisor = None
        from h2o3_tpu.parallel import oplog

        oplog.REST_SERVING = False


def start_server(port: int = 54321, auth_file: Optional[str] = None,
                 host: Optional[str] = None,
                 ssl_certfile: Optional[str] = None,
                 ssl_keyfile: Optional[str] = None) -> ApiServer:
    from h2o3_tpu.obs import flight, phases
    from h2o3_tpu.parallel import distributed as D
    from h2o3_tpu.parallel import oplog

    oplog.REST_SERVING = True     # handler-thread collectives need op turns
    # fatal-signal flight hooks: an externally killed server leaves a
    # postmortem (H2O_TPU_OBS_SIGNALS=0 disables; no-op off-main-thread)
    flight.install_signal_hooks()
    # the whole bring-up (HTTP bind + supervision wiring) is one
    # deadline-supervisable lifecycle phase on /3/Runtime's history
    with phases.enter("server_start", port=port):
        srv = ApiServer(port, auth_file=auth_file, host=host,
                        ssl_certfile=ssl_certfile,
                        ssl_keyfile=ssl_keyfile).start()
        if D.process_count() > 1:
            # multi-process cloud: the coordinator beats + supervises
            # without manual wiring, so /3/Cloud liveness and the
            # /3/CloudStatus state machine are live for every REST-served
            # cloud (stopped by stop())
            from h2o3_tpu.core.failure import HeartbeatThread
            from h2o3_tpu.parallel import supervisor as _sup

            # a RE-started cloud begins from evidence, not from the
            # previous incarnation's sticky verdict: reset, then let
            # Supervisor.start's synchronous first evaluate() re-derive
            # FAILED from any error keys still in the coordination KV
            _sup.reset()
            # core.runtime's cluster boot already runs a beater on every
            # process of a REAL multi-process cloud — only start our own
            # when none is running (REST served without a booted
            # Runtime); the runtime's beater outlives stop() on purpose:
            # the process is still a live cloud member after its HTTP
            # server closes
            import sys as _sys

            _rt = _sys.modules.get("h2o3_tpu.core.runtime")
            _cl = getattr(_rt, "_CLUSTER", None) if _rt else None
            if getattr(_cl, "_heartbeat", None) is None:
                srv.heartbeat_thread = HeartbeatThread().start()
            srv.supervisor = _sup.Supervisor().start()
        return srv


def assume_coordination(port: int = 54321, caught_up_seq=None,
                        force: bool = False, **server_kw) -> ApiServer:
    """Standby-coordinator handoff, REST side: win the election
    (``oplog.assume_coordination`` — deterministic lowest-live-process
    rule, only past ``H2O_TPU_ELECTION_GRACE_S`` of coordinator silence),
    then bind THIS process's REST server so ``/3/*`` keeps being served
    under the new epoch. The old coordinator, if it returns, finds the
    newer epoch record and demotes to follower (its broadcasts 503).

    Raises ``oplog.ElectionLost`` without side effects when this process
    is not the winner or the coordinator is not dead enough yet."""
    from h2o3_tpu.parallel import oplog

    oplog.assume_coordination(caught_up_seq=caught_up_seq, force=force)
    return start_server(port=port, **server_kw)


# ---------------------------------------------------------------------------
# extended surface (routes_ext.py) — appended after every server name exists
# so dispatch and /3/Metadata/endpoints see the full table. If routes_ext
# was imported FIRST (it is mid-import here and `register` not yet defined),
# its own module bottom self-registers + recompiles instead.
# ---------------------------------------------------------------------------
from h2o3_tpu.api import routes_ext as _ext  # noqa: E402

if hasattr(_ext, "register"):
    _ext.register(ROUTES, {"h_model_mojo": h_model_mojo,
                           "h_importfiles": h_importfiles,
                           "h_pdp_post": h_pdp_post,
                           "h_pdp_get": h_pdp_get,
                           "h_modelbuilder_train": h_modelbuilder_train,
                           "h_session_end_legacy": h_session_end})
    _COMPILED = _compile_routes()
