"""REST API server — the /3 (+/99) HTTP surface.

Reference: water/api/RequestServer.java:56 (route table RegisterV3Api.java,
~122 routes), schemas under water/api/schemas3. Serving stack is jetty in the
reference; here it's a stdlib ThreadingHTTPServer — the API layer carries
only JSON metadata, all heavy data stays device-side, so a native web stack
buys nothing on TPU.

Endpoints (V3 contract subset, grown round over round):
  GET  /3/Cloud /3/About /3/Jobs/{id} /3/Frames /3/Frames/{id}
  GET  /3/Frames/{id}/summary /3/Models /3/Models/{id} /3/ModelBuilders
  GET  /3/ImportFiles?path=  /3/Logs  /4/sessions
  POST /3/ParseSetup /3/Parse /99/Rapids /3/ModelBuilders/{algo}
  POST /3/Predictions/models/{m}/frames/{f}  /3/Shutdown
  DELETE /3/Frames/{id} /3/Models/{id} /3/DKV/{key}
"""

from __future__ import annotations

import json
import threading
import traceback
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, unquote, urlparse

import numpy as np

from h2o3_tpu.core.dkv import DKV
from h2o3_tpu.core.frame import Frame
from h2o3_tpu.core.job import Job
from h2o3_tpu.models.model import Model
from h2o3_tpu.rapids import Session, exec_rapids

_JOBS: Dict[str, Job] = {}
_SESSIONS: Dict[str, Session] = {}


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        v = float(o)
        return None if v != v else v
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


def _frame_json(fr: Frame, rows: int = 10) -> dict:
    cols = []
    n = min(fr.nrows, rows)
    for name in fr.names:
        c = fr.col(name)
        data = c.values()[:n]
        cols.append({
            "label": name, "type": c.ctype,
            "domain": c.domain,
            "data": [None if (v is None or (isinstance(v, float) and v != v))
                     else v for v in data.tolist()],
        })
    return {"frame_id": {"name": str(fr.key)}, "rows": fr.nrows,
            "num_columns": fr.ncols, "columns": cols,
            "column_names": fr.names}


def _summary_json(fr: Frame) -> dict:
    out = _frame_json(fr, rows=0)
    out["summary"] = fr.summary()
    return out


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # -- plumbing ---------------------------------------------------------
    def log_message(self, fmt, *args):   # quiet; reference logs to file
        pass

    def _reply(self, obj: Any, code: int = 200):
        body = json.dumps(obj, default=_json_default).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, msg: str, code: int = 400):
        self._reply({"__meta": {"schema_type": "H2OError"},
                     "msg": msg, "exception_msg": msg,
                     "stacktrace": traceback.format_exc().splitlines()[-8:]},
                    code)

    def _body(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length).decode() if length else ""
        ctype = self.headers.get("Content-Type", "")
        if "json" in ctype and raw:
            return json.loads(raw)
        out: Dict[str, Any] = {}
        for k, vs in parse_qs(raw).items():
            out[k] = vs[0]
        return out

    # -- routing ----------------------------------------------------------
    def do_GET(self):
        try:
            self._route("GET")
        except Exception as e:        # noqa: BLE001 — API boundary
            self._error(f"{type(e).__name__}: {e}", 500)

    def do_POST(self):
        try:
            self._route("POST")
        except Exception as e:        # noqa: BLE001
            self._error(f"{type(e).__name__}: {e}", 500)

    def do_DELETE(self):
        try:
            self._route("DELETE")
        except Exception as e:        # noqa: BLE001
            self._error(f"{type(e).__name__}: {e}", 500)

    def _route(self, method: str):
        u = urlparse(self.path)
        parts = [unquote(p) for p in u.path.strip("/").split("/")]
        q = {k: v[0] for k, v in parse_qs(u.query).items()}

        if parts[0] not in ("3", "99", "4"):
            return self._error(f"unknown route {u.path}", 404)
        rest = parts[1:]
        name = rest[0] if rest else ""

        fn = getattr(self, f"_{method.lower()}_{name.lower().replace('.', '_')}", None)
        if fn is None:
            return self._error(f"unknown endpoint {method} {u.path}", 404)
        return fn(rest[1:], q)

    # -- cloud / misc ------------------------------------------------------
    def _get_cloud(self, rest, q):
        from h2o3_tpu.core.runtime import cluster_info

        info = cluster_info()
        size = int(info.get("cloud_size", 1))
        self._reply({"version": info.get("version", "0.1.0"),
                     "cloud_name": info.get("cloud_name", "h2o3_tpu"),
                     "cloud_size": size,
                     "cloud_uptime_millis": info.get("cloud_uptime_millis", 0),
                     "cloud_healthy": bool(info.get("cloud_healthy", True)),
                     "consensus": True, "locked": bool(info.get("locked", True)),
                     "nodes": [{"h2o": f"device{i}", "healthy": True}
                               for i in range(size)]})

    def _get_about(self, rest, q):
        self._reply({"entries": [
            {"name": "Build project", "value": "h2o3_tpu"},
            {"name": "Backend", "value": "jax/XLA (TPU-native)"}]})

    def _post_shutdown(self, rest, q):
        self._reply({"result": "shutting down"})
        threading.Thread(target=self.server.shutdown, daemon=True).start()

    def _get_sessions(self, rest, q):
        sid = f"_sid{uuid.uuid4().hex[:12]}"
        _SESSIONS[sid] = Session(sid)
        self._reply({"session_key": sid})

    # h2o-py's connection handshake issues POST /4/sessions (advisor finding)
    _post_sessions = _get_sessions
    _post_initid = _get_sessions
    _get_initid = _get_sessions

    def _get_logs(self, rest, q):
        import logging

        lines = []
        for h in logging.getLogger("h2o3_tpu").handlers:
            f = getattr(h, "baseFilename", None)
            if f:
                try:
                    with open(f) as fh:
                        lines = fh.read().splitlines()[-500:]
                except OSError:
                    pass
        self._reply({"log": "\n".join(lines)})

    # -- import / parse ----------------------------------------------------
    def _get_importfiles(self, rest, q):
        path = q.get("path", "")
        import glob as _g
        import os

        files = sorted(_g.glob(path)) if any(ch in path for ch in "*?") \
            else ([path] if os.path.exists(path) else [])
        self._reply({"files": files, "destination_frames": files,
                     "fails": [] if files else [path]})

    def _post_parsesetup(self, rest, q):
        from h2o3_tpu.ingest.parse_setup import guess_setup

        body = self._body()
        paths = body.get("source_frames") or []
        if isinstance(paths, str):
            paths = json.loads(paths) if paths.startswith("[") else [paths]
        paths = [p.strip('"') for p in paths]
        setup = guess_setup(paths[0])
        self._reply({"source_frames": paths,
                     "separator": ord(setup.separator),
                     "check_header": setup.check_header,
                     "column_names": setup.column_names,
                     "column_types": setup.column_types,
                     "number_columns": len(setup.column_names),
                     "destination_frame": paths[0].split("/")[-1] + ".hex"})

    def _post_parse(self, rest, q):
        from h2o3_tpu.ingest.parser import import_file

        body = self._body()
        paths = body.get("source_frames") or []
        if isinstance(paths, str):
            paths = json.loads(paths) if paths.startswith("[") else [paths]
        paths = [p.strip('"') for p in paths]
        dest = (body.get("destination_frame") or "").strip('"') or None
        job = Job(description="Parse")
        _JOBS[str(job.key)] = job
        # synchronous on this worker thread (we already run threaded per
        # request); the job object exists for /3/Jobs polling parity
        try:
            job.status = Job.RUNNING
            fr = import_file(paths[0], destination_frame=dest)
            job.dest_key = str(fr.key)
            job.status = Job.DONE
            job.progress = 1.0
        except Exception:            # noqa: BLE001
            job.status = Job.FAILED
            job.exception = traceback.format_exc()
        self._reply({"job": _job_json(job), "destination_frame": {"name": getattr(job, "dest_key", None)}})

    # -- rapids ------------------------------------------------------------
    def _post_rapids(self, rest, q):
        body = self._body()
        ast = body.get("ast", "")
        sid = body.get("session_id", "default")
        sess = _SESSIONS.setdefault(sid, Session(sid))
        val = exec_rapids(ast, sess)
        if isinstance(val, Frame):
            if DKV.get(str(val.key)) is None:
                val.install()      # expression results stay addressable
            self._reply({"key": {"name": str(val.key)},
                         **_frame_json(val)})
        elif isinstance(val, (int, float)):
            self._reply({"scalar": None if val != val else val})
        elif isinstance(val, str):
            self._reply({"string": val})
        else:
            self._reply({"scalar": None})

    # -- frames ------------------------------------------------------------
    def _get_frames(self, rest, q):
        if not rest:
            frames = [v for v in (DKV.get(k) for k in DKV.keys())
                      if isinstance(v, Frame)]
            return self._reply({"frames": [_frame_json(f, rows=0) for f in frames]})
        fid = rest[0]
        fr = DKV.get(fid)
        if not isinstance(fr, Frame):
            return self._error(f"frame {fid} not found", 404)
        if len(rest) > 1 and rest[1] == "summary":
            return self._reply({"frames": [_summary_json(fr)]})
        nrows = int(q.get("row_count", 10) or 10)
        offset = int(q.get("row_offset", 0) or 0)
        from h2o3_tpu.ops.filters import slice_rows

        view = slice_rows(fr, offset, min(offset + nrows, fr.nrows)) \
            if offset else fr
        return self._reply({"frames": [_frame_json(view, rows=nrows)]})

    def _delete_frames(self, rest, q):
        if rest:
            DKV.remove(rest[0])
        self._reply({})

    def _delete_dkv(self, rest, q):
        if rest:
            DKV.remove(rest[0])
        else:
            DKV.clear()
        self._reply({})

    # -- models / training -------------------------------------------------
    def _get_modelbuilders(self, rest, q):
        from h2o3_tpu.models.model_builder import BUILDERS

        self._reply({"model_builders": {
            name: {"algo": name, "parameters": [
                {"name": k, "default_value": v}
                for k, v in cls.default_params().items()]}
            for name, cls in BUILDERS.items()}})

    def _post_modelbuilders(self, rest, q):
        from h2o3_tpu.models.model_builder import BUILDERS

        algo = rest[0].lower() if rest else ""
        cls = BUILDERS.get(algo)
        if cls is None:
            return self._error(f"unknown algo {algo!r}", 404)
        body = self._body()
        params: Dict[str, Any] = {}
        defaults = cls.default_params()
        for k, v in body.items():
            kk = "lambda_" if k == "lambda" else k
            kk = cls.translate_param(kk)
            if kk not in defaults:
                continue
            d = defaults[kk]
            if isinstance(v, str):
                if v.startswith("[") or v.startswith("{"):
                    v = json.loads(v)
                elif isinstance(d, bool):
                    v = v.lower() == "true"
                elif isinstance(d, int) and not isinstance(d, bool):
                    v = int(float(v))
                elif isinstance(d, float):
                    v = float(v)
                else:
                    v = v.strip('"')
            params[kk] = v
        train_key = str(params.pop("training_frame", "")).strip('"')
        valid_key = str(params.pop("validation_frame", "") or "").strip('"')
        y = str(params.pop("response_column", "") or "").strip('"') or None
        train = DKV.get(train_key)
        if not isinstance(train, Frame):
            return self._error(f"training_frame {train_key!r} not found", 404)
        valid = DKV.get(valid_key) if valid_key else None

        builder = cls(**params)
        job = Job(description=f"{algo} train")
        _JOBS[str(job.key)] = job

        def run():
            try:
                job.status = Job.RUNNING
                model = builder.train(y=y, training_frame=train,
                                      validation_frame=valid)
                job.dest_key = str(model.key)
                job.status = Job.DONE
                job.progress = 1.0
            except Exception:            # noqa: BLE001
                job.status = Job.FAILED
                job.exception = traceback.format_exc()

        threading.Thread(target=run, daemon=True).start()
        self._reply({"job": _job_json(job)})

    def _get_models(self, rest, q):
        if not rest:
            models = [v for v in (DKV.get(k) for k in DKV.keys())
                      if isinstance(v, Model)]
            return self._reply({"models": [m.to_dict() for m in models]})
        m = DKV.get(rest[0])
        if not isinstance(m, Model):
            return self._error(f"model {rest[0]} not found", 404)
        self._reply({"models": [m.to_dict()]})

    def _delete_models(self, rest, q):
        if rest:
            DKV.remove(rest[0])
        self._reply({})

    def _post_predictions(self, rest, q):
        # /3/Predictions/models/{model}/frames/{frame}
        if len(rest) < 4 or rest[0] != "models" or rest[2] != "frames":
            return self._error("bad predictions path", 400)
        m = DKV.get(rest[1])
        fr = DKV.get(rest[3])
        if not isinstance(m, Model):
            return self._error(f"model {rest[1]} not found", 404)
        if not isinstance(fr, Frame):
            return self._error(f"frame {rest[3]} not found", 404)
        body = self._body()
        dest = str(body.get("predictions_frame", "") or "").strip('"') or None
        pred = m.predict(fr, key=dest)
        pred.install()
        mm = m.model_performance(fr)
        self._reply({"predictions_frame": {"name": str(pred.key)},
                     "model_metrics": [mm.to_dict() if mm else {}]})

    # -- jobs --------------------------------------------------------------
    def _get_jobs(self, rest, q):
        if not rest:
            return self._reply({"jobs": [_job_json(j) for j in _JOBS.values()]})
        job = _JOBS.get(rest[0])
        if job is None:
            return self._error(f"job {rest[0]} not found", 404)
        self._reply({"jobs": [_job_json(job)]})


def _job_json(job: Job) -> dict:
    return {"key": {"name": str(job.key)},
            "description": job.description,
            "status": str(job.status),
            "progress": job.progress,
            "exception": getattr(job, "exception", None),
            "dest": {"name": getattr(job, "dest_key", None)}}


class ApiServer:
    """Owns the HTTP thread (reference: water.webserver jetty adapters)."""

    def __init__(self, port: int = 54321):
        self.port = port
        self.httpd: Optional[ThreadingHTTPServer] = None
        self.thread: Optional[threading.Thread] = None

    def start(self) -> "ApiServer":
        self.httpd = ThreadingHTTPServer(("127.0.0.1", self.port), _Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()
        return self

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()
            self.httpd = None


def start_server(port: int = 54321) -> ApiServer:
    return ApiServer(port).start()
