"""V3 REST schema emission — the JSON shapes stock h2o-py parses.

Reference: water/api/Schema.java:95 (versioned DTOs with @API fields),
water/api/schemas3/*V3.java, hex/schemas/*V3.java. h2o-py dispatches on
`__meta.schema_name` (h2o-py/h2o/backend/connection.py H2OResponse.__new__):
CloudV3 -> H2OCluster, TwoDimTableV3 -> H2OTwoDimTable, ModelMetrics*V3 ->
metric classes — so every response here carries the exact meta tag and the
exact field names the client's accessors read.

Notable client-side contracts honored here:
- CloudV3 may only contain keys in h2o-py's _cloud_v3_valid_keys
  (backend/cluster.py:381) — an unknown key raises AttributeError client-side.
- TwoDimTableV3 "data" is COLUMN-major; client transposes
  (two_dim_table.py:146 `zip(*values)`).
- thresholds_and_metric_scores rows are indexed positionally by
  metrics_base.confusion_matrix (tns=row[11], fns=12, fps=13, tps=14).
- Frame ColV3 "data" NAs are the string "NaN" (expr.py _fill_data).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.core.frame import Column, Frame, T_CAT, T_STR
from h2o3_tpu.models import metrics as M
from h2o3_tpu.models.model import Model, ModelCategory

SERVER_VERSION = "3.46.0.1"   # advertise a modern h2o-3 line for client checks


def meta(name: str, schema_type: str = "Iced") -> dict:
    return {"schema_version": 3, "schema_name": name, "schema_type": schema_type}


def key_ref(name: Optional[str], ktype: str = "Key<Frame>") -> Optional[dict]:
    if name is None:
        return None
    return {"__meta": meta("KeyV3", ktype.replace("<", "").replace(">", "")),
            "name": str(name), "type": ktype,
            "URL": f"/3/{'Frames' if 'Frame' in ktype else 'Models'}/{name}"}


def trace_v3(trace_id: str, spans: List[dict], tree: List[dict]) -> dict:
    """One trace's span tree (GET /3/Trace/{id}): the flat start-ordered
    span list plus the parent-nested tree — clients graph either."""
    return {"__meta": meta("TraceV3"), "trace_id": trace_id,
            "span_count": len(spans), "spans": spans, "tree": tree}


def flight_records_v3(records: List[dict]) -> dict:
    """Flight-record listing (GET /3/FlightRecords)."""
    return {"__meta": meta("FlightRecordsV3"), "records": records,
            "count": len(records)}


def artifact_v3(info: dict, **extra) -> dict:
    """AOT-artifact DTO (the /3/Artifacts family): a validated manifest
    summary — never raw manifest internals — plus route-specific fields
    (dir, model_id)."""
    out = {"__meta": meta("ArtifactV3")}
    out.update(info)
    out.update(extra)
    return out


# ---------------------------------------------------------------------------
# TwoDimTable
# ---------------------------------------------------------------------------

def twodim(name: str, cols: Sequence[Tuple[str, str]], data_cols: Sequence[Sequence],
           description: str = "") -> dict:
    """cols = [(col_name, col_type)]; data_cols is COLUMN-major.
    col_type in {"string","int","long","float","double"}."""
    return {
        "__meta": meta("TwoDimTableV3"),
        "name": name,
        "description": description,
        "columns": [{"__meta": meta("ColumnSpecsBase"),
                     "name": cn, "type": ct,
                     "format": "%s" if ct == "string" else "%d" if ct in ("int", "long") else "%.5f",
                     "description": cn} for cn, ct in cols],
        "rowcount": len(data_cols[0]) if data_cols else 0,
        "data": [list(c) for c in data_cols],
    }


def dict_table(name: str, d: Dict[str, Sequence], types: Optional[Dict[str, str]] = None) -> dict:
    cols = [(k, (types or {}).get(k, "double")) for k in d]
    return twodim(name, cols, [list(v) for v in d.values()])


# ---------------------------------------------------------------------------
# Cloud
# ---------------------------------------------------------------------------

def cloud_v3(info: Dict[str, Any]) -> dict:
    size = int(info.get("cloud_size", 1))
    node = {
        "__meta": meta("NodeV3"),
        "h2o": info.get("cloud_name", "h2o3_tpu"),
        "ip_port": "127.0.0.1:54321",
        "healthy": True, "last_ping": int(time.time() * 1000),
        "pid": 0, "num_cpus": 1, "cpus_allowed": 1, "nthreads": 1,
        "sys_load": 0.0, "my_cpu_pct": 0, "sys_cpu_pct": 0,
        "mem_value_size": 0, "pojo_mem": 0, "swap_mem": 0,
        "free_mem": 0, "max_mem": 0, "num_keys": 0,
        "free_disk": 0, "max_disk": 0,
        "rpcs_active": 0, "fjthrds": [], "fjqueue": [],
        "open_fds": 0, "gflops": info.get("gflops", 0.0),
        "mem_bw": info.get("mem_bw", 0.0),
        "tcps_active": 0,
    }
    # ONLY _cloud_v3_valid_keys (h2o-py backend/cluster.py:381) may appear.
    return {
        "__meta": meta("CloudV3"),
        "version": SERVER_VERSION,
        "branch_name": "rel-tpu",
        "build_number": "1",
        "build_age": "0 days",
        "build_too_old": False,
        "cloud_name": info.get("cloud_name", "h2o3_tpu"),
        "cloud_size": size,
        "cloud_uptime_millis": int(info.get("cloud_uptime_millis", 0)),
        "cloud_internal_timezone": "UTC",
        "datafile_parser_timezone": "UTC",
        "cloud_healthy": bool(info.get("cloud_healthy", True)),
        "consensus": True,
        "locked": bool(info.get("locked", True)),
        "bad_nodes": 0,
        "is_client": False,
        "node_idx": 0,
        "leader_idx": 0,
        "skip_ticks": False,
        "internal_security_enabled": False,
        "nodes": [dict(node) for _ in range(size)],
    }


# ---------------------------------------------------------------------------
# Jobs
# ---------------------------------------------------------------------------

def _auto_recoverable(job, status: str) -> bool:
    """True only while the watchdog could actually still resume this job:
    it has a re-dispatch recipe, is not terminal-successful, and has not
    been parked at the attempt cap (a stale True makes operators wait for
    a recovery that can never happen instead of resubmitting)."""
    from h2o3_tpu.parallel.watchdog import MAX_ATTEMPTS, enabled

    if not enabled():
        return False                 # manual drills: nothing will resume it
    if status == "FAILED":
        if not getattr(job, "failed_externally", False):
            return False             # worker-crashed: client resubmits
        from h2o3_tpu.parallel import ckpt

        if not ckpt.has_job_progress(str(job.key)):
            return False             # died before the first durable save
    return (bool(getattr(job, "resume_spec", None))
            and status not in ("DONE", "CANCELLED")
            and int(getattr(job, "attempt", 1) or 1) < MAX_ATTEMPTS)


def job_v3(job) -> dict:
    status = str(job.status)
    if status == "RESUMING":
        # internal recovery state: h2o-py pollers treat anything beyond
        # CREATED/RUNNING as terminal, so on the wire a resuming job is
        # simply RUNNING (attempt/resumed_from_iteration tell the story)
        status = "RUNNING"
    dest = getattr(job, "dest_key", None) or getattr(job, "dest", None)
    start = getattr(job, "start_time", 0.0) or 0.0
    end = getattr(job, "end_time", 0.0) or 0.0
    out = {
        "__meta": meta("JobV3"),
        "key": {"__meta": meta("JobKeyV3"), "name": str(job.key),
                "type": "Key<Job>", "URL": f"/3/Jobs/{job.key}"},
        "description": job.description,
        "status": status,
        "progress": float(job.progress),
        "progress_msg": getattr(job, "progress_msg", "") or "",
        "start_time": int(start * 1000),
        "msec": int(((end or time.time()) - start) * 1000) if start else 0,
        "dest": key_ref(dest, getattr(job, "dest_type", "Key<Frame>"))
        or {"name": None},
        "exception": getattr(job, "exception", None),
        "warnings": list(getattr(job, "warnings", []) or []),
        # crash-survivable jobs: dispatch count (1 = original submit) and,
        # after a watchdog resume, the iteration training continued from
        "attempt": int(getattr(job, "attempt", 1) or 1),
        "resumed_from_iteration": getattr(job, "resumed_from_iteration",
                                          None),
        "failed_externally": bool(getattr(job, "failed_externally", False)),
        "auto_recoverable": _auto_recoverable(job, status),
        "ready_for_view": True,
    }
    if status == "FAILED" and getattr(job, "exception", None):
        out["stacktrace"] = job.exception
    return out


# ---------------------------------------------------------------------------
# Frames
# ---------------------------------------------------------------------------

_CTYPE_TO_REST = {"real": "real", "int": "int", "enum": "enum", "time": "time",
                  "string": "string", "uuid": "uuid", "bad": "bad"}


def col_v3(name: str, col: Column, offset: int, count: int) -> dict:
    n = col.nrows
    lo = max(0, min(offset, n))
    hi = max(lo, min(lo + count, n)) if count >= 0 else n
    rtype = _CTYPE_TO_REST.get(col.ctype, "real")
    out = {
        "__meta": meta("ColV3"),
        "label": name,
        "type": rtype,
        "missing_count": 0, "zero_count": 0,
        "positive_infinity_count": 0, "negative_infinity_count": 0,
        "mins": [], "maxs": [], "mean": None, "sigma": None,
        "histogram_bins": None, "histogram_base": None, "histogram_stride": None,
        "percentiles": None,
        "domain": col.domain, "domain_cardinality": col.cardinality,
        "data": None, "string_data": None, "precision": -1,
    }
    if col.is_string:
        vals = col.host_data[lo:hi]
        out["string_data"] = [None if v is None else str(v) for v in vals]
        out["missing_count"] = int(sum(1 for v in col.host_data if v is None))
        return out
    r = col.rollups
    out["missing_count"] = int(r.na_count)
    if col.is_categorical:
        arr = np.asarray(col.data)[lo:hi]
        out["data"] = [("NaN" if v < 0 else int(v)) for v in arr.tolist()]
        return out
    out["zero_count"] = int(max(r.rows - r.nz_count, 0))
    out["mins"] = [float(r.min)] if r.min == r.min else []
    out["maxs"] = [float(r.max)] if r.max == r.max else []
    out["mean"] = float(r.mean) if r.mean == r.mean else None
    out["sigma"] = float(r.sigma) if r.sigma == r.sigma else None
    arr = np.asarray(col.data, np.float64)[lo:hi]
    data = []
    for v in arr.tolist():
        if v != v:
            data.append("NaN")
        elif col.ctype == "int" and float(v).is_integer():
            data.append(int(v))
        else:
            data.append(v)
    out["data"] = data
    return out


def frame_v3(fr: Frame, row_count: int = 10, row_offset: int = 0,
             column_count: int = -1, column_offset: int = 0,
             with_data: bool = True) -> dict:
    names = fr.names
    ncols = len(names)
    if column_count is None or column_count < 0:
        column_count = ncols
    sel = names[column_offset: column_offset + column_count]
    columns = []
    if with_data:
        columns = [col_v3(n, fr.col(n), row_offset, row_count) for n in sel]
    return {
        "__meta": meta("FrameV3"),
        "frame_id": key_ref(str(fr.key), "Key<Frame>"),
        "byte_size": sum(4 * fr.nrows for _ in names),
        "is_text": False,
        "row_offset": row_offset, "row_count": min(row_count, fr.nrows),
        "column_offset": column_offset, "column_count": len(sel),
        "full_column_count": ncols, "total_column_count": ncols,
        "rows": fr.nrows, "num_columns": ncols,
        "checksum": 0, "default_percentiles": [],
        "columns": columns,
        "compatible_models": [],
        "chunk_summary": None, "distribution_summary": None,
    }


def frame_key_v3(fr: Frame) -> dict:
    return {"__meta": meta("FrameKeyV3"), "name": str(fr.key),
            "type": "Key<Frame>", "URL": f"/3/Frames/{fr.key}"}


# ---------------------------------------------------------------------------
# Model metrics
# ---------------------------------------------------------------------------

_EPS = 1e-15


def _binomial_threshold_tables(aucd: M.AUCData) -> Tuple[dict, dict]:
    """Rebuild AUC2's thresholds_and_metric_scores + max_criteria tables from
    the 400-bin sweep (hex/AUC2.java ThresholdCriterion). Column ORDER is a
    client contract: metrics_base.confusion_matrix reads tns=row[11],
    fns=row[12], fps=row[13], tps=row[14]."""
    thr = np.asarray(aucd.thresholds, np.float64)
    tps = np.asarray(aucd.tps, np.float64)
    fps = np.asarray(aucd.fps, np.float64)
    p, n = float(aucd.p), float(aucd.n)
    fns = p - tps
    tns = n - fps
    with np.errstate(invalid="ignore", divide="ignore"):
        precision = np.where(tps + fps > 0, tps / (tps + fps), 1.0)
        recall = np.where(p > 0, tps / max(p, _EPS), 0.0)
        specificity = np.where(n > 0, tns / max(n, _EPS), 0.0)
        accuracy = (tps + tns) / max(p + n, _EPS)
        f1 = np.where(precision + recall > 0, 2 * precision * recall / (precision + recall), 0.0)
        f2 = np.where(4 * precision + recall > 0, 5 * precision * recall / (4 * precision + recall), 0.0)
        f05 = np.where(0.25 * precision + recall > 0, 1.25 * precision * recall / (0.25 * precision + recall), 0.0)
        mcc_den = np.sqrt((tps + fps) * (tps + fns) * (tns + fps) * (tns + fns))
        mcc = np.where(mcc_den > 0, (tps * tns - fps * fns) / np.maximum(mcc_den, _EPS), 0.0)
        tpr = recall
        fpr = np.where(n > 0, fps / max(n, _EPS), 0.0)
        tnr = specificity
        fnr = np.where(p > 0, fns / max(p, _EPS), 0.0)
        min_pca = np.minimum(tpr, tnr)
        mean_pca = 0.5 * (tpr + tnr)
    idx = np.arange(len(thr))
    col_order = [
        ("threshold", thr), ("f1", f1), ("f2", f2), ("f0point5", f05),
        ("accuracy", accuracy), ("precision", precision), ("recall", recall),
        ("specificity", specificity), ("absolute_mcc", np.abs(mcc)),
        ("min_per_class_accuracy", min_pca), ("mean_per_class_accuracy", mean_pca),
        ("tns", tns), ("fns", fns), ("fps", fps), ("tps", tps),
        ("tnr", tnr), ("fnr", fnr), ("fpr", fpr), ("tpr", tpr),
        ("idx", idx),
    ]
    thresh_table = twodim(
        "Metrics for Thresholds",
        [(cn, "long" if cn == "idx" else "double") for cn, _ in col_order],
        [np.nan_to_num(cv, nan=0.0).tolist() for _, cv in col_order],
        description="Binomial metrics as a function of classification thresholds",
    )
    criteria = [("max f1", f1), ("max f2", f2), ("max f0point5", f05),
                ("max accuracy", accuracy), ("max precision", precision),
                ("max recall", recall), ("max specificity", specificity),
                ("max absolute_mcc", np.abs(mcc)),
                ("max min_per_class_accuracy", min_pca),
                ("max mean_per_class_accuracy", mean_pca),
                ("max tns", tns), ("max fns", fns), ("max fps", fps),
                ("max tps", tps), ("max tnr", tnr), ("max fnr", fnr),
                ("max fpr", fpr), ("max tpr", tpr)]
    names, thrs, vals, idxs = [], [], [], []
    for cname, cvals in criteria:
        i = int(np.nanargmax(cvals)) if len(cvals) else 0
        names.append(cname)
        thrs.append(float(thr[i]))
        vals.append(float(cvals[i]))
        idxs.append(i)
    max_table = twodim(
        "Maximum Metrics",
        [("metric", "string"), ("threshold", "double"), ("value", "double"), ("idx", "long")],
        [names, thrs, vals, idxs],
        description="Maximum metrics at their respective thresholds",
    )
    return thresh_table, max_table


def _metrics_common(mm: M.ModelMetrics, schema: str, model_key: Optional[str],
                    frame_key: Optional[str]) -> dict:
    return {
        "__meta": meta(schema + "V3", schema),
        "model": key_ref(model_key, "Key<Model>") if model_key else None,
        "model_checksum": 0,
        "frame": {"name": str(frame_key)} if frame_key else None,
        "frame_checksum": 0,
        "description": mm.description or None,
        "scoring_time": int(time.time() * 1000),
        "MSE": mm.mse, "RMSE": mm.rmse, "nobs": int(mm.nobs),
        "custom_metric_name": None, "custom_metric_value": 0.0,
    }


def metrics_v3(mm, model_key: Optional[str] = None,
               frame_key: Optional[str] = None) -> Optional[dict]:
    """Map a framework metrics dataclass to its reference V3 schema."""
    if mm is None:
        return None
    if isinstance(mm, M.ModelMetricsBinomial):
        out = _metrics_common(mm, "ModelMetricsBinomial", model_key, frame_key)
        gl = getattr(mm, "gains_lift_table", None)
        out.update({"r2": None, "logloss": mm.logloss, "AUC": mm.auc,
                    "pr_auc": mm.pr_auc, "Gini": mm.gini,
                    "mean_per_class_error": mm.mean_per_class_error,
                    "domain": (mm.cm.domain if mm.cm else None),
                    # genuine h2o-py metrics_base.gains_lift reads this as a
                    # TwoDimTableV3
                    "gains_lift_table": gl.to_v3() if gl is not None else None})
        if mm.auc_data is not None:
            tt, mt = _binomial_threshold_tables(mm.auc_data)
            out["thresholds_and_metric_scores"] = tt
            out["max_criteria_and_metric_scores"] = mt
        return out
    if isinstance(mm, M.ModelMetricsMultinomial):
        out = _metrics_common(mm, "ModelMetricsMultinomial", model_key, frame_key)
        cm_table = None
        if mm.cm is not None:
            dom = list(mm.cm.domain)
            tbl = np.asarray(mm.cm.table, np.float64)
            rates = []
            for i in range(len(dom)):
                tot = tbl[i].sum()
                err = (tot - tbl[i, i]) / tot if tot else 0.0
                rates.append("%.4f = %d / %d" % (err, int(tot - tbl[i, i]), int(tot)))
            cols = [(d, "long") for d in dom] + [("Error", "double"), ("Rate", "string")]
            data = [tbl[:, j].tolist() for j in range(len(dom))]
            errs = [float((tbl[i].sum() - tbl[i, i]) / tbl[i].sum()) if tbl[i].sum() else 0.0
                    for i in range(len(dom))]
            cm_table = {"__meta": meta("ConfusionMatrixV3", "ConfusionMatrix"),
                        "table": twodim("Confusion Matrix", cols, data + [errs, rates])}
        hit = None
        if mm.hit_ratios:
            hit = twodim("Top-K Hit Ratios", [("k", "int"), ("hit_ratio", "double")],
                         [list(range(1, len(mm.hit_ratios) + 1)), list(mm.hit_ratios)])
        out.update({"r2": None, "logloss": mm.logloss,
                    "mean_per_class_error": mm.mean_per_class_error,
                    "cm": cm_table, "hit_ratio_table": hit,
                    "multinomial_auc_table": None, "multinomial_aucpr_table": None})
        return out
    if isinstance(mm, M.ModelMetricsRegression):
        out = _metrics_common(mm, "ModelMetricsRegression", model_key, frame_key)
        out.update({"r2": mm.r2, "mae": mm.mae, "rmsle": mm.rmsle,
                    "mean_residual_deviance": mm.mean_residual_deviance})
        return out
    if isinstance(mm, M.ModelMetricsClustering):
        out = _metrics_common(mm, "ModelMetricsClustering", model_key, frame_key)
        out.update({"tot_withinss": mm.tot_withinss, "totss": mm.totss,
                    "betweenss": mm.betweenss,
                    "centroid_stats": None})
        return out
    # generic fallback: emit the base fields under the plain schema
    out = _metrics_common(mm, "ModelMetrics", model_key, frame_key)
    for k, v in (mm.to_dict() or {}).items():
        out.setdefault(k, v)
    return out


# ---------------------------------------------------------------------------
# Models
# ---------------------------------------------------------------------------

def _mojo_available() -> bool:
    try:
        import h2o3_tpu.models.mojo  # noqa: F401, PLC0415
        return True
    except ImportError:
        return False


def _param_type(v: Any) -> str:
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, int):
        return "long"
    if isinstance(v, float):
        return "double"
    if isinstance(v, (list, tuple)):
        return "string[]"
    return "string"


def model_parameter_v3(name: str, default: Any, actual: Any) -> dict:
    def enc(v):
        if isinstance(v, Frame):
            return {"name": str(v.key)}
        if isinstance(v, (list, tuple)):
            return list(v)
        return v
    return {
        "__meta": meta("ModelParameterSchemaV3"),
        "name": name, "label": name, "help": name,
        "required": False, "type": _param_type(default if default is not None else actual),
        "default_value": enc(default), "actual_value": enc(actual),
        "input_value": enc(actual),
        "level": "critical", "values": [], "gridable": True,
        "is_member_of_frames": [], "is_mutually_exclusive_with": [],
    }


def _varimp_table(vi: Dict[str, float]) -> dict:
    names = list(vi.keys())
    rel = np.asarray([max(float(vi[k]), 0.0) for k in names], np.float64)
    mx = rel.max() if len(rel) and rel.max() > 0 else 1.0
    scaled = rel / mx
    tot = rel.sum() or 1.0
    pct = rel / tot
    order = np.argsort(-rel)
    return twodim(
        "Variable Importances",
        [("variable", "string"), ("relative_importance", "double"),
         ("scaled_importance", "double"), ("percentage", "double")],
        [[names[i] for i in order], rel[order].tolist(),
         scaled[order].tolist(), pct[order].tolist()])


def _scoring_history_table(hist: List[dict]) -> Optional[dict]:
    if not hist:
        return None
    keys: List[str] = []
    for h in hist:
        for k in h:
            if k not in keys:
                keys.append(k)
    cols = [(k, "string" if any(isinstance(h.get(k), str) for h in hist) else "double")
            for k in keys]
    data = [[h.get(k) for h in hist] for k in keys]
    return twodim("Scoring History", cols, data)


def model_v3(model: Model, builder_cls=None) -> dict:
    o = model._output
    algo = model.algo_name
    params = []
    defaults = builder_cls.default_params() if builder_cls else {}
    merged = dict(defaults)
    merged.update(model._parms or {})
    for k in merged:
        params.append(model_parameter_v3(k, defaults.get(k), merged[k]))
    mk = str(model.key)
    col_names = list(o.names)
    if o.response_name:
        col_names = col_names + [o.response_name]
    domains = [o.domains.get(c) for c in o.names]
    if o.response_name:
        domains = domains + [o.response_domain]
    output = {
        "__meta": meta("ModelOutputSchemaV3", "ModelOutput"),
        "model_category": o.model_category,
        "names": col_names,
        "original_names": col_names,
        "column_types": ["Enum" if (o.domains.get(c) or
                                    (c == o.response_name and o.response_domain))
                         else "Numeric" for c in col_names],
        "domains": domains,
        "cross_validation_models": ([key_ref(str(k), "Key<Model>") for k in
                                     getattr(o, "cv_model_keys", [])] or None),
        "cross_validation_predictions": None,
        "cross_validation_holdout_predictions_frame_id": None,
        "cross_validation_fold_assignment_frame_id": None,
        "training_metrics": metrics_v3(o.training_metrics, mk, None),
        "validation_metrics": metrics_v3(o.validation_metrics, mk, None),
        "cross_validation_metrics": metrics_v3(o.cross_validation_metrics, mk, None),
        "cross_validation_metrics_summary": None,
        "model_summary": None,
        "scoring_history": _scoring_history_table(o.scoring_history),
        "variable_importances": (_varimp_table(o.variable_importances)
                                 if o.variable_importances else None),
        "status": "DONE",
        "start_time": int(o.start_time * 1000) if o.start_time else 0,
        "end_time": int(o.start_time * 1000 + o.run_time_ms) if o.start_time else 0,
        "run_time": o.run_time_ms,
        "default_threshold": (float(o.training_metrics.auc_data.max_f1_threshold)
                              if getattr(o.training_metrics, "auc_data", None) is not None
                              else 0.5),
        "help": {},
    }
    return {
        "__meta": meta(f"{algo.upper()}ModelV3", "Model"),
        "model_id": key_ref(mk, "Key<Model>"),
        "algo": algo,
        "algo_full_name": algo.upper(),
        "parameters": params,
        "output": output,
        "compatible_frames": [],
        "have_pojo": False,
        "have_mojo": _mojo_available(),
        "response_column_name": o.response_name,
        "data_frame": {"name": str(model._parms.get("training_frame"))
                       if model._parms.get("training_frame") else None},
        "timestamp": int(time.time() * 1000),
    }


# ---------------------------------------------------------------------------
# Errors
# ---------------------------------------------------------------------------

def error_v3(msg: str, status: int, stacktrace: Optional[List[str]] = None,
             exception_type: str = "java.lang.RuntimeException",
             schema: str = "H2OErrorV3") -> dict:
    out = {
        "__meta": meta(schema, "H2OError"),
        "timestamp": int(time.time() * 1000),
        "error_url": "",
        "msg": msg,
        "dev_msg": msg,
        "http_status": status,
        "values": {},
        "exception_type": exception_type,
        "exception_msg": msg,
        "stacktrace": stacktrace or [],
    }
    if schema == "H2OModelBuilderErrorV3":
        out["messages"] = []
        out["error_count"] = 1
        out["parameters"] = {}
    return out
