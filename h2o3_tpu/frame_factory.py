"""H2OFrame — the user-facing frame with h2o-py operator surface.

Reference: h2o-py/h2o/frame.py builds a lazy client-side AST (expr.py:27
ExprNode) shipped as Rapids strings; the server evaluates them as MRTasks.
Here client and server are one process, so operators evaluate eagerly into
new device columns — XLA's jit cache plays the role of the Rapids compile
cache (SURVEY.md §7 "compile-cache by AST shape"). The textual Rapids
surface still exists (ops/rapids/) for REST clients.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from h2o3_tpu.core.dkv import DKV, Key
from h2o3_tpu.core.frame import Column, Frame, T_CAT, T_NUM
from h2o3_tpu.ops import elementwise as ew
from h2o3_tpu.ops import filters as flt


class H2OFrame(Frame):
    """Frame with h2o-py surface (h2o-py/h2o/frame.py parity subset)."""

    # -- construction -----------------------------------------------------
    @staticmethod
    def _wrap(fr: Frame) -> "H2OFrame":
        out = H2OFrame.__new__(H2OFrame)
        out.__dict__.update(fr.__dict__)
        out.install()
        return out

    def __init__(self, python_obj=None, destination_frame: Optional[str] = None,
                 column_names: Optional[Sequence[str]] = None,
                 column_types: Optional[Dict[str, str]] = None):
        super().__init__(key=destination_frame)
        if python_obj is None:
            pass
        elif isinstance(python_obj, dict):
            for name, vals in python_obj.items():
                ctype = (column_types or {}).get(name)
                arr = np.asarray(vals)
                self.add(str(name), Column.from_numpy(arr, ctype=ctype))
        elif isinstance(python_obj, (list, tuple, np.ndarray)):
            arr = np.asarray(python_obj)
            if arr.ndim == 1:
                arr = arr[:, None]
            names = list(column_names) if column_names else [f"C{i+1}" for i in range(arr.shape[1])]
            for i, name in enumerate(names):
                ctype = (column_types or {}).get(name)
                self.add(name, Column.from_numpy(arr[:, i], ctype=ctype))
        else:
            try:
                import pandas as pd

                if isinstance(python_obj, pd.DataFrame):
                    for n in python_obj.columns:
                        s = python_obj[n]
                        ctype = (column_types or {}).get(n)
                        if ctype is None and (s.dtype.name == "category" or s.dtype.kind in "OUS"):
                            ctype = T_CAT
                        self.add(str(n), Column.from_numpy(s.to_numpy(), ctype=ctype))
                else:
                    raise TypeError
            except (ImportError, TypeError):
                raise TypeError(f"cannot build H2OFrame from {type(python_obj)}")
        self.install()

    @property
    def frame_id(self) -> str:
        return str(self.key)

    # -- selection --------------------------------------------------------
    def __getitem__(self, sel):
        if isinstance(sel, str):
            return H2OFrame._wrap(self.subframe([sel]))
        if isinstance(sel, int):
            return H2OFrame._wrap(self.subframe([sel]))
        if isinstance(sel, (list, np.ndarray)) and len(sel) and isinstance(sel[0], (str, int, np.integer)):
            return H2OFrame._wrap(self.subframe(list(sel)))
        if isinstance(sel, slice):
            return H2OFrame._wrap(flt.slice_rows(self, sel.start or 0, sel.stop if sel.stop is not None else self.nrows))
        if isinstance(sel, (H2OFrame, Frame)):
            return H2OFrame._wrap(flt.filter_rows(self, sel.col(0)))
        if isinstance(sel, tuple) and len(sel) == 2:
            rows, cols = sel
            fr = self
            if isinstance(cols, (str, int)):
                fr = fr.subframe([cols])
            elif isinstance(cols, (list, np.ndarray)):
                fr = fr.subframe(list(cols))
            elif isinstance(cols, slice):
                fr = fr.subframe(fr.names[cols])
            if isinstance(rows, (H2OFrame, Frame)):
                return H2OFrame._wrap(flt.filter_rows(fr, rows.col(0)))
            if isinstance(rows, slice):
                return H2OFrame._wrap(flt.slice_rows(fr, rows.start or 0, rows.stop if rows.stop is not None else fr.nrows))
            if isinstance(rows, (list, np.ndarray)):
                return H2OFrame._wrap(flt.take_rows(fr, np.asarray(rows)))
            if rows is None or (isinstance(rows, slice) and rows == slice(None)):
                return H2OFrame._wrap(fr) if fr is not self else self
            raise TypeError(f"bad row selector {rows!r}")
        raise TypeError(f"bad selector {sel!r}")

    def __setitem__(self, name, value):
        if isinstance(value, (H2OFrame, Frame)):
            col = value.col(0)
        elif isinstance(value, Column):
            col = value
        elif np.isscalar(value):
            col = Column.from_numpy(np.full(self.nrows, value))
        else:
            col = Column.from_numpy(np.asarray(value))
        self.replace(name, col)

    # -- operators --------------------------------------------------------
    def _bin(self, op, other, rev=False):
        a = self.col(0)
        b = other.col(0) if isinstance(other, (H2OFrame, Frame)) else other
        left, right = (b, a) if rev else (a, b)
        out = ew.binop(op, left, right)
        name = self.names[0]
        return H2OFrame._wrap(Frame({name: out}))

    def __add__(self, o):
        return self._bin("+", o)

    def __radd__(self, o):
        return self._bin("+", o, rev=True)

    def __sub__(self, o):
        return self._bin("-", o)

    def __rsub__(self, o):
        return self._bin("-", o, rev=True)

    def __mul__(self, o):
        return self._bin("*", o)

    def __rmul__(self, o):
        return self._bin("*", o, rev=True)

    def __truediv__(self, o):
        return self._bin("/", o)

    def __rtruediv__(self, o):
        return self._bin("/", o, rev=True)

    def __pow__(self, o):
        return self._bin("^", o)

    def __mod__(self, o):
        return self._bin("%", o)

    def __eq__(self, o):  # noqa — h2o-py semantics: elementwise
        return self._bin("==", o)

    def __ne__(self, o):
        return self._bin("!=", o)

    def __lt__(self, o):
        return self._bin("<", o)

    def __le__(self, o):
        return self._bin("<=", o)

    def __gt__(self, o):
        return self._bin(">", o)

    def __ge__(self, o):
        return self._bin(">=", o)

    def __hash__(self):
        return hash(self._key)

    def __and__(self, o):
        return self._bin("*", o)  # boolean and == product on 0/1 cols

    def __or__(self, o):
        s = self._bin("+", o)
        return s._bin(">", 0)

    def __invert__(self):
        return H2OFrame._wrap(Frame({self.names[0]: ew.unop("not", self.col(0))}))

    def __len__(self):
        return self.nrows

    # -- math methods -----------------------------------------------------
    def _un(self, op):
        return H2OFrame._wrap(Frame({self.names[0]: ew.unop(op, self.col(0))}))

    def abs(self):
        return self._un("abs")

    def exp(self):
        return self._un("exp")

    def log(self):
        return self._un("log")

    def log10(self):
        return self._un("log10")

    def log1p(self):
        return self._un("log1p")

    def sqrt(self):
        return self._un("sqrt")

    def floor(self):
        return self._un("floor")

    def ceil(self):
        return self._un("ceiling")

    def sign(self):
        return self._un("sign")

    def tanh(self):
        return self._un("tanh")

    def isna(self):
        return H2OFrame._wrap(Frame({self.names[0]: ew.is_na(self.col(0))}))

    def ifelse(self, yes, no):
        y = yes.col(0) if isinstance(yes, Frame) else yes
        n = no.col(0) if isinstance(no, Frame) else no
        return H2OFrame._wrap(Frame({"ifelse": ew.ifelse(self.col(0), y, n)}))

    # -- reductions -------------------------------------------------------
    def mean(self, na_rm=True, axis=0, return_frame=False):
        vals = [self.col(n).mean() for n in self.names]
        return vals if len(vals) > 1 else vals[0]

    def sum(self, na_rm=True):
        vals = [self.col(n).rollups.mean * self.col(n).rollups.rows for n in self.names]
        return vals if len(vals) > 1 else vals[0]

    def min(self):
        vals = [self.col(n).min() for n in self.names]
        return min(vals)

    def max(self):
        vals = [self.col(n).max() for n in self.names]
        return max(vals)

    def sd(self):
        vals = [self.col(n).sigma() for n in self.names]
        return vals if len(vals) > 1 else vals[0]

    def nacnt(self):
        return [self.col(n).na_count() for n in self.names]

    def median(self):
        from h2o3_tpu.ops.quantile import quantile_column

        vals = [quantile_column(self.col(n), [0.5])[0] for n in self.names]
        return vals if len(vals) > 1 else vals[0]

    def quantile(self, prob=None):
        from h2o3_tpu.ops.quantile import quantile_column

        prob = prob or [0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9, 0.99]
        qs = {n: quantile_column(self.col(n), prob) for n in self.names if self.col(n).is_numeric}
        out = H2OFrame({"Probs": np.asarray(prob)})
        for n, v in qs.items():
            out.add(n + "Quantiles", Column.from_numpy(np.asarray(v)))
        return out

    # -- type munging -----------------------------------------------------
    def asfactor(self):
        fr = Frame()
        for n in self.names:
            c = self.col(n)
            if c.is_categorical:
                fr.add(n, c)
            else:
                vals = c.to_numpy()
                fr.add(n, Column.from_numpy(vals.astype(np.int64).astype(str) if not np.isnan(vals).any()
                                            else np.asarray([("" if np.isnan(v) else str(int(v))) for v in vals], dtype=object),
                                            ctype=T_CAT))
        return H2OFrame._wrap(fr)

    def asnumeric(self):
        fr = Frame()
        for n in self.names:
            c = self.col(n)
            fr.add(n, Column.from_device(ew._as_f32(c), T_NUM, c.nrows) if c.data is not None
                   else Column.from_numpy(c.host_data.astype(np.float32)))
        return H2OFrame._wrap(fr)

    def levels(self):
        return [self.col(n).domain or [] for n in self.names]

    def nlevels(self):
        return [self.col(n).cardinality for n in self.names]

    def set_names(self, names: List[str]):
        assert len(names) == self.ncols
        for old, new in zip(list(self._names), names):
            if old != new:
                self.rename(old, new)
        return self

    def set_name(self, col, name):
        old = self._names[col] if isinstance(col, int) else col
        self.rename(old, name)
        return self

    # -- shape ops --------------------------------------------------------
    def cbind(self, other):
        return H2OFrame._wrap(super().cbind(other))

    def rbind(self, other):
        return H2OFrame._wrap(flt.rbind([self, other]))

    def split_frame(self, ratios=None, destination_frames=None, seed=None):
        ratios = ratios if ratios is not None else [0.75]
        parts = flt.split_frame(self, ratios, seed=seed, destination_frames=destination_frames)
        return [H2OFrame._wrap(p) for p in parts]

    def head(self, rows=10):
        return H2OFrame._wrap(flt.slice_rows(self, 0, min(rows, self.nrows)))

    def tail(self, rows=10):
        return H2OFrame._wrap(flt.slice_rows(self, max(0, self.nrows - rows), self.nrows))

    def drop(self, cols):
        if isinstance(cols, (str, int)):
            cols = [cols]
        names = [self._names[c] if isinstance(c, int) else c for c in cols]
        return H2OFrame._wrap(self.subframe([n for n in self.names if n not in names]))

    def describe(self):
        return self.summary()

    def as_data_frame(self, use_pandas=True):
        return self.to_pandas()

    def structure(self):
        return self.summary()

    def group_by(self, by):
        from h2o3_tpu.ops.groupby import GroupBy

        return GroupBy(self, by)

    def impute(self, column=-1, method="mean"):
        from h2o3_tpu.ops.impute import impute

        return impute(self, column, method)

    def table(self, dense=True):
        from h2o3_tpu.ops.groupby import table

        return H2OFrame._wrap(table(self))

    def unique(self):
        c = self.col(0)
        vals = c.to_numpy()
        u = np.unique(vals[~np.isnan(vals)] if c.is_numeric else vals[vals >= 0])
        return H2OFrame({self.names[0]: u})

    def runif(self, seed=None):
        rng = np.random.default_rng(seed)
        return H2OFrame({"rnd": rng.random(self.nrows)})

    def merge(self, other, all_x=False, all_y=False, by_x=None, by_y=None, method="auto"):
        from h2o3_tpu.ops.merge import merge

        return H2OFrame._wrap(merge(self, other, all_x=all_x, all_y=all_y,
                                    by_x=by_x, by_y=by_y))

    def sort(self, by, ascending=True):
        from h2o3_tpu.ops.sort import sort_frame

        return H2OFrame._wrap(sort_frame(self, by, ascending))

    def __repr__(self):
        return f"<H2OFrame {self._key} {self.nrows}x{self.ncols}>"


def create_frame(rows=100, cols=4, key=None, randomize=True, real_fraction=None,
                 categorical_fraction=None, integer_fraction=None,
                 binary_fraction=0.0, factors=5, real_range=100,
                 integer_range=100, missing_fraction=0.0, seed=None,
                 has_response=False, response_factors=2, **kw) -> H2OFrame:
    """Synthetic frame generator (hex/CreateFrame.java parity)."""
    rng = np.random.default_rng(seed)
    rf = real_fraction if real_fraction is not None else 0.5
    cf = categorical_fraction if categorical_fraction is not None else 0.25
    if integer_fraction is None:
        integer_fraction = max(0.0, 1.0 - rf - cf - binary_fraction)
    fracs = np.array([rf, cf, integer_fraction, binary_fraction], np.float64)
    raw = fracs / max(fracs.sum(), 1e-12) * cols
    counts = np.floor(raw).astype(int)
    # largest-remainder apportionment: flooring must not silently starve a
    # requested type (cols=4 with cat 0.25 must yield one enum, not zero)
    rem = raw - counts
    while counts.sum() < cols:
        i = int(np.argmax(rem))
        counts[i] += 1
        rem[i] = -1.0
    fr = H2OFrame(destination_frame=key)
    ci = 0
    for _ in range(counts[0]):
        v = rng.uniform(-real_range, real_range, rows)
        _add_missing(v, missing_fraction, rng)
        fr.add(f"C{ci+1}", Column.from_numpy(v))
        ci += 1
    for _ in range(counts[1]):
        codes = rng.integers(0, factors, rows)
        labels = np.asarray([f"c{ci}.l{k}" for k in codes], dtype=object)
        if missing_fraction:
            labels[rng.random(rows) < missing_fraction] = None
        fr.add(f"C{ci+1}", Column.from_numpy(labels, ctype=T_CAT))
        ci += 1
    for _ in range(counts[2]):
        v = rng.integers(-integer_range, integer_range, rows).astype(np.float64)
        _add_missing(v, missing_fraction, rng)
        fr.add(f"C{ci+1}", Column.from_numpy(v))
        ci += 1
    for _ in range(counts[3]):
        v = rng.integers(0, 2, rows).astype(np.float64)
        _add_missing(v, missing_fraction, rng)
        fr.add(f"C{ci+1}", Column.from_numpy(v))
        ci += 1
    if has_response:
        if response_factors and response_factors > 1:
            codes = rng.integers(0, response_factors, rows)
            fr.add("response", Column.from_numpy(
                np.asarray([f"r{k}" for k in codes], dtype=object), ctype=T_CAT))
        else:
            fr.add("response", Column.from_numpy(rng.normal(size=rows)))
    return fr


def _add_missing(v, frac, rng):
    if frac:
        v[rng.random(len(v)) < frac] = np.nan
