"""Extension SPI + user-defined Rapids functions.

Reference: water/AbstractH2OExtension.java + water/ExtensionManager.java —
extensions discovered on the classpath get init hooks at cloud boot and can
register REST endpoints; water/rapids/ast/AstFunction + AstApply give
Rapids user-defined functions.

TPU mapping: extensions are plain callables registered before (or after)
init — `register_extension` runs the hook immediately if the cluster is
already up, else at the next `h2o3_tpu.init()`. UDFs register as Rapids
prims that execute HOST-side on the gathered column values (strings or
numerics) and re-shard the result — the escape hatch for logic outside the
device op set, like the reference's AstApply running user ASTs per row."""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

_EXTENSIONS: Dict[str, Callable] = {}
_INITIALIZED: List[str] = []


def register_extension(name: str, init_hook: Callable) -> None:
    """Install an extension; its hook runs with the Cluster at boot (or now,
    if the cluster is already booted)."""
    _EXTENSIONS[name] = init_hook
    from h2o3_tpu.core import runtime

    if runtime._CLUSTER is not None:
        init_hook(runtime._CLUSTER)
        if name not in _INITIALIZED:
            _INITIALIZED.append(name)


def run_extension_hooks(cluster) -> None:
    """Called at cluster boot (ExtensionManager.extensionsLoaded analog).
    A failing hook is logged and recorded as attempted — it neither kills
    the boot nor leaves the runtime half-published; it re-arms only after
    shutdown() like every other hook."""
    from h2o3_tpu.utils import log

    for name, hook in _EXTENSIONS.items():
        if name not in _INITIALIZED:
            _INITIALIZED.append(name)
            try:
                hook(cluster)
            except Exception as e:   # noqa: BLE001 — extension isolation
                log.warn(f"extension {name!r} init failed: "
                         f"{type(e).__name__}: {e}")


def extensions() -> List[str]:
    return sorted(_EXTENSIONS)


def register_udf(name: str, fn: Callable, ctype: str = "real") -> None:
    """Register `(udf.<name> frame)` as a Rapids prim: fn receives one host
    numpy array per input column and returns one array (row-aligned).
    ctype: 'real' | 'enum' | 'string' for the result column."""
    from h2o3_tpu.core.frame import Column, Frame
    from h2o3_tpu.rapids.eval import PRIMS, _is_fr

    def run(env, *args):
        cols = []
        for a in args:
            if _is_fr(a):
                for c in a.columns:
                    cols.append(c.to_numpy() if not c.is_string
                                else c.host_data)
            else:
                cols.append(a)
        result = np.asarray(fn(*cols))
        out = Frame()
        # 'string' results live host-side (the ctype=None object path);
        # there is deliberately no device storage for strings
        ct = None if ctype in ("real", "string") else ctype
        out.add(name, Column.from_numpy(result, ctype=ct))
        return out

    PRIMS[f"udf.{name}"] = run


def udfs() -> List[str]:
    from h2o3_tpu.rapids.eval import PRIMS

    return sorted(p[4:] for p in PRIMS if p.startswith("udf."))
