"""TimeLine event ring + task profiling + jax.profiler wiring.

Reference: water/TimeLine.java:22 — a per-node lock-free ring of wire events
snapshotted over REST; water/MRTask.java:188-192,314-376 — opt-in `.profile()`
phase timings (setup/map/reduce/remote-block) per distributed task.

TPU-native mapping: the interesting events are no longer UDP packets but XLA
dispatches — per-task host-side phases (build/trace lookup, device run,
blocking fetch) — plus HBM gauges and the XLA profiler's own trace files.
The ring is process-wide and cheap enough to stay always-on; per-phase task
timing is opt-in via H2O_TPU_PROFILE=1 (it forces a device sync per task,
which the async dispatch pipeline must not pay by default)."""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Dict, List, Optional

_RING: collections.deque = collections.deque(maxlen=4096)
_LOCK = threading.Lock()

# the closed enumeration of event kinds h2o3_tpu/ may record: free-form
# kind drift makes the ring un-queryable (and un-documentable), so
# tests/test_consistency.py pins every record()/task() call-site literal
# to this set (mirroring the faultpoint-name guard). "rest" is emitted by
# the API layer's request ring merge, not by record().
KINDS = frozenset({
    "artifact",         # AOT artifact export/import
    "cloud",            # supervision/election/rejoin/demotion events
    "flight",           # flight-recorder dumps (obs/flight.py)
    "job",              # durable job-progress saves
    "oplog",            # control-plane checkpoints
    "pallas_auto",      # pallas-vs-XLA microbenchmark verdicts
    "phase",            # lifecycle phase begin/end (obs/phases.py)
    "profiler",         # /3/Profiler start/stop captures
    "rest",             # REST request ring (api/server.py merge)
    "scoring",          # fused serving dispatches
    "search",           # durable AutoML/grid search-state saves + resumes
    "self_benchmark",   # mesh boot probes
    "task_profile",     # opt-in per-task phase timings (H2O_TPU_PROFILE)
    "tree",             # per-tree / per-level trainer timings
    "xla_trace",        # XLA profiler captures
})

_RESERVED = ("time_ms", "kind", "what", "ms")


def record(kind: str, what: str, ms: Optional[float] = None, **meta) -> None:
    ev = {"time_ms": int(time.time() * 1000), "kind": kind, "what": what}
    if ms is not None:
        ev["ms"] = round(float(ms), 3)
    # reserved keys win: caller meta must not clobber the event's identity
    # fields (a meta dict splatted with e.g. time_ms used to silently
    # overwrite the timestamp) — colliding meta lands under a meta_ prefix
    for k, v in meta.items():
        ev[f"meta_{k}" if k in _RESERVED else k] = v
    with _LOCK:
        _RING.append(ev)


def events(n: Optional[int] = None) -> List[dict]:
    with _LOCK:
        evs = list(_RING)
    return evs[-n:] if n else evs


def clear() -> None:
    with _LOCK:
        _RING.clear()


def profiling_enabled() -> bool:
    return bool(os.environ.get("H2O_TPU_PROFILE", ""))


@contextlib.contextmanager
def task(kind: str, what: str, **meta):
    """Time a host-side phase into the ring (always-on; no device sync)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record(kind, what, ms=(time.perf_counter() - t0) * 1000, **meta)


class TaskProfile:
    """MRTask.profile() analog: per-phase wall times of one distributed task.
    Collected only under H2O_TPU_PROFILE=1 (the fetch phase forces a device
    sync)."""

    __slots__ = ("what", "build_ms", "run_ms", "sync_ms")

    def __init__(self, what: str):
        self.what = what
        self.build_ms = 0.0   # program lookup/trace (compile on cache miss)
        self.run_ms = 0.0     # dispatch
        self.sync_ms = 0.0    # block_until_ready

    def emit(self):
        record("task_profile", self.what, ms=self.build_ms + self.run_ms + self.sync_ms,
               build_ms=round(self.build_ms, 3), run_ms=round(self.run_ms, 3),
               sync_ms=round(self.sync_ms, 3))


# -- XLA profiler wiring (reference: opt-in MRTask profiling; here the real
#    hardware story is the XLA trace, viewable in xprof/tensorboard) ---------

@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA profiler trace around a code block (profiler API
    routed through compat.py — its kwargs have shifted across jax
    releases)."""
    from h2o3_tpu import compat

    compat.profiler_start(log_dir)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        compat.profiler_stop()
        record("xla_trace", log_dir, ms=(time.perf_counter() - t0) * 1000)


def annotate(name: str):
    """Named region inside a captured trace (TraceAnnotation)."""
    from h2o3_tpu import compat

    return compat.profiler_annotation(name)


def device_memory() -> List[Dict]:
    """Per-device HBM gauges (the per-node memory columns of /3/Cloud;
    water.Cleaner's MemoryManager numbers are the reference analog)."""
    import jax

    out = []
    for d in jax.local_devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:   # noqa: BLE001 — not all backends implement it
            pass
        out.append({"device": str(d),
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                    "peak_bytes_in_use": stats.get("peak_bytes_in_use")})
    return out
