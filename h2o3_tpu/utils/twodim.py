"""TwoDimTable — the tabular display/value container every reference
summary uses (water/util/TwoDimTable.java: header + typed columns + cell
grid, rendered by toString and serialized in schemas as {name, columns,
data}).

Host-side only: tables hold final small results (gains/lift, varimp,
scoring history); device arrays never pass through here."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence


class TwoDimTable:
    def __init__(self, name: str, col_names: Sequence[str],
                 col_types: Optional[Sequence[str]] = None,
                 description: str = ""):
        self.name = name
        self.description = description
        self.col_names = list(col_names)
        self.col_types = list(col_types or ["double"] * len(self.col_names))
        self.rows: List[List[Any]] = []

    def add_row(self, *cells) -> "TwoDimTable":
        if len(cells) == 1 and isinstance(cells[0], (list, tuple)):
            cells = tuple(cells[0])
        assert len(cells) == len(self.col_names), (cells, self.col_names)
        self.rows.append(list(cells))
        return self

    def col(self, name: str) -> List[Any]:
        i = self.col_names.index(name)
        return [r[i] for r in self.rows]

    def to_dict(self) -> dict:
        """The water/api/schemas3/TwoDimTableV3 wire shape (columnar)."""
        return {
            "name": self.name,
            "description": self.description,
            "columns": [{"name": n, "type": t}
                        for n, t in zip(self.col_names, self.col_types)],
            "data": [[r[i] for r in self.rows]
                     for i in range(len(self.col_names))],
        }

    def to_v3(self) -> dict:
        """The exact water/api/schemas3/TwoDimTableV3 wire shape genuine
        h2o-py parses (H2OTwoDimTable.make reads name/description/columns
        [name,type,format]/data) — to_dict extended with __meta/rowcount/
        per-column format."""
        fmt = {"int": "%d", "long": "%d", "double": "%f", "float": "%f"}
        d = self.to_dict()
        d["__meta"] = {"schema_version": 3, "schema_name": "TwoDimTableV3",
                       "schema_type": "TwoDimTable"}
        d["rowcount"] = len(self.rows)
        for c in d["columns"]:
            c["format"] = fmt.get(c["type"], "%s")
            c["description"] = c["name"]
        return d

    def as_data_frame(self):
        import pandas as pd

        return pd.DataFrame(self.rows, columns=self.col_names)

    def __repr__(self):
        head = f"{self.name}: " + ", ".join(self.col_names)
        body = "\n".join(
            "  " + " | ".join(f"{c:.5g}" if isinstance(c, float) else str(c)
                              for c in r)
            for r in self.rows[:20])
        more = f"\n  ... {len(self.rows) - 20} more rows" if len(self.rows) > 20 else ""
        return head + "\n" + body + more

    def __len__(self):
        return len(self.rows)
