"""Restricted unpickling — the ONE sanctioned deserializer for framework
bytes that crossed a process/file/KV boundary.

Reference contract: a model artifact, an oplog checkpoint, a KV blob —
anything a process did not build in its own address space — is untrusted
input (it may arrive over shared storage, an upload route, or a peer's
KV write), and one raw ``pickle.load`` is a remote-code-execution door.
The static analyzer's serialization pass bans raw loads repo-wide; the
allowed modules (``parallel/ckpt.py``, ``artifact/``) either use this
unpickler or their own equally-restricted subclass.

``find_class`` admits framework / numeric / container types only —
never arbitrary callables. The allowlist intentionally mirrors
``parallel/ckpt.py``'s checkpoint contract so every surface refuses the
same payloads.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, BinaryIO

_PREFIXES = ("h2o3_tpu.", "numpy.", "jax.", "jaxlib.", "collections.",
             "functools.", "optax.")
_MODULES = {"numpy", "jax", "jaxlib", "collections", "functools",
            "threading", "optax"}
_BUILTINS = {"set", "frozenset", "slice", "complex", "range", "bytearray",
             "object"}


class RestrictedUnpickler(pickle.Unpickler):
    """Framework/numeric types only; anything else raises
    :class:`pickle.UnpicklingError` (refuse, never fall back)."""

    what = "payload"        # subclasses override for error context

    def find_class(self, module, name):
        if module == "builtins" and name in _BUILTINS:
            return super().find_class(module, name)
        if module in _MODULES or \
                any(module.startswith(pfx) for pfx in _PREFIXES):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"{self.what} references disallowed type {module}.{name} — "
            f"refusing to unpickle (restricted loader contract)")


def restricted_loads(data: bytes, what: str = "payload") -> Any:
    up = RestrictedUnpickler(io.BytesIO(data))
    up.what = what
    return up.load()


def restricted_load(fileobj: BinaryIO, what: str = "payload") -> Any:
    up = RestrictedUnpickler(fileobj)
    up.what = what
    return up.load()
