"""Logging (water/util/Log.java parity: leveled, per-node file under ice_root)."""

from __future__ import annotations

import logging
import os
import sys

PROGRESS = True
_LOGGER = None


class _MetricsHandler(logging.Handler):
    """Warning-and-up log records become the ``h2o3_log_messages_total``
    series on /3/Metrics — an error-rate alarm needs no log scraping."""

    def emit(self, record):
        try:
            from h2o3_tpu.obs import metrics

            metrics.inc("h2o3_log_messages_total",
                        level=record.levelname.lower())
        except Exception:   # noqa: BLE001 — counting must never re-log
            pass


def get_logger() -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        lg = logging.getLogger("h2o3_tpu")
        lg.setLevel(os.environ.get("H2O_TPU_LOG_LEVEL", "INFO"))
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter("%(asctime)s %(levelname).1s h2o3_tpu: %(message)s"))
        lg.addHandler(h)
        mh = _MetricsHandler()
        mh.setLevel(logging.WARNING)
        lg.addHandler(mh)
        try:
            ice = os.environ.get("H2O_TPU_ICE_ROOT", "/tmp/h2o3_tpu")
            os.makedirs(ice, exist_ok=True)
            fh = logging.FileHandler(os.path.join(ice, "h2o3_tpu.log"))
            fh.setFormatter(logging.Formatter("%(asctime)s %(levelname).1s %(message)s"))
            lg.addHandler(fh)
        except OSError:
            pass
        lg.propagate = False
        _LOGGER = lg
    return _LOGGER


def info(msg: str) -> None:
    get_logger().info(msg)


def warn(msg: str) -> None:
    get_logger().warning(msg)


def debug(msg: str) -> None:
    get_logger().debug(msg)
