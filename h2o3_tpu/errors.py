"""Shared error types."""


class CapabilityGate(NotImplementedError):
    """A DELIBERATE capability gate (missing optional decoder/SDK), as
    opposed to an unimplemented abstract hook. The REST layer maps this —
    and only this — to HTTP 501."""
