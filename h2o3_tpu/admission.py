"""Admission control for the serving tier: queue, don't collapse.

Reference framing: the Gemma-on-TPU serving comparison (PAPERS.md) scores
serving stacks on sustained QPS under overload — the failure mode that
matters is collapse (every request slow, none finishing), and the fix is
classic admission control in front of the expensive path.

Two gating modes, combinable:

- **Static** (``H2O_TPU_SCORE_MAX_INFLIGHT``): per model key, at most N
  requests run the fused predict path concurrently — the PR-6 knob,
  unchanged.
- **SLO-adaptive** (``H2O_TPU_SCORE_SLO_MS``): instead of a hand-tuned
  static cap, the per-model inflight limit is DERIVED from the observed
  service-latency ring (the same per-request latencies the
  ``h2o3_score_request_seconds`` histogram serves on ``/3/Metrics``)
  against the target p99: AIMD — p99 over target shrinks the limit
  multiplicatively (×0.7, floor 1), p99 comfortably under target with
  demand pressure grows it additively (+1, capped at
  ``H2O_TPU_SCORE_SLO_MAX_INFLIGHT``, or at the static knob when both are
  set). On top of the bounded FIFO, a queue-TIME gate sheds requests whose
  estimated drain time (backlog × observed mean latency / parallelism)
  would already blow the SLO — saturation degrades to clean 429s with a
  drain-rate-derived Retry-After instead of a queue whose wait grows
  without bound.

The next ``H2O_TPU_SCORE_QUEUE_CAP`` requests wait in a bounded FIFO (so a
burst drains in order instead of thundering); anything beyond that is
rejected IMMEDIATELY with :class:`AdmissionRejected` (HTTP 429 +
Retry-After at the REST layer). A queued request that cannot start within
``H2O_TPU_SCORE_QUEUE_TIMEOUT_S`` is failed with 503 + Retry-After rather
than holding its socket forever.

Both knobs at 0 (the default) disable the gate — the library-mode and
single-tenant behavior is unchanged unless an operator opts the serving
tier in.
"""

from __future__ import annotations

import collections
import threading
from contextlib import contextmanager
from typing import Dict, Optional

import numpy as np

from h2o3_tpu.parallel import retry

# adapt the derived limit every this many recorded latencies (count-based,
# so tests are deterministic)
_ADAPT_EVERY = 16
# AIMD shape: breach → ×_MD (floor 1); comfortably under target under
# demand pressure → +1
_MD = 0.7
_HEADROOM = 0.6


def max_inflight() -> int:
    """Per-model concurrent fused-path requests (env
    ``H2O_TPU_SCORE_MAX_INFLIGHT``; 0 = no static cap)."""
    return max(retry.env_int("H2O_TPU_SCORE_MAX_INFLIGHT", 0), 0)


def slo_ms() -> float:
    """Target p99 service latency in milliseconds (env
    ``H2O_TPU_SCORE_SLO_MS``; 0 = SLO-adaptive admission off)."""
    import os

    try:
        return max(float(os.environ.get("H2O_TPU_SCORE_SLO_MS", "0")), 0.0)
    except ValueError:
        return 0.0


def slo_max_inflight() -> int:
    """Ceiling for the SLO-derived per-model inflight limit (env
    ``H2O_TPU_SCORE_SLO_MAX_INFLIGHT``, default 64)."""
    return max(retry.env_int("H2O_TPU_SCORE_SLO_MAX_INFLIGHT", 64), 1)


def queue_cap() -> int:
    """Bounded queue depth per model once the inflight limit is reached
    (env ``H2O_TPU_SCORE_QUEUE_CAP``, default 64)."""
    return max(retry.env_int("H2O_TPU_SCORE_QUEUE_CAP", 64), 0)


def queue_timeout_s() -> float:
    """Max seconds a queued request waits for a slot before failing with
    503 (env ``H2O_TPU_SCORE_QUEUE_TIMEOUT_S``, default 30)."""
    import os

    try:
        return max(float(os.environ.get("H2O_TPU_SCORE_QUEUE_TIMEOUT_S",
                                        "30")), 0.1)
    except ValueError:
        return 30.0


class AdmissionRejected(Exception):
    """Request refused/expired by admission control; carries the HTTP
    status (429 overflow/SLO shed / 503 queue timeout) and a Retry-After
    hint."""

    def __init__(self, msg: str, status: int = 429,
                 retry_after_s: float = 1.0):
        super().__init__(msg)
        self.status = int(status)
        self.retry_after_s = max(float(retry_after_s), 0.1)


class _ModelGate:
    __slots__ = ("cond", "inflight", "queue", "lat_ms", "limit", "notes")

    def __init__(self):
        self.cond = threading.Condition()
        self.inflight = 0
        self.queue: collections.deque = collections.deque()   # ticket FIFO
        # observed per-request service latencies (ms), the SLO signal
        self.lat_ms: collections.deque = collections.deque(maxlen=256)
        self.limit: Optional[int] = None     # SLO-derived; lazily seeded
        self.notes = 0


class AdmissionController:
    """Per-model-key gates plus aggregate counters for /3/ScoringMetrics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gates: Dict[str, _ModelGate] = {}
        self.admitted = 0
        self.queued = 0
        self.rejected = 0
        self.timed_out = 0
        self.shed_slo = 0            # 429s from the SLO queue-time gate
        self.shed_mem = 0            # 503s from device memory pressure

    def _shed_if_mem_pressure(self, model_key: str) -> None:
        """Memory-pressure gate: an exhausted OOM degradation ladder
        (h2o3_tpu/memory) flags pressure for a cooldown window; a request
        admitted during it would dispatch straight into the same
        exhausted device, so it is shed like an SLO breach — 503 +
        Retry-After sized to the cooldown remainder. Runs BEFORE the
        admission-disabled early return: pressure shedding guards the
        device even when inflight gating is off."""
        from h2o3_tpu.memory import budget as membudget

        if not membudget.pressure_active():
            return
        with self._lock:
            self.rejected += 1
            self.shed_mem += 1
        raise AdmissionRejected(
            f"model {model_key!r}: device memory pressure — the OOM "
            f"degradation ladder exhausted its retry budget; shedding "
            f"until resident frames unload", status=503,
            retry_after_s=membudget.pressure_retry_after_s())

    def _gate(self, key: str) -> _ModelGate:
        with self._lock:
            g = self._gates.get(key)
            if g is None:
                g = self._gates[key] = _ModelGate()
            return g

    # -- SLO-adaptive limit ------------------------------------------------
    def _limit(self, g: _ModelGate) -> int:
        """Effective inflight limit for one gate RIGHT NOW: the static
        knob when SLO mode is off; otherwise the AIMD-derived limit,
        seeded from the static knob (or a conservative 8) and capped at
        the SLO ceiling (and at the static knob when both are set).
        Callers hold g.cond."""
        static = max_inflight()
        if slo_ms() <= 0:
            return static
        if g.limit is None:
            g.limit = static if static > 0 else min(8, slo_max_inflight())
        cap = min(static, slo_max_inflight()) if static > 0 \
            else slo_max_inflight()
        return max(1, min(g.limit, cap))

    def note_latency(self, model_key: str, ms: float) -> None:
        """Record one served request's service latency (queue wait
        excluded) and — every ``_ADAPT_EVERY`` samples in SLO mode —
        re-derive the gate's inflight limit from the ring's p99 against
        the target."""
        g = self._gate(str(model_key))
        with g.cond:
            g.lat_ms.append(float(ms))
            g.notes += 1
            target = slo_ms()
            if target <= 0 or g.notes % _ADAPT_EVERY:
                return
            cur = self._limit(g)
            lat = np.asarray(g.lat_ms, np.float64)
            p99 = float(np.percentile(lat, 99))
            if p99 > target:
                g.limit = max(1, int(cur * _MD))
            elif p99 < target * _HEADROOM and \
                    (g.queue or g.inflight >= cur):
                # additive increase only under demand pressure — an idle
                # model must not drift to the ceiling on easy traffic
                g.limit = min(cur + 1, slo_max_inflight())
            if g.limit != cur:
                g.cond.notify_all()

    def _mean_ms(self, g: _ModelGate) -> float:
        """Observed mean service latency (callers hold g.cond); 0.0 when
        the ring is empty."""
        return float(sum(g.lat_ms) / len(g.lat_ms)) if g.lat_ms else 0.0

    def _retry_after(self, g: _ModelGate, limit: int) -> float:
        """Retry-After derived from the observed per-model drain rate:
        the backlog ahead of a retrying client drains at roughly
        limit / mean_latency requests per second, so the hint is
        backlog × mean / limit — proportional to real saturation, not a
        constant. Falls back to the batch-window heuristic before any
        latency has been observed. Floored at 1s, capped at 120s; never a
        promise."""
        backlog = len(g.queue) + max(g.inflight, 1)
        mean = self._mean_ms(g)
        if mean > 0:
            return min(max(1.0, backlog * (mean / 1000.0)
                           / max(limit, 1)), 120.0)
        from h2o3_tpu.scoring import _window_s

        return max(1.0, backlog * max(_window_s(), 0.002))

    def _est_wait_s(self, g: _ModelGate, limit: int) -> float:
        """Estimated queue drain time for a request joining now (callers
        hold g.cond): backlog ahead × observed mean service latency /
        parallelism. 0.0 before any latency sample exists (never shed
        blind)."""
        mean = self._mean_ms(g)
        if mean <= 0:
            return 0.0
        return (len(g.queue) + 1) * (mean / 1000.0) / max(limit, 1)

    def _maybe_shed(self, model_key: str, g: _ModelGate,
                    limit: int) -> None:
        """Shared 429 logic for slot() and check(): callers hold g.cond
        and have established inflight >= limit. Raises AdmissionRejected
        when a request arriving now must be shed (SLO queue-time gate or
        queue overflow); returns when it may queue."""
        target = slo_ms()
        est = self._est_wait_s(g, limit)
        if target > 0 and est * 1000.0 > target:
            # SLO queue-time gate: this request would already be out of
            # SLO before it reached a device — shed it NOW with a
            # drain-derived backoff instead of queueing it into certain
            # failure (queue collapse)
            with self._lock:
                self.rejected += 1
                self.shed_slo += 1
            raise AdmissionRejected(
                f"model {model_key!r}: estimated queue drain "
                f"{est * 1000.0:.0f}ms exceeds the "
                f"{target:.0f}ms latency SLO "
                f"({g.inflight} in flight, {len(g.queue)} queued, "
                f"limit {limit}) — retry later",
                status=429,
                retry_after_s=self._retry_after(g, limit))
        if len(g.queue) >= queue_cap():
            with self._lock:
                self.rejected += 1
            raise AdmissionRejected(
                f"model {model_key!r}: {g.inflight} requests in "
                f"flight and {len(g.queue)} queued (caps "
                f"{limit}/{queue_cap()}) — retry later",
                status=429,
                retry_after_s=self._retry_after(g, limit))

    def check(self, model_key: str) -> None:
        """Non-consuming admission probe: raise AdmissionRejected when a
        request arriving NOW would be shed. Async handlers (the /4 route)
        call this BEFORE detaching work into a background job so
        saturation surfaces as a synchronous 429 + Retry-After instead of
        a failed job with no backoff hint. No slot is reserved — the
        job's own slot() may still queue (or, on a race, shed) later."""
        self._shed_if_mem_pressure(str(model_key))
        if max_inflight() <= 0 and slo_ms() <= 0:
            return
        g = self._gate(str(model_key))
        with g.cond:
            limit = self._limit(g)
            if g.inflight >= limit:
                self._maybe_shed(str(model_key), g, limit)

    @contextmanager
    def slot(self, model_key: str):
        self._shed_if_mem_pressure(str(model_key))
        if max_inflight() <= 0 and slo_ms() <= 0:
            yield                      # admission disabled: zero overhead
            return
        g = self._gate(str(model_key))
        ticket = object()
        with g.cond:
            limit = self._limit(g)
            if g.inflight >= limit:
                self._maybe_shed(str(model_key), g, limit)
                g.queue.append(ticket)
                with self._lock:
                    self.queued += 1
                deadline = queue_timeout_s()
                import time as _t

                from h2o3_tpu.obs import tracing

                t0 = _t.monotonic()
                # the admission queue wait lands in the request's span
                # tree (distinct from the micro-batcher's queue_wait —
                # this one is the overload gate, that one the coalescing
                # window); inert without an active trace
                with tracing.span("admission_wait", model=str(model_key)):
                    # FIFO: only the queue head may take a freed slot.
                    # The limit is re-read every wakeup — the SLO
                    # controller moves it while requests wait.
                    while True:
                        limit = self._limit(g)
                        if g.inflight < limit and g.queue \
                                and g.queue[0] is ticket:
                            break
                        left = deadline - (_t.monotonic() - t0)
                        if left <= 0:
                            g.queue.remove(ticket)
                            g.cond.notify_all()
                            with self._lock:
                                self.timed_out += 1
                            raise AdmissionRejected(
                                f"model {model_key!r}: queued request "
                                f"expired after {deadline:.0f}s without a "
                                f"free slot", status=503,
                                retry_after_s=self._retry_after(g, limit))
                        g.cond.wait(timeout=left)
                    g.queue.popleft()
            g.inflight += 1
            with self._lock:
                self.admitted += 1
        try:
            yield
        finally:
            with g.cond:
                g.inflight -= 1
                g.cond.notify_all()

    def snapshot(self) -> dict:
        with self._lock:
            out = {"admitted": self.admitted, "queued": self.queued,
                   "rejected": self.rejected, "timed_out": self.timed_out,
                   "shed_slo": self.shed_slo,
                   "shed_mem": self.shed_mem,
                   "max_inflight": max_inflight(),
                   "slo_ms": slo_ms(),
                   "slo_max_inflight": slo_max_inflight(),
                   "queue_cap": queue_cap()}
            gates = list(self._gates.items())
        models = {}
        for k, g in gates:
            if not (g.inflight or g.queue or g.lat_ms):
                continue
            with g.cond:
                ent = {"inflight": g.inflight,
                       "queue_depth": len(g.queue),
                       "limit": self._limit(g)}
                if g.lat_ms:
                    lat = np.asarray(g.lat_ms, np.float64)
                    ent["mean_ms"] = round(float(lat.mean()), 3)
                    ent["p99_ms"] = round(float(np.percentile(lat, 99)), 3)
            models[k] = ent
        out["models"] = models
        return out

    def derived_limits(self) -> Dict[str, int]:
        """Per-model effective inflight limits (the h2o3_admission_limit
        gauge's collector)."""
        with self._lock:
            gates = list(self._gates.items())
        out = {}
        for k, g in gates:
            with g.cond:
                out[k] = self._limit(g)
        return out

    def reset(self) -> None:
        """Drop counters + idle gates (tests)."""
        with self._lock:
            self.admitted = self.queued = self.rejected = self.timed_out = 0
            self.shed_slo = 0
            self.shed_mem = 0
            self._gates = {k: g for k, g in self._gates.items()
                           if g.inflight or g.queue}


CONTROLLER = AdmissionController()
