"""Admission control for the serving tier: queue, don't collapse.

Reference framing: the Gemma-on-TPU serving comparison (PAPERS.md) scores
serving stacks on sustained QPS under overload — the failure mode that
matters is collapse (every request slow, none finishing), and the fix is
classic admission control in front of the expensive path.

Per model key, at most ``H2O_TPU_SCORE_MAX_INFLIGHT`` requests run the
fused predict path concurrently; the next ``H2O_TPU_SCORE_QUEUE_CAP``
wait in a bounded FIFO (so a burst drains in order instead of thundering);
anything beyond that is rejected IMMEDIATELY with
:class:`AdmissionRejected` (HTTP 429 + Retry-After at the REST layer). A
queued request that cannot start within ``H2O_TPU_SCORE_QUEUE_TIMEOUT_S``
is failed with 503 + Retry-After rather than holding its socket forever.

``H2O_TPU_SCORE_MAX_INFLIGHT=0`` (the default) disables the gate — the
library-mode and single-tenant behavior is unchanged unless an operator
opts the serving tier in.
"""

from __future__ import annotations

import collections
import threading
from contextlib import contextmanager
from typing import Dict

from h2o3_tpu.parallel import retry


def max_inflight() -> int:
    """Per-model concurrent fused-path requests (env
    ``H2O_TPU_SCORE_MAX_INFLIGHT``; 0 = unlimited, admission off)."""
    return max(retry.env_int("H2O_TPU_SCORE_MAX_INFLIGHT", 0), 0)


def queue_cap() -> int:
    """Bounded queue depth per model once the inflight limit is reached
    (env ``H2O_TPU_SCORE_QUEUE_CAP``, default 64)."""
    return max(retry.env_int("H2O_TPU_SCORE_QUEUE_CAP", 64), 0)


def queue_timeout_s() -> float:
    """Max seconds a queued request waits for a slot before failing with
    503 (env ``H2O_TPU_SCORE_QUEUE_TIMEOUT_S``, default 30)."""
    import os

    try:
        return max(float(os.environ.get("H2O_TPU_SCORE_QUEUE_TIMEOUT_S",
                                        "30")), 0.1)
    except ValueError:
        return 30.0


class AdmissionRejected(Exception):
    """Request refused/expired by admission control; carries the HTTP
    status (429 overflow / 503 queue timeout) and a Retry-After hint."""

    def __init__(self, msg: str, status: int = 429,
                 retry_after_s: float = 1.0):
        super().__init__(msg)
        self.status = int(status)
        self.retry_after_s = max(float(retry_after_s), 0.1)


class _ModelGate:
    __slots__ = ("cond", "inflight", "queue")

    def __init__(self):
        self.cond = threading.Condition()
        self.inflight = 0
        self.queue: collections.deque = collections.deque()   # ticket FIFO


class AdmissionController:
    """Per-model-key gates plus aggregate counters for /3/ScoringMetrics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gates: Dict[str, _ModelGate] = {}
        self.admitted = 0
        self.queued = 0
        self.rejected = 0
        self.timed_out = 0

    def _gate(self, key: str) -> _ModelGate:
        with self._lock:
            g = self._gates.get(key)
            if g is None:
                g = self._gates[key] = _ModelGate()
            return g

    def _retry_after(self, g: _ModelGate, limit: int) -> float:
        """Retry-After heuristic: one batch window per queued request ahead,
        floored at 1s — cheap, monotone in backlog, never a promise."""
        from h2o3_tpu.scoring import _window_s

        backlog = len(g.queue) + max(g.inflight - limit + 1, 1)
        return max(1.0, backlog * max(_window_s(), 0.002))

    @contextmanager
    def slot(self, model_key: str):
        limit = max_inflight()
        if limit <= 0:
            yield                      # admission disabled: zero overhead
            return
        g = self._gate(str(model_key))
        ticket = object()
        with g.cond:
            if g.inflight >= limit:
                if len(g.queue) >= queue_cap():
                    with self._lock:
                        self.rejected += 1
                    raise AdmissionRejected(
                        f"model {model_key!r}: {g.inflight} requests in "
                        f"flight and {len(g.queue)} queued (caps "
                        f"{limit}/{queue_cap()}) — retry later",
                        status=429,
                        retry_after_s=self._retry_after(g, limit))
                g.queue.append(ticket)
                with self._lock:
                    self.queued += 1
                deadline = queue_timeout_s()
                import time as _t

                from h2o3_tpu.obs import tracing

                t0 = _t.monotonic()
                # the admission queue wait lands in the request's span
                # tree (distinct from the micro-batcher's queue_wait —
                # this one is the overload gate, that one the coalescing
                # window); inert without an active trace
                with tracing.span("admission_wait", model=str(model_key)):
                    # FIFO: only the queue head may take a freed slot
                    while not (g.inflight < limit and g.queue
                               and g.queue[0] is ticket):
                        left = deadline - (_t.monotonic() - t0)
                        if left <= 0:
                            g.queue.remove(ticket)
                            g.cond.notify_all()
                            with self._lock:
                                self.timed_out += 1
                            raise AdmissionRejected(
                                f"model {model_key!r}: queued request "
                                f"expired after {deadline:.0f}s without a "
                                f"free slot", status=503,
                                retry_after_s=self._retry_after(g, limit))
                        g.cond.wait(timeout=left)
                    g.queue.popleft()
            g.inflight += 1
            with self._lock:
                self.admitted += 1
        try:
            yield
        finally:
            with g.cond:
                g.inflight -= 1
                g.cond.notify_all()

    def snapshot(self) -> dict:
        with self._lock:
            out = {"admitted": self.admitted, "queued": self.queued,
                   "rejected": self.rejected, "timed_out": self.timed_out,
                   "max_inflight": max_inflight(),
                   "queue_cap": queue_cap()}
            gates = list(self._gates.items())
        out["models"] = {k: {"inflight": g.inflight,
                             "queue_depth": len(g.queue)}
                         for k, g in gates
                         if g.inflight or g.queue}
        return out

    def reset(self) -> None:
        """Drop counters + idle gates (tests)."""
        with self._lock:
            self.admitted = self.queued = self.rejected = self.timed_out = 0
            self._gates = {k: g for k, g in self._gates.items()
                           if g.inflight or g.queue}


CONTROLLER = AdmissionController()
