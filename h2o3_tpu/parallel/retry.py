"""Bounded retry with exponential backoff + jitter, and adaptive polling.

Reference: water/RPC.java retries every remote task on a doubling backoff
schedule (RPC.java `_retry`: resend with exponentially growing delay until
the target answers or is declared dead). The control-plane calls here —
coordination-service KV puts/gets, oplog publishes, follower polls — get
the same treatment: transient coordination hiccups are absorbed by a small
bounded retry budget, and genuine failures surface quickly instead of
either hanging or failing on the first blip.

Env knobs (documented in README "Robustness & fault tolerance"):
- ``H2O_TPU_RETRY_MAX``      attempts per call (default 3)
- ``H2O_TPU_RETRY_BASE_MS``  first backoff delay (default 10 ms)
- ``H2O_TPU_RETRY_MAX_MS``   backoff cap (default 2000 ms)
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type


def env_float(name: str, default: float) -> float:
    """Float env knob with fallback (shared by every supervision tunable:
    retry budget, ack/turn timeouts, heartbeat staleness, poll interval)."""
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    """Integer env knob with fallback (checkpoint interval, caps)."""
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def max_attempts() -> int:
    try:
        n = int(os.environ.get("H2O_TPU_RETRY_MAX", "") or 3)
    except ValueError:
        n = 3
    return max(1, n)


def base_delay_s() -> float:
    return max(env_float("H2O_TPU_RETRY_BASE_MS", 10.0), 0.0) / 1000.0


def max_delay_s() -> float:
    return max(env_float("H2O_TPU_RETRY_MAX_MS", 2000.0), 1.0) / 1000.0


def backoff_delays(attempts: Optional[int] = None,
                   base_s: Optional[float] = None,
                   max_s: Optional[float] = None,
                   jitter: float = 0.5,
                   rng=None) -> Iterator[float]:
    """Yield the ``attempts - 1`` sleep durations between attempts:
    ``base * 2^i`` capped at ``max_s``, each multiplied by a uniform
    ``1 ± jitter`` factor so a fleet of processes retrying the same dead
    peer doesn't stampede in lockstep."""
    attempts = max_attempts() if attempts is None else attempts
    base = base_delay_s() if base_s is None else base_s
    cap = max_delay_s() if max_s is None else max_s
    rnd = rng or random
    for i in range(max(attempts - 1, 0)):
        d = min(base * (2.0 ** i), cap)
        if jitter > 0:
            d *= 1.0 + jitter * (2.0 * rnd.random() - 1.0)
        yield max(d, 0.0)


def retry_call(fn: Callable, *args,
               retries: Optional[int] = None,
               base_s: Optional[float] = None,
               max_s: Optional[float] = None,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               describe: str = "",
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call ``fn(*args, **kwargs)`` with bounded exponential-backoff-plus-
    jitter retries on ``retry_on`` exceptions; the final attempt's exception
    propagates unwrapped (callers keep their existing except clauses)."""
    attempts = max_attempts() if retries is None else max(1, retries)
    delays = backoff_delays(attempts, base_s, max_s)
    for attempt in range(1, attempts + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if attempt >= attempts:
                raise
            if on_retry is not None:
                try:
                    on_retry(attempt, e)
                except Exception:   # noqa: BLE001 — observer must not kill
                    pass            # the retry loop it observes
            from h2o3_tpu.utils.log import get_logger

            get_logger().warning("retrying %s (attempt %d/%d): %s",
                                 describe or getattr(fn, "__name__", "call"),
                                 attempt, attempts, e)
            sleep(next(delays))


class AdaptivePoll:
    """Adaptive busy-wait: starts hot (1 ms — a follower mid-replay-stream
    sees the next op almost instantly) and decays exponentially to a cold
    cap (250 ms — an idle follower costs ~4 KV reads/s instead of 20).
    ``reset()`` on activity snaps back to the hot end."""

    def __init__(self, min_s: float = 0.001, max_s: float = 0.25,
                 factor: float = 2.0,
                 sleep: Callable[[float], None] = time.sleep):
        self.min_s = float(min_s)
        self.max_s = float(max_s)
        self.factor = float(factor)
        self._sleep = sleep
        self._cur = self.min_s

    @property
    def current_s(self) -> float:
        return self._cur

    def wait(self) -> None:
        self._sleep(self._cur)
        self._cur = min(self._cur * self.factor, self.max_s)

    def reset(self) -> None:
        self._cur = self.min_s
