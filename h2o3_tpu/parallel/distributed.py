"""Multi-host runtime bootstrap.

Reference: cloud formation by UDP heartbeat gossip + Paxos-lite voting
(water/Paxos.java:27, water/HeartBeatThread.java:16) with flatfile or
multicast discovery (water/init/NetworkInit.java).

TPU-native: `jax.distributed.initialize(coordinator, n, id)` — the JAX
coordination service plays the Paxos/heartbeat role (barrier at startup,
health checks, failure propagation), and the resulting global device list
forms the mesh. Membership is static for the job's lifetime, which is
exactly H2O's post-lock semantics (water/Paxos.java:144): H2O never
supported elastic join after the first job either (SURVEY.md §5.3)."""

from __future__ import annotations

import contextlib
import os
from typing import Dict, Optional


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host cloud. No-op when single-process (local mode)."""
    import jax

    coordinator_address = coordinator_address or os.environ.get("H2O_TPU_COORDINATOR")
    if coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes or os.environ.get("H2O_TPU_NUM_PROCESSES", 1)),
        process_id=int(process_id or os.environ.get("H2O_TPU_PROCESS_ID", 0)),
    )


# ---------------------------------------------------------------------------
# leadership (water/Paxos.java leader = lowest H2ONode; here the epoch
# record in the cloud KV names the leader, and a standby-coordinator
# election can move it — see oplog.assume_coordination)
# ---------------------------------------------------------------------------

# process-local view of who leads: proc index, and the epoch it was
# learned under. Epoch 0 / leader 0 is the boot default (jax process 0
# hosts the coordination service, so it is the natural first leader).
_LEADER = 0
_EPOCH = 0

_EPOCH_KEY = "oplog/epoch"


def leader() -> int:
    return _LEADER


def epoch() -> int:
    return _EPOCH


def set_leader(proc: int, epoch_no: int) -> None:
    """Adopt a leadership view (election win, or demotion on discovering a
    newer epoch record)."""
    global _LEADER, _EPOCH
    _LEADER = int(proc)
    _EPOCH = int(epoch_no)


def reset_leadership() -> None:
    """Back to the boot default (tests / cloud restart)."""
    set_leader(0, 0)


def epoch_record() -> dict:
    """The cloud-wide epoch record ({epoch, leader, ts}); the boot default
    when none was ever written."""
    import json as _json

    raw = kv_try_get(_EPOCH_KEY)
    if raw is None:
        return {"epoch": 0, "leader": 0, "ts": 0.0}
    try:
        rec = _json.loads(raw)
        return {"epoch": int(rec.get("epoch", 0)),
                "leader": int(rec.get("leader", 0)),
                "ts": float(rec.get("ts", 0.0))}
    except (ValueError, TypeError):
        return {"epoch": 0, "leader": 0, "ts": 0.0}


def write_epoch_record(epoch_no: int, leader_proc: int) -> bool:
    import json as _json
    import time as _time

    return kv_put(_EPOCH_KEY, _json.dumps({"epoch": int(epoch_no),
                                           "leader": int(leader_proc),
                                           "ts": _time.time()}))


def is_coordinator() -> bool:
    import jax

    return jax.process_index() == _LEADER


def process_count() -> int:
    import jax

    return jax.process_count()


def rejoin():
    """Readmit THIS (restarted) process to the cloud: fresh incarnation,
    state restored from the latest oplog checkpoint, acknowledged suffix
    replayed, heartbeat re-registered. Returns the oplog sequence this
    process is caught up to (the follower_loop resume cursor).

    The thin public entry; the protocol lives in ``oplog.rejoin`` (it owns
    the replay/ack machinery)."""
    from h2o3_tpu.parallel import oplog

    return oplog.rejoin()


# ---------------------------------------------------------------------------
# cloud-wide key/value channel (water/DKV.java's control plane)
#
# The JAX coordination service ships a distributed KV store (the same one
# jax uses for topology exchange at init). It is exactly the "host-side
# object store + RPC" SURVEY §7 maps the reference DKV onto: small control-
# plane values, replicated through the coordinator, visible to every
# process. Device DATA never travels here — columns are already globally
# sharded jax.Arrays; this channel carries metadata and small host objects.
# ---------------------------------------------------------------------------

# in-memory KV override: a plain dict standing in for the coordination
# service when no real cloud exists. The supervision/chaos test tier uses
# this to drive the full oplog/heartbeat/supervisor machinery — follower
# replay, acks, error keys, health folding — deterministically inside ONE
# process, with faultpoint() injections supplying the failures a real dead
# peer would (the 2-process gloo tier is env-flaky on this jax build).
_MEM_KV: Optional[Dict[str, str]] = None


@contextlib.contextmanager
def memory_kv(initial: Optional[Dict[str, str]] = None):
    """Install (and on exit remove) a dict-backed cloud KV."""
    global _MEM_KV
    prev = _MEM_KV
    _MEM_KV = dict(initial or {})
    try:
        yield _MEM_KV
    finally:
        _MEM_KV = prev


def _kv_client():
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:   # noqa: BLE001 — not initialized / API moved
        return None


class KVWriteError(RuntimeError):
    """A cloud-KV write that neither landed nor was superseded by a
    concurrent writer (retryable: transient coordination failure)."""


class KVTransientError(RuntimeError):
    """A cloud-KV read that failed at the transport layer (UNAVAILABLE /
    connection reset — retryable), as opposed to an absent key's deadline
    expiry (not retryable: it already waited its timeout)."""


def _transient(e: BaseException) -> bool:
    """Transport-level failures worth retrying, as opposed to an absent
    key's deadline expiry (gRPC status text is all the client exposes)."""
    s = str(e).upper()
    return any(t in s for t in ("UNAVAILABLE", "CONNECTION", "RESET",
                                "INTERNAL", "BROKEN PIPE"))


def kv_put(key: str, value: str) -> bool:
    """Publish a small value cloud-wide; False when not in a multi-process
    cloud (callers treat local mode as a no-op). Upsert semantics like
    DKV.put — re-publishing a key overwrites. Transient coordination
    failures are absorbed by a bounded backoff-with-jitter retry budget
    (water/RPC.java's resend schedule); False after exhaustion."""
    if _MEM_KV is not None:
        _MEM_KV[key] = value
        return True
    c = _kv_client()
    if c is None:
        return False

    def _attempt():
        try:
            c.key_value_set(key, value, allow_overwrite=True)
            return
        except TypeError:      # older client without the kwarg
            pass
        try:
            c.key_value_set(key, value)
        except Exception:  # noqa: BLE001 — ALREADY_EXISTS: delete + retry
            kv_delete(key)
            try:
                c.key_value_set(key, value)
            except Exception:   # noqa: BLE001
                # a CONCURRENT writer winning leaves a value in place —
                # success; a missing value means a real write failure
                if kv_try_get(key) is None:
                    raise KVWriteError(f"kv_put({key!r}) did not land")

    from h2o3_tpu.parallel import retry

    try:
        retry.retry_call(_attempt, describe=f"kv_put {key}")
        return True
    except Exception:   # noqa: BLE001 — budget exhausted
        return False


def kv_get(key: str, timeout_ms: int = 5000) -> Optional[str]:
    """Blocking get with a server-side deadline. An absent key times out
    (None); transient transport failures retry with backoff, a plain
    deadline expiry does NOT (it already waited timeout_ms)."""
    if _MEM_KV is not None:
        return _MEM_KV.get(key)
    c = _kv_client()
    if c is None:
        return None
    from h2o3_tpu.parallel import retry

    def _get():
        try:
            return c.blocking_key_value_get(key, timeout_ms)
        except Exception as e:   # noqa: BLE001 — absent key times out
            if _transient(e):
                raise KVTransientError(str(e)) from e
            return None

    try:
        return retry.retry_call(_get, retry_on=(KVTransientError,),
                                describe=f"kv_get {key}")
    except KVTransientError:
        return None


def kv_try_get(key: str) -> Optional[str]:
    if _MEM_KV is not None:
        return _MEM_KV.get(key)
    c = _kv_client()
    if c is None:
        return None
    try:
        return c.key_value_try_get(key)
    except Exception:   # noqa: BLE001 — absent
        return None


def kv_dir(prefix: str):
    """List (key, value) pairs under a prefix (key_value_dir_get)."""
    if _MEM_KV is not None:
        return [(k, v) for k, v in list(_MEM_KV.items())
                if k.startswith(prefix)]
    c = _kv_client()
    if c is None:
        return []
    try:
        return list(c.key_value_dir_get(prefix))
    except Exception:   # noqa: BLE001
        return []


def kv_delete(key: str) -> None:
    if _MEM_KV is not None:
        _MEM_KV.pop(key, None)
        return
    c = _kv_client()
    if c is not None:
        try:
            c.key_value_delete(key)
        except Exception:   # noqa: BLE001
            pass
