"""Multi-host runtime bootstrap.

Reference: cloud formation by UDP heartbeat gossip + Paxos-lite voting
(water/Paxos.java:27, water/HeartBeatThread.java:16) with flatfile or
multicast discovery (water/init/NetworkInit.java).

TPU-native: `jax.distributed.initialize(coordinator, n, id)` — the JAX
coordination service plays the Paxos/heartbeat role (barrier at startup,
health checks, failure propagation), and the resulting global device list
forms the mesh. Membership is static for the job's lifetime, which is
exactly H2O's post-lock semantics (water/Paxos.java:144): H2O never
supported elastic join after the first job either (SURVEY.md §5.3)."""

from __future__ import annotations

import os
from typing import Optional


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host cloud. No-op when single-process (local mode)."""
    import jax

    coordinator_address = coordinator_address or os.environ.get("H2O_TPU_COORDINATOR")
    if coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes or os.environ.get("H2O_TPU_NUM_PROCESSES", 1)),
        process_id=int(process_id or os.environ.get("H2O_TPU_PROCESS_ID", 0)),
    )


def is_coordinator() -> bool:
    import jax

    return jax.process_index() == 0


def process_count() -> int:
    import jax

    return jax.process_count()


# ---------------------------------------------------------------------------
# cloud-wide key/value channel (water/DKV.java's control plane)
#
# The JAX coordination service ships a distributed KV store (the same one
# jax uses for topology exchange at init). It is exactly the "host-side
# object store + RPC" SURVEY §7 maps the reference DKV onto: small control-
# plane values, replicated through the coordinator, visible to every
# process. Device DATA never travels here — columns are already globally
# sharded jax.Arrays; this channel carries metadata and small host objects.
# ---------------------------------------------------------------------------

def _kv_client():
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:   # noqa: BLE001 — not initialized / API moved
        return None


def kv_put(key: str, value: str) -> bool:
    """Publish a small value cloud-wide; False when not in a multi-process
    cloud (callers treat local mode as a no-op). Upsert semantics like
    DKV.put — re-publishing a key overwrites."""
    c = _kv_client()
    if c is None:
        return False
    try:
        c.key_value_set(key, value, allow_overwrite=True)
    except TypeError:      # older client without the kwarg
        try:
            c.key_value_set(key, value)
        except Exception:  # noqa: BLE001 — ALREADY_EXISTS: delete + retry
            kv_delete(key)
            try:
                c.key_value_set(key, value)
            except Exception:   # noqa: BLE001
                # a CONCURRENT writer winning leaves a value in place —
                # success; a missing value means a real write failure
                return kv_try_get(key) is not None
    return True


def kv_get(key: str, timeout_ms: int = 5000) -> Optional[str]:
    c = _kv_client()
    if c is None:
        return None
    try:
        return c.blocking_key_value_get(key, timeout_ms)
    except Exception:   # noqa: BLE001 — absent key times out
        return None


def kv_try_get(key: str) -> Optional[str]:
    c = _kv_client()
    if c is None:
        return None
    try:
        return c.key_value_try_get(key)
    except Exception:   # noqa: BLE001 — absent
        return None


def kv_dir(prefix: str):
    """List (key, value) pairs under a prefix (key_value_dir_get)."""
    c = _kv_client()
    if c is None:
        return []
    try:
        return list(c.key_value_dir_get(prefix))
    except Exception:   # noqa: BLE001
        return []


def kv_delete(key: str) -> None:
    c = _kv_client()
    if c is not None:
        try:
            c.key_value_delete(key)
        except Exception:   # noqa: BLE001
            pass
