"""Multi-host runtime bootstrap.

Reference: cloud formation by UDP heartbeat gossip + Paxos-lite voting
(water/Paxos.java:27, water/HeartBeatThread.java:16) with flatfile or
multicast discovery (water/init/NetworkInit.java).

TPU-native: `jax.distributed.initialize(coordinator, n, id)` — the JAX
coordination service plays the Paxos/heartbeat role (barrier at startup,
health checks, failure propagation), and the resulting global device list
forms the mesh. Membership is static for the job's lifetime, which is
exactly H2O's post-lock semantics (water/Paxos.java:144): H2O never
supported elastic join after the first job either (SURVEY.md §5.3)."""

from __future__ import annotations

import os
from typing import Optional


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host cloud. No-op when single-process (local mode)."""
    import jax

    coordinator_address = coordinator_address or os.environ.get("H2O_TPU_COORDINATOR")
    if coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes or os.environ.get("H2O_TPU_NUM_PROCESSES", 1)),
        process_id=int(process_id or os.environ.get("H2O_TPU_PROCESS_ID", 0)),
    )


def is_coordinator() -> bool:
    import jax

    return jax.process_index() == 0


def process_count() -> int:
    import jax

    return jax.process_count()
