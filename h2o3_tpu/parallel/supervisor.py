"""Cloud supervision: fold liveness + oplog errors into one health state.

Reference: water/HeartBeatThread.java — a node that misses enough beats is
declared dead and the cloud reacts (jobs against it fail, new work is
refused) instead of hanging. Podracer-style TPU fleets (arXiv:2104.06272)
need the same property layered over the collective runtime: a dead peer
otherwise manifests only as an indefinite hang inside the next collective.

This module is that layer for the REST-driven cloud:

- a **state machine** HEALTHY → DEGRADED → FAILED → RECOVERING. Stale
  heartbeats degrade the cloud (and it recovers when beats resume); a
  follower replay crash (an ``oplog/error/{seq}`` key) fails it — the
  per-process program counters have diverged. FAILED is no longer
  terminal: a restarted follower that readmits (``oplog.rejoin``:
  checkpoint restore + suffix re-replay under a fresh incarnation) moves
  the cloud FAILED → RECOVERING, and when every rejoined incarnation is
  caught up with fresh beats and no error evidence remains, RECOVERING →
  HEALTHY — new multi-process ops are accepted again. Jobs failed while
  the cloud was down STAY failed (clients resubmit); only FAILED →
  HEALTHY without passing through RECOVERING is forbidden.
- a **supervisor thread** on the coordinator that re-evaluates the state
  every ``H2O_TPU_SUPERVISE_INTERVAL_S`` (default 2 s) and, on failure,
  marks every in-flight Job FAILED with the follower's traceback (their
  worker threads may be wedged inside a dead collective and never unwind).
- **degraded-mode fail-fast**: `ensure_operable()` — called by
  ``oplog.broadcast`` — refuses new multi-process ops immediately with a
  clear :class:`~h2o3_tpu.core.failure.CloudUnhealthyError`. Coordinator-
  local (single-process) scoring keeps serving.

Surfaced via ``GET /3/Cloud`` (``cloud_status`` field) and the dedicated
``GET /3/CloudStatus`` route.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from h2o3_tpu.parallel import retry

HEALTHY, DEGRADED, FAILED = "HEALTHY", "DEGRADED", "FAILED"
RECOVERING = "RECOVERING"

# re-entrant: evaluate() must hold it across its hold_until check AND the
# recover() transition, or a degrade(hold_s=...) landing between the two is
# instantly erased together with its hold
_LOCK = threading.RLock()
_STATE: Dict = {"state": HEALTHY, "since": time.time(), "reason": "",
                "remote_trace": "", "hold_until": 0.0,
                "incs_at_failure": {}}
_TRANSITIONS: List[dict] = []          # bounded history for /3/CloudStatus
_TRANSITIONS_MAX = 64
# first evaluate() timestamp: the grace window for processes that have
# NEVER heartbeat (a follower that died at startup has no stale row to
# trip on — absence past the staleness window is itself the signal)
_FIRST_EVAL_TS: Optional[float] = None


def interval_s() -> float:
    return retry.env_float("H2O_TPU_SUPERVISE_INTERVAL_S", 2.0)


def state() -> str:
    with _LOCK:
        return _STATE["state"]


def status() -> Dict:
    """Snapshot for the REST surface: current state + why + history."""
    with _LOCK:
        out = dict(_STATE)
        out["transitions"] = list(_TRANSITIONS)
    return out


def reset() -> None:
    """Back to HEALTHY with a clean history (cloud restart / tests)."""
    global _FIRST_EVAL_TS
    with _LOCK:
        _STATE.update(state=HEALTHY, since=time.time(), reason="",
                      remote_trace="", hold_until=0.0, incs_at_failure={})
        _TRANSITIONS.clear()
        _FIRST_EVAL_TS = None


def _transition(new: str, reason: str, remote_trace: str = "") -> bool:
    """Move to `new` if legal; returns True when the state changed.
    FAILED is sticky EXCEPT toward RECOVERING: replay divergence is only
    healed by a follower readmission (checkpoint restore + suffix
    re-replay under a fresh incarnation) or a cloud restart — never by
    fresh heartbeats alone."""
    with _LOCK:
        cur = _STATE["state"]
        if cur == new or (cur == FAILED and new != RECOVERING):
            return False
        _STATE.update(state=new, since=time.time(), reason=reason,
                      remote_trace=remote_trace)
        _TRANSITIONS.append({"ts": _STATE["since"], "from": cur, "to": new,
                             "reason": reason})
        if len(_TRANSITIONS) > _TRANSITIONS_MAX:
            del _TRANSITIONS[: len(_TRANSITIONS) - _TRANSITIONS_MAX]
    from h2o3_tpu.obs import metrics as obs_metrics
    from h2o3_tpu.utils import timeline
    from h2o3_tpu.utils.log import get_logger

    log = get_logger()
    (log.error if new == FAILED else log.warning)(
        "cloud %s -> %s: %s", cur, new, reason)
    timeline.record("cloud", f"{cur}->{new}", reason=reason)
    obs_metrics.inc("h2o3_cloud_transitions_total", to=new)
    return True


def degrade(reason: str, hold_s: float = 0.0) -> None:
    """Mark the cloud DEGRADED: new multi-process ops are refused until it
    recovers. `hold_s` pins the state for at least that long — degrades
    whose evidence is NOT in the heartbeat table (ack timeouts, abandoned
    turnstile slots: the peer may be wedged yet still beating) must not be
    erased by the supervisor's next fresh-heartbeat evaluation."""
    changed = _transition(DEGRADED, reason)
    with _LOCK:
        if _STATE["state"] != DEGRADED:
            return
        if not changed:
            # already degraded: the newest evidence becomes the headline
            # (operators reading /3/CloudStatus see why it is STILL down)
            _STATE["reason"] = reason
        if hold_s > 0:
            _STATE["hold_until"] = max(_STATE.get("hold_until", 0.0),
                                       time.time() + hold_s)


def release_hold() -> None:
    """Lift an event-derived degrade hold ahead of its expiry — used when
    the event is positively resolved (e.g. a demoted ex-coordinator
    completed its rejoin as a follower), so the next evaluation can
    recover on liveness evidence instead of waiting out (or never
    outliving) the pin."""
    with _LOCK:
        _STATE["hold_until"] = 0.0


def recover(reason: str = "heartbeats fresh, no oplog errors") -> None:
    """DEGRADED/RECOVERING → HEALTHY when liveness (and, for RECOVERING,
    catch-up) evidence returns — never straight from FAILED: that edge
    only exists through RECOVERING (readmission) or reset()."""
    if _transition(HEALTHY, reason):
        with _LOCK:
            _STATE["hold_until"] = 0.0


def _incarnations_now() -> Dict[int, int]:
    """Highest incarnation currently on record per process, folded from
    the heartbeat table and any standing rejoin records. Snapshotted at
    fail() time so the FAILED -> RECOVERING gate can demand a STRICTLY
    newer incarnation — wall-clock comparisons would let cross-host clock
    skew block (or leftover records trigger) recovery."""
    from h2o3_tpu.core import failure
    from h2o3_tpu.parallel import oplog

    incs: Dict[int, int] = {}
    for r in failure.cluster_health(stale_after_s=float("inf")):
        if r.get("process") is not None:
            incs[int(r["process"])] = int(r.get("incarnation", 0))
    for p, i in oplog.expected_incarnations().items():
        incs[p] = max(incs.get(p, 0), i)
    return incs


def fail(reason: str, remote_trace: str = "") -> None:
    """Mark the cloud FAILED (follower replay crash: program counters
    diverged) and fail every in-flight Job with the remote traceback.
    Jobs are failed ONCE, here — a later recovery readmits the cloud for
    NEW ops but never resurrects a job built against the diverged state."""
    incs = _incarnations_now()
    with _LOCK:
        if not _transition(FAILED, reason, remote_trace):
            return
        _STATE["incs_at_failure"] = incs
    # a FAILED cloud is exactly the moment evidence starts evaporating
    # (jobs get failed, clients give up): dump the flight record NOW so
    # the postmortem has the timeline/spans/metrics as they stood
    from h2o3_tpu.obs import flight

    flight.record_flight("cloud_failed",
                         extra={"reason": reason,
                                "remote_trace": remote_trace[-2000:]})
    _fail_running_jobs(reason, remote_trace)


def begin_recovery(reason: str) -> bool:
    """FAILED → RECOVERING: readmission evidence arrived (a rejoin record
    under a fresh incarnation). New multi-process ops stay refused until
    every rejoined incarnation is caught up (then RECOVERING → HEALTHY)."""
    return _transition(RECOVERING, reason)


def ensure_operable() -> None:
    """Degraded-mode fail-fast for new multi-process ops."""
    from h2o3_tpu.core.failure import CloudUnhealthyError

    with _LOCK:
        st, reason, trace = (_STATE["state"], _STATE["reason"],
                             _STATE["remote_trace"])
    if st != HEALTHY:
        raise CloudUnhealthyError(
            f"cloud is {st} ({reason}) — refusing new multi-process op; "
            "single-process scoring stays available", remote_trace=trace)


def _fail_running_jobs(reason: str, remote_trace: str) -> None:
    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.core.job import Job

    msg = f"cloud FAILED while this job was in flight: {reason}"
    if remote_trace:
        msg += f"\n--- remote traceback ---\n{remote_trace}"
    for k in list(DKV.keys()):
        j = DKV.get(k)
        if isinstance(j, Job) and j.is_running:
            j.fail(msg)


def evaluate() -> str:
    """One supervision pass: fold oplog error keys and the heartbeat table
    into the state machine. Returns the resulting state. Deterministic and
    thread-free — the chaos tests drive it directly; the Supervisor thread
    just calls it on a timer."""
    global _FIRST_EVAL_TS
    from h2o3_tpu.core import failure
    from h2o3_tpu.parallel import distributed as D
    from h2o3_tpu.parallel import oplog

    failure.faultpoint("supervisor.evaluate")
    if _FIRST_EVAL_TS is None:
        _FIRST_EVAL_TS = time.time()
    if D.process_count() > 1:
        # leadership-view refresh: a returned ex-coordinator discovers a
        # standby's newer epoch here (within one supervision tick) and
        # demotes instead of broadcasting against a cloud it lost
        oplog.maybe_demote()
    errors = oplog.error_records()
    fatal = [(s, r) for s, r in errors if r.get("fatal", True)]
    if fatal:
        seq, rec = fatal[0]
        fail(f"follower replay of op {seq} ({rec.get('kind', '?')}) crashed",
             str(rec.get("trace", "")))
        return state()
    if errors:
        # non-fatal follower faults only (e.g. a lost ack write after a
        # successful replay): the op stream did not diverge — degrade, and
        # hold so fresh beats from the faulting peer don't erase it while
        # the record stands
        seq, rec = errors[0]
        degrade(f"follower non-fatal oplog fault at op {seq} "
                f"({rec.get('kind', '?')}): "
                f"{str(rec.get('trace', ''))[-200:]}",
                hold_s=failure.heartbeat_stale_s())
        return state()
    # -- readmission arc: FAILED -> RECOVERING -> HEALTHY ----------------
    if state() == FAILED:
        # fresh = an incarnation STRICTLY newer than the one on record at
        # fail() time — not a wall-clock comparison, which cross-host
        # clock skew would defeat (a rejoin stamped a few seconds "before"
        # the failure would block the arc forever)
        incs0 = status().get("incs_at_failure") or {}
        fresh = [r for r in oplog.rejoin_records()
                 if r.get("proc") is not None
                 and int(r.get("inc", 0)) > int(incs0.get(int(r["proc"]), 0))]
        if fresh:
            begin_recovery(
                f"process(es) {[r.get('proc') for r in fresh]} rejoined "
                "with fresh incarnation(s); replaying oplog suffix from "
                "checkpoint")
    if state() == RECOVERING:
        recs = oplog.rejoin_records()
        health = failure.cluster_health()
        health_by = {r["process"]: r for r in health}
        # every rejoined incarnation caught up AND no process anywhere in
        # the cluster gone stale — a SECOND follower dying during the
        # outage (no rejoin record of its own) must keep us out of
        # HEALTHY, or new ops get accepted and burn the full ack timeout
        stale = [r["process"] for r in health if not r["healthy"]]
        # ... including a peer that died leaving NO heartbeat row (same
        # never-beat signal as the degrade path below: absence past the
        # staleness window, measured from supervision start)
        missing_dead = (D.process_count() - len(health) > 0
                        and time.time() - _FIRST_EVAL_TS
                        > failure.heartbeat_stale_s())
        caught_up = bool(recs) and not stale and not missing_dead and all(
            r.get("phase") == "caught_up"
            and health_by.get(r.get("proc"), {}).get("healthy", False)
            and health_by.get(r.get("proc"), {}).get("incarnation", 0)
            >= int(r.get("inc", 0))
            for r in recs)
        if caught_up:
            recover("all rejoined incarnations caught up (checkpoint + "
                    "suffix replayed, heartbeats fresh, no oplog errors)")
        return state()
    health = failure.cluster_health()
    expected = D.process_count()
    if expected > 1:
        stale_s = failure.heartbeat_stale_s()
        dead = [r for r in health if not r["healthy"]]
        missing = expected - len(health)
        if dead:
            degrade("stale heartbeat from process(es) "
                    f"{[r['process'] for r in dead]} (age > {stale_s:.1f}s)")
        elif missing > 0 and time.time() - _FIRST_EVAL_TS > stale_s:
            # a process that NEVER beat has no stale row to trip on —
            # absence past the staleness window is the death signal (a
            # follower that crashed at startup)
            degrade(f"{missing} process(es) have never heartbeat "
                    f"(> {stale_s:.1f}s after supervision start)")
        elif health and missing <= 0:
            with _LOCK:
                # check-and-recover under one lock acquisition: a concurrent
                # degrade(hold_s=...) from an ack-timeout handler must either
                # land before (hold observed, no recovery) or after (its hold
                # survives the transition) — never in between
                if time.time() >= _STATE.get("hold_until", 0.0):
                    # fresh beats only recover once any event-derived degrade
                    # (ack timeout / turnstile abandonment) has aged out — a
                    # wedged peer can keep beating while not replaying
                    recover()
    return state()


class Supervisor:
    """Background evaluator (coordinator-side HeartBeatThread analog).

    Owns the autonomous recovery watchdog (parallel/watchdog.py) when
    ``H2O_TPU_AUTO_RECOVER`` is on: supervision detects the failures, the
    watchdog's daemon thread performs the recoveries — elections, rejoins,
    durable-job resumes — with no operator in the loop."""

    def __init__(self, interval: Optional[float] = None):
        self.interval = interval_s() if interval is None else float(interval)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.watchdog = None

    def start(self) -> "Supervisor":
        from h2o3_tpu.utils.log import get_logger

        def run():
            while not self._stop.wait(self.interval):
                try:
                    evaluate()
                except Exception as e:   # noqa: BLE001 — a transient KV
                    # hiccup must not kill supervision for good; but a
                    # PERMANENTLY-failing evaluate dying silently is an
                    # outage multiplier — leave a trace
                    get_logger().debug("supervisor tick failed "
                                       "(will retry): %s", e)

        try:
            evaluate()
        except Exception as e:   # noqa: BLE001
            get_logger().debug("initial supervision pass failed "
                               "(thread will retry): %s", e)
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="h2o3-supervisor")
        self._thread.start()
        from h2o3_tpu.parallel import watchdog as _wd

        # at most ONE watchdog per process: a standby whose own watchdog
        # just won the election re-enters here via start_server — stacking
        # a second ticker would double every recovery scan and corrupt the
        # module-level counters
        if _wd.enabled() and not _wd.status().get("running"):
            self.watchdog = _wd.Watchdog().start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
