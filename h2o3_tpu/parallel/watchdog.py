"""Autonomous recovery watchdog: close the loop from "self-healing cloud"
to "self-healing workloads" with zero operator intervention.

PRs 3-4 built every recovery mechanism — degrade/fail supervision,
checkpoint + rejoin readmission, standby-coordinator election — but each
transition still needed an operator's hand: ``assume_coordination()`` was
driver-invoked, a demoted ex-coordinator never rejoined, and a follower
whose replay crashed stayed dead until someone called ``rejoin()``.
Podracer-style TPU fleets (arXiv:2104.06272) treat preemption as the
NORMAL failure mode, so recovery must be a daemon, not a runbook.

The watchdog is that daemon. Each tick (supervisor-owned thread, or driven
directly by the chaos tests) it takes at most one recovery action:

- **demoted ex-coordinator** → ``distributed.rejoin()`` as a follower
  (and optionally resume replay duty), exactly the remediation the
  demotion error advertises;
- **crashed follower** (``oplog.replay_crashed()``) → ``rejoin()`` too —
  the FAILED cloud walks RECOVERING → HEALTHY without an operator;
- **follower watching a silent leader** → once the recorded leader's
  heartbeat is stale past ``H2O_TPU_ELECTION_GRACE_S``, run the standby
  election. The default ``oplog.assume_coordination`` is enough for a
  process that already runs a REST server (handlers consult epoch-based
  leadership per request, so the existing bind keeps serving as the new
  coordinator); a follower with NO server yet passes
  ``api.server.assume_coordination`` as ``elect`` so ``/3/*`` comes up
  on a win. ``ElectionLost`` just means "standing by".
- **coordinator on a workable cloud** → re-dispatch externally-failed
  jobs that left durable training progress (``resume_failed_jobs``):
  FAILED → RESUMING → RUNNING → DONE from the last completed iteration;
  then re-dispatch orphaned AutoML/grid searches that left durable
  search state (``automl/search.resume_orphaned``) under their ORIGINAL
  keys, so a killed coordinator's search completes autonomously.

``H2O_TPU_AUTO_RECOVER=0`` disables every action (manual drills / chaos
tests drive transitions by hand); state is surfaced on GET /3/CloudStatus.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from h2o3_tpu.parallel import retry

_LOCK = threading.Lock()
_STATE: Dict = {"ticks": 0, "elections": 0, "rejoins": 0,
                "jobs_resumed": 0, "searches_resumed": 0,
                "last_action": "", "last_error": "",
                "last_tick": 0.0, "running": False}

# a job that keeps dying is not resumed forever (poisoned input, a bug in
# the trainer): after this many dispatches it stays FAILED for the client
MAX_ATTEMPTS = 5


def enabled() -> bool:
    """Autonomous recovery master switch (env ``H2O_TPU_AUTO_RECOVER``,
    default on — set 0 for manual drills / hand-driven chaos tests)."""
    return retry.env_int("H2O_TPU_AUTO_RECOVER", 1) != 0


# adaptive replay idle bounds: never retire under traffic jitter, never
# pin an idle thread for the old fixed hour
_REPLAY_IDLE_MIN_S = 120.0
_REPLAY_IDLE_MAX_S = 3600.0
_REPLAY_IDLE_DEFAULT_S = 900.0


def replay_idle_timeout_s() -> float:
    """Idle timeout for watchdog-spawned replay threads.

    ``H2O_TPU_REPLAY_IDLE_S`` > 0 pins it; otherwise it ADAPTS to observed
    op traffic (oplog.observed_op_gap_s): 20× the median inter-op gap,
    clamped to [2 min, 1 h], defaulting to 15 min before any traffic has
    been seen. Replaces the fixed 3600 s that kept replay threads (and
    whatever their last replayed op pinned) alive for an hour on an idle
    cloud while ALSO being too short for genuinely slow op cadences."""
    pinned = retry.env_int("H2O_TPU_REPLAY_IDLE_S", 0)
    if pinned > 0:
        return float(pinned)
    from h2o3_tpu.parallel import oplog

    gap = oplog.observed_op_gap_s()
    if gap is None:
        return _REPLAY_IDLE_DEFAULT_S
    return float(min(max(20.0 * gap, _REPLAY_IDLE_MIN_S),
                     _REPLAY_IDLE_MAX_S))


def status() -> Dict:
    """Snapshot for GET /3/CloudStatus."""
    with _LOCK:
        out = dict(_STATE)
    out["enabled"] = enabled()
    out["replay_idle_timeout_s"] = round(replay_idle_timeout_s(), 1)
    return out


def reset() -> None:
    """Clear the counters (tests / cloud restart)."""
    with _LOCK:
        _STATE.update(ticks=0, elections=0, rejoins=0, jobs_resumed=0,
                      searches_resumed=0, last_action="", last_error="",
                      last_tick=0.0)
    _STRIKES.clear()
    from h2o3_tpu.automl import search

    search._STRIKES.clear()


def _note(action: str, **counters) -> str:
    with _LOCK:
        _STATE["last_action"] = action
        for k, v in counters.items():
            _STATE[k] = _STATE.get(k, 0) + v
    return action


# ---------------------------------------------------------------------------
# job resume: FAILED(externally) + durable progress -> re-dispatch
# ---------------------------------------------------------------------------

def resume_failed_jobs() -> List[str]:
    """Re-dispatch every externally-failed job that persisted durable
    training progress; returns the job keys resumed. Jobs whose Job object
    did not survive to this process (a standby coordinator whose
    control-plane checkpoint predates the job) are RECREATED under their
    original key from the progress file's spec, so clients polling
    ``GET /3/Jobs/{id}`` watch the same id across the handoff."""
    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.core.job import Job
    from h2o3_tpu.parallel import ckpt

    resumed: List[str] = []
    for rec in ckpt.job_progress_records():
        jk = str(rec.get("job"))
        job = DKV.get(jk)
        data = None
        if job is None:
            # post-handoff: the Job object lived on the dead coordinator —
            # this recreate path is the only one that pays the full state
            # load before the cheap verdict checks
            data = ckpt.load_job_progress(jk)
            if data is None:
                _strike(jk)              # unreadable: bounded retries
                continue
            spec = data.get("spec") or {}
            if not spec.get("algo"):
                # no re-dispatch recipe in the FILE either: no process can
                # ever act on this record — GC it now
                ckpt.delete_job_progress(jk)
                continue
            job = _recreate_job(jk, spec)
        if not isinstance(job, Job):
            continue
        # cheap verdict checks BEFORE unpickling the training state (the
        # record for a large RUNNING build sits here on every tick)
        if job.status in (Job.DONE, Job.CANCELLED) or \
                (job.status == Job.FAILED and not job.failed_externally):
            # nobody will ever resume this progress (completed: the model
            # supersedes it; worker-crashed/cancelled: the client's to
            # resubmit) — GC the file + record instead of leaking them
            ckpt.delete_job_progress(jk)
            continue
        if not (job.status == Job.FAILED and job.failed_externally):
            continue                     # RUNNING/RESUMING: leave it be
        if job.attempt >= MAX_ATTEMPTS:
            ckpt.delete_job_progress(jk)   # parked for good
            continue
        if data is None:
            data = ckpt.load_job_progress(jk)
        if data is None:
            # torn/corrupt progress file: count the pass so the attempt
            # cap parks (and GCs) it instead of re-reading it every tick
            job.attempt += 1
            job.exception = (f"resume dispatch pass {job.attempt}: durable "
                             f"progress for {jk} is unreadable")
            continue
        if _dispatch_resume(job, data.get("spec") or {}, data):
            resumed.append(jk)
    return resumed


def resume_orphaned_searches() -> List[str]:
    """Re-dispatch every orphaned AutoML/grid search that persisted
    durable search state (automl/search.py owns the machinery; the
    lazy import keeps the recovery layer free of workload imports)."""
    from h2o3_tpu.automl import search

    return search.resume_orphaned()


# bounded retries for records whose Job is gone AND whose progress file is
# unreadable: a transient shared-storage blip deserves another look, a
# permanently torn file must not be re-probed every tick forever
_STRIKES: Dict[str, int] = {}


def _strike(job_key: str) -> None:
    from h2o3_tpu.parallel import ckpt

    _STRIKES[job_key] = _STRIKES.get(job_key, 0) + 1
    if _STRIKES[job_key] >= MAX_ATTEMPTS:
        ckpt.delete_job_progress(job_key)
        _STRIKES.pop(job_key, None)
        from h2o3_tpu.utils.log import get_logger

        get_logger().warning(
            "watchdog: durable progress for job %s was unreadable %d "
            "times — record dropped", job_key, MAX_ATTEMPTS)


def _recreate_job(job_key: str, spec: dict):
    """Rebuild a Job shell under its ORIGINAL key (post-handoff: the new
    leader's DKV may predate the job) so the resume is client-visible."""
    from h2o3_tpu.core.dkv import DKV, Key
    from h2o3_tpu.core.job import Job

    job = Job(description=spec.get("description")
              or f"{spec.get('algo')} Model Build",
              dest=spec.get("model_id"))
    DKV.remove(str(job.key))             # drop the auto-made key
    job._key = Key(job_key)
    job.status = Job.FAILED
    job.failed_externally = True
    job.exception = ("job was in flight when its coordinator died; "
                     "recreated from durable progress for resume")
    job.resume_spec = dict(spec)
    job.install()
    return job


def _dispatch_resume(job, spec: dict, data: dict) -> bool:
    """One re-dispatch: RESUMING (atomic — two recovery passes can never
    double-dispatch), rebuild the builder with the restored loop state,
    broadcast the resume op so followers fast-forward from the same file,
    and run the train on the job's (new) worker thread."""
    from h2o3_tpu.core.dkv import DKV, Key
    from h2o3_tpu.core.job import Job
    from h2o3_tpu.models.model_builder import BUILDERS
    from h2o3_tpu.parallel import oplog

    cls = BUILDERS.get(spec.get("algo"))
    train = DKV.get(str(spec.get("training_frame") or ""))
    if cls is None or train is None:
        # not re-dispatchable HERE (unknown builder / frame not in this
        # DKV): count the pass so MAX_ATTEMPTS eventually parks the job
        # instead of it being re-probed on every tick forever
        job.attempt += 1
        what = (f"unknown algo {spec.get('algo')!r}" if cls is None else
                f"training frame {spec.get('training_frame')!r} is not in "
                f"this process's DKV")
        job.exception = f"resume dispatch pass {job.attempt}: {what}"
        return False
    valid = DKV.get(str(spec["validation_frame"])) \
        if spec.get("validation_frame") else None
    if not job.restart(resumed_from_iteration=data.get("iteration")):
        return False
    y = spec.get("y")
    dest = spec.get("model_id") or job.dest
    params = dict(spec.get("params") or {})
    if oplog.active() and float(params.get("max_runtime_secs") or 0.0) > 0:
        # re-broadcast resume on a multi-process cloud: the wall-clock
        # budget is per-process time and would desynchronize the mirrored
        # fit loops (the train/grid handlers clear it the same way; a
        # resume whose ORIGINAL submit predates that fix may still carry
        # one in its durable spec)
        params["max_runtime_secs"] = 0.0
        spec = dict(spec, params=params)
    try:
        builder = cls(**params)
    except Exception as e:   # noqa: BLE001 — param drift is deterministic:
        # fail_local keeps failed_externally False so the identical doomed
        # rebuild is NOT retried on the next recovery pass
        job.fail_local(f"resume dispatch failed rebuilding the "
                       f"{spec.get('algo')} builder: {e}")
        return False
    builder._progress_job = job
    builder._resume_state = data.get("state")
    op_seq = None
    if oplog.active():
        try:
            op_seq = oplog.broadcast("train", {
                "algo": spec["algo"], "params": spec.get("params"),
                "training_frame": spec.get("training_frame"),
                "validation_frame": spec.get("validation_frame"),
                "y": y, "model_id": dest, "resume_job": str(job.key)})
        except Exception as e:   # noqa: BLE001 — cloud relapsed mid-resume
            job.fail(f"resume dispatch could not broadcast: {e}")
            return False

    def run(j):
        with oplog.turn(op_seq):
            model = builder.train(y=y, training_frame=train,
                                  validation_frame=valid)
        if j.status == Job.FAILED:
            # an external FAILED landed mid-train: the wrapper discards
            # the result — installing it at dest here would serve a model
            # built against a diverged cloud
            return model
        # same re-home contract as the REST train handler's wrapper: the
        # client captured dest at submit, and /3/Models metadata must not
        # differ between a resumed build and an uninterrupted one
        old = str(model.key)
        if dest and old != dest:
            DKV.remove(old)
            model._key = Key(dest)
        if dest:
            DKV.put(dest, model)
        model._parms.setdefault("training_frame", str(train.key))
        return model

    job.start(run, background=True)
    from h2o3_tpu.utils import timeline

    timeline.record("cloud", "job_resumed", job=str(job.key),
                    attempt=job.attempt,
                    from_iteration=data.get("iteration"))
    from h2o3_tpu.utils.log import get_logger

    get_logger().warning(
        "watchdog: resumed job %s (attempt %d) from iteration %s",
        job.key, job.attempt, data.get("iteration"))
    return True


# ---------------------------------------------------------------------------
# the watchdog itself
# ---------------------------------------------------------------------------

class Watchdog:
    """One recovery action per tick; never raises out of tick().

    `elect` overrides the election action (default
    ``oplog.assume_coordination`` — pass ``api.server.assume_coordination``
    to re-bind REST on a win). `follow=True` spawns a follower replay loop
    after an auto-rejoin so the readmitted process resumes replay duty."""

    def __init__(self, interval: Optional[float] = None,
                 elect: Optional[Callable] = None, follow: bool = True):
        from h2o3_tpu.parallel import supervisor

        self.interval = (supervisor.interval_s() if interval is None
                         else float(interval))
        self._elect = elect
        self.follow = follow
        self._born = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._follower_thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Watchdog":
        def run():
            while not self._stop.wait(self.interval):
                self.tick()

        with _LOCK:
            _STATE["running"] = True
        self.tick()
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="h2o3-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with _LOCK:
            _STATE["running"] = False

    # -- one pass ---------------------------------------------------------
    def tick(self) -> str:
        """Evaluate the cloud and take at most one recovery action.
        Returns a short tag naming what happened (tests assert on it)."""
        from h2o3_tpu.parallel import distributed as D
        from h2o3_tpu.parallel import oplog, supervisor

        if not enabled():
            return _note("disabled")
        with _LOCK:
            _STATE["ticks"] += 1
            _STATE["last_tick"] = time.time()
        # follower-side freshness for the coordinator's cluster-wide
        # /3/Metrics rides the watchdog tick (throttled; best-effort)
        try:
            from h2o3_tpu.obs import metrics as _om

            _om.maybe_publish()
        except Exception as e:   # noqa: BLE001 — observability never
            # blocks recovery, but its death should not be invisible
            from h2o3_tpu.utils.log import get_logger

            get_logger().debug("watchdog metrics publish failed: %s", e)
        try:
            if D.process_count() > 1:
                oplog.maybe_demote()
            if oplog.demoted():
                return self._auto_rejoin("demoted ex-coordinator")
            if not D.is_coordinator():
                if oplog.replay_crashed():
                    return self._auto_rejoin("crashed follower")
                return self._maybe_elect()
            # coordinator: fold evidence, then revive resumable work. The
            # evaluate() here makes the watchdog self-sufficient when the
            # Supervisor thread is parked (long intervals / tests).
            st = supervisor.evaluate()
            if st == supervisor.HEALTHY or D.process_count() <= 1:
                got = resume_failed_jobs()
                if got:
                    from h2o3_tpu.obs import flight

                    flight.record_flight("watchdog_job_resume",
                                         extra={"jobs": got})
                    return _note(f"resumed jobs {got}",
                                 jobs_resumed=len(got))
                sr = resume_orphaned_searches()
                if sr:
                    from h2o3_tpu.obs import flight

                    flight.record_flight("watchdog_search_resume",
                                         extra={"searches": sr})
                    return _note(f"resumed searches {sr}",
                                 searches_resumed=len(sr))
            return _note("idle")
        except Exception as e:   # noqa: BLE001 — a transient KV fault must
            with _LOCK:          # not kill recovery for good
                _STATE["last_error"] = f"{type(e).__name__}: {e}"
            return "error"

    def _auto_rejoin(self, why: str) -> str:
        from h2o3_tpu.parallel import distributed as D

        cursor = D.rejoin()
        if self.follow:
            self._spawn_follower(cursor)
        from h2o3_tpu.obs import flight
        from h2o3_tpu.utils.log import get_logger

        # every autonomous recovery action leaves a flight record: the
        # state that FORCED the action is the postmortem evidence
        flight.record_flight("watchdog_rejoin",
                             extra={"why": why, "caught_up_seq": cursor})
        get_logger().warning("watchdog: auto-rejoined as follower (%s), "
                             "caught up to seq %d", why, cursor)
        return _note(f"rejoined ({why})", rejoins=1)

    def _spawn_follower(self, cursor: int) -> None:
        from h2o3_tpu.parallel import oplog

        t = self._follower_thread
        if t is not None and t.is_alive():
            return
        self._follower_thread = threading.Thread(
            target=lambda: oplog.follower_loop(
                idle_timeout_s=replay_idle_timeout_s(), start_seq=cursor),
            daemon=True, name="h2o3-watchdog-follower")
        self._follower_thread.start()

    def _maybe_elect(self) -> str:
        from h2o3_tpu.core import failure
        from h2o3_tpu.parallel import distributed as D
        from h2o3_tpu.parallel import oplog

        rec = D.epoch_record()
        grace = failure.election_grace_s()
        rows = {r["process"]: r
                for r in failure.cluster_health(stale_after_s=grace)}
        lead = rows.get(rec["leader"])
        if lead is not None and lead["age_s"] < grace:
            return _note("follower (leader alive)")
        if lead is None and time.monotonic() - self._born < grace:
            # no heartbeat row is NOT silence evidence during boot: a
            # follower's watchdog can start before the coordinator's first
            # beat lands — electing now would steal a healthy cloud
            return _note("follower (no leader evidence yet)")
        try:
            elect = self._elect or oplog.assume_coordination
            elect()
        except oplog.ElectionLost as e:
            return _note(f"standing by ({e})")
        from h2o3_tpu.obs import flight
        from h2o3_tpu.utils.log import get_logger

        flight.record_flight("watchdog_election",
                             extra={"epoch": D.epoch(),
                                    "old_leader": rec["leader"]})
        get_logger().warning("watchdog: won the standby election "
                             "(epoch %d)", D.epoch())
        return _note("elected", elections=1)
