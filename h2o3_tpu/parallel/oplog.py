"""Operation log — the cross-process control plane for REST-driven work.

Reference: in the JVM cloud any node can accept a REST request and fan the
work out over the RPC layer (water/RPC.java + MRTask dispatch). Under SPMD
multi-controller JAX there is no RPC: every process must enter the SAME
jitted collective program. This module gives the coordinator a way to make
that happen for REST-initiated operations: the coordinator appends ops to
a sequence in the jax.distributed coordination-service KV, follower
processes replay them in order (`follower_loop`), and both sides execute
the identical framework call — so the shard_map programs line up and the
collectives complete.

Ops carry ONLY metadata (paths, keys, params) — data stays sharded on
device; files are read from the shared filesystem by every process, the
same contract the parse tier already uses.

Supervision (water/RPC.java retry + HeartBeatThread failure propagation):
every hand-off in this protocol is acknowledged and bounded. Followers
write ``oplog/ack/{seq}/{proc}`` after each replay; the coordinator's
`turn()` ends with `wait_acks(seq)` — a bounded wait that raises
:class:`~h2o3_tpu.core.failure.CloudUnhealthyError` carrying the remote
traceback from ``oplog/error/{seq}`` when a follower's replay crashed, or
a timeout error when a follower went silent — instead of letting the next
collective hang the REST handler forever. `publish()` retries lost KV
puts with backoff and rolls back its claimed sequence slot on failure, so
a lost op can never leave the follower stalled at a sequence gap.
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from h2o3_tpu.core import failure
from h2o3_tpu.parallel import distributed as D
from h2o3_tpu.parallel import retry

_SEQ = 0
_PREFIX = "oplog"
_RAPIDS_SESSIONS: Dict[str, Any] = {}     # follower-side session mirror

# coordinator-side execution turnstile: broadcast order == device-program
# order. REST jobs run in background threads, so without this two
# concurrent requests could enter their shard_map programs in the opposite
# order from the follower's strictly sequential replay — a mesh deadlock.
_EXEC_COND = threading.Condition()
_NEXT_EXEC = 0
# ops whose holder gave up (turn timeout) or died: the turnstile skips
# them instead of waiting forever on a thread that will never arrive
_ABANDONED: set = set()
# the seq currently INSIDE its turn (None between turns): lets a timed-out
# waiter tell a slow-but-alive head holder (leave it be) from one that
# died before ever entering its turn (release its slot)
_EXECUTING: Optional[int] = None
# turnstile epoch: reset() bumps it, and a turn that entered under an
# older epoch must NOT advance the new epoch's _NEXT_EXEC on exit — a
# straggler op thread outliving a cloud restart would otherwise clobber
# the restarted sequence mid-stream
_GEN = 0
# when the turnstile head last moved (advance/enter/exit), monotonic. A
# waiter only declares the head holder DEAD if the head has sat idle —
# parked on the same slot with nobody executing — for a full grace
# window: a LIVE holder between publish and turn enters within one
# cond-wait tick, so transient _EXECUTING==None gaps must not read as
# death (they would sticky-FAIL a merely backlogged cloud)
_HEAD_IDLE_SINCE = 0.0
_HEAD_GRACE_S = 5.0
# publish() runs on concurrent REST handler threads: sequence allocation
# and the kv_put must be atomic or two ops can claim the same slot (one
# overwrites the other in the KV and the follower stalls at the gap)
_PUB_LOCK = threading.Lock()
# coordinator-side seq -> op identity token. Acks are matched on the
# TOKEN, not just the slot number: a rolled-back slot can be reclaimed by
# a different op (that is the rollback contract), and an indeterminate
# kv_put (reported lost but actually landed) can leave a follower ack for
# the ORIGINAL op under the same seq — which must not satisfy wait_acks
# for the reclaiming op.
_OP_IDS: Dict[int, str] = {}
_OP_IDS_CAP = 4096


class OplogPublishError(RuntimeError):
    """An op could not be durably published to the cloud KV (after the
    retry budget); its claimed sequence slot was rolled back."""


class OplogTurnTimeout(RuntimeError):
    """The coordinator-side execution turnstile did not reach this op's
    slot within the deadline — an earlier ticket holder is wedged or died
    before entering its turn. The slot is abandoned (later ops skip it)."""


class OplogAckError(RuntimeError):
    """A follower replayed an op but could not durably write its ack (after
    a second retry round on top of kv_put's own budget). The follower must
    not proceed silently: to the coordinator a lost ack is
    indistinguishable from this process dying."""


# reentrancy guard: while the coordinator executes an op inside turn() (or
# a follower replays one in _apply), nested framework calls — AutoML's base
# models, CV submodels, grid entries — must NOT broadcast their own ops:
# the follower replays the TOP-level op and re-runs the nested programs
# itself, so a nested broadcast would double-execute them on the follower.
_TLS = threading.local()

# set by api.server.start_server: this process is the coordinator of a
# REST-driven cloud, so device/collective work on handler threads is only
# legal inside a broadcast op's turn (the follower replays ops, nothing
# else). Framework internals consult this to fail fast instead of entering
# a collective the follower will never join.
REST_SERVING = False

# set when this process discovers a NEWER epoch record naming another
# leader while it believed itself the coordinator: it must refuse to run
# multi-process ops (locally OR broadcast) until it rejoins as a follower
_DEMOTED = False

# set when THIS process's replay loop died on a replay crash: the recovery
# watchdog reads it to nudge the failed follower through rejoin() without
# an operator; rejoin() clears it
_REPLAY_CRASHED = False


def demoted() -> bool:
    """True when this process lost coordination to a newer epoch and has
    not yet rejoined as a follower (see maybe_demote)."""
    return _DEMOTED


def replay_crashed() -> bool:
    """True when this process's follower replay loop crashed and it has
    not yet rejoined (the watchdog's auto-rejoin trigger)."""
    return _REPLAY_CRASHED


# recent op arrival times (coordinator: publish; follower: replay) — the
# signal the watchdog's ADAPTIVE replay idle timeout is derived from: a
# busy cloud keeps its replay threads patient, an idle one lets them
# retire quickly instead of pinning a thread for a fixed hour
_OP_TIMES: "collections.deque[float]" = collections.deque(maxlen=32)


def note_op_seen() -> None:
    _OP_TIMES.append(time.time())


def observed_op_gap_s() -> Optional[float]:
    """Median gap between recently seen ops (seconds); None until at least
    two ops have been observed this process-lifetime."""
    ts = list(_OP_TIMES)
    if len(ts) < 2:
        return None
    gaps = sorted(b - a for a, b in zip(ts, ts[1:]))
    return float(gaps[len(gaps) // 2])


def _in_op() -> bool:
    return bool(getattr(_TLS, "in_op", False))


def unmirrored_collective_risk() -> bool:
    """True when the calling thread is about to run a collective the other
    processes will NOT mirror: coordinator of a REST-serving multi-process
    cloud, outside any op turn."""
    return (REST_SERVING and D.process_count() > 1 and D.is_coordinator()
            and not _in_op())


def active() -> bool:
    """Coordinator with followers attached: REST handlers must broadcast."""
    return D.process_count() > 1 and D.is_coordinator() and not _in_op()


def _turn_timeout_s() -> float:
    return retry.env_float("H2O_TPU_TURN_TIMEOUT_S", 1800.0)


def _ack_timeout_s() -> float:
    return retry.env_float("H2O_TPU_OP_ACK_TIMEOUT_S", 300.0)


def reset(next_seq: int = 0) -> None:
    """Reset the coordinator-side protocol state (sequence counter,
    turnstile, abandoned slots). Test/bootstrap/standby-takeover use."""
    global _SEQ, _NEXT_EXEC, _EXECUTING, _GEN, _HEAD_IDLE_SINCE
    global _REPLAY_CRASHED
    _REPLAY_CRASHED = False
    with _EXEC_COND:
        _SEQ = next_seq
        _NEXT_EXEC = next_seq
        _EXECUTING = None
        _GEN += 1
        _HEAD_IDLE_SINCE = time.monotonic()
        _ABANDONED.clear()
        _OP_IDS.clear()
        _EXEC_COND.notify_all()
    from h2o3_tpu.parallel import ckpt

    ckpt.reset()


def snapshot_op_ids() -> Dict[int, str]:
    """Recent op identity tokens, for the control-plane checkpoint: a
    coordinator restored from it can still match in-flight acks."""
    with _PUB_LOCK:
        return dict(_OP_IDS)


def current_seq() -> int:
    """Next sequence to be claimed (ops < this are published)."""
    with _PUB_LOCK:
        return _SEQ


def publish(kind: str, payload: Dict[str, Any]) -> int:
    """Append one op (coordinator only); followers replay in sequence.
    Returns the op's sequence number (the coordinator's execution ticket).

    The KV put is retried with exponential backoff + jitter; if it still
    does not land, the claimed sequence slot is rolled back and a clear
    :class:`OplogPublishError` raises — the old silent-False path left
    the follower stalled at a sequence gap forever."""
    global _SEQ
    failure.faultpoint("oplog.publish")
    note_op_seen()            # adaptive replay-idle signal (traffic clock)
    # _PUB_LOCK spans claim + put: rollback is only sound while no LATER
    # slot has been claimed (a gap would stall the follower forever). The
    # hold is bounded — kv_put absorbs transient transport faults with its
    # own small backoff budget; a put that still fails is a HARD loss that
    # rolls back and raises (callers that must survive it, e.g. the
    # scoring micro-batcher, retry the whole publish for a fresh slot).
    from h2o3_tpu.obs import metrics as obs_metrics
    from h2o3_tpu.obs import tracing

    with _PUB_LOCK:
        seq = _SEQ
        _SEQ += 1
        op_id = uuid.uuid4().hex[:16]
        ok, cause = False, None
        # the op record carries the REST ingress trace context so the
        # follower's replay + ack land in the SAME span tree as the
        # coordinator's handler (publish -> replay -> ack, one trace)
        with tracing.span("oplog.publish", kind=kind, seq=seq) as psp:
            try:
                failure.faultpoint("oplog.kv_put")
                op_rec = {"kind": kind, "payload": payload, "op_id": op_id}
                if psp:
                    op_rec["trace"] = psp.ctx()
                ok = D.kv_put(f"{_PREFIX}/{seq}", json.dumps(op_rec))
            except Exception as e:   # noqa: BLE001 — converted below
                cause = e
            if not ok:
                _SEQ = seq       # gapless rollback: next publish reuses it
                raise OplogPublishError(
                    f"failed to publish oplog op {seq} ({kind}): "
                    f"{cause or 'kv_put did not land'}") from cause
        _OP_IDS[seq] = op_id     # reclaim overwrites: acks match THIS op
        if len(_OP_IDS) > _OP_IDS_CAP:
            for old in sorted(_OP_IDS)[: len(_OP_IDS) - _OP_IDS_CAP]:
                del _OP_IDS[old]
    obs_metrics.inc("h2o3_oplog_ops_published_total")
    return seq


def broadcast(kind: str, payload: Dict[str, Any]) -> Optional[int]:
    """Publish when this process is the coordinator of a live multi-process
    cloud; no-op single-process (the common local path pays nothing).
    Returns the execution ticket (None single-process).

    Degraded-mode fail-fast: when the supervisor has marked the cloud
    DEGRADED/FAILED, new multi-process ops are refused immediately with a
    clear CloudUnhealthyError instead of being queued toward a collective
    the dead/stale follower will never join. A DEMOTED ex-coordinator
    (a standby won the epoch while this process was away) refuses too:
    silently falling through to local execution would fork its state from
    the cloud the new coordinator now leads."""
    if D.process_count() > 1:
        # leadership-view refresh before publishing: a standby's takeover
        # must be discovered here, not one supervision tick later. Single-
        # process there is no standby — that fast path keeps paying
        # nothing (the docstring's contract).
        maybe_demote()
    if _DEMOTED:
        rec = D.epoch_record()
        raise failure.CloudUnhealthyError(
            f"this process was demoted to follower (epoch "
            f"{rec['epoch']} is led by process {rec['leader']}): refusing "
            "to execute a multi-process op against a cloud it no longer "
            "coordinates — rejoin() as a follower or restart")
    if active():
        from h2o3_tpu.parallel import supervisor

        supervisor.ensure_operable()
        return publish(kind, payload)
    return None


def _neutralize_slots(slots: List[int], why: str) -> None:
    """Best-effort cleanup for abandoned turnstile slots, OUTSIDE the
    condition lock: overwrite each published op with a 'noop' (KV upsert
    semantics) so a follower that has not reached it yet replays nothing
    instead of running a program the coordinator never will. If a
    follower ALREADY acked one of these ops, the divergence is certain —
    the follower ran a program the coordinator never will — and the
    cloud FAILs (sticky); otherwise it degrades with a hold. A follower
    mid-replay that acks after the check is the residual race; the hold
    window plus the next op's ack matching bounds how long that hides."""
    diverged = []
    for s in slots:
        if acks_for(s, _OP_IDS.get(s)):
            diverged.append(s)
        try:
            D.kv_put(f"{_PREFIX}/{s}",
                     json.dumps({"kind": "noop",
                                 "payload": {"abandoned": why}}))
        except Exception:   # noqa: BLE001 — cleanup stays best-effort
            pass
    from h2o3_tpu.parallel import supervisor

    if diverged:
        supervisor.fail(f"abandoned op(s) {diverged} were already "
                        f"replayed by a follower ({why}): program "
                        "counters diverged")
    else:
        supervisor.degrade(f"turnstile abandoned op(s) {slots}: {why}",
                           hold_s=failure.heartbeat_stale_s())


@contextlib.contextmanager
def turn(seq: Optional[int], timeout_s: Optional[float] = None):
    """Hold the coordinator's device-execution turnstile for op `seq`:
    ops run their device programs in exactly broadcast order, matching the
    follower's sequential replay. No-op when seq is None.

    Bounded: if the turnstile does not reach `seq` within `timeout_s`
    (env ``H2O_TPU_TURN_TIMEOUT_S``), this raises
    :class:`OplogTurnTimeout` and abandons `seq`'s slot so later ops skip
    it; if the op at the head of the turnstile never ENTERED its turn
    (its holder died between publish and turn — as opposed to being alive
    inside a long device program), the head slot is released too, so ops
    behind it do not each re-pay the full deadline. Abandoned slots are
    neutralized to 'noop' in the KV and the cloud is degraded.
    On successful completion the coordinator waits (bounded, env
    ``H2O_TPU_OP_ACK_TIMEOUT_S``) for every follower's replay ack."""
    global _NEXT_EXEC, _EXECUTING, _HEAD_IDLE_SINCE
    if seq is None:
        yield
        return
    if timeout_s is None:
        timeout_s = _turn_timeout_s()
    deadline = time.monotonic() + timeout_s
    abandoned: List[int] = []
    with _EXEC_COND:
        my_gen = _GEN
        while True:
            if _GEN != my_gen:
                raise OplogTurnTimeout(
                    f"turnstile was reset (cloud restart) while op {seq} "
                    "waited — op not executed")
            if seq < _NEXT_EXEC or seq in _ABANDONED:
                # a timed-out waiter released this slot presuming its
                # holder dead; executing now would be out of broadcast
                # order — refuse (the op in the KV is already a noop).
                # If the turnstile is parked ON this slot, advance it so
                # waiters behind do not stall on a holder that just left.
                if _NEXT_EXEC == seq:
                    _ABANDONED.discard(seq)
                    _NEXT_EXEC = seq + 1
                    while _NEXT_EXEC in _ABANDONED:
                        _ABANDONED.discard(_NEXT_EXEC)
                        _NEXT_EXEC += 1
                    _HEAD_IDLE_SINCE = time.monotonic()
                    _EXEC_COND.notify_all()
                raise OplogTurnTimeout(
                    f"op {seq}'s turnstile slot was abandoned (holder "
                    "presumed dead after a waiter's deadline) — op not "
                    "executed")
            while _NEXT_EXEC in _ABANDONED:
                _ABANDONED.discard(_NEXT_EXEC)
                _NEXT_EXEC += 1
                _HEAD_IDLE_SINCE = time.monotonic()
                _EXEC_COND.notify_all()
            if _NEXT_EXEC == seq:
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                stuck = _NEXT_EXEC
                abandoned.append(seq)
                _ABANDONED.add(seq)
                # release the head slot ONLY if its holder never entered
                # for a full grace window: a LIVE holder between publish
                # and turn enters within one cond-wait tick, so a
                # transient _EXECUTING gap right after the previous op's
                # exit must not read as death on a busy-but-healthy cloud
                grace = min(_HEAD_GRACE_S, timeout_s)
                if _EXECUTING != stuck and \
                        time.monotonic() - _HEAD_IDLE_SINCE >= grace:
                    abandoned.append(stuck)
                    _ABANDONED.add(stuck)
                _EXEC_COND.notify_all()
                break
            _EXEC_COND.wait(timeout=min(remaining, 1.0))
        if abandoned:
            head_note = (f"; released never-entered head slot "
                         f"{abandoned[1]}" if len(abandoned) > 1 else "")
            err = OplogTurnTimeout(
                f"op {seq} waited {timeout_s:.1f}s for the execution "
                f"turnstile (stuck at op {_NEXT_EXEC} — its holder is "
                f"wedged or died); slot {seq} abandoned{head_note}")
        else:
            _EXECUTING = seq
            _HEAD_IDLE_SINCE = time.monotonic()
    if abandoned:
        _neutralize_slots(abandoned, f"turn timeout after {timeout_s:.1f}s")
        raise err
    _TLS.in_op = True
    try:
        yield
    finally:
        _TLS.in_op = False
        with _EXEC_COND:
            if _GEN == my_gen:
                _EXECUTING = None
                _NEXT_EXEC = seq + 1
                while _NEXT_EXEC in _ABANDONED:
                    _ABANDONED.discard(_NEXT_EXEC)
                    _NEXT_EXEC += 1
                _HEAD_IDLE_SINCE = time.monotonic()
                _EXEC_COND.notify_all()
            # else: the turnstile was reset() (cloud restart) while this
            # op was in flight — a straggler must not clobber the new
            # epoch's sequence position
    # reached only when the body completed: bounded follower-ack wait, so a
    # dead/crashed follower surfaces HERE as a clear error instead of
    # hanging the NEXT collective this handler (or any later op) runs
    wait_acks(seq)
    # the op is fully acknowledged cloud-wide: feed the checkpoint
    # accountant — every H2O_TPU_OPLOG_CHECKPOINT_OPS acked ops it
    # snapshots the control plane and truncates the acked prefix, keeping
    # live oplog/* keys O(interval) (never raises; see parallel/ckpt.py)
    from h2o3_tpu.parallel import ckpt

    ckpt.note_acked_op(seq)


# ---------------------------------------------------------------------------
# acknowledgment protocol
# ---------------------------------------------------------------------------

def expected_acks() -> int:
    """Follower count: every non-coordinator process acks each replay."""
    return max(D.process_count() - 1, 0)


def acks_for(seq: int, op_id: Optional[str] = None,
             min_incs: Optional[Dict[int, int]] = None) -> List[str]:
    """Ack keys recorded for op `seq`; with `op_id`, only acks carrying
    that identity token (stale acks from a lost-then-landed op whose slot
    was rolled back and reclaimed do not count for the reclaiming op).
    With `min_incs` ({proc: incarnation}), acks from an OLDER incarnation
    of a since-rejoined process are rejected too: the dead predecessor's
    leftover ack must not vouch for a replay only its successor can do."""
    out = []
    for k, v in D.kv_dir(f"{_PREFIX}/ack/{seq}/"):
        try:
            rec = json.loads(v)
        except (ValueError, TypeError):
            continue
        if not isinstance(rec, dict):
            continue               # truncated/corrupt ack: doesn't count
        if op_id is not None and rec.get("op_id") != op_id:
            continue
        if min_incs:
            try:
                proc = int(rec.get("proc", k.rsplit("/", 1)[-1]))
            except (ValueError, TypeError):
                continue
            if int(rec.get("inc", 0)) < min_incs.get(proc, 0):
                continue
        out.append(k)
    return out


def error_for(seq: int) -> Optional[dict]:
    raw = D.kv_try_get(f"{_PREFIX}/error/{seq}")
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return {"kind": "?", "trace": str(raw)}


def error_records() -> List[Tuple[int, dict]]:
    """All follower replay failures, as (seq, {kind, trace}) sorted by seq
    (the supervisor folds these into the cloud health state)."""
    out = []
    for k, v in D.kv_dir(f"{_PREFIX}/error/"):
        try:
            seq = int(k.rsplit("/", 1)[-1])
            out.append((seq, json.loads(v)))
        except (ValueError, TypeError):
            continue
    return sorted(out, key=lambda t: t[0])


def wait_acks(seq: Optional[int], timeout_s: Optional[float] = None) -> None:
    """Bounded wait until every follower acked replaying op `seq`.

    Raises :class:`~h2o3_tpu.core.failure.CloudUnhealthyError` — carrying
    the follower's traceback when its replay crashed (``oplog/error/{seq}``
    appears), or a timeout diagnosis when a follower went silent. Either
    way the supervisor is notified so the cloud health state degrades and
    subsequent multi-process ops are refused fast. No-op single-process,
    with acks disabled (timeout <= 0), or for a None ticket."""
    if seq is None:
        return
    n = expected_acks()
    if n <= 0:
        return
    if timeout_s is None:
        timeout_s = _ack_timeout_s()
    if timeout_s <= 0:
        return
    from h2o3_tpu.parallel import ckpt, supervisor

    poll = retry.AdaptivePoll(min_s=0.001, max_s=0.25)
    deadline = time.monotonic() + timeout_s
    # one rejoin-record scan per wait, not per poll tick: an incarnation
    # bump mid-wait means the follower crashed, which surfaces through the
    # error/FAILED branches below — the stale-ack floor can't regress
    min_incs = expected_incarnations()
    while True:
        err = error_for(seq)
        if err is not None:
            trace = str(err.get("trace", ""))
            if err.get("fatal", True):
                msg = (f"follower replay of op {seq} ({err.get('kind', '?')}) "
                       f"crashed")
                supervisor.fail(msg, trace)
            else:
                # e.g. a lost ack write: the replay itself succeeded, so
                # states did not diverge — degrade, don't sticky-FAIL
                msg = (f"follower reported a non-fatal oplog fault at op "
                       f"{seq} ({err.get('kind', '?')})")
                supervisor.degrade(msg, hold_s=failure.heartbeat_stale_s())
            raise failure.CloudUnhealthyError(msg, remote_trace=trace)
        if supervisor.state() == supervisor.FAILED:
            # the cloud already failed on ANOTHER op's evidence (a replay
            # crash elsewhere in the stream): no ack for this op is ever
            # coming — bail now with that diagnosis, not a generic
            # timeout 300s later
            st = supervisor.status()
            raise failure.CloudUnhealthyError(
                f"cloud FAILED while waiting for op {seq} acks: "
                f"{st['reason']}", remote_trace=st["remote_trace"])
        got = len(acks_for(seq, _OP_IDS.get(seq), min_incs))
        if got >= n:
            return
        if seq <= ckpt.truncated_through():
            # the compactor truncated this op's records mid-wait: that
            # only happens after the checkpoint op covering it was fully
            # acked, which proves every follower replayed through `seq` —
            # the acks are gone, not missing
            return
        if time.monotonic() >= deadline:
            msg = (f"op {seq}: {got}/{n} follower acks within "
                   f"{timeout_s:.1f}s — follower dead or stalled "
                   f"(H2O_TPU_OP_ACK_TIMEOUT_S bounds this wait)")
            # event-derived degrade: hold it past the next heartbeat
            # evaluation so fresh beats from a wedged-but-beating peer do
            # not instantly erase the evidence
            supervisor.degrade(msg, hold_s=failure.heartbeat_stale_s())
            raise failure.CloudUnhealthyError(msg)
        poll.wait()


# ---------------------------------------------------------------------------
# follower side
# ---------------------------------------------------------------------------

def _ack(seq: int, op_id: Optional[str] = None) -> None:
    """Record this process's replay acknowledgment for op `seq`, carrying
    the op's identity token so the coordinator can tell this replay from
    one of a lost op that previously occupied the same slot.

    A lost ack write is NOT swallowed: silently proceeding would convert a
    SUCCESSFUL replay into a full coordinator ``wait_acks`` stall plus a
    misleading "follower dead" degrade. After a second retry round (on top
    of kv_put's own budget) this best-effort records a NON-fatal error for
    the op — ``wait_acks`` surfaces it immediately with the true story
    instead of a generic timeout, and the supervisor degrades (states did
    not diverge, so the cloud is not FAILED) — then raises
    :class:`OplogAckError`: a follower that cannot write acks cannot
    participate."""
    import jax

    failure.faultpoint("oplog.ack")
    proc = jax.process_index()
    key = f"{_PREFIX}/ack/{seq}/{proc}"
    val = json.dumps({"proc": proc, "ts": time.time(), "op_id": op_id,
                      "inc": failure.incarnation()})
    ok = D.kv_put(key, val)
    for delay in retry.backoff_delays():
        if ok:
            return
        time.sleep(delay)
        ok = D.kv_put(key, val)
    if ok:
        return
    msg = (f"process {proc} replayed op {seq} but could not write its ack "
           f"({key}) — replay succeeded, states did not diverge, but this "
           f"process can no longer confirm replays")
    _record_error(seq, "ack", msg, fatal=False)
    raise OplogAckError(msg)


def _record_error(seq: int, kind: str, trace: str, fatal: bool = True) -> None:
    """Best-effort publish of a follower-side failure for op `seq` so the
    coordinator's ``wait_acks`` and the supervisor see the real story
    instead of a bare timeout. `fatal=False` marks faults where the replay
    itself did NOT diverge (e.g. a lost ack write) — the supervisor
    degrades instead of sticky-FAILing. A loss of the error record itself
    is logged loudly: there is no further channel left."""
    from h2o3_tpu.obs import metrics as obs_metrics

    obs_metrics.inc("h2o3_oplog_errors_total")
    if not D.kv_put(f"{_PREFIX}/error/{seq}",
                    json.dumps({"kind": kind, "trace": trace[-4000:],
                                "fatal": bool(fatal)})):
        from h2o3_tpu.utils.log import get_logger

        get_logger().error(
            "oplog: error record for op %d (%s) could not be written — the "
            "coordinator will only see a generic ack timeout: %s",
            seq, kind, trace[-500:])


def _apply(kind: str, p: Dict[str, Any]) -> None:
    if kind == "noop":
        # liveness probe / chaos-test vehicle: replay + ack with no
        # framework work
        return
    if kind == "checkpoint":
        # coordinator-side snapshot marker: the follower's ack IS its
        # participation (it proves the follower replayed everything before
        # this op, which is what licenses the coordinator's truncation)
        return
    if kind == "import_file":
        from h2o3_tpu.ingest.parser import import_file

        kw = {}
        if p.get("col_names"):
            kw["col_names"] = p["col_names"]
        if p.get("col_types"):
            kw["col_types"] = p["col_types"]
        if p.get("header") is not None:
            kw["header"] = int(p["header"])
        import_file(p["path"], destination_frame=p.get("destination_frame"),
                    **kw)
        return
    if kind == "parse_stream":
        # streaming micro-batch append: every process parses the SAME
        # batch text and grows its own shard tails through the same fused
        # concat programs (ingest/chunked.append_csv), so the sharded
        # frame stays consistent cloud-wide
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.ingest.chunked import append_csv

        fr = DKV.get(p["frame"])
        if fr is None:
            raise KeyError(f"parse_stream: frame {p['frame']!r} not found")
        append_csv(fr, p["data"], p.get("separator") or None)
        return
    if kind == "train":
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.models.model_builder import BUILDERS

        cls = BUILDERS[p["algo"]]
        params = dict(p.get("params") or {})
        train = DKV.get(p["training_frame"])
        valid = DKV.get(p["validation_frame"]) if p.get("validation_frame") \
            else None
        y = p.get("y")
        builder = cls(**params)
        if p.get("resume_job"):
            # resumed dispatch: every process fast-forwards from the SAME
            # durable progress file (shared checkpoint dir), so the device
            # program sequence lines up with the coordinator's continuation.
            # A process that CANNOT read it must fail the replay loudly —
            # silently training from iteration 0 while the coordinator
            # fast-forwards desynchronizes the per-iteration collectives
            # with no error record naming the real cause.
            from h2o3_tpu.parallel import ckpt

            data = ckpt.load_job_progress(p["resume_job"])
            if data is None:
                raise RuntimeError(
                    f"resumed train for job {p['resume_job']}: durable "
                    f"progress is not readable on this process — "
                    f"H2O_TPU_OPLOG_CKPT_DIR must be shared storage for "
                    f"cross-host job resume")
            builder._resume_state = data.get("state")
        model = builder.train(y=y, training_frame=train,
                              validation_frame=valid)
        if p.get("model_id"):
            from h2o3_tpu.core.dkv import Key

            model._key = Key(p["model_id"])
        model.install()
        return
    if kind == "predict":
        from h2o3_tpu.core.dkv import DKV

        m = DKV.get(p["model"])
        fr = DKV.get(p["frame"])
        if p.get("contributions"):
            pred = m.predict_contributions(fr, key=p.get("destination_frame"))
        else:
            pred = m.predict(fr, key=p.get("destination_frame"))
        pred.install()
        if p.get("with_metrics"):
            # the v3 handler also computes metrics: same program sequence
            m.model_performance(fr)
        return
    if kind == "score_batch":
        # the serving fast path's coalesced op: ONE replay scores every
        # request of the coordinator's micro-batch through the same
        # executor (scoring.execute_batch), so the device program sequence
        # — fused traversal dispatches or, multi-process, the generic
        # predict + metrics passes — lines up exactly
        from h2o3_tpu import scoring
        from h2o3_tpu.core.dkv import DKV

        m = DKV.get(p["model"])
        entries = [(DKV.get(r["frame"]), r.get("destination_frame"),
                    bool(r.get("with_metrics")))
                   for r in p.get("requests", [])]
        scoring.execute_batch(m, entries)
        return
    if kind == "rapids":
        from h2o3_tpu.rapids import Session, exec_rapids

        sid = p.get("session_id", "oplog")
        sess = _RAPIDS_SESSIONS.get(sid)
        if sess is None:
            sess = _RAPIDS_SESSIONS[sid] = Session(sid)
        exec_rapids(p["ast"], sess)
        return
    if kind == "leaf_assignment":
        from h2o3_tpu.core.dkv import DKV

        m = DKV.get(p["model"])
        fr = DKV.get(p["frame"])
        pred = m.predict_leaf_node_assignment(fr, type=p["type"],
                                              key=p["destination_frame"])
        pred.install()
        return
    if kind == "staged_proba":
        from h2o3_tpu.core.dkv import DKV

        m = DKV.get(p["model"])
        fr = DKV.get(p["frame"])
        pred = m.staged_predict_proba(fr, key=p["destination_frame"])
        pred.install()
        return
    if kind == "generic":
        from h2o3_tpu.core.dkv import DKV, Key
        from h2o3_tpu.models.generic import Generic

        model = Generic(path=p["path"]).train()
        model._key = Key(p["model_id"])
        DKV.put(p["model_id"], model)
        return
    if kind == "artifact_import":
        # AOT artifact -> servable model, mirrored like "generic": the dir
        # rides the shared-filesystem contract, every process installs the
        # model under the SAME key so later predict ops resolve it
        from h2o3_tpu.artifact import load_model

        load_model(p["dir"], p.get("model_id"))
        return
    if kind == "grid":
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.grid import H2OGridSearch
        from h2o3_tpu.models.model_builder import BUILDERS

        cls = BUILDERS[p["algo"]]
        base = cls(**(p.get("params") or {}))
        grid = H2OGridSearch(base, p["hyper"], grid_id=p["grid_id"],
                             search_criteria=p.get("criteria"))
        train = DKV.get(p["training_frame"])
        valid = DKV.get(p["validation_frame"]) if p.get("validation_frame") \
            else None
        grid.train(y=p.get("y"), training_frame=train,
                   validation_frame=valid)
        return
    if kind == "automl":
        # one op = the WHOLE deterministic build: seed is pinned and
        # max_runtime_secs cleared by the coordinator before broadcast, so
        # every process walks the identical model sequence and the nested
        # device programs line up without per-model ops
        from h2o3_tpu.automl.automl import H2OAutoML
        from h2o3_tpu.core.dkv import DKV

        aml = H2OAutoML(**p["spec"])
        train = DKV.get(p["training_frame"])
        valid = DKV.get(p["validation_frame"]) if p.get("validation_frame") \
            else None
        lb = DKV.get(p["leaderboard_frame"]) if p.get("leaderboard_frame") \
            else None
        aml.train(x=p.get("x"), y=p["y"], training_frame=train,
                  validation_frame=valid, leaderboard_frame=lb)
        # mirror the coordinator's Job.start(dest=project) install so the
        # project key resolves on every process
        DKV.put(p["spec"]["project_name"], aml)
        return
    if kind == "search_resume":
        # re-dispatch of an orphaned AutoML/grid search after a
        # coordinator handoff: every process reloads the SAME durable
        # search state (shared checkpoint dir) and walks the remaining
        # members in plan order, so the device program sequence lines up
        # exactly like the monolithic "automl"/"grid" ops
        from h2o3_tpu.automl import search

        search.apply_resume_op(p)
        return
    raise ValueError(f"unknown oplog op {kind!r}")


def follower_loop(idle_timeout_s: float = 120.0,
                  on_op: Optional[Callable[[str, dict], None]] = None,
                  start_seq: int = 0) -> int:
    """Replay coordinator ops until a 'shutdown' op (or idle timeout).
    Returns the number of ops applied. Runs on every non-coordinator
    process of a multi-process cloud whose coordinator serves REST.

    Each successful replay is acknowledged (``oplog/ack/{seq}/{proc}``);
    a replay crash is surfaced to the cloud (``oplog/error/{seq}`` with
    the traceback) BEFORE re-raising, so the coordinator's `wait_acks`
    and the supervisor see the failure instead of a bare collective hang.
    Polling is adaptive (1→250 ms): hot while ops stream, cheap idle.
    `start_seq` resumes the replay cursor after a checkpoint restore
    (``rejoin()`` returns it): ops before it were truncated or already
    folded into this process's state."""
    i, applied = start_seq, 0
    poll = retry.AdaptivePoll(min_s=0.001, max_s=0.25)
    deadline = time.time() + idle_timeout_s
    while time.time() < deadline:
        raw = D.kv_try_get(f"{_PREFIX}/{i}")
        if raw is None:
            poll.wait()
            continue
        poll.reset()
        op = json.loads(raw)
        if op["kind"] == "shutdown":
            _ack(i, op.get("op_id"))
            return applied
        from h2o3_tpu.obs import metrics as obs_metrics
        from h2o3_tpu.obs import tracing

        # the op's trace context (minted at the coordinator's REST
        # ingress) parents this replay — and the ack nests under the
        # replay — so /3/Trace/{id} shows publish -> replay -> ack
        tctx = op.get("trace")
        t_replay0 = time.time() * 1000.0
        try:
            failure.faultpoint("oplog.replay")
            _apply(op["kind"], op["payload"])
        except Exception:
            # surface the replay failure to the cloud BEFORE dying: the
            # coordinator (and operators reading /3/Cloud health) see the
            # error instead of a bare collective hang. The crash flag lets
            # this process's recovery watchdog auto-rejoin.
            global _REPLAY_CRASHED
            _REPLAY_CRASHED = True
            _record_error(i, op["kind"], traceback.format_exc())
            tracing.record_span("oplog.replay", tctx, t_replay0,
                                publish=True, status="error",
                                kind=op["kind"], seq=i)
            raise
        t_ack0 = time.time() * 1000.0
        _ack(i, op.get("op_id"))
        # span KV writes happen AFTER the ack landed: tracing must never
        # add latency to the coordinator's wait_acks path
        rsp = tracing.record_span("oplog.replay", tctx, t_replay0, t_ack0,
                                  publish=True, kind=op["kind"], seq=i)
        tracing.record_span(
            "oplog.ack",
            {"trace_id": tctx["trace_id"],
             "span_id": rsp["span_id"]} if rsp else None,
            t_ack0, publish=True, seq=i)
        obs_metrics.inc("h2o3_oplog_ops_replayed_total")
        # keep this follower's published metrics snapshot fresh for the
        # coordinator's cluster-wide /3/Metrics (throttled)
        obs_metrics.maybe_publish()
        note_op_seen()        # adaptive replay-idle signal (traffic clock)
        if on_op is not None:
            on_op(op["kind"], op["payload"])
        applied += 1
        i += 1
        deadline = time.time() + idle_timeout_s
    raise TimeoutError(f"oplog follower idle for {idle_timeout_s}s at op {i}")


# ---------------------------------------------------------------------------
# follower readmission (rejoin) — water/Paxos.java re-admission analog:
# a restarted node re-derives state (here: checkpoint + oplog suffix)
# instead of the cloud staying FAILED forever
# ---------------------------------------------------------------------------

_REJOIN_PREFIX = f"{_PREFIX}/rejoin/"


def _write_rejoin(proc: int, inc: int, phase: str, seq: int) -> None:
    D.kv_put(f"{_REJOIN_PREFIX}{proc}",
             json.dumps({"proc": proc, "inc": inc, "phase": phase,
                         "seq": int(seq), "ts": time.time()}))


def rejoin_records() -> List[dict]:
    """Per-process readmission records ({proc, inc, phase, seq, ts}),
    sorted by proc. Phase is 'replaying' while the suffix replay runs and
    'caught_up' once the process reached the oplog head."""
    out = []
    for _k, v in D.kv_dir(_REJOIN_PREFIX):
        try:
            rec = json.loads(v)
        except (ValueError, TypeError):
            continue
        if isinstance(rec, dict):       # truncated/corrupt record: skip
            out.append(rec)
    return sorted(out, key=lambda r: r.get("proc", -1))


def expected_incarnations() -> Dict[int, int]:
    """Minimum acceptable incarnation per process: a proc that rejoined at
    incarnation i must ack with inc >= i — anything older is a leftover
    from its dead predecessor."""
    return {int(r["proc"]): int(r.get("inc", 0)) for r in rejoin_records()
            if r.get("proc") is not None}


def rejoin() -> int:
    """Readmit THIS restarted process: bump the incarnation, restore the
    latest control-plane checkpoint, replay the acknowledged oplog suffix
    (acking each op under the fresh incarnation), delete the failure
    evidence this replay supersedes, and publish a 'caught_up' rejoin
    record the supervisor folds into FAILED -> RECOVERING -> HEALTHY.

    Returns the caught-up sequence — pass it to ``follower_loop(...,
    start_seq=...)`` to keep replaying live ops. A crash during the
    suffix replay records ``oplog/error/{seq}`` like the normal loop (the
    cloud re-FAILs with the true story) and re-raises.

    A DEMOTED ex-coordinator rejoining this way is restored to service:
    it adopts the newer epoch's leadership view, and on a successful
    catch-up the demotion flag and the supervisor's demotion hold are
    cleared — this is exactly the "rejoin() as a follower" remediation
    the demotion error advertises."""
    global _DEMOTED, _REPLAY_CRASHED
    import jax

    from h2o3_tpu.parallel import ckpt

    proc = jax.process_index()
    rec = D.epoch_record()
    if rec["epoch"] >= D.epoch():
        # adopt the cloud's current leadership view before replaying: a
        # standby may have taken a newer epoch while this process was down
        D.set_leader(rec["leader"], rec["epoch"])
    # a REAL process restart boots with the local incarnation counter at
    # 0 — seed it from the cloud's evidence (heartbeat table + standing
    # rejoin record) first, or the second crash/restart cycle would rejoin
    # at an incarnation the supervisor's strictly-newer FAILED->RECOVERING
    # gate has already seen and the cloud would stay FAILED forever
    on_record = expected_incarnations().get(proc, 0)
    for r in failure.cluster_health(stale_after_s=float("inf")):
        if r.get("process") == proc:
            on_record = max(on_record, int(r.get("incarnation", 0)))
    if failure.incarnation() < on_record:
        failure.set_incarnation(on_record)
    inc = failure.bump_incarnation()
    failure.heartbeat()                    # announce the fresh incarnation
    cursor, _snap = ckpt.load_latest()
    _write_rejoin(proc, inc, "replaying", cursor)
    while True:
        raw = D.kv_try_get(f"{_PREFIX}/{cursor}")
        if raw is None:
            break                          # reached the head
        op = json.loads(raw)
        if op["kind"] == "shutdown":
            break
        try:
            failure.faultpoint("oplog.rejoin.replay")
            _apply(op["kind"], op["payload"])
        except Exception:
            _record_error(cursor, op["kind"], traceback.format_exc())
            raise
        _ack(cursor, op.get("op_id"))
        cursor += 1
    # a successful re-replay through `cursor` supersedes the dead
    # incarnation's failure evidence for those ops: the programs ARE
    # replayable, and this process's state now includes them
    for s, _rec in error_records():
        if s < cursor:
            D.kv_delete(f"{_PREFIX}/error/{s}")
    _write_rejoin(proc, inc, "caught_up", cursor)
    _REPLAY_CRASHED = False          # readmitted: the crashed loop's state
    if _DEMOTED:                     # was rebuilt from ckpt + suffix
        # caught up as a follower of the new epoch: the demotion did its
        # job. Clear the flag and lift the supervisor's infinite demotion
        # hold so liveness evidence can recover the health state.
        _DEMOTED = False
        from h2o3_tpu.parallel import supervisor

        supervisor.release_hold()
    from h2o3_tpu.obs import metrics as obs_metrics
    from h2o3_tpu.utils import timeline

    obs_metrics.inc("h2o3_oplog_rejoins_total")
    timeline.record("cloud", "rejoin", proc=proc, inc=inc,
                    caught_up_seq=cursor)
    return cursor


# ---------------------------------------------------------------------------
# standby-coordinator handoff — water/Paxos.java leader = lowest live node.
# A follower assumes coordination when the coordinator's heartbeat stays
# silent past the election grace; the old coordinator, if it returns,
# detects the newer epoch and demotes.
# ---------------------------------------------------------------------------

class ElectionLost(RuntimeError):
    """This process is not the deterministic election winner (the lowest
    live process index), or the coordinator is not dead enough yet."""


def _sealed_next_seq(caught_up_seq: Optional[int] = None) -> int:
    """Where the new epoch's sequence starts: past everything any
    follower acknowledged, past the newest checkpoint, and past whatever
    the caller itself replayed — the new coordinator must never reuse a
    slot some process already ran a program for."""
    from h2o3_tpu.parallel import ckpt

    hi = -1
    for k, _v in D.kv_dir(f"{_PREFIX}/ack/"):
        parts = k.split("/")
        if len(parts) >= 3 and parts[1] == "ack" and parts[2].isdigit():
            hi = max(hi, int(parts[2]))
    rec = ckpt.latest()
    if rec is not None:
        hi = max(hi, int(rec[1].get("next_seq", rec[0] + 1)) - 1)
    if caught_up_seq is not None:
        hi = max(hi, int(caught_up_seq) - 1)
    return hi + 1


def assume_coordination(caught_up_seq: Optional[int] = None,
                        force: bool = False) -> dict:
    """Deterministic standby takeover (Paxos-lite: lowest live process
    index wins). Preconditions unless `force`: the recorded leader's
    heartbeat is silent past ``H2O_TPU_ELECTION_GRACE_S`` AND this
    process is the lowest-indexed live one. On win: seal the old epoch's
    oplog at the last acknowledged sequence, write the new epoch record,
    adopt leadership locally (``distributed.is_coordinator`` flips), and
    reset the turnstile at the sealed sequence. Device-resident scoring
    sessions are dropped (they rebuild from the DKV on first use).

    Returns {epoch, leader, next_seq}. The caller re-binds the REST
    server (``api.server.assume_coordination`` does both)."""
    import jax

    proc = jax.process_index()
    rec = D.epoch_record()
    old_leader, old_epoch = rec["leader"], rec["epoch"]
    if not force:
        if proc == old_leader:
            raise ElectionLost(
                f"process {proc} already leads epoch {old_epoch}")
        grace = failure.election_grace_s()
        health = failure.cluster_health(stale_after_s=grace)
        by_proc = {r["process"]: r for r in health}
        lead_row = by_proc.get(old_leader)
        if lead_row is not None and lead_row["age_s"] < grace:
            raise ElectionLost(
                f"coordinator {old_leader} beat {lead_row['age_s']:.1f}s "
                f"ago — inside the election grace "
                f"({grace:.1f}s, H2O_TPU_ELECTION_GRACE_S); not assuming")
        live = sorted(r["process"] for r in failure.cluster_health()
                      if r["healthy"] and r["process"] != old_leader)
        winner = live[0] if live else proc
        if winner != proc:
            raise ElectionLost(
                f"election winner is process {winner} (lowest live index; "
                f"this is {proc}) — standing by")
    failure.faultpoint("oplog.election")
    sealed_next = _sealed_next_seq(caught_up_seq)
    D.kv_put(f"{_PREFIX}/sealed/{old_epoch}",
             json.dumps({"next_seq": sealed_next, "by": proc,
                         "ts": time.time()}))
    new_epoch = old_epoch + 1
    if not D.write_epoch_record(new_epoch, proc):
        raise failure.CloudUnhealthyError(
            f"could not write epoch record {new_epoch} — election aborted")
    # the epoch record is a last-writer-wins upsert: a concurrent standby
    # racing this election may have written its own claim on top of ours.
    # Re-read before adopting leadership — the overwritten claimant is the
    # only one who can see it lost (the overwriter never sees our write),
    # so it must stand down here; maybe_demote's same-epoch check catches
    # the residual window where the overwrite lands after this read-back.
    rb = D.epoch_record()
    if rb["epoch"] != new_epoch or rb["leader"] != proc:
        D.set_leader(rb["leader"], rb["epoch"])
        raise ElectionLost(
            f"concurrent election: process {rb['leader']} claimed epoch "
            f"{rb['epoch']} over this claim of {new_epoch} — standing down")
    D.set_leader(proc, new_epoch)
    global _DEMOTED
    _DEMOTED = False
    reset(next_seq=sealed_next)
    # device-resident scoring sessions belonged to the old epoch's program
    # stream; drop them so first use rebuilds from the (checkpoint-
    # restored) DKV models on THIS process's devices
    from h2o3_tpu import scoring

    scoring.purge()
    # supervision restarts from evidence: the dead old leader's stale beat
    # will degrade the cloud until it rejoins as a follower
    from h2o3_tpu.parallel import supervisor

    supervisor.reset()
    failure.heartbeat()
    from h2o3_tpu.utils import timeline

    timeline.record("cloud", "assume_coordination", epoch=new_epoch,
                    leader=proc, next_seq=sealed_next)
    from h2o3_tpu.utils.log import get_logger

    get_logger().warning(
        "process %d assumed cloud coordination: epoch %d (was %d led by "
        "%d), oplog sealed at next_seq=%d", proc, new_epoch, old_epoch,
        old_leader, sealed_next)
    return {"epoch": new_epoch, "leader": proc, "next_seq": sealed_next}


def maybe_demote() -> Optional[dict]:
    """Leadership-view refresh: if the cloud's epoch record is newer than
    this process's view, adopt it. When this process BELIEVED it was the
    coordinator (it returned from a stall to find a standby leading a
    newer epoch), it demotes: the flag makes `broadcast` refuse ops, and
    the supervisor records why. Returns the adopted record, else None."""
    global _DEMOTED
    import jax

    rec = D.epoch_record()
    if rec["epoch"] < D.epoch():
        return None
    if rec["epoch"] == D.epoch() and rec["leader"] == D.leader():
        return None
    # same-epoch leader mismatch happens when two standbys raced an
    # election and both wrote epoch N+1 (the record is a last-writer-wins
    # upsert): the overwritten winner must discover it lost here, or the
    # cloud splits brain with two coordinators publishing under one epoch
    was_leading = D.is_coordinator()
    D.set_leader(rec["leader"], rec["epoch"])
    if was_leading and rec["leader"] != jax.process_index():
        _DEMOTED = True
        from h2o3_tpu.parallel import supervisor

        supervisor.degrade(
            f"demoted: process {rec['leader']} assumed coordination "
            f"(epoch {rec['epoch']}) while this ex-coordinator was away — "
            "rejoin() as a follower or restart this process",
            hold_s=float("inf"))
        from h2o3_tpu.utils import timeline

        timeline.record("cloud", "demoted", epoch=rec["epoch"],
                        leader=rec["leader"])
    return rec


def follower_lag() -> List[dict]:
    """Per-follower replay progress for GET /3/CloudStatus: last acked
    sequence, ack lag vs the coordinator's published head, incarnation.
    Truncated (checkpointed) acks count as caught-up-to-checkpoint."""
    from h2o3_tpu.parallel import ckpt

    head = current_seq()                 # ops < head are published
    last: Dict[int, int] = {}
    incs: Dict[int, int] = {}
    for k, v in D.kv_dir(f"{_PREFIX}/ack/"):
        parts = k.split("/")
        if len(parts) < 4 or not parts[2].isdigit():
            continue
        try:
            s, p = int(parts[2]), int(parts[3])
        except ValueError:
            continue
        if s >= last.get(p, -1):
            last[p] = s
            try:
                rec = json.loads(v)
            except (ValueError, TypeError):
                rec = None
            if isinstance(rec, dict):   # guard like acks_for: a corrupt
                incs[p] = int(rec.get("inc", 0))   # ack must not 500 the
                                                   # status route
    base = ckpt.latest_seq()
    exp_incs = expected_incarnations()
    procs = set(last) | set(exp_incs)
    rows = []
    for p in sorted(procs):
        la = last.get(p, base if base is not None else -1)
        rows.append({"process": p,
                     "incarnation": incs.get(p, exp_incs.get(p, 0)),
                     "last_acked_seq": la,
                     "ack_lag": max(head - 1 - la, 0)})
    return rows
