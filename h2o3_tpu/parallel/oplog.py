"""Operation log — the cross-process control plane for REST-driven work.

Reference: in the JVM cloud any node can accept a REST request and fan the
work out over the RPC layer (water/RPC.java + MRTask dispatch). Under SPMD
multi-controller JAX there is no RPC: every process must enter the SAME
jitted collective program. This module gives the coordinator a way to make
that happen for REST-initiated operations: the coordinator appends ops to
a sequence in the jax.distributed coordination-service KV, follower
processes replay them in order (`follower_loop`), and both sides execute
the identical framework call — so the shard_map programs line up and the
collectives complete.

Ops carry ONLY metadata (paths, keys, params) — data stays sharded on
device; files are read from the shared filesystem by every process, the
same contract the parse tier already uses.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from h2o3_tpu.parallel import distributed as D

_SEQ = 0
_PREFIX = "oplog"
_RAPIDS_SESSIONS: Dict[str, Any] = {}     # follower-side session mirror

# coordinator-side execution turnstile: broadcast order == device-program
# order. REST jobs run in background threads, so without this two
# concurrent requests could enter their shard_map programs in the opposite
# order from the follower's strictly sequential replay — a mesh deadlock.
_EXEC_COND = threading.Condition()
_NEXT_EXEC = 0
# publish() runs on concurrent REST handler threads: sequence allocation
# and the kv_put must be atomic or two ops can claim the same slot (one
# overwrites the other in the KV and the follower stalls at the gap)
_PUB_LOCK = threading.Lock()


# reentrancy guard: while the coordinator executes an op inside turn() (or
# a follower replays one in _apply), nested framework calls — AutoML's base
# models, CV submodels, grid entries — must NOT broadcast their own ops:
# the follower replays the TOP-level op and re-runs the nested programs
# itself, so a nested broadcast would double-execute them on the follower.
_TLS = threading.local()

# set by api.server.start_server: this process is the coordinator of a
# REST-driven cloud, so device/collective work on handler threads is only
# legal inside a broadcast op's turn (the follower replays ops, nothing
# else). Framework internals consult this to fail fast instead of entering
# a collective the follower will never join.
REST_SERVING = False


def _in_op() -> bool:
    return bool(getattr(_TLS, "in_op", False))


def unmirrored_collective_risk() -> bool:
    """True when the calling thread is about to run a collective the other
    processes will NOT mirror: coordinator of a REST-serving multi-process
    cloud, outside any op turn."""
    return (REST_SERVING and D.process_count() > 1 and D.is_coordinator()
            and not _in_op())


def active() -> bool:
    """Coordinator with followers attached: REST handlers must broadcast."""
    return D.process_count() > 1 and D.is_coordinator() and not _in_op()


def publish(kind: str, payload: Dict[str, Any]) -> int:
    """Append one op (coordinator only); followers replay in sequence.
    Returns the op's sequence number (the coordinator's execution ticket)."""
    global _SEQ
    with _PUB_LOCK:
        seq = _SEQ
        _SEQ += 1
        D.kv_put(f"{_PREFIX}/{seq}",
                 json.dumps({"kind": kind, "payload": payload}))
    return seq


def broadcast(kind: str, payload: Dict[str, Any]) -> Optional[int]:
    """Publish when this process is the coordinator of a live multi-process
    cloud; no-op single-process (the common local path pays nothing).
    Returns the execution ticket (None single-process)."""
    if active():
        return publish(kind, payload)
    return None


@contextlib.contextmanager
def turn(seq: Optional[int]):
    """Hold the coordinator's device-execution turnstile for op `seq`:
    ops run their device programs in exactly broadcast order, matching the
    follower's sequential replay. No-op when seq is None."""
    global _NEXT_EXEC
    if seq is None:
        yield
        return
    with _EXEC_COND:
        while _NEXT_EXEC != seq:
            _EXEC_COND.wait(timeout=1.0)
    _TLS.in_op = True
    try:
        yield
    finally:
        _TLS.in_op = False
        with _EXEC_COND:
            _NEXT_EXEC = seq + 1
            _EXEC_COND.notify_all()


# ---------------------------------------------------------------------------
# follower side
# ---------------------------------------------------------------------------

def _apply(kind: str, p: Dict[str, Any]) -> None:
    if kind == "import_file":
        from h2o3_tpu.ingest.parser import import_file

        kw = {}
        if p.get("col_names"):
            kw["col_names"] = p["col_names"]
        if p.get("col_types"):
            kw["col_types"] = p["col_types"]
        if p.get("header") is not None:
            kw["header"] = int(p["header"])
        import_file(p["path"], destination_frame=p.get("destination_frame"),
                    **kw)
        return
    if kind == "train":
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.models.model_builder import BUILDERS

        cls = BUILDERS[p["algo"]]
        params = dict(p.get("params") or {})
        train = DKV.get(p["training_frame"])
        valid = DKV.get(p["validation_frame"]) if p.get("validation_frame") \
            else None
        y = p.get("y")
        model = cls(**params).train(y=y, training_frame=train,
                                    validation_frame=valid)
        if p.get("model_id"):
            from h2o3_tpu.core.dkv import Key

            model._key = Key(p["model_id"])
        model.install()
        return
    if kind == "predict":
        from h2o3_tpu.core.dkv import DKV

        m = DKV.get(p["model"])
        fr = DKV.get(p["frame"])
        if p.get("contributions"):
            pred = m.predict_contributions(fr, key=p.get("destination_frame"))
        else:
            pred = m.predict(fr, key=p.get("destination_frame"))
        pred.install()
        if p.get("with_metrics"):
            # the v3 handler also computes metrics: same program sequence
            m.model_performance(fr)
        return
    if kind == "score_batch":
        # the serving fast path's coalesced op: ONE replay scores every
        # request of the coordinator's micro-batch through the same
        # executor (scoring.execute_batch), so the device program sequence
        # — fused traversal dispatches or, multi-process, the generic
        # predict + metrics passes — lines up exactly
        from h2o3_tpu import scoring
        from h2o3_tpu.core.dkv import DKV

        m = DKV.get(p["model"])
        entries = [(DKV.get(r["frame"]), r.get("destination_frame"),
                    bool(r.get("with_metrics")))
                   for r in p.get("requests", [])]
        scoring.execute_batch(m, entries)
        return
    if kind == "rapids":
        from h2o3_tpu.rapids import Session, exec_rapids

        sid = p.get("session_id", "oplog")
        sess = _RAPIDS_SESSIONS.get(sid)
        if sess is None:
            sess = _RAPIDS_SESSIONS[sid] = Session(sid)
        exec_rapids(p["ast"], sess)
        return
    if kind == "leaf_assignment":
        from h2o3_tpu.core.dkv import DKV

        m = DKV.get(p["model"])
        fr = DKV.get(p["frame"])
        pred = m.predict_leaf_node_assignment(fr, type=p["type"],
                                              key=p["destination_frame"])
        pred.install()
        return
    if kind == "staged_proba":
        from h2o3_tpu.core.dkv import DKV

        m = DKV.get(p["model"])
        fr = DKV.get(p["frame"])
        pred = m.staged_predict_proba(fr, key=p["destination_frame"])
        pred.install()
        return
    if kind == "generic":
        from h2o3_tpu.core.dkv import DKV, Key
        from h2o3_tpu.models.generic import Generic

        model = Generic(path=p["path"]).train()
        model._key = Key(p["model_id"])
        DKV.put(p["model_id"], model)
        return
    if kind == "grid":
        from h2o3_tpu.core.dkv import DKV
        from h2o3_tpu.grid import H2OGridSearch
        from h2o3_tpu.models.model_builder import BUILDERS

        cls = BUILDERS[p["algo"]]
        base = cls(**(p.get("params") or {}))
        grid = H2OGridSearch(base, p["hyper"], grid_id=p["grid_id"],
                             search_criteria=p.get("criteria"))
        train = DKV.get(p["training_frame"])
        valid = DKV.get(p["validation_frame"]) if p.get("validation_frame") \
            else None
        grid.train(y=p.get("y"), training_frame=train,
                   validation_frame=valid)
        return
    if kind == "automl":
        # one op = the WHOLE deterministic build: seed is pinned and
        # max_runtime_secs cleared by the coordinator before broadcast, so
        # every process walks the identical model sequence and the nested
        # device programs line up without per-model ops
        from h2o3_tpu.automl.automl import H2OAutoML
        from h2o3_tpu.core.dkv import DKV

        aml = H2OAutoML(**p["spec"])
        train = DKV.get(p["training_frame"])
        valid = DKV.get(p["validation_frame"]) if p.get("validation_frame") \
            else None
        lb = DKV.get(p["leaderboard_frame"]) if p.get("leaderboard_frame") \
            else None
        aml.train(x=p.get("x"), y=p["y"], training_frame=train,
                  validation_frame=valid, leaderboard_frame=lb)
        # mirror the coordinator's Job.start(dest=project) install so the
        # project key resolves on every process
        DKV.put(p["spec"]["project_name"], aml)
        return
    raise ValueError(f"unknown oplog op {kind!r}")


def follower_loop(idle_timeout_s: float = 120.0,
                  on_op: Optional[Callable[[str, dict], None]] = None) -> int:
    """Replay coordinator ops until a 'shutdown' op (or idle timeout).
    Returns the number of ops applied. Runs on every non-coordinator
    process of a multi-process cloud whose coordinator serves REST."""
    i, applied = 0, 0
    deadline = time.time() + idle_timeout_s
    while time.time() < deadline:
        raw = D.kv_try_get(f"{_PREFIX}/{i}")
        if raw is None:
            time.sleep(0.05)
            continue
        op = json.loads(raw)
        if op["kind"] == "shutdown":
            return applied
        try:
            _apply(op["kind"], op["payload"])
        except Exception:
            # surface the replay failure to the cloud BEFORE dying: the
            # coordinator (and operators reading /3/Cloud health) see the
            # error instead of a bare collective hang
            D.kv_put(f"{_PREFIX}/error/{i}",
                     json.dumps({"kind": op["kind"],
                                 "trace": traceback.format_exc()[-4000:]}))
            raise
        if on_op is not None:
            on_op(op["kind"], op["payload"])
        applied += 1
        i += 1
        deadline = time.time() + idle_timeout_s
    raise TimeoutError(f"oplog follower idle for {idle_timeout_s}s at op {i}")
