"""Mesh construction and sharding rules.

Replaces H2O's node membership + key homing (water/Key.java:88-107) with a
`jax.sharding.Mesh`. Axes:
- 'rows'  : data parallelism — every Frame column is sharded on this axis
            (the chunk-scatter analog).
- 'model' : model/tensor parallelism for wide linear algebra (Gram blocks,
            wide MLP layers) — a capability the reference lacks (SURVEY.md
            §2.11: "Pipeline/model parallelism: absent"); on TPU it is nearly
            free to provide via PartitionSpec.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(devices=None, shape: Optional[Tuple[int, int]] = None,
              axes: Sequence[str] = ("rows", "model")):
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if shape is None:
        shape = (n, 1)
    grid = np.array(devices).reshape(shape)
    return Mesh(grid, tuple(axes[: grid.ndim]))


def row_spec():
    from jax.sharding import PartitionSpec as P

    return P("rows")


def replicated_spec():
    from jax.sharding import PartitionSpec as P

    return P()


def shard_rows(arr, mesh=None):
    """Pin a host array into HBM row-sharded. Multi-process safe: each
    process materializes only its addressable shards."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding

    if mesh is None:
        from h2o3_tpu.core.runtime import cluster

        mesh = cluster().mesh
    sh = NamedSharding(mesh, row_spec())
    if jax.process_count() > 1 and isinstance(arr, np.ndarray):
        return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])
    return jax.device_put(arr, sh)
