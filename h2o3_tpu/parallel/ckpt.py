"""Oplog checkpoint + compaction: bound the control-plane KV footprint.

Reference: H2O-3 never replays history — any node can re-derive state from
the DKV (SURVEY §1, water/H2O.java), so its control plane carries no log.
Our REST-driven oplog DOES carry one (parallel/oplog.py), and before this
module every op slot and ack lived in the coordination KV forever. Podracer
TPU fleets (arXiv:2104.06272) checkpoint/restore workers as the NORMAL
response to preemption; this is that layer for the cloud control plane:

- every ``H2O_TPU_OPLOG_CHECKPOINT_OPS`` fully-acknowledged ops the
  coordinator publishes a ``checkpoint`` op; inside its execution turn
  (turnstile held: no other op mutates the DKV) it serializes a consistent
  control-plane snapshot — DKV-resident objects (models, frames, jobs'
  metadata), announced key metadata + replicated blobs, the next oplog
  sequence and the recent op identity tokens — to a file under the
  checkpoint dir, recording ``oplog/ckpt/{seq}`` in the cloud KV;
- once the checkpoint op is fully acked (every follower has replayed
  through it), the acknowledged prefix — ``oplog/{s}`` slots and their
  ``oplog/ack/{s}/*`` records for s <= seq — is truncated, so live oplog
  keys stay O(interval) no matter how many ops the cloud has served;
- a restarted follower readmits from the newest checkpoint
  (``oplog.rejoin``): restore the snapshot, replay the suffix, re-register
  with a fresh incarnation.

Checkpoint paths resolve through ``persist/`` on load, so a checkpoint dir
on shared storage (file:// today, s3:// etc. via the scheme registry) lets
a follower restarted on a DIFFERENT host readmit too.
"""

from __future__ import annotations

import io
import json
import os
import pickle
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from h2o3_tpu.core import failure
from h2o3_tpu.parallel import distributed as D
from h2o3_tpu.parallel import retry
from h2o3_tpu.utils import unpickle

_CKPT_PREFIX = "oplog/ckpt/"

# acked-op counter since the last checkpoint + single-flight guard: two
# handler threads crossing the threshold together must not both publish a
# checkpoint op
_LOCK = threading.Lock()
_ACKED_SINCE = 0
_IN_CKPT = False
_CKPT_THREAD: Optional[threading.Thread] = None
# seq of the in-flight (or last) checkpoint op: its OWN ack must not count
# toward the next interval, but user ops acked while an async checkpoint
# is still truncating DO — otherwise a slow snapshot under load silently
# stretches the effective interval past H2O_TPU_OPLOG_CHECKPOINT_OPS and
# the documented O(interval) bound on live oplog keys
_CKPT_SEQ: Optional[int] = None
# highest seq whose slots + acks were truncated. Truncation only runs after
# the checkpoint op is FULLY acked (every follower replayed through it), so
# an op at or below this floor is proven-acknowledged even though its ack
# records are gone — oplog.wait_acks consults it so a waiter still polling
# for an op the compactor just truncated returns instead of timing out.
_TRUNCATED_THROUGH = -1


def interval_ops() -> int:
    """Checkpoint every N fully-acked ops (env
    ``H2O_TPU_OPLOG_CHECKPOINT_OPS``, default 64; <= 0 disables)."""
    return retry.env_int("H2O_TPU_OPLOG_CHECKPOINT_OPS", 64)


def keep_ckpts() -> int:
    """Control-plane snapshots retained after a newer checkpoint is fully
    acked (env ``H2O_TPU_OPLOG_CKPT_KEEP``, default 3; <= 0 keeps all)."""
    return retry.env_int("H2O_TPU_OPLOG_CKPT_KEEP", 3)


def job_ckpt_iters() -> int:
    """Iterative trainers persist durable per-job progress every N
    completed iterations (env ``H2O_TPU_JOB_CKPT_ITERS``; 0 — the default
    — disables, keeping library-mode training cost-free)."""
    return retry.env_int("H2O_TPU_JOB_CKPT_ITERS", 0)


def ckpt_dir() -> str:
    d = os.environ.get("H2O_TPU_OPLOG_CKPT_DIR") or os.path.join(
        os.environ.get("H2O_TPU_ICE_ROOT", "/tmp/h2o3_tpu"), "oplog_ckpt")
    os.makedirs(d, exist_ok=True)
    return d


def async_enabled() -> bool:
    """Run interval checkpoints on a background thread (env
    ``H2O_TPU_OPLOG_CKPT_ASYNC``, default on). The snapshot + cloud-wide
    ack of the checkpoint op can take seconds; the user request that
    happened to cross the interval threshold should not absorb that
    latency. The chaos tests pin this off: a synchronous checkpoint lands
    at a deterministic sequence position."""
    return retry.env_int("H2O_TPU_OPLOG_CKPT_ASYNC", 1) != 0


def reset() -> None:
    """Clear the coordinator-side counter (cloud restart / tests)."""
    global _ACKED_SINCE, _TRUNCATED_THROUGH, _CKPT_SEQ
    with _LOCK:
        _ACKED_SINCE = 0
        _TRUNCATED_THROUGH = -1
        _CKPT_SEQ = None


def truncated_through() -> int:
    """Highest seq compacted away — every op at or below it was fully
    acknowledged cloud-wide before its records were deleted (-1: none)."""
    return _TRUNCATED_THROUGH


def wait_idle(timeout_s: float = 30.0) -> bool:
    """Join an in-flight background checkpoint, if any (tests / orderly
    shutdown). True when no checkpoint is running on return."""
    t = _CKPT_THREAD
    if t is not None and t.is_alive():
        t.join(timeout_s)
        return not t.is_alive()
    return True


class _CkptUnpickler(unpickle.RestrictedUnpickler):
    """Framework/numeric types only — a checkpoint file (possibly fetched
    from shared storage) must not smuggle arbitrary callables, same
    contract as the binary-artifact loader in api/routes_ext.py. The
    allowlist lives in utils/unpickle.py (shared with Model.load,
    assembly load and the DKV blob fetch)."""

    what = "checkpoint"


def _loads(data: bytes) -> Any:
    return _CkptUnpickler(io.BytesIO(data)).load()


# ---------------------------------------------------------------------------
# coordinator side: write + truncate
# ---------------------------------------------------------------------------

def note_acked_op(seq: int) -> None:
    """Called by the coordinator after op `seq` is fully acknowledged
    (oplog.turn's tail). Every ``interval_ops()`` acked ops, takes a
    checkpoint and truncates the acknowledged prefix. Never raises: a
    checkpoint failure must not fail the user op that crossed the
    threshold — the next acked op simply re-tries."""
    global _ACKED_SINCE, _IN_CKPT, _CKPT_THREAD
    n = interval_ops()
    if n <= 0:
        return
    with _LOCK:
        if seq == _CKPT_SEQ:            # the checkpoint op's own ack
            return
        _ACKED_SINCE += 1
        if _ACKED_SINCE < n or _IN_CKPT:
            return                      # counted; _IN_CKPT only gates the
                                        # single-flight spawn — the next op
                                        # acked after it clears triggers
        _IN_CKPT = True
        _ACKED_SINCE = 0
    if async_enabled():
        # off the acked op's thread: the checkpoint op still serializes
        # behind the turnstile like any other op, but the user request
        # that crossed the threshold returns without paying for the
        # snapshot or the cloud-wide ack wait
        t = threading.Thread(target=_run_checkpoint, daemon=True,
                             name="h2o3-oplog-ckpt")
        with _LOCK:
            _CKPT_THREAD = t
        t.start()
    else:
        _run_checkpoint()


def _run_checkpoint() -> None:
    global _IN_CKPT
    try:
        checkpoint_now()
    except Exception as e:   # noqa: BLE001 — best-effort by contract
        from h2o3_tpu.utils.log import get_logger

        get_logger().warning("oplog checkpoint failed (will retry at the "
                             "next interval): %s", e)
    finally:
        with _LOCK:
            _IN_CKPT = False


def checkpoint_now() -> Optional[int]:
    """Publish + execute one ``checkpoint`` op: snapshot under the
    turnstile (no concurrent op is mutating the DKV), then — once every
    follower acked it — truncate the acknowledged prefix. Returns the
    checkpoint's sequence (None when the cloud is not broadcasting or
    this process no longer leads it: an async checkpoint thread resuming
    on a stalled ex-coordinator must not publish at a stale seq — or
    truncate records in the SHARED KV — under an epoch it lost."""
    global _CKPT_SEQ
    from h2o3_tpu.parallel import oplog

    oplog.maybe_demote()
    if oplog.demoted() or not oplog.active():
        return None
    epoch0 = D.epoch()
    seq = oplog.publish("checkpoint", {})
    with _LOCK:
        _CKPT_SEQ = seq
    with oplog.turn(seq):
        write_checkpoint(seq)
    # turn()'s exit completed wait_acks(seq): every follower replayed
    # through seq, so the prefix (seq included) is dead weight — unless
    # leadership moved while we snapshotted, in which case the records
    # now belong to the new coordinator's epoch and are not ours to drop
    oplog.maybe_demote()
    if oplog.demoted() or not D.is_coordinator() or D.epoch() != epoch0:
        return None
    truncate_through(seq)
    return seq


def write_checkpoint(seq: int) -> str:
    """Serialize the control-plane snapshot for checkpoint op `seq` and
    record it at ``oplog/ckpt/{seq}``. The snapshot's ``next_seq`` is
    seq + 1: state includes ops < seq, and op seq is the checkpoint
    itself (no state change), so a restorer resumes replay after it."""
    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.parallel import oplog

    failure.faultpoint("ckpt.write")
    snap = {
        "seq": int(seq),
        "next_seq": int(seq) + 1,
        "epoch": D.epoch(),
        "ts": time.time(),
        "op_ids": oplog.snapshot_op_ids(),
        "dkv": DKV.snapshot_control_plane(),
    }
    path = os.path.join(ckpt_dir(), f"ckpt_{int(seq):012d}.pkl")
    tmp = path + ".part"
    with open(tmp, "wb") as f:
        pickle.dump(snap, f)
    os.replace(tmp, path)                  # readers never see a torn file
    if not D.kv_put(_CKPT_PREFIX + str(int(seq)),
                    json.dumps({"seq": int(seq), "next_seq": int(seq) + 1,
                                "path": path, "epoch": D.epoch(),
                                "ts": snap["ts"],
                                "skipped": snap["dkv"].get("skipped", [])})):
        raise RuntimeError(f"checkpoint {seq}: KV record did not land")
    _prune_old()
    from h2o3_tpu.utils import timeline

    timeline.record("oplog", "checkpoint", seq=int(seq),
                    objects=len(snap["dkv"].get("objects", {})),
                    skipped=len(snap["dkv"].get("skipped", [])))
    return path


def records() -> List[Tuple[int, dict]]:
    """All checkpoint records, sorted by seq."""
    out = []
    for k, v in D.kv_dir(_CKPT_PREFIX):
        try:
            out.append((int(k.rsplit("/", 1)[-1]), json.loads(v)))
        except (ValueError, TypeError):
            continue
    return sorted(out, key=lambda t: t[0])


def latest() -> Optional[Tuple[int, dict]]:
    recs = records()
    return recs[-1] if recs else None


def latest_seq() -> Optional[int]:
    rec = latest()
    return rec[0] if rec else None


def _prune_old(keep: Optional[int] = None) -> None:
    """Checkpoint-dir GC: drop all but the newest `keep` snapshots (env
    ``H2O_TPU_OPLOG_CKPT_KEEP``) — KV records + files. A snapshot a
    rejoining follower is mid-restore on is pinned: its standing rejoin
    record (phase ``replaying``) names the restore cursor, which equals
    the snapshot's ``next_seq`` — deleting that file under the restorer
    would turn a routine readmission into a permanent FAILED."""
    from h2o3_tpu.parallel import oplog

    if keep is None:
        keep = keep_ckpts()
    if keep <= 0:
        return
    # pin only while the restorer might still be alive: a process that
    # died mid-rejoin leaves a 'replaying' record forever, and an eternal
    # pin would let snapshots accumulate past the keep budget for the
    # cloud's lifetime. A stale heartbeat is proof the restore died; a
    # missing row is NOT (the restorer may not have beaten yet).
    health = {r["process"]: r for r in failure.cluster_health()}

    def _maybe_alive(proc: int) -> bool:
        row = health.get(proc)
        return row is None or bool(row.get("healthy", True))

    pinned = {int(r.get("seq", -1)) for r in oplog.rejoin_records()
              if r.get("phase") == "replaying"
              and _maybe_alive(int(r.get("proc", -1)))}
    recs = records()
    for seq, rec in recs[:-keep]:
        if int(rec.get("next_seq", seq + 1)) in pinned:
            continue
        D.kv_delete(_CKPT_PREFIX + str(seq))
        p = rec.get("path")
        if p:
            try:
                os.unlink(p)
            except OSError:
                pass


def truncate_through(seq: int) -> int:
    """Delete acknowledged oplog slots + ack records for seqs <= `seq`.
    Error records are NOT touched: they are failure evidence, superseded
    only by a successful rejoin re-replay. Returns keys deleted."""
    global _TRUNCATED_THROUGH
    # raise the floor BEFORE deleting: a wait_acks(s<=seq) poller that
    # races the deletes must see either its ack records or the floor,
    # never neither (the floor is sound — the caller only truncates a
    # fully-acknowledged prefix)
    with _LOCK:
        _TRUNCATED_THROUGH = max(_TRUNCATED_THROUGH, int(seq))
    n = 0
    for k, _v in D.kv_dir("oplog/"):
        tail = k[len("oplog/"):]
        parts = tail.split("/")
        s = None
        if len(parts) == 1 and parts[0].isdigit():          # oplog/{s}
            s = int(parts[0])
        elif len(parts) >= 2 and parts[0] == "ack" and parts[1].isdigit():
            s = int(parts[1])                               # oplog/ack/{s}/..
        if s is not None and s <= seq:
            D.kv_delete(k)
            n += 1
    return n


# ---------------------------------------------------------------------------
# restore side (follower rejoin / standby takeover)
# ---------------------------------------------------------------------------

def load_latest(restore_dkv: bool = True) -> Tuple[int, Optional[dict]]:
    """Load the newest checkpoint: returns ``(next_seq, snapshot)`` —
    the oplog cursor to resume replay at, and the raw snapshot dict
    (``(0, None)`` when no checkpoint exists). With `restore_dkv`, the
    snapshot's DKV objects and announced-key metadata are installed into
    this process's store first. The path resolves through ``persist/`` so
    checkpoints on shared storage restore across hosts."""
    from h2o3_tpu import persist
    from h2o3_tpu.core.dkv import DKV

    rec = latest()
    if rec is None:
        return 0, None
    seq, meta = rec
    path = persist.resolve(meta["path"])
    with open(path, "rb") as f:
        snap = _CkptUnpickler(f).load()
    if restore_dkv:
        DKV.restore_control_plane(snap.get("dkv") or {}, loads=_loads)
    return int(snap.get("next_seq", seq + 1)), snap


# ---------------------------------------------------------------------------
# durable per-job training progress (crash-survivable jobs)
#
# Reference: hex/Model._checkpoint treats training continuation as
# first-class — an interrupted build resumes from the last completed
# iteration instead of restarting. Here iterative trainers persist their
# loop state every H2O_TPU_JOB_CKPT_ITERS completed iterations, keyed by
# the REST-visible Job id: one file per job in the (shared-storage-capable)
# checkpoint dir plus a small KV record, so a recovered cloud — including a
# NEW coordinator after a standby handoff — can re-dispatch the job from
# where it died (parallel/watchdog.resume_failed_jobs).
# ---------------------------------------------------------------------------

_JOB_PREFIX = "oplog/jobckpt/"


def _job_path(job_key: str) -> str:
    safe = re.sub(r"[^\w.-]", "_", str(job_key))
    return os.path.join(ckpt_dir(), f"jobckpt_{safe}.pkl")


def save_job_progress(job_key: str, iteration: int, spec: Dict[str, Any],
                      state: Dict[str, Any]) -> str:
    """Persist one job's training progress: `spec` is the re-dispatch
    recipe (algo, wire params, frame keys, response, destination) and
    `state` the trainer's loop state at `iteration` completed iterations.
    Atomic file replace — a reader never sees a torn snapshot. Discovery
    is double-booked: a KV record makes the progress visible cloud-wide,
    and a small JSON sidecar next to the pickle keeps it visible where
    the KV can't (single-process clouds, a wiped KV) without readers
    having to unpickle the full loop state."""
    payload = {"job": str(job_key), "iteration": int(iteration),
               "spec": dict(spec or {}), "state": state, "ts": time.time()}
    path = _job_path(job_key)
    tmp = path + ".part"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f)
    os.replace(tmp, path)
    meta = {"job": str(job_key), "iteration": int(iteration),
            "path": path, "algo": (spec or {}).get("algo"),
            "dest": (spec or {}).get("model_id"), "ts": payload["ts"]}
    mtmp = path + ".json.part"
    with open(mtmp, "w", encoding="utf-8") as f:
        json.dump(meta, f)
    os.replace(mtmp, path + ".json")
    D.kv_put(_JOB_PREFIX + str(job_key), json.dumps(meta))
    from h2o3_tpu.utils import timeline

    timeline.record("job", "progress_saved", job=str(job_key),
                    iteration=int(iteration))
    return path


def job_progress_records() -> List[dict]:
    """Cloud-wide durable-progress records ({job, iteration, path, algo,
    dest, ts}), sorted by job key. KV records first; progress FILES the
    KV does not know about are folded in from the checkpoint dir — on a
    single-process cloud ``kv_put`` is a no-op, and on a wiped KV the
    files are the only surviving evidence, so discovery (and therefore
    the watchdog's job resume) must not depend on the KV alone."""
    out = []
    for _k, v in D.kv_dir(_JOB_PREFIX):
        try:
            rec = json.loads(v)
        except (ValueError, TypeError):
            continue
        if isinstance(rec, dict) and rec.get("job"):
            out.append(rec)
    seen = {r["job"] for r in out}
    try:
        names = sorted(os.listdir(ckpt_dir()))
    except OSError:
        names = []
    for name in names:
        if not (name.startswith("jobckpt_") and name.endswith(".pkl.json")):
            continue
        try:
            with open(os.path.join(ckpt_dir(), name), encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and rec.get("job") \
                and rec["job"] not in seen:
            out.append(rec)
    return sorted(out, key=lambda r: r["job"])


def has_job_progress(job_key: str) -> bool:
    """Cheap existence probe — KV record or JSON sidecar, no state
    unpickle (``/3/Jobs`` consults this per job)."""
    if D.kv_try_get(_JOB_PREFIX + str(job_key)) is not None:
        return True
    return os.path.exists(_job_path(job_key) + ".json")


def load_job_progress(job_key: str) -> Optional[dict]:
    """Load a job's durable progress ({job, iteration, spec, state, ts});
    None when no record exists or the file is gone/corrupt. The path
    resolves through ``persist/`` like control-plane checkpoints, so a new
    coordinator on another host can read a shared-storage progress file."""
    from h2o3_tpu import persist

    raw = D.kv_try_get(_JOB_PREFIX + str(job_key))
    path = None
    if raw is not None:
        try:
            path = json.loads(raw).get("path")
        except (ValueError, TypeError):
            path = None
    path = path or _job_path(job_key)
    try:
        with open(persist.resolve(path), "rb") as f:
            return _CkptUnpickler(f).load()
    except (OSError, pickle.UnpicklingError, EOFError, ValueError):
        return None


def delete_job_progress(job_key: str) -> None:
    """Drop a job's durable progress (called when the job completes — the
    finished model supersedes the partial state), including any
    append-only tree-progress suffix chunks."""
    D.kv_delete(_JOB_PREFIX + str(job_key))
    paths = [_job_path(job_key), _job_path(job_key) + ".json"]
    safe = re.sub(r"[^\w.-]", "_", str(job_key))
    try:
        paths += [os.path.join(ckpt_dir(), n)
                  for n in os.listdir(ckpt_dir())
                  if n.startswith(f"jobckpt_{safe}_trees_")
                  and n.endswith(".npz")]
    except OSError:
        pass
    for p in paths:
        try:
            os.unlink(p)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# durable search state (AutoML / grid)
#
# The search controller (automl/search.py) holds only small durable state:
# the member plan, per-member status/attempts/scores, and the re-dispatch
# spec. Same discipline as job progress — atomic file replace, JSON
# sidecar, KV record, restricted unpickler, persist/-resolved path — plus
# one extra: the previous snapshot is rotated to ``.prev`` before each
# replace, so a torn/corrupt current file is refused LOUDLY and the
# previous snapshot wins (a search must never resume from garbage).
# ---------------------------------------------------------------------------

_SEARCH_PREFIX = "oplog/searchckpt/"


def _search_path(search_key: str, sdir: Optional[str] = None) -> str:
    safe = re.sub(r"[^\w.-]", "_", str(search_key))
    return os.path.join(sdir or ckpt_dir(), f"searchckpt_{safe}.pkl")


def save_search_state(search_key: str, state: Dict[str, Any],
                      sdir: Optional[str] = None) -> str:
    """Persist one search's durable state (member plan + statuses +
    attempt counts + re-dispatch spec). The current snapshot is rotated
    to ``.prev`` before the atomic replace so there are always two
    generations on disk: if the newest file is torn, the previous one
    still describes a valid (slightly older) leaderboard."""
    members = state.get("members") or {}
    payload = {"search": str(search_key), "kind": state.get("kind"),
               "state": state, "ts": time.time()}
    path = _search_path(search_key, sdir)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".part"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f)
    if os.path.exists(path):
        try:
            os.replace(path, path + ".prev")
        except OSError:
            pass
    os.replace(tmp, path)
    counts: Dict[str, int] = {}
    for m in members.values():
        st = str(m.get("status", "pending"))
        counts[st] = counts.get(st, 0) + 1
    meta = {"search": str(search_key), "kind": state.get("kind"),
            "dest": state.get("dest"), "path": path,
            "members": counts, "ts": payload["ts"]}
    mtmp = path + ".json.part"
    with open(mtmp, "w", encoding="utf-8") as f:
        json.dump(meta, f)
    os.replace(mtmp, path + ".json")
    D.kv_put(_SEARCH_PREFIX + str(search_key), json.dumps(meta))
    from h2o3_tpu.utils import timeline

    timeline.record("search", "state_saved", search=str(search_key),
                    done=counts.get("done", 0))
    return path


def search_state_records() -> List[dict]:
    """Cloud-wide durable search records ({search, kind, dest, path,
    members, ts}), sorted by search key. Same double-booked discovery as
    job progress: KV records first, then sidecar files the KV does not
    know about (single-process clouds, a wiped KV)."""
    out = []
    for _k, v in D.kv_dir(_SEARCH_PREFIX):
        try:
            rec = json.loads(v)
        except (ValueError, TypeError):
            continue
        if isinstance(rec, dict) and rec.get("search"):
            out.append(rec)
    seen = {r["search"] for r in out}
    try:
        names = sorted(os.listdir(ckpt_dir()))
    except OSError:
        names = []
    for name in names:
        if not (name.startswith("searchckpt_") and name.endswith(".pkl.json")):
            continue
        try:
            with open(os.path.join(ckpt_dir(), name), encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and rec.get("search") \
                and rec["search"] not in seen:
            out.append(rec)
    return sorted(out, key=lambda r: r["search"])


def load_search_state(search_key: str,
                      sdir: Optional[str] = None) -> Optional[dict]:
    """Load a search's durable state ({search, kind, state, ts}); None
    when no readable snapshot exists. A torn/corrupt CURRENT file is
    refused loudly and the ``.prev`` generation is tried — the previous
    snapshot wins over garbage. Paths resolve through ``persist/`` so a
    new coordinator on another host can read shared-storage state."""
    from h2o3_tpu import persist
    from h2o3_tpu.utils.log import get_logger

    path = None
    if sdir is None:
        raw = D.kv_try_get(_SEARCH_PREFIX + str(search_key))
        if raw is not None:
            try:
                path = json.loads(raw).get("path")
            except (ValueError, TypeError):
                path = None
    path = path or _search_path(search_key, sdir)
    for i, p in enumerate((path, path + ".prev")):
        try:
            with open(persist.resolve(p), "rb") as f:
                rec = _CkptUnpickler(f).load()
        except OSError:
            continue
        except (pickle.UnpicklingError, EOFError, ValueError,
                AttributeError, ImportError, IndexError) as e:
            get_logger().error(
                "search state %s is torn/corrupt (%s: %s) — refusing it%s",
                p, type(e).__name__, e,
                "; trying previous snapshot" if i == 0 else "")
            continue
        if isinstance(rec, dict) and rec.get("state"):
            if i == 1:
                get_logger().warning(
                    "search %s resuming from PREVIOUS snapshot %s",
                    search_key, p)
            return rec
    return None


def delete_search_state(search_key: str, sdir: Optional[str] = None,
                        keep_files: bool = False) -> None:
    """Drop a search's durable state (the completed search supersedes
    it). ``keep_files`` drops only the KV record — used when the state
    doubles as a user-visible export directory (grid recovery_dir)."""
    D.kv_delete(_SEARCH_PREFIX + str(search_key))
    if keep_files:
        return
    path = _search_path(search_key, sdir)
    for p in (path, path + ".prev", path + ".json"):
        try:
            os.unlink(p)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# append-only tree-progress suffix chunks
#
# The tree trainers' loop state is dominated by the per-tree tables (packed
# nodes, leaf values) — O(forest) and strictly append-only. Before this
# layer every progress save re-pickled the WHOLE list (the recorded PR-5
# quadratic cost). Now each save writes ONE npz chunk holding only the
# trees grown since the previous save (artifact/packer.py codec — the same
# packed-forest discipline as the AOT artifact), and the main progress
# pickle carries just the chunk paths. Chunks resolve through persist/ on
# load like every other checkpoint artifact, so cross-host resume holds.
# ---------------------------------------------------------------------------

def job_tree_chunk_path(job_key: str, idx: int) -> str:
    safe = re.sub(r"[^\w.-]", "_", str(job_key))
    return os.path.join(ckpt_dir(), f"jobckpt_{safe}_trees_{int(idx):06d}.npz")


def append_job_tree_chunk(job_key: str, idx: int, packs, leaf_vals,
                          leaf_wys) -> str:
    """Atomically write suffix chunk `idx` for `job_key`; returns its
    path (recorded in the main progress state)."""
    from h2o3_tpu.artifact import packer

    data = packer.pack_tree_chunk(packs, leaf_vals, leaf_wys)
    path = job_tree_chunk_path(job_key, idx)
    tmp = path + ".part"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path


def load_job_tree_chunks(paths) -> Tuple[list, list, list]:
    """Re-assemble the per-tree lists from ordered chunk paths. Raises on
    a missing/torn chunk — a partial forest must fail the resume loudly
    (the caller's unreadable-progress handling takes over), never train
    silently from a truncated tree list."""
    from h2o3_tpu import persist
    from h2o3_tpu.artifact import packer

    packs: list = []
    leaf_vals: list = []
    leaf_wys: list = []
    for p in paths:
        with open(persist.resolve(str(p)), "rb") as f:
            pk, lv, lw = packer.unpack_tree_chunk(f.read())
        packs.extend(pk)
        leaf_vals.extend(lv)
        leaf_wys.extend(lw)
    return packs, leaf_vals, leaf_wys
