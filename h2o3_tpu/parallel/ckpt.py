"""Oplog checkpoint + compaction: bound the control-plane KV footprint.

Reference: H2O-3 never replays history — any node can re-derive state from
the DKV (SURVEY §1, water/H2O.java), so its control plane carries no log.
Our REST-driven oplog DOES carry one (parallel/oplog.py), and before this
module every op slot and ack lived in the coordination KV forever. Podracer
TPU fleets (arXiv:2104.06272) checkpoint/restore workers as the NORMAL
response to preemption; this is that layer for the cloud control plane:

- every ``H2O_TPU_OPLOG_CHECKPOINT_OPS`` fully-acknowledged ops the
  coordinator publishes a ``checkpoint`` op; inside its execution turn
  (turnstile held: no other op mutates the DKV) it serializes a consistent
  control-plane snapshot — DKV-resident objects (models, frames, jobs'
  metadata), announced key metadata + replicated blobs, the next oplog
  sequence and the recent op identity tokens — to a file under the
  checkpoint dir, recording ``oplog/ckpt/{seq}`` in the cloud KV;
- once the checkpoint op is fully acked (every follower has replayed
  through it), the acknowledged prefix — ``oplog/{s}`` slots and their
  ``oplog/ack/{s}/*`` records for s <= seq — is truncated, so live oplog
  keys stay O(interval) no matter how many ops the cloud has served;
- a restarted follower readmits from the newest checkpoint
  (``oplog.rejoin``): restore the snapshot, replay the suffix, re-register
  with a fresh incarnation.

Checkpoint paths resolve through ``persist/`` on load, so a checkpoint dir
on shared storage (file:// today, s3:// etc. via the scheme registry) lets
a follower restarted on a DIFFERENT host readmit too.
"""

from __future__ import annotations

import io
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from h2o3_tpu.core import failure
from h2o3_tpu.parallel import distributed as D
from h2o3_tpu.parallel import retry

_CKPT_PREFIX = "oplog/ckpt/"

# acked-op counter since the last checkpoint + single-flight guard: two
# handler threads crossing the threshold together must not both publish a
# checkpoint op
_LOCK = threading.Lock()
_ACKED_SINCE = 0
_IN_CKPT = False
_CKPT_THREAD: Optional[threading.Thread] = None
# seq of the in-flight (or last) checkpoint op: its OWN ack must not count
# toward the next interval, but user ops acked while an async checkpoint
# is still truncating DO — otherwise a slow snapshot under load silently
# stretches the effective interval past H2O_TPU_OPLOG_CHECKPOINT_OPS and
# the documented O(interval) bound on live oplog keys
_CKPT_SEQ: Optional[int] = None
# highest seq whose slots + acks were truncated. Truncation only runs after
# the checkpoint op is FULLY acked (every follower replayed through it), so
# an op at or below this floor is proven-acknowledged even though its ack
# records are gone — oplog.wait_acks consults it so a waiter still polling
# for an op the compactor just truncated returns instead of timing out.
_TRUNCATED_THROUGH = -1


def interval_ops() -> int:
    """Checkpoint every N fully-acked ops (env
    ``H2O_TPU_OPLOG_CHECKPOINT_OPS``, default 64; <= 0 disables)."""
    return retry.env_int("H2O_TPU_OPLOG_CHECKPOINT_OPS", 64)


def ckpt_dir() -> str:
    d = os.environ.get("H2O_TPU_OPLOG_CKPT_DIR") or os.path.join(
        os.environ.get("H2O_TPU_ICE_ROOT", "/tmp/h2o3_tpu"), "oplog_ckpt")
    os.makedirs(d, exist_ok=True)
    return d


def async_enabled() -> bool:
    """Run interval checkpoints on a background thread (env
    ``H2O_TPU_OPLOG_CKPT_ASYNC``, default on). The snapshot + cloud-wide
    ack of the checkpoint op can take seconds; the user request that
    happened to cross the interval threshold should not absorb that
    latency. The chaos tests pin this off: a synchronous checkpoint lands
    at a deterministic sequence position."""
    return retry.env_int("H2O_TPU_OPLOG_CKPT_ASYNC", 1) != 0


def reset() -> None:
    """Clear the coordinator-side counter (cloud restart / tests)."""
    global _ACKED_SINCE, _TRUNCATED_THROUGH, _CKPT_SEQ
    with _LOCK:
        _ACKED_SINCE = 0
        _TRUNCATED_THROUGH = -1
        _CKPT_SEQ = None


def truncated_through() -> int:
    """Highest seq compacted away — every op at or below it was fully
    acknowledged cloud-wide before its records were deleted (-1: none)."""
    return _TRUNCATED_THROUGH


def wait_idle(timeout_s: float = 30.0) -> bool:
    """Join an in-flight background checkpoint, if any (tests / orderly
    shutdown). True when no checkpoint is running on return."""
    t = _CKPT_THREAD
    if t is not None and t.is_alive():
        t.join(timeout_s)
        return not t.is_alive()
    return True


class _CkptUnpickler(pickle.Unpickler):
    """Framework/numeric types only — a checkpoint file (possibly fetched
    from shared storage) must not smuggle arbitrary callables, same
    contract as the binary-artifact loader in api/routes_ext.py."""

    _PREFIXES = ("h2o3_tpu.", "numpy.", "jax.", "jaxlib.", "collections.",
                 "functools.")
    _MODULES = {"numpy", "jax", "jaxlib", "collections", "functools",
                "threading"}
    _BUILTINS = {"set", "frozenset", "slice", "complex", "range",
                 "bytearray", "object"}

    def find_class(self, module, name):
        if module == "builtins" and name in self._BUILTINS:
            return super().find_class(module, name)
        if module in self._MODULES or \
                any(module.startswith(pfx) for pfx in self._PREFIXES):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"checkpoint references disallowed type {module}.{name}")


def _loads(data: bytes) -> Any:
    return _CkptUnpickler(io.BytesIO(data)).load()


# ---------------------------------------------------------------------------
# coordinator side: write + truncate
# ---------------------------------------------------------------------------

def note_acked_op(seq: int) -> None:
    """Called by the coordinator after op `seq` is fully acknowledged
    (oplog.turn's tail). Every ``interval_ops()`` acked ops, takes a
    checkpoint and truncates the acknowledged prefix. Never raises: a
    checkpoint failure must not fail the user op that crossed the
    threshold — the next acked op simply re-tries."""
    global _ACKED_SINCE, _IN_CKPT, _CKPT_THREAD
    n = interval_ops()
    if n <= 0:
        return
    with _LOCK:
        if seq == _CKPT_SEQ:            # the checkpoint op's own ack
            return
        _ACKED_SINCE += 1
        if _ACKED_SINCE < n or _IN_CKPT:
            return                      # counted; _IN_CKPT only gates the
                                        # single-flight spawn — the next op
                                        # acked after it clears triggers
        _IN_CKPT = True
        _ACKED_SINCE = 0
    if async_enabled():
        # off the acked op's thread: the checkpoint op still serializes
        # behind the turnstile like any other op, but the user request
        # that crossed the threshold returns without paying for the
        # snapshot or the cloud-wide ack wait
        t = threading.Thread(target=_run_checkpoint, daemon=True,
                             name="h2o3-oplog-ckpt")
        with _LOCK:
            _CKPT_THREAD = t
        t.start()
    else:
        _run_checkpoint()


def _run_checkpoint() -> None:
    global _IN_CKPT
    try:
        checkpoint_now()
    except Exception as e:   # noqa: BLE001 — best-effort by contract
        from h2o3_tpu.utils.log import get_logger

        get_logger().warning("oplog checkpoint failed (will retry at the "
                             "next interval): %s", e)
    finally:
        with _LOCK:
            _IN_CKPT = False


def checkpoint_now() -> Optional[int]:
    """Publish + execute one ``checkpoint`` op: snapshot under the
    turnstile (no concurrent op is mutating the DKV), then — once every
    follower acked it — truncate the acknowledged prefix. Returns the
    checkpoint's sequence (None when the cloud is not broadcasting or
    this process no longer leads it: an async checkpoint thread resuming
    on a stalled ex-coordinator must not publish at a stale seq — or
    truncate records in the SHARED KV — under an epoch it lost."""
    global _CKPT_SEQ
    from h2o3_tpu.parallel import oplog

    oplog.maybe_demote()
    if oplog.demoted() or not oplog.active():
        return None
    epoch0 = D.epoch()
    seq = oplog.publish("checkpoint", {})
    with _LOCK:
        _CKPT_SEQ = seq
    with oplog.turn(seq):
        write_checkpoint(seq)
    # turn()'s exit completed wait_acks(seq): every follower replayed
    # through seq, so the prefix (seq included) is dead weight — unless
    # leadership moved while we snapshotted, in which case the records
    # now belong to the new coordinator's epoch and are not ours to drop
    oplog.maybe_demote()
    if oplog.demoted() or not D.is_coordinator() or D.epoch() != epoch0:
        return None
    truncate_through(seq)
    return seq


def write_checkpoint(seq: int) -> str:
    """Serialize the control-plane snapshot for checkpoint op `seq` and
    record it at ``oplog/ckpt/{seq}``. The snapshot's ``next_seq`` is
    seq + 1: state includes ops < seq, and op seq is the checkpoint
    itself (no state change), so a restorer resumes replay after it."""
    from h2o3_tpu.core.dkv import DKV
    from h2o3_tpu.parallel import oplog

    failure.faultpoint("ckpt.write")
    snap = {
        "seq": int(seq),
        "next_seq": int(seq) + 1,
        "epoch": D.epoch(),
        "ts": time.time(),
        "op_ids": oplog.snapshot_op_ids(),
        "dkv": DKV.snapshot_control_plane(),
    }
    path = os.path.join(ckpt_dir(), f"ckpt_{int(seq):012d}.pkl")
    tmp = path + ".part"
    with open(tmp, "wb") as f:
        pickle.dump(snap, f)
    os.replace(tmp, path)                  # readers never see a torn file
    if not D.kv_put(_CKPT_PREFIX + str(int(seq)),
                    json.dumps({"seq": int(seq), "next_seq": int(seq) + 1,
                                "path": path, "epoch": D.epoch(),
                                "ts": snap["ts"],
                                "skipped": snap["dkv"].get("skipped", [])})):
        raise RuntimeError(f"checkpoint {seq}: KV record did not land")
    _prune_old(keep=2)
    from h2o3_tpu.utils import timeline

    timeline.record("oplog", "checkpoint", seq=int(seq),
                    objects=len(snap["dkv"].get("objects", {})),
                    skipped=len(snap["dkv"].get("skipped", [])))
    return path


def records() -> List[Tuple[int, dict]]:
    """All checkpoint records, sorted by seq."""
    out = []
    for k, v in D.kv_dir(_CKPT_PREFIX):
        try:
            out.append((int(k.rsplit("/", 1)[-1]), json.loads(v)))
        except (ValueError, TypeError):
            continue
    return sorted(out, key=lambda t: t[0])


def latest() -> Optional[Tuple[int, dict]]:
    recs = records()
    return recs[-1] if recs else None


def latest_seq() -> Optional[int]:
    rec = latest()
    return rec[0] if rec else None


def _prune_old(keep: int = 2) -> None:
    """Drop all but the newest `keep` checkpoints (KV records + files)."""
    recs = records()
    for seq, rec in recs[:-keep] if keep > 0 else recs:
        D.kv_delete(_CKPT_PREFIX + str(seq))
        p = rec.get("path")
        if p:
            try:
                os.unlink(p)
            except OSError:
                pass


def truncate_through(seq: int) -> int:
    """Delete acknowledged oplog slots + ack records for seqs <= `seq`.
    Error records are NOT touched: they are failure evidence, superseded
    only by a successful rejoin re-replay. Returns keys deleted."""
    global _TRUNCATED_THROUGH
    # raise the floor BEFORE deleting: a wait_acks(s<=seq) poller that
    # races the deletes must see either its ack records or the floor,
    # never neither (the floor is sound — the caller only truncates a
    # fully-acknowledged prefix)
    with _LOCK:
        _TRUNCATED_THROUGH = max(_TRUNCATED_THROUGH, int(seq))
    n = 0
    for k, _v in D.kv_dir("oplog/"):
        tail = k[len("oplog/"):]
        parts = tail.split("/")
        s = None
        if len(parts) == 1 and parts[0].isdigit():          # oplog/{s}
            s = int(parts[0])
        elif len(parts) >= 2 and parts[0] == "ack" and parts[1].isdigit():
            s = int(parts[1])                               # oplog/ack/{s}/..
        if s is not None and s <= seq:
            D.kv_delete(k)
            n += 1
    return n


# ---------------------------------------------------------------------------
# restore side (follower rejoin / standby takeover)
# ---------------------------------------------------------------------------

def load_latest(restore_dkv: bool = True) -> Tuple[int, Optional[dict]]:
    """Load the newest checkpoint: returns ``(next_seq, snapshot)`` —
    the oplog cursor to resume replay at, and the raw snapshot dict
    (``(0, None)`` when no checkpoint exists). With `restore_dkv`, the
    snapshot's DKV objects and announced-key metadata are installed into
    this process's store first. The path resolves through ``persist/`` so
    checkpoints on shared storage restore across hosts."""
    from h2o3_tpu import persist
    from h2o3_tpu.core.dkv import DKV

    rec = latest()
    if rec is None:
        return 0, None
    seq, meta = rec
    path = persist.resolve(meta["path"])
    with open(path, "rb") as f:
        snap = _CkptUnpickler(f).load()
    if restore_dkv:
        DKV.restore_control_plane(snap.get("dkv") or {}, loads=_loads)
    return int(snap.get("next_seq", seq + 1)), snap
