"""Collective reductions over the device mesh.

Replaces the reference's entire communication stack — custom UDP/TCP RPC
with ACK/ACKACK (water/RPC.java:19-47), AutoBuffer framing
(water/AutoBuffer.java), binary-tree reductions (water/MRTask.java:751
reduce3) and the Rabit all-reduce rebuilt for XGBoost
(h2o-extensions/xgboost/rabit/RabitTrackerH2O.java:14) — with XLA
collectives compiled onto ICI links. Inside `shard_map` bodies use these
thin wrappers; outside, just annotate shardings and let XLA insert them."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def all_reduce_sum(x, axis: str = "rows"):
    return jax.lax.psum(x, axis)


def all_reduce_max(x, axis: str = "rows"):
    return jax.lax.pmax(x, axis)


def all_reduce_min(x, axis: str = "rows"):
    return jax.lax.pmin(x, axis)


def all_reduce_mean(x, axis: str = "rows"):
    return jax.lax.pmean(x, axis)


def all_gather(x, axis: str = "rows", tiled: bool = False):
    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str = "rows"):
    return jax.lax.psum_scatter(x, axis, tiled=True)


def axis_index(axis: str = "rows"):
    return jax.lax.axis_index(axis)


def ppermute_ring(x, axis: str = "rows", shift: int = 1):
    """Ring permute over the mesh axis — building block for ring-style
    pipelined reductions (used by the ring histogram merge in ops/histogram
    and available for sequence-parallel patterns)."""
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)
