"""CLI for the distributed-invariant static analyzer.

``python -m h2o3_tpu.analysis [options] [root]`` — see the package
docstring for the pass table. Exit codes: 0 clean (all findings
baselined or none), 1 findings (or baseline-hygiene problems), 2 usage.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from h2o3_tpu import analysis


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m h2o3_tpu.analysis",
        description="Distributed-invariant static analyzer "
                    "(mirrored programs, lock order, serialization, "
                    "compat routing, sync hygiene + registry guards).")
    ap.add_argument("root", nargs="?", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--select", default=None, metavar="P1,P2",
                    help="comma-separated pass subset")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help=f"baseline file (default: <root>/"
                         f"{analysis.BASELINE_NAME})")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept current baselineable findings into the "
                         "baseline (preserving existing notes)")
    ap.add_argument("--list", action="store_true", dest="list_passes",
                    help="list available passes and exit")
    args = ap.parse_args(argv)

    if args.list_passes:
        for name in analysis.PASSES:
            print(name)
        return 0

    passes = [p.strip() for p in args.select.split(",")] \
        if args.select else None
    t0 = time.perf_counter()
    try:
        new, baselined, problems = analysis.run_repo(
            root=Path(args.root) if args.root else None,
            passes=passes,
            baseline=Path(args.baseline) if args.baseline else None)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    dt = time.perf_counter() - t0

    if args.update_baseline:
        ctx_root = Path(args.root) if args.root else \
            Path(analysis.__file__).resolve().parents[2]
        bl_path = Path(args.baseline) if args.baseline else \
            ctx_root / analysis.BASELINE_NAME
        old_entries = analysis.load_baseline(bl_path)
        old = {e.get("fingerprint"): e.get("note", "")
               for e in old_entries}
        keep = [f for f in new + baselined
                if f.pass_id in analysis.BASELINEABLE]
        # a --select run only re-derives entries for the SELECTED passes:
        # everything else carries over verbatim, or a partial update
        # would silently delete audited entries
        carried = [e for e in old_entries
                   if passes and e.get("pass") not in passes]
        analysis.save_baseline(bl_path, keep, notes=old,
                               keep_entries=carried)
        hard = [f for f in new if f.pass_id not in analysis.BASELINEABLE]
        print(f"baseline written: {bl_path} "
              f"({len(keep) + len(carried)} entries; fill in any TODO "
              f"notes)")
        for f in hard:
            print(f.render())
        return 1 if hard else 0

    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baseline_problems": [f.to_dict() for f in problems],
            "baselined": [dict(f.to_dict(), note=f.note)
                          for f in baselined],
            "elapsed_s": round(dt, 3),
        }, indent=2))
    else:
        for f in new + problems:
            print(f.render())
        print(f"-- {len(new)} finding(s), {len(problems)} baseline "
              f"problem(s), {len(baselined)} baselined, "
              f"{len(analysis.PASSES)} passes in {dt:.2f}s")
    return 1 if (new or problems) else 0


if __name__ == "__main__":
    sys.exit(main())
