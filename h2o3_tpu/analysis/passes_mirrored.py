"""Mirrored-program pass: per-process-divergent decisions in lockstep code.

Every process of a multi-process cloud replays the oplog and must walk an
IDENTICAL device-program sequence (PAPER L1/L4): a branch that resolves
differently on two processes around a collective wedges or desyncs the
mesh. This pass closes over the project call graph from the checked-in
mirrored roots (``registry.MIRRORED_ROOTS``) and flags, inside every
reachable function:

- **wall-clock** reads (``time.time/monotonic/perf_counter``) whose value
  feeds control flow (directly in a branch test/comparison, or through
  intra-function assignment taint) — the ``max_runtime_secs``-over-
  broadcast class of bug;
- **fresh PRNG / entropy** (``random.*``, ``np.random`` module state,
  ``default_rng()`` with no/None seed, ``SeedSequence()``, ``uuid4``) —
  flagged on sight: divergent entropy shapes data and shapes, not just
  branches — the unpinned-wildcard-seed class;
- **raw env reads** (``os.environ`` / ``os.getenv``) outside the declared
  knob helpers, when they feed control flow — the
  ``H2O_TPU_PALLAS_HIST=auto`` class;
- **process-local topology** (``jax.process_index()``,
  ``local_device_count()``, ``local_devices()``) feeding control flow.

Functions listed in ``registry.GUARDED`` (audited, reason required) and
modules declared host-side are exempt; the call graph still flows
through them.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from h2o3_tpu.analysis.core import Context, Finding

PASS_ID = "mirrored"

_WALLCLOCK_ATTRS = {"time", "monotonic", "perf_counter", "time_ns",
                    "monotonic_ns", "perf_counter_ns"}
_TOPOLOGY_ATTRS = {"process_index", "local_device_count", "local_devices"}


def _dotted(node) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _normalize(dotted: Optional[str], imports: Dict[str, str]) \
        -> Optional[str]:
    if not dotted:
        return None
    head, _, rest = dotted.partition(".")
    target = imports.get(head)
    if target:
        return f"{target}.{rest}" if rest else target
    return dotted


def _contains_none(node) -> bool:
    return any(isinstance(n, ast.Constant) and n.value is None
               for n in ast.walk(node))


def _classify_call(node: ast.Call, imports: Dict[str, str]) \
        -> Optional[str]:
    """Divergence category for a call expression, else None."""
    name = _normalize(_dotted(node.func), imports)
    if not name:
        return None
    if name.startswith("time.") and name.split(".")[-1] in _WALLCLOCK_ATTRS:
        return "wall-clock"
    if name.split(".")[-1] in _TOPOLOGY_ATTRS:
        return "process-topology"
    if name.startswith("random.") or name.startswith("secrets."):
        return "fresh-prng"
    if name in ("uuid.uuid4", "uuid.uuid1"):
        return "fresh-prng"
    if name.endswith(".random.default_rng") or name == "random.default_rng":
        if not node.args and not node.keywords:
            return "fresh-prng"
        if any(_contains_none(a) for a in node.args) or \
                any(_contains_none(k.value) for k in node.keywords):
            return "fresh-prng"
        return None                     # explicitly seeded: deterministic
    if name.endswith(".random.SeedSequence") and not node.args:
        return "fresh-prng"
    if name.startswith("jax.random."):
        # jax PRNG is functional: every sampler is a deterministic
        # function of its explicit key — divergence can only enter where
        # the SEED is derived (np/random/uuid above), not here
        return None
    if ".random." in name and name.split(".random.")[0] in ("numpy", "np"):
        # module-global numpy samplers (np.random.randint etc.)
        if name.split(".")[-1] not in ("default_rng", "SeedSequence",
                                       "Generator"):
            return "fresh-prng"
    if name in ("os.getenv",):
        return "raw-env"
    if name in ("os.environ.get",):
        return "raw-env"
    return None


def _divergent_nodes(fn_node, imports) -> List[tuple]:
    """[(ast node, category, code)] divergent sources in the function."""
    out = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            cat = _classify_call(node, imports)
            if cat:
                out.append((node, cat,
                            _normalize(_dotted(node.func), imports)))
        elif isinstance(node, ast.Subscript):
            name = _normalize(_dotted(node.value), imports)
            if name == "os.environ":
                out.append((node, "raw-env", "os.environ[...]"))
    return out


def _test_region_ids(fn_node) -> Set[int]:
    """ids of every AST node living inside a control-flow test: If/While/
    IfExp tests, assert tests, comprehension conditions, and any
    comparison/boolean expression (a compared divergent value is a branch
    in the making wherever the bool lands)."""
    region: Set[int] = set()

    def mark(sub):
        for n in ast.walk(sub):
            region.add(id(n))

    for node in ast.walk(fn_node):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            mark(node.test)
        elif isinstance(node, ast.Assert):
            mark(node.test)
        elif isinstance(node, ast.comprehension):
            for cond in node.ifs:
                mark(cond)
        elif isinstance(node, (ast.Compare, ast.BoolOp)):
            mark(node)
    return region


def _flagged_sources(fn_node, divergents) -> List[tuple]:
    """Subset of divergent sources that feed control flow (fresh-prng is
    flagged unconditionally). Taint flows through simple intra-function
    assignments: ``t0 = time.time() ... while time.time() < deadline``."""
    region = _test_region_ids(fn_node)
    flagged = []
    prng = [(n, c, code) for n, c, code in divergents if c == "fresh-prng"]
    rest = [(n, c, code) for n, c, code in divergents if c != "fresh-prng"]
    flagged.extend(prng)
    if not rest:
        return flagged
    direct = [(n, c, code) for n, c, code in rest if id(n) in region]
    flagged.extend(direct)
    pending = [t for t in rest if t not in direct]
    if not pending:
        return flagged
    # taint: name -> contributing source tuples
    taint: Dict[str, list] = {}
    for _ in range(5):
        changed = False
        for node in ast.walk(fn_node):
            if not isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign, ast.NamedExpr)):
                continue
            value = node.value
            if value is None:
                continue
            sources = []
            vids = {id(n) for n in ast.walk(value)}
            for t in pending:
                if id(t[0]) in vids:
                    sources.append(t)
            for n in ast.walk(value):
                if isinstance(n, ast.Name) and n.id in taint:
                    sources.extend(taint[n.id])
            if not sources:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                # plain names (and name tuples) only: tainting the BASE of
                # an attribute target (`self.t0 = time.time()` -> `self`)
                # would poison every later `self` comparison
                names = [tgt] if isinstance(tgt, ast.Name) else (
                    [e for e in tgt.elts if isinstance(e, ast.Name)]
                    if isinstance(tgt, (ast.Tuple, ast.List)) else [])
                for n in names:
                    cur = taint.setdefault(n.id, [])
                    for s in sources:
                        if s not in cur:
                            cur.append(s)
                            changed = True
        if not changed:
            break
    tainted_hits: List[tuple] = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Name) and id(node) in region and \
                node.id in taint:
            for s in taint[node.id]:
                if s not in tainted_hits and s not in flagged:
                    tainted_hits.append(s)
    flagged.extend(tainted_hits)
    return flagged


def run(ctx: Context) -> List[Finding]:
    proj = ctx.project
    roots = ctx.reg("MIRRORED_ROOTS", ())
    guarded = ctx.reg("GUARDED", {})
    helpers = ctx.reg("KNOB_HELPERS", frozenset())
    host = tuple(ctx.reg("HOST_SIDE_MODULES", {}))
    reach = proj.reachable(roots, loose=True)
    findings: List[Finding] = []

    # registry self-check: an unresolvable qualname would silently shrink
    # the closure (renamed root => green no-op pass) or leave a stale
    # exemption standing — both are findings, mirroring the stale-baseline
    # rule. Registry findings are not baselineable by construction.
    reg_file = "h2o3_tpu/analysis/registry.py"
    for name, what in ((roots, "MIRRORED_ROOTS"),
                       (tuple(guarded), "GUARDED"),
                       (tuple(helpers), "KNOB_HELPERS")):
        for q in name:
            if q not in proj.functions:
                findings.append(Finding(
                    PASS_ID, reg_file, 0,
                    f"{what} entry `{q}` resolves to no project function "
                    f"— a renamed symbol silently defuses the mirrored "
                    f"pass (or leaves a stale audit); fix the registry",
                    symbol=q, snippet=q))
    for h in host:
        if not any(m.rel == h or m.rel.startswith(h)
                   for m in proj.modules.values()):
            findings.append(Finding(
                PASS_ID, reg_file, 0,
                f"HOST_SIDE_MODULES entry `{h}` matches no module — "
                f"stale exemption; fix the path", symbol=h, snippet=h))
    for q in sorted(reach):
        if q in guarded:
            continue
        fi = proj.functions[q]
        rel = fi.module.rel
        if any(rel == h or rel.startswith(h) for h in host):
            continue
        divergents = _divergent_nodes(fi.node, fi.module.imports)
        if not divergents:
            continue
        if q in helpers:
            divergents = [t for t in divergents if t[1] != "raw-env"]
        for node, cat, code in _flagged_sources(fi.node, divergents):
            sym = q.split("h2o3_tpu.", 1)[-1]
            findings.append(ctx.finding(
                PASS_ID, fi.module, node,
                f"{cat} source `{code}` in mirrored code (reachable from "
                f"the oplog/trainer roots) — every process must walk an "
                f"identical program sequence; pin/route it or add an "
                f"audited GUARDED entry", symbol=sym))
    return findings
