"""Checked-in analysis registry: the audited inputs every pass starts from.

This file is the reviewable surface of the analyzer. It declares:

- the **mirrored roots** — functions every process of a multi-process
  cloud executes in lockstep (oplog op handlers, broadcast trainer
  entries); the mirrored-program pass closes over the project call graph
  from here;
- the **knob helpers** — the sanctioned ``os.environ`` accessors (reads
  anywhere else inside mirrored code are findings);
- the **guarded functions** — audited call sites that LOOK divergent but
  are provably mirrored-safe; every entry carries its one-line audit
  reason. Adding an entry here is a review event, exactly like editing a
  lock-order declaration;
- the **host-side modules** — control-plane/observability code that never
  lowers or dispatches device programs: mirrored findings inside them are
  suppressed (the call graph still flows THROUGH them);
- the lock-order scope + declared order, the serialization allowlist, the
  compat-routing API list, and the sync-hygiene configuration.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# mirrored-program pass
# ---------------------------------------------------------------------------

# every process replaying the oplog walks these in lockstep; any
# per-process-divergent decision reachable from here desynchronizes the
# device program sequence around collectives (the PR-5/PR-7 invariant)
MIRRORED_ROOTS = (
    "h2o3_tpu.parallel.oplog._apply",                 # all oplog op handlers
    "h2o3_tpu.models.model_builder.ModelBuilder.train",   # broadcast trains
    "h2o3_tpu.scoring.execute_batch",                 # score_batch replays
    "h2o3_tpu.rapids.eval.exec_rapids",               # rapids op replays
    "h2o3_tpu.automl.search.SearchEngine.run",        # search member walks
)

# sanctioned env accessors: defaulting + documentation ride these, and the
# ops contract pins the env uniform across cloud processes. A RAW environ
# read inside mirrored code bypasses that contract.
KNOB_HELPERS = frozenset({
    "h2o3_tpu.parallel.retry.env_int",
    "h2o3_tpu.parallel.retry.env_float",
    "h2o3_tpu.scoring._env_buckets",
    "h2o3_tpu.parallel.ckpt.job_ckpt_iters",
    "h2o3_tpu.core.runtime.OptArgs.from_env",      # boot-time config fold
    "h2o3_tpu.core.sharded_frame.enabled",         # H2O_TPU_SHARDED_PLANE
    "h2o3_tpu.rapids.fusion.enabled",              # H2O_TPU_RAPIDS_FUSION
    "h2o3_tpu.rapids.planner.enabled",             # H2O_TPU_RAPIDS_LAZY —
    # reads process_count() too: deferral is deterministically OFF on
    # multi-process clouds (a coordinator-only flush must never dispatch
    # unmirrored collectives), so every process takes the same branch
    "h2o3_tpu.scoring.enabled",                    # H2O_TPU_SCORE_FAST —
    # the fused leaf routing (leaf_assignment/staged_proba replay) reads
    # it mirrored; like the sharded-plane switch, the documented contract
    # is "set identically on every process" (README env index)
    "h2o3_tpu.pipeline.enabled",                   # H2O_TPU_PIPELINE_FUSION
    # — requires planner.enabled() which is deterministically OFF on
    # multi-process clouds, so the splice never fires mirrored
    "h2o3_tpu.artifact.compile_cache.cache_dir",   # cache DIR (host I/O)
    # chunked sharded ingest knobs (ISSUE 15): read mirrored inside the
    # import_file / parse_stream op replays; the ops contract pins the
    # env uniform, and chunk layout is a pure function of (bytes, knobs)
    "h2o3_tpu.ingest.chunked.enabled",             # H2O_TPU_INGEST_CHUNKED
    "h2o3_tpu.ingest.chunked.chunk_bytes",         # H2O_TPU_INGEST_CHUNK_BYTES
    "h2o3_tpu.ingest.chunked.ingest_workers",      # H2O_TPU_INGEST_WORKERS
    "h2o3_tpu.ingest.chunked.parquet_batch",       # lazy-parquet batch width
    "h2o3_tpu.models.tree.pallas_hist.hist_budget_bytes",
    # — H2O_TPU_HIST_VMEM_MB: the frontier-tile budget is a pure function
    # of (env, geometry); the ops contract pins the env uniform, so every
    # process plans the same tiling and lowers the same program
    "h2o3_tpu.automl.search.search_concurrency",
    # — H2O_TPU_SEARCH_CONCURRENCY: deterministically 1 when oplog is
    # active (every process walks the identical member sequence); the
    # env/admission sizing only runs single-process
    "h2o3_tpu.automl.search.search_ckpt_enabled",
    # — H2O_TPU_SEARCH_CKPT gates host-side durable-state writes only;
    # it never shapes the member/program sequence
    "h2o3_tpu.automl.search.member_deadline_s",
    # — H2O_TPU_SEARCH_MEMBER_DEADLINE_S is deterministically 0 when
    # oplog is active (per-process deadline kills would desynchronize the
    # mirrored member walks)
    # HBM budget planner knobs (ISSUE 20): read mirrored inside fused
    # dispatch; the ops contract pins the env uniform, and the window
    # plan is a pure function of (env, rows, estimates) so every process
    # streams the same windows — and a chunked window computes bitwise
    # the same rows as a full dispatch by the row-local contract
    "h2o3_tpu.memory.budget.budget_mb",       # H2O_TPU_MEM_BUDGET_MB
    "h2o3_tpu.memory.budget.headroom",        # H2O_TPU_MEM_HEADROOM
    "h2o3_tpu.memory.budget.pressure_cooldown_s",
    # — H2O_TPU_MEM_PRESSURE_COOLDOWN_S gates host-side admission
    # shedding only; it never shapes a device program
})

# audited divergent-looking call sites that are mirrored-safe; reason is
# the audit note (shown next to any suppressed finding with --verbose)
GUARDED = {
    "h2o3_tpu.models.model_builder.random_seed":
        "the ONE seed-derivation policy: REST pins wildcard seeds before "
        "any broadcast (_pin_seed_and_wire), so this fresh entropy only "
        "runs library-mode (single process)",
    "h2o3_tpu.models.tree.pallas_hist.decide_lowering":
        "H2O_TPU_PALLAS_HIST read is env-contract-pinned; the auto-mode "
        "branch is wall-clock but multi-process clouds deterministically "
        "keep the matmul lowering (PR-7 hardening) — the timing path is "
        "single-process only",
    "h2o3_tpu.models.tree.pallas_hist.auto_decide":
        "three-way microbenchmark: wall-clock timing + cache-dir verdict "
        "reads, reachable only through decide_lowering's single-process "
        "auto branch (multi-process clouds never call it)",
    "h2o3_tpu.core.dkv.Key.make":
        "random key suffixes are process-local DKV names; cross-process "
        "keys always ride op payloads, never shape device programs",
    "h2o3_tpu.models.model_builder.ModelBuilder._out_of_time":
        "wall-clock budget gate: broadcast handlers clear "
        "max_runtime_secs before the op ships (train/grid/automl), so "
        "_deadline is None whenever this runs mirrored",
    "h2o3_tpu.models.model_builder.ModelBuilder.train":
        "t0/run_time_ms wall-clock reads are model metadata only; the "
        "deadline they seed is cleared for broadcast ops (see "
        "_out_of_time)",
    "h2o3_tpu.grid.H2OGridSearch.train":
        "wall-clock budget loop: the REST grid handler zeroes "
        "search_criteria max_runtime_secs before broadcast, so the "
        "time-based break never fires mirrored",
    "h2o3_tpu.automl.automl.H2OAutoML.__init__":
        "the timestamp default for project_name only fires when the "
        "caller passed none; broadcast specs always pin project_name "
        "(the coordinator's value rides the op payload)",
    "h2o3_tpu.automl.automl.H2OAutoML.train":
        "wall-clock budget + explore window: the REST AutoML handler "
        "zeroes max_runtime_secs before broadcast (recorded in the op "
        "spec), so budget branches never fire mirrored",
}

# control-plane / observability modules: they never lower or dispatch a
# device program, so per-process wall-clock / env decisions inside them
# cannot desynchronize collectives. Reachability still flows through.
HOST_SIDE_MODULES = {
    "h2o3_tpu/obs/": "observability plane: span ids/timestamps are "
                     "process-local by design",
    "h2o3_tpu/utils/": "logging/timeline/2D-table host utilities",
    "h2o3_tpu/api/": "REST layer runs on the coordinator only; broadcast "
                     "payload prep is covered by its own fixtures",
    "h2o3_tpu/parallel/retry.py": "backoff timing is per-process host "
                                  "waiting, not program lowering",
    "h2o3_tpu/parallel/supervisor.py": "health state machine (host)",
    "h2o3_tpu/parallel/watchdog.py": "recovery daemon (host)",
    "h2o3_tpu/parallel/distributed.py": "KV transport + leadership",
    "h2o3_tpu/parallel/ckpt.py": "durable-progress I/O timing is "
                                 "host-side; restored STATE is shared "
                                 "via one file by contract",
    "h2o3_tpu/parallel/oplog.py": "turnstile/ack deadlines are "
                                  "coordinator-host waiting; the replay "
                                  "handlers' CALLEES are the mirrored "
                                  "surface",
    "h2o3_tpu/admission.py": "serving admission happens before the op is "
                             "published; all processes see the op or "
                             "none do",
    "h2o3_tpu/core/failure.py": "heartbeat/health evidence is host-side "
                                "supervision input",
    "h2o3_tpu/core/job.py": "job lifecycle metadata (timestamps/status); "
                            "the device work lives in the builders",
    "h2o3_tpu/persist/": "storage backends (host I/O)",
    "h2o3_tpu/bench.py": "bench harness is operator-invoked, not "
                         "oplog-mirrored",
}

# ---------------------------------------------------------------------------
# lock-order pass
# ---------------------------------------------------------------------------

# modules whose lock acquisitions are modeled (ISSUE scope: the cloud
# control plane + the serving session)
LOCK_SCOPE = (
    "h2o3_tpu/parallel/",
    "h2o3_tpu/core/job.py",
    "h2o3_tpu/scoring.py",
)

# declared acquisition order: (outer, inner) pairs that are LEGAL; the
# observed reverse edge is a finding even without a full cycle. Lock ids
# are "<module-tail>.<name>" / "<module-tail>.<Class>.<attr>".
LOCK_ORDER = (
    # supervisor state machine may fail jobs (job.fail takes the status
    # lock) — job code must never call back into supervisor state
    ("supervisor._LOCK", "job.Job._status_lock"),
)

# ---------------------------------------------------------------------------
# serialization pass
# ---------------------------------------------------------------------------

# the sanctioned homes of restricted-Unpickler SUBCLASSES (a security
# surface that must not proliferate). NOTE: nothing is exempt from the
# raw pickle.load / allow_pickle=True ban — this list only bounds where
# Unpickler definitions may live; raw loads are findings everywhere.
PICKLE_ALLOWED = (
    "h2o3_tpu/utils/unpickle.py",
    "h2o3_tpu/parallel/ckpt.py",
    "h2o3_tpu/artifact/",
    "h2o3_tpu/api/routes_ext.py",
    "h2o3_genmodel/aot.py",
)

# ---------------------------------------------------------------------------
# compat-routing pass
# ---------------------------------------------------------------------------

# device-only / version-mobile jax APIs that must be imported via
# h2o3_tpu/compat.py (module prefix -> why)
DEVICE_ONLY_APIS = {
    "jax.experimental.shard_map": "moved to jax.shard_map in 0.5",
    "jax.shard_map": "absent before 0.5 — use compat.shard_map",
    "jax.experimental.serialize_executable": "moved/changed signature "
                                             "across releases",
    "jax.experimental.pallas": "TPU-only lowering; CPU fallback must not "
                               "import-crash",
    "jax.profiler": "kwargs shifted across releases; REST maps its "
                    "errors to clean 4xx",
}
COMPAT_MODULE = "h2o3_tpu/compat.py"

# ---------------------------------------------------------------------------
# compile-ledger pass (ISSUE 12)
# ---------------------------------------------------------------------------

# the ONE chokepoint allowed to run `.lower(...).compile(` /
# `compat.compile_stablehlo` / `compile_cache.note_compile` — every XLA
# compile must land a ledger row (family, signature, duration, cache
# disposition, HBM estimate) or /3/Runtime and the compile-seconds
# series silently under-count. h2o3_genmodel/ is exempt like the compat
# pass: the standalone runners are framework-free by contract.
COMPILE_LEDGER_MODULES = ("h2o3_tpu/obs/compiles.py",)

# module prefixes where BARE `jax.jit` is banned outright (ISSUE 17):
# every jit in these subsystems must be a `compiles.ledgered_jit` so the
# compiles it triggers land under the subsystem's family. models/tree/
# predates the ledger (histogram.py's bare @jax.jit was the one compile
# family /3/Runtime couldn't see) — this scope closes that hole.
JIT_LEDGER_SCOPE = ("h2o3_tpu/models/tree/",)

# ---------------------------------------------------------------------------
# sync-hygiene pass
# ---------------------------------------------------------------------------

# modules whose except-pass handlers are findings (watchdog/supervisor
# tick paths: a silently-dead recovery loop is an outage multiplier)
SWALLOW_SCOPE = (
    "h2o3_tpu/parallel/watchdog.py",
    "h2o3_tpu/parallel/supervisor.py",
)

# ---------------------------------------------------------------------------
# registry passes (folded from tests/test_consistency.py)
# ---------------------------------------------------------------------------

# test files whose STRINGS deliberately contain armed-looking faultpoint /
# pickle / span text (analysis fixtures, this analyzer's own suite)
FAULTPOINT_SCAN_EXCLUDE = (
    "tests/test_analysis.py",
    "tests/test_consistency.py",
)
