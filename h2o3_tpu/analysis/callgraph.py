"""Project model + call graph for the static-analysis passes.

Stdlib-``ast`` only. Every ``*.py`` under the scanned roots is parsed once
into a :class:`Project`: modules, top-level functions, class methods, a
class hierarchy (bases resolved through imports), and a call graph with
two resolution modes:

- **strict** — only edges the resolver can actually justify: direct names
  (same module or imported), ``module.attr`` calls through an imported
  project module, and ``self.m()`` / ``cls.m()`` resolved within the
  enclosing class family (ancestors + descendants). The lock-order pass
  builds on this shape (its own ``_call_targets`` adds a scoped-unique
  bare-name rule) because a speculative edge can fabricate a deadlock
  cycle.
- **loose** — strict plus bare-name attribute calls (``obj.m()`` on an
  arbitrary value resolves to every project method named ``m``). Used by
  the mirrored-program pass, where MISSING an edge means missing a
  divergence: the oplog replay handler reaches trainers through dynamic
  registries (``BUILDERS[algo]().train``), so reachability must
  over-approximate.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple


class ModuleInfo:
    __slots__ = ("path", "rel", "modname", "tree", "lines", "imports",
                 "text")

    def __init__(self, path: Path, rel: str, modname: str, text: str):
        self.path = path
        self.rel = rel                  # repo-relative posix path
        self.modname = modname          # dotted module name
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=str(path))
        # alias bound in this module -> dotted target ("oplog" ->
        # "h2o3_tpu.parallel.oplog", "load_model" ->
        # "h2o3_tpu.artifact.load_model")
        self.imports: Dict[str, str] = {}

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class FunctionInfo:
    __slots__ = ("qualname", "node", "module", "cls")

    def __init__(self, qualname: str, node: ast.AST, module: ModuleInfo,
                 cls: Optional[str]):
        self.qualname = qualname        # "pkg.mod.Class.meth" / "pkg.mod.fn"
        self.node = node
        self.module = module
        self.cls = cls                  # class qualname or None

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[1]


class ClassInfo:
    __slots__ = ("qualname", "node", "module", "bases", "methods")

    def __init__(self, qualname: str, node: ast.ClassDef,
                 module: ModuleInfo):
        self.qualname = qualname
        self.node = node
        self.module = module
        self.bases: List[str] = []      # resolved base class qualnames
        self.methods: Dict[str, str] = {}   # bare name -> fn qualname


def _modname_for(rel: str) -> str:
    parts = Path(rel).with_suffix("").parts
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class Project:
    """Parsed view of the repo's python sources (package roots only)."""

    def __init__(self, root: Path, pkg_dirs: Iterable[str] = ("h2o3_tpu",)):
        self.root = Path(root)
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.methods_by_name: Dict[str, List[str]] = {}
        self._family_cache: Dict[str, Set[str]] = {}
        self._callee_cache: Dict[Tuple[str, bool], Set[str]] = {}
        for pkg in pkg_dirs:
            base = self.root / pkg
            if not base.is_dir():
                continue
            for p in sorted(base.rglob("*.py")):
                if "__pycache__" in p.parts:
                    continue
                rel = p.relative_to(self.root).as_posix()
                try:
                    text = p.read_text(encoding="utf-8", errors="replace")
                    mod = ModuleInfo(p, rel, _modname_for(rel), text)
                except SyntaxError:
                    continue            # not this tool's finding to make
                self.modules[mod.modname] = mod
        for mod in self.modules.values():
            self._index_module(mod)
        self._resolve_bases()

    # -- indexing ---------------------------------------------------------
    def _index_module(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        mod.imports[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # relative import -> absolute (best-effort: the repo
                    # itself uses absolute imports throughout)
                    parent = mod.modname.rsplit(".", node.level)[0] \
                        if "." in mod.modname else mod.modname
                    base = f"{parent}.{base}" if base else parent
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports[a.asname or a.name] = f"{base}.{a.name}"
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{mod.modname}.{node.name}"
                self.functions[q] = FunctionInfo(q, node, mod, None)
            elif isinstance(node, ast.ClassDef):
                cq = f"{mod.modname}.{node.name}"
                ci = ClassInfo(cq, node, mod)
                self.classes[cq] = ci
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fq = f"{cq}.{sub.name}"
                        self.functions[fq] = FunctionInfo(fq, sub, mod, cq)
                        ci.methods[sub.name] = fq
                        self.methods_by_name.setdefault(
                            sub.name, []).append(fq)

    def _resolve_bases(self) -> None:
        for ci in self.classes.values():
            for b in ci.node.bases:
                name = None
                if isinstance(b, ast.Name):
                    name = b.id
                elif isinstance(b, ast.Attribute) and \
                        isinstance(b.value, ast.Name):
                    target = ci.module.imports.get(b.value.id)
                    if target:
                        name = f"{target}.{b.attr}"
                if name is None:
                    continue
                if name in self.classes:
                    ci.bases.append(name)
                    continue
                target = ci.module.imports.get(name, name)
                if target in self.classes:
                    ci.bases.append(target)
                else:
                    same = f"{ci.module.modname}.{name}"
                    if same in self.classes:
                        ci.bases.append(same)

    # -- class family (ancestors + descendants) ---------------------------
    def family(self, cls_qualname: str) -> Set[str]:
        cached = self._family_cache.get(cls_qualname)
        if cached is not None:
            return cached
        up: Set[str] = set()
        stack = [cls_qualname]
        while stack:
            c = stack.pop()
            if c in up:
                continue
            up.add(c)
            ci = self.classes.get(c)
            if ci:
                stack.extend(ci.bases)
        down: Set[str] = set(up)
        changed = True
        while changed:
            changed = False
            for q, ci in self.classes.items():
                if q not in down and any(b in down for b in ci.bases):
                    down.add(q)
                    changed = True
        self._family_cache[cls_qualname] = down
        return down

    def _family_methods(self, cls_qualname: str, name: str) -> List[str]:
        out = []
        for c in self.family(cls_qualname):
            fq = self.classes[c].methods.get(name) if c in self.classes \
                else None
            if fq:
                out.append(fq)
        return out

    # -- call resolution --------------------------------------------------
    def callees(self, qualname: str, loose: bool = False) -> Set[str]:
        """Project-function qualnames the body of `qualname` may call."""
        key = (qualname, loose)
        cached = self._callee_cache.get(key)
        if cached is not None:
            return cached
        fi = self.functions.get(qualname)
        out: Set[str] = set()
        if fi is None:
            self._callee_cache[key] = out
            return out
        mod = fi.module
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name):
                target = mod.imports.get(fn.id)
                if target and target in self.functions:
                    out.add(target)
                elif target and target in self.classes:
                    init = self.classes[target].methods.get("__init__")
                    if init:
                        out.add(init)
                elif f"{mod.modname}.{fn.id}" in self.functions:
                    out.add(f"{mod.modname}.{fn.id}")
                elif f"{mod.modname}.{fn.id}" in self.classes:
                    init = self.classes[
                        f"{mod.modname}.{fn.id}"].methods.get("__init__")
                    if init:
                        out.add(init)
            elif isinstance(fn, ast.Attribute):
                base = fn.value
                if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                        and fi.cls:
                    out.update(self._family_methods(fi.cls, fn.attr))
                elif isinstance(base, ast.Name):
                    target = mod.imports.get(base.id)
                    if target and f"{target}.{fn.attr}" in self.functions:
                        out.add(f"{target}.{fn.attr}")
                    elif target and f"{target}.{fn.attr}" in self.classes:
                        init = self.classes[
                            f"{target}.{fn.attr}"].methods.get("__init__")
                        if init:
                            out.add(init)
                    elif target and target in self.classes:
                        # ClassName.method(...) through an imported class
                        m = self.classes[target].methods.get(fn.attr)
                        if m:
                            out.add(m)
                    elif loose:
                        out.update(self.methods_by_name.get(fn.attr, ()))
                elif loose:
                    out.update(self.methods_by_name.get(fn.attr, ()))
        self._callee_cache[key] = out
        return out

    def reachable(self, roots: Iterable[str], loose: bool = True) \
            -> Set[str]:
        """Transitive closure of :meth:`callees` from `roots`."""
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(c for c in self.callees(q, loose=loose)
                         if c not in seen)
        return seen
