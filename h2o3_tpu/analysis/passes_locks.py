"""Lock-order pass: deadlock cycles + declared-order violations.

Scope: the modules named in ``registry.LOCK_SCOPE`` (the cloud control
plane and the serving session — where the ``Job._status_lock`` vs
supervisor-state-lock class of race was found by hand in PR 5).

The pass identifies every lock object (module-level ``threading.Lock/
RLock/Condition`` assignments and ``self.X = threading.Lock()`` instance
attributes), extracts acquisition nesting — ``with`` blocks, including
acquisitions made by functions CALLED inside a held block (one closure
over the call graph) — and reports:

- **cycles** in the resulting lock graph (a potential AB/BA deadlock),
- **self-nesting** of a non-reentrant ``Lock``,
- **reversals** of the declared order pairs in ``registry.LOCK_ORDER``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from h2o3_tpu.analysis.core import Context, Finding

PASS_ID = "lock-order"

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def _mod_tail(modname: str) -> str:
    return modname.rsplit(".", 1)[-1]


def _is_lock_ctor(node, imports) -> Optional[str]:
    """'Lock'/'RLock'/... when `node` constructs a threading primitive."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and imports.get(fn.value.id, fn.value.id) == "threading" \
            and fn.attr in _LOCK_CTORS:
        return fn.attr
    if isinstance(fn, ast.Name) and fn.id in _LOCK_CTORS and \
            imports.get(fn.id, "").startswith("threading."):
        return fn.id
    return None


class _LockIndex:
    def __init__(self):
        self.kinds: Dict[str, str] = {}          # lock id -> ctor kind
        # module tail -> {name -> lock id} (module-level locks)
        self.module_locks: Dict[str, Dict[str, str]] = {}
        # attr name -> {lock ids} (instance locks, for `obj.attr` sites)
        self.attr_locks: Dict[str, Set[str]] = {}
        # class qualname tail -> {attr -> lock id}
        self.class_locks: Dict[str, Dict[str, str]] = {}


def _index_locks(ctx: Context, scoped_mods) -> _LockIndex:
    idx = _LockIndex()
    for mod in scoped_mods:
        tail = _mod_tail(mod.modname)
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _is_lock_ctor(node.value, mod.imports)
                if kind:
                    lid = f"{tail}.{node.targets[0].id}"
                    idx.kinds[lid] = kind
                    idx.module_locks.setdefault(tail, {})[
                        node.targets[0].id] = lid
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    kind = _is_lock_ctor(node.value, mod.imports)
                    if kind:
                        cls = _enclosing_class(mod, node)
                        if cls:
                            lid = f"{tail}.{cls}.{tgt.attr}"
                            idx.kinds[lid] = kind
                            idx.attr_locks.setdefault(tgt.attr,
                                                      set()).add(lid)
                            idx.class_locks.setdefault(cls, {})[
                                tgt.attr] = lid
            # class-level: `_slock = threading.RLock()` inside a ClassDef
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.Assign) and \
                            len(sub.targets) == 1 and \
                            isinstance(sub.targets[0], ast.Name):
                        kind = _is_lock_ctor(sub.value, mod.imports)
                        if kind:
                            lid = f"{tail}.{node.name}." \
                                  f"{sub.targets[0].id}"
                            idx.kinds[lid] = kind
                            idx.attr_locks.setdefault(
                                sub.targets[0].id, set()).add(lid)
                            idx.class_locks.setdefault(node.name, {})[
                                sub.targets[0].id] = lid
    return idx


def _enclosing_class(mod, target) -> Optional[str]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            for sub in ast.walk(node):
                if sub is target:
                    return node.name
    return None


def _resolve_lock(expr, mod, fi, idx: _LockIndex) -> Optional[str]:
    """Lock id for a with-item / acquire() receiver expression."""
    tail = _mod_tail(mod.modname)
    if isinstance(expr, ast.Name):
        lid = idx.module_locks.get(tail, {}).get(expr.id)
        if lid:
            return lid
        target = mod.imports.get(expr.id)
        if target:
            mt, _, name = target.rpartition(".")
            lid = idx.module_locks.get(_mod_tail(mt), {}).get(name)
            if lid:
                return lid
        return None
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            cls = (fi.cls or "").rsplit(".", 1)[-1]
            lid = idx.class_locks.get(cls, {}).get(expr.attr)
            if lid:
                return lid
            cands = idx.attr_locks.get(expr.attr, set())
            return next(iter(cands)) if len(cands) == 1 else None
        if isinstance(base, ast.Name):
            target = mod.imports.get(base.id)
            if target:
                lid = idx.module_locks.get(_mod_tail(target),
                                           {}).get(expr.attr)
                if lid:
                    return lid
            # `job._status_lock` style: unique instance-attr owner wins
        cands = idx.attr_locks.get(expr.attr, set())
        return next(iter(cands)) if len(cands) == 1 else None
    return None


def _direct_acquisitions(fi, idx) -> List[Tuple[str, ast.AST]]:
    out = []
    for node in ast.walk(fi.node):
        if isinstance(node, ast.With):
            for item in node.items:
                lid = _resolve_lock(item.context_expr, fi.module, fi, idx)
                if lid:
                    out.append((lid, node))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire":
            lid = _resolve_lock(node.func.value, fi.module, fi, idx)
            if lid:
                out.append((lid, node))
    return out


def run(ctx: Context) -> List[Finding]:
    proj = ctx.project
    scope = tuple(ctx.reg("LOCK_SCOPE", ()))
    scoped_mods = [m for m in proj.modules.values()
                   if any(m.rel == s or m.rel.startswith(s)
                          for s in scope)]
    idx = _index_locks(ctx, scoped_mods)
    scoped_fns = [fi for fi in proj.functions.values()
                  if fi.module in scoped_mods]

    # bare-name `obj.m()` calls resolve ONLY when exactly one scoped
    # method bears the name (e.g. `job.fail()` -> Job.fail): callgraph
    # strict mode plus this uniqueness rule — a speculative loose edge
    # could fabricate a deadlock cycle out of two unrelated same-named
    # methods that each take a lock
    counts: Dict[str, List[str]] = {}
    for fi in scoped_fns:
        if fi.cls:
            counts.setdefault(fi.name, []).append(fi.qualname)
    scoped_unique = {n: qs[0] for n, qs in counts.items() if len(qs) == 1}
    by_fn: Dict[str, Set[str]] = {}
    for fi in scoped_fns:
        targets: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                targets |= _call_targets(node, fi, proj, scoped_unique)
        by_fn[fi.qualname] = targets

    # closure: every lock a function may acquire (itself or via calls)
    acq: Dict[str, Set[str]] = {
        fi.qualname: {lid for lid, _ in _direct_acquisitions(fi, idx)}
        for fi in scoped_fns}
    changed = True
    while changed:
        changed = False
        for fi in scoped_fns:
            mine = acq[fi.qualname]
            for callee in by_fn[fi.qualname]:
                extra = acq.get(callee)
                if extra and not extra <= mine:
                    mine |= extra
                    changed = True

    # edges: held lock -> lock acquired inside the held block
    edges: Dict[Tuple[str, str], List[str]] = {}

    def note(outer, inner, where):
        if outer == inner and idx.kinds.get(outer) != "Lock":
            return                       # re-entrant self-nesting is fine
        edges.setdefault((outer, inner), []).append(where)

    for fi in scoped_fns:
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.With):
                continue
            lids = [_resolve_lock(it.context_expr, fi.module, fi, idx)
                    for it in node.items]
            lids = [lid for lid in lids if lid]
            if not lids:
                continue
            where = f"{fi.module.rel}:{node.lineno}"
            for a, b in zip(lids, lids[1:]):
                note(a, b, where)
            body_calls: Set[str] = set()
            inner_direct: List[str] = []
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.With):
                        for it in sub.items:
                            lid = _resolve_lock(it.context_expr,
                                                fi.module, fi, idx)
                            if lid:
                                inner_direct.append(lid)
                    elif isinstance(sub, ast.Call):
                        if isinstance(sub.func, ast.Attribute) and \
                                sub.func.attr == "acquire":
                            lid = _resolve_lock(sub.func.value,
                                                fi.module, fi, idx)
                            if lid:
                                inner_direct.append(lid)
                        body_calls.add(id(sub))
            held = lids[-1]
            for lid in inner_direct:
                note(held, lid, where)
            # acquisitions by functions called while held
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        for callee in _call_targets(sub, fi, proj,
                                                    scoped_unique):
                            for lid in acq.get(callee, ()):
                                note(held, lid,
                                     f"{where} via "
                                     f"{callee.rsplit('.', 1)[-1]}()")

    findings: List[Finding] = []

    def emit(file_hint, message, symbol):
        findings.append(Finding(PASS_ID, file_hint, 0, message,
                                symbol=symbol, snippet=symbol))

    # registry self-check: a LOCK_SCOPE entry that matches no module
    # would silently shrink the scan to nothing (the renamed-faultpoint
    # failure mode, applied to this registry)
    for s in scope:
        if not any(m.rel == s or m.rel.startswith(s)
                   for m in proj.modules.values()):
            emit("h2o3_tpu/analysis/registry.py",
                 f"LOCK_SCOPE entry `{s}` matches no module — the lock "
                 f"scan silently lost that scope; fix the path", symbol=s)

    # self-deadlock on a non-reentrant Lock
    for (a, b), sites in sorted(edges.items()):
        if a == b and idx.kinds.get(a) == "Lock":
            emit(sites[0].split(":")[0],
                 f"non-reentrant Lock `{a}` may be acquired while already "
                 f"held ({sites[0]}) — self-deadlock", symbol=a)

    # declared-order reversals
    for outer, inner in ctx.reg("LOCK_ORDER", ()):
        rev = edges.get((inner, outer))
        if rev:
            emit(rev[0].split(":")[0],
                 f"declared lock order `{outer}` -> `{inner}` is reversed "
                 f"at {rev[0]} — AB/BA deadlock with the declared sites",
                 symbol=f"{inner}->{outer}")

    # cycles (Tarjan SCC)
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        if a != b:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
    for comp in _sccs(graph):
        if len(comp) > 1:
            comp = sorted(comp)
            sites = [s for (a, b), ss in edges.items()
                     if a in comp and b in comp for s in ss[:1]]
            emit(sites[0].split(":")[0] if sites else "h2o3_tpu/",
                 f"lock cycle {' -> '.join(comp)} -> {comp[0]} "
                 f"(sites: {', '.join(sites[:4])}) — potential deadlock",
                 symbol="+".join(comp))
    return findings


def _call_targets(call, fi, proj, scoped_unique: Dict[str, str]) \
        -> Set[str]:
    """Strict resolution (names, module attrs, self/cls family) plus
    bare ``obj.m()`` ONLY via the scoped-uniqueness map — never the
    global loose fallback, which fabricates edges between unrelated
    same-named methods."""
    fn = call.func
    out: Set[str] = set()
    mod = fi.module
    if isinstance(fn, ast.Name):
        target = mod.imports.get(fn.id)
        if target in proj.functions:
            out.add(target)
        elif f"{mod.modname}.{fn.id}" in proj.functions:
            out.add(f"{mod.modname}.{fn.id}")
    elif isinstance(fn, ast.Attribute):
        base = fn.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                and fi.cls:
            out.update(proj._family_methods(fi.cls, fn.attr))
        elif isinstance(base, ast.Name):
            target = mod.imports.get(base.id)
            if target and f"{target}.{fn.attr}" in proj.functions:
                out.add(f"{target}.{fn.attr}")
            elif fn.attr in scoped_unique:
                out.add(scoped_unique[fn.attr])
        elif fn.attr in scoped_unique:
            out.add(scoped_unique[fn.attr])
    return out


def _sccs(graph: Dict[str, Set[str]]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strong(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in graph.get(v, ()):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(comp)

    import sys
    old = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old, 10000))
    try:
        for v in graph:
            if v not in index:
                strong(v)
    finally:
        sys.setrecursionlimit(old)
    return out
