"""Finding/baseline plumbing shared by every analysis pass.

A finding's **fingerprint** is content-addressed — sha1 over (pass id,
repo-relative file, enclosing symbol, the stripped source line text) — so
baseline entries survive unrelated line drift but go STALE the moment the
offending line changes or disappears. Stale entries are themselves
findings: a baseline that references nothing keeps nobody honest.

Baselines are deliberately narrow: only the ``sync-hygiene`` and
``compat-routing`` passes may be baselined (benign, audited leftovers).
A baseline entry against any other pass is an error finding — mirrored-
program, lock-order and serialization violations get FIXED, not filed.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from h2o3_tpu.analysis.callgraph import Project

BASELINE_NAME = "ANALYSIS_BASELINE.json"
# passes whose findings may be accepted into the baseline (with a note)
BASELINEABLE = frozenset({"sync-hygiene", "compat-routing"})


@dataclass
class Finding:
    pass_id: str
    file: str              # repo-relative posix path
    line: int
    message: str
    symbol: str = ""       # enclosing function/class qualname (tail)
    snippet: str = ""      # stripped source line (fingerprint input)
    note: str = ""         # set when matched by a baseline entry

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1()
        h.update("|".join((self.pass_id, self.file, self.symbol,
                           self.snippet)).encode("utf-8"))
        return h.hexdigest()[:12]

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.file}:{self.line}: [{self.pass_id}]{sym} "
                f"{self.message}  (fp={self.fingerprint})")

    def to_dict(self) -> dict:
        return {"pass": self.pass_id, "file": self.file, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "fingerprint": self.fingerprint, "snippet": self.snippet}


@dataclass
class Context:
    """Everything a pass needs: parsed project + registry + roots."""

    root: Path
    project: Project
    registry: object            # registry module (or a test stand-in)
    tests_dir: Optional[Path] = None
    _cache: dict = field(default_factory=dict)

    def reg(self, name: str, default=None):
        return getattr(self.registry, name, default)

    def finding(self, pass_id: str, module, node, message: str,
                symbol: str = "") -> Finding:
        line = getattr(node, "lineno", 0) if node is not None else 0
        return Finding(pass_id=pass_id, file=module.rel, line=line,
                       message=message, symbol=symbol,
                       snippet=module.line(line))


def make_context(root: Optional[Path] = None, registry=None) -> Context:
    from h2o3_tpu.analysis import registry as default_registry

    if root is None:
        root = Path(__file__).resolve().parents[2]
    root = Path(root)
    pkgs = [d for d in ("h2o3_tpu", "h2o3_genmodel") if (root / d).is_dir()]
    project = Project(root, pkg_dirs=pkgs or ("h2o3_tpu",))
    tests = root / "tests"
    return Context(root=root, project=project,
                   registry=registry or default_registry,
                   tests_dir=tests if tests.is_dir() else None)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: Path) -> List[dict]:
    if not Path(path).is_file():
        return []
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    entries = data.get("entries", []) if isinstance(data, dict) else data
    return [e for e in entries if isinstance(e, dict)]


def save_baseline(path: Path, findings: List[Finding],
                  notes: Optional[Dict[str, str]] = None,
                  keep_entries: Optional[List[dict]] = None) -> None:
    """Write accepted findings as a baseline, preserving notes by
    fingerprint. Refuses non-baselineable passes. `keep_entries` are
    existing entries carried over verbatim (a partial ``--select``
    update must not delete entries belonging to unselected passes)."""
    notes = notes or {}
    entries = []
    for f in sorted(findings, key=lambda f: (f.file, f.line)):
        if f.pass_id not in BASELINEABLE:
            raise ValueError(
                f"finding {f.fingerprint} ({f.pass_id}) is not "
                f"baselineable — fix it ({', '.join(sorted(BASELINEABLE))} "
                f"only)")
        entries.append({
            "fingerprint": f.fingerprint, "pass": f.pass_id,
            "file": f.file, "symbol": f.symbol,
            "note": notes.get(f.fingerprint, f.note
                              or "TODO: one-line justification"),
        })
    have = {e["fingerprint"] for e in entries}
    for e in keep_entries or []:
        if e.get("fingerprint") not in have:
            entries.append(e)
    payload = {"version": 1, "entries": entries}
    Path(path).write_text(json.dumps(payload, indent=2) + "\n",
                          encoding="utf-8")


def apply_baseline(findings: List[Finding], entries: List[dict]) \
        -> Tuple[List[Finding], List[Finding]]:
    """Split (new, problems): `new` are findings not covered by the
    baseline; `problems` are baseline-hygiene findings (stale entries,
    entries against non-baselineable passes, missing notes)."""
    by_fp: Dict[str, List[Finding]] = {}
    for f in findings:
        by_fp.setdefault(f.fingerprint, []).append(f)
    new = list(findings)
    problems: List[Finding] = []
    for e in entries:
        fp = str(e.get("fingerprint", ""))
        pid = str(e.get("pass", ""))
        note = str(e.get("note", "")).strip()
        if pid not in BASELINEABLE:
            problems.append(Finding(
                "baseline", BASELINE_NAME, 0,
                f"entry {fp} accepts a {pid!r} finding — only "
                f"{sorted(BASELINEABLE)} may be baselined; fix the code",
                symbol=fp, snippet=fp))
            continue
        if not note or note.startswith("TODO"):
            problems.append(Finding(
                "baseline", BASELINE_NAME, 0,
                f"entry {fp} has no justification note", symbol=fp,
                snippet=fp))
        hits = by_fp.get(fp)
        if not hits:
            problems.append(Finding(
                "baseline", BASELINE_NAME, 0,
                f"stale entry {fp} ({e.get('file')}): the finding it "
                f"accepts no longer exists — remove it", symbol=fp,
                snippet=fp))
            continue
        # one entry covers EVERY finding sharing the fingerprint (the
        # same line repeated at several call sites hashes identically)
        for hit in hits:
            hit.note = note
            if hit in new:
                new.remove(hit)
    return new, problems
