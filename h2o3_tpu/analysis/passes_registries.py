"""Registry passes: the four text guards folded in from the consistency
suite (ISSUE 11 satellite) so there is ONE invariant engine.

- **faultpoints** — every faultpoint a test arms must exist in source (a
  renamed faultpoint silently defuses its chaos tests);
- **metric-registry** — metric names unique, ``^h2o3_[a-z0-9_]+$``, and
  at least the promised series count (the live-registry agreement half
  stays a behavioral test);
- **timeline-kinds** — every recorded timeline kind is declared in
  ``utils/timeline.py KINDS`` and no declared kind is dead;
- **knob-docs** — every ``H2O_TPU_*`` env knob read in source is
  documented in README.md.
"""

from __future__ import annotations

import ast
import re
from collections import Counter
from typing import List

from h2o3_tpu.analysis.core import Context, Finding

_MIN_METRICS = 20

# the one source-scan pattern for metric registrations — the live-registry
# behavioral test (tests/test_consistency.py) reuses it so the two halves
# of the guard can never drift apart
METRIC_REG_PAT = re.compile(
    r"\br\.(?:counter|gauge|histogram)(?:_fn)?\(\s*['\"]([^'\"]+)['\"]")


def _src_texts(ctx: Context):
    for mod in ctx.project.modules.values():
        if mod.rel.startswith("h2o3_tpu/"):
            yield mod


def _test_texts(ctx: Context, exclude=()):
    if ctx.tests_dir is None:
        return
    for p in sorted(ctx.tests_dir.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        rel = p.relative_to(ctx.root).as_posix()
        if rel in exclude:
            continue
        yield rel, p.read_text(encoding="utf-8", errors="replace")


def run_faultpoints(ctx: Context) -> List[Finding]:
    defined = set()
    for mod in _src_texts(ctx):
        defined |= set(re.findall(
            r"faultpoint\(\s*['\"]([^'\"]+)['\"]", mod.text))
    exclude = ctx.reg("FAULTPOINT_SCAN_EXCLUDE", ())
    armed = {}
    for rel, text in _test_texts(ctx, exclude):
        for name in re.findall(r"\binject\(\s*['\"]([^'\"]+)['\"]", text):
            armed.setdefault(name, rel)
        for name in re.findall(r"_FAULTS\[\s*['\"]([^'\"]+)['\"]\s*\]",
                               text):
            armed.setdefault(name, rel)
        # mechanism self-tests define throwaway faultpoints inline
        defined |= set(re.findall(r"faultpoint\(\s*['\"]([^'\"]+)['\"]",
                                  text))
    return [Finding("faultpoints", rel, 0,
                    f"test arms faultpoint `{name}` that no longer exists "
                    f"in h2o3_tpu/ — a renamed faultpoint silently "
                    f"defuses its chaos tests", symbol=name, snippet=name)
            for name, rel in sorted(armed.items()) if name not in defined]


def run_metric_registry(ctx: Context) -> List[Finding]:
    names: Counter = Counter()
    where = {}
    for mod in _src_texts(ctx):
        for m in METRIC_REG_PAT.finditer(mod.text):
            names[m.group(1)] += 1
            where.setdefault(m.group(1), mod.rel)
    findings: List[Finding] = []
    if not names:
        findings.append(Finding("metric-registry", "h2o3_tpu/", 0,
                                "no metric registrations found",
                                snippet="none"))
        return findings
    for n in sorted(names):
        if not re.match(r"^h2o3_[a-z0-9_]+$", n):
            findings.append(Finding(
                "metric-registry", where[n], 0,
                f"metric name `{n}` does not match ^h2o3_[a-z0-9_]+$ — "
                f"Prometheus scrapes reject it", symbol=n, snippet=n))
        if names[n] > 1:
            findings.append(Finding(
                "metric-registry", where[n], 0,
                f"metric `{n}` registered {names[n]} times — the registry "
                f"raises on the second registration", symbol=n,
                snippet=n))
    if len(names) < _MIN_METRICS:
        findings.append(Finding(
            "metric-registry", "h2o3_tpu/obs/metrics.py", 0,
            f"only {len(names)} metrics registered — /3/Metrics promises "
            f">= {_MIN_METRICS} series", snippet="count"))
    return findings


def _declared_kinds(ctx: Context) -> set:
    mod = ctx.project.modules.get("h2o3_tpu.utils.timeline")
    if mod is None:
        return set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "KINDS":
            return {n.value for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)}
    return set()


def run_timeline_kinds(ctx: Context) -> List[Finding]:
    declared = _declared_kinds(ctx)
    call_pat = re.compile(
        r"\btimeline\.(?:record|task)\(\s*['\"]([^'\"]+)['\"]")
    bare_pat = re.compile(r"(?<![\w.])record\(\s*['\"]([^'\"]+)['\"]")
    used = {}
    for mod in _src_texts(ctx):
        for m in call_pat.finditer(mod.text):
            used.setdefault(m.group(1), mod.rel)
        if mod.rel.endswith("utils/timeline.py"):
            for m in bare_pat.finditer(mod.text):
                used.setdefault(m.group(1), mod.rel)
    findings = [Finding("timeline-kinds", rel, 0,
                        f"timeline kind `{k}` is recorded but not "
                        f"declared in utils/timeline.py KINDS (the "
                        f"enumeration is the ring's query surface)",
                        symbol=k, snippet=k)
                for k, rel in sorted(used.items()) if k not in declared]
    for k in sorted(declared - set(used) - {"rest"}):
        findings.append(Finding(
            "timeline-kinds", "h2o3_tpu/utils/timeline.py", 0,
            f"timeline kind `{k}` is declared in KINDS but never "
            f"recorded — drop it or record it", symbol=k, snippet=k))
    findings.extend(_phase_name_findings(ctx))
    return findings


def _declared_phases(ctx: Context) -> set:
    mod = ctx.project.modules.get("h2o3_tpu.obs.phases")
    if mod is None:
        return set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "PHASES":
            return {n.value for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)}
    return set()


def _phase_name_findings(ctx: Context) -> List[Finding]:
    """The lifecycle-phase enumeration half of the timeline-kinds guard
    (ISSUE 12): every phase literal passed to obs.phases ``enter`` must
    be declared in ``obs/phases.py PHASES``, and every declared phase
    must be entered somewhere — a dead phase name makes /3/Runtime's
    table lie."""
    declared = _declared_phases(ctx)
    enter_pat = re.compile(r"\bphases\.enter\(\s*['\"]([^'\"]+)['\"]")
    used = {}
    for mod in _src_texts(ctx):
        for m in enter_pat.finditer(mod.text):
            used.setdefault(m.group(1), mod.rel)
    if not declared and not used:
        # synthetic fixture projects without a phase tracker have
        # nothing to guard; a real repo that renamed obs/phases.py but
        # kept enter() calls still gets findings below
        return []
    findings = [Finding(
        "timeline-kinds", rel, 0,
        f"lifecycle phase `{p}` is entered but not declared in "
        f"obs/phases.py PHASES (closed enumeration)", symbol=p, snippet=p)
        for p, rel in sorted(used.items()) if p not in declared]
    for p in sorted(declared - set(used)):
        findings.append(Finding(
            "timeline-kinds", "h2o3_tpu/obs/phases.py", 0,
            f"lifecycle phase `{p}` is declared in PHASES but never "
            f"entered — drop it or wrap its boot step", symbol=p,
            snippet=p))
    return findings


def run_knob_docs(ctx: Context) -> List[Finding]:
    used = {}
    for mod in _src_texts(ctx):
        for m in re.finditer(r"\bH2O_TPU_[A-Z0-9_]+\b", mod.text):
            used.setdefault(m.group(0), mod.rel)
    readme = ctx.root / "README.md"
    documented = set()
    if readme.is_file():
        documented = set(re.findall(
            r"\bH2O_TPU_[A-Z0-9_]+\b",
            readme.read_text(encoding="utf-8", errors="replace")))
    return [Finding("knob-docs", rel, 0,
                    f"env knob `{k}` is read in h2o3_tpu/ but not "
                    f"documented in README.md — operators discover knobs "
                    f"there, not by grepping source", symbol=k, snippet=k)
            for k, rel in sorted(used.items()) if k not in documented]
