"""Distributed-invariant static analyzer (ISSUE 11).

H2O-3's engine correctness rests on invariants no compiler checks: every
process must walk an identical device-program sequence when replaying the
oplog, locks must nest in one global order, nothing may raw-unpickle
external bytes, device-only jax APIs must route through ``compat.py``,
and trace spans must not smuggle device syncs into hot paths. Six review
rounds across PRs 3-9 re-found violations of exactly these classes by
hand; this package checks them at the program level, before execution
("Memory Safe Computations with XLA Compiler" applies the same idea to
resource safety).

Usage::

    python -m h2o3_tpu.analysis              # all passes, repo root
    python -m h2o3_tpu.analysis --json       # machine-readable findings
    python -m h2o3_tpu.analysis --select mirrored,lock-order
    python -m h2o3_tpu.analysis --update-baseline   # accept benign rest

Exit code 0 = zero non-baselined findings. The baseline
(``ANALYSIS_BASELINE.json``) may only carry ``sync-hygiene`` /
``compat-routing`` entries, each with a one-line justification; stale
entries are findings themselves. Tier-1 wiring: the consistency suite
runs the full analyzer and asserts a clean exit.

Everything is stdlib-``ast`` based — no new dependencies, no imports of
the framework's heavy modules, full-repo run well under the 10 s budget.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional

from h2o3_tpu.analysis.core import (BASELINE_NAME, BASELINEABLE, Context,
                                    Finding, apply_baseline, load_baseline,
                                    make_context, save_baseline)

__all__ = ["Finding", "Context", "PASSES", "make_context", "run",
           "run_repo", "load_baseline", "save_baseline", "apply_baseline",
           "BASELINE_NAME", "BASELINEABLE"]


def _passes() -> Dict[str, object]:
    from h2o3_tpu.analysis import (passes_locks, passes_mirrored,
                                   passes_misc, passes_registries)

    return {
        "mirrored": passes_mirrored.run,
        "lock-order": passes_locks.run,
        "serialization": passes_misc.run_serialization,
        "compat-routing": passes_misc.run_compat,
        "compile-ledger": passes_misc.run_compile_ledger,
        "sync-hygiene": passes_misc.run_sync_hygiene,
        "faultpoints": passes_registries.run_faultpoints,
        "metric-registry": passes_registries.run_metric_registry,
        "timeline-kinds": passes_registries.run_timeline_kinds,
        "knob-docs": passes_registries.run_knob_docs,
    }


PASSES = _passes()


def run(ctx: Context, passes: Optional[List[str]] = None) -> List[Finding]:
    """Run the selected passes (default: all) over `ctx`, deduplicated
    and ordered by (file, line, pass)."""
    selected = list(PASSES) if passes is None else list(passes)
    unknown = [p for p in selected if p not in PASSES]
    if unknown:
        raise ValueError(f"unknown pass(es) {unknown}; "
                         f"available: {sorted(PASSES)}")
    findings: List[Finding] = []
    seen = set()
    for name in selected:
        for f in PASSES[name](ctx):
            key = (f.pass_id, f.file, f.line, f.message)
            if key not in seen:
                seen.add(key)
                findings.append(f)
    findings.sort(key=lambda f: (f.file, f.line, f.pass_id, f.message))
    return findings


def run_repo(root: Optional[Path] = None,
             passes: Optional[List[str]] = None,
             baseline: Optional[Path] = None):
    """One-call repo run: returns ``(new_findings, baselined, problems)``
    where `new_findings` must be empty for a clean exit, `baselined` are
    accepted findings (note attached) and `problems` are baseline-hygiene
    findings (stale entries, illegal passes, missing notes)."""
    ctx = make_context(root)
    findings = run(ctx, passes)
    bl_path = Path(baseline) if baseline else ctx.root / BASELINE_NAME
    entries = load_baseline(bl_path)
    if passes is not None:
        # a partial run produces findings for the SELECTED passes only —
        # judging the whole baseline against it would misreport every
        # unselected pass's entry as stale
        entries = [e for e in entries if e.get("pass") in passes]
    covered_before = list(findings)
    new, problems = apply_baseline(findings, entries)
    baselined = [f for f in covered_before if f not in new]
    return new, baselined, problems
