"""Serialization, compat-routing and sync-hygiene passes.

- **serialization** — repo-wide ban on raw ``pickle.load(s)`` and
  ``np.load(allow_pickle=True)`` outside the restricted-unpickler homes
  (``registry.PICKLE_ALLOWED``): anything crossing a file/KV boundary is
  untrusted input and one raw load is a pickle-RCE door.
- **compat-routing** — device-only / version-mobile jax APIs
  (``registry.DEVICE_ONLY_APIS``) must be imported through
  ``h2o3_tpu/compat.py``, never directly: a direct import crashes the
  CPU/old-jax fallback paths the container relies on.
- **sync-hygiene** — inside ``obs.tracing.span(...)``-instrumented
  blocks, device-sync-forcing calls (``np.asarray``/``np.array`` on
  device values, ``.block_until_ready()``, ``jax.device_get``,
  ``float()/int()`` on arrays) are flagged: a span that silently blocks
  turns the observability plane into a perf regression. Plus the
  swallowed-exception lint (``except: pass``) in the watchdog/supervisor
  tick paths — a silently-dead recovery loop is an outage multiplier.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from h2o3_tpu.analysis.core import Context, Finding
from h2o3_tpu.analysis.passes_mirrored import _dotted, _normalize

# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------

def run_serialization(ctx: Context) -> List[Finding]:
    """No module is exempt from the raw-load ban (zero raw loads exist
    after ISSUE 11, so an allowlist hole would only ever hide a NEW one).
    ``PICKLE_ALLOWED`` instead bounds where ``pickle.Unpickler``
    subclasses may be DEFINED — restricted unpicklers are a security
    surface and must not proliferate into bespoke per-module copies.
    Both call sites (``pickle.load(f)``) and bare references
    (``loads = loads or pickle.loads``) are findings."""
    allowed = tuple(ctx.reg("PICKLE_ALLOWED", ()))
    findings: List[Finding] = []
    for mod in ctx.project.modules.values():
        seen_lines = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Attribute, ast.Name)):
                name = _normalize(_dotted(node), mod.imports) \
                    if isinstance(node, ast.Attribute) \
                    else mod.imports.get(node.id)
                if name in ("pickle.load", "pickle.loads") and \
                        node.lineno not in seen_lines:
                    seen_lines.add(node.lineno)
                    findings.append(ctx.finding(
                        "serialization", mod, node,
                        f"raw `{name}` on external bytes — route through "
                        f"the restricted unpickler (utils/unpickle.py); "
                        f"arbitrary pickles are remote code execution",
                        symbol=mod.rel))
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "allow_pickle" and \
                            isinstance(kw.value, ast.Constant) and \
                            kw.value.value is True:
                        findings.append(ctx.finding(
                            "serialization", mod, node,
                            "`allow_pickle=True` — npz/npy payloads must "
                            "stay pickle-free (allow_pickle=False is the "
                            "contract for every artifact surface)",
                            symbol=mod.rel))
            elif isinstance(node, ast.ClassDef) and not any(
                    mod.rel == a or mod.rel.startswith(a)
                    for a in allowed):
                for b in node.bases:
                    bname = _normalize(_dotted(b), mod.imports) or ""
                    if bname.endswith("Unpickler"):
                        findings.append(ctx.finding(
                            "serialization", mod, node,
                            f"Unpickler subclass `{node.name}` outside "
                            f"the sanctioned homes ({', '.join(allowed)})"
                            f" — extend utils/unpickle.py instead of "
                            f"forking the allowlist", symbol=mod.rel))
    return findings


# ---------------------------------------------------------------------------
# compat-routing
# ---------------------------------------------------------------------------

def _matches(name: str, key: str) -> bool:
    return name == key or name.startswith(key + ".")


def run_compat(ctx: Context) -> List[Finding]:
    apis = ctx.reg("DEVICE_ONLY_APIS", {})
    compat = ctx.reg("COMPAT_MODULE", "h2o3_tpu/compat.py")
    findings: List[Finding] = []
    for mod in ctx.project.modules.values():
        if mod.rel == compat or mod.rel.startswith("h2o3_genmodel/"):
            # the genmodel runners are framework-free by contract and run
            # exactly the exporter's program — compat shims live with the
            # framework, not in the standalone runtime
            continue
        seen_lines = set()

        def emit(node, api, how):
            if node.lineno in seen_lines:
                return
            seen_lines.add(node.lineno)
            findings.append(ctx.finding(
                "compat-routing", mod, node,
                f"direct {how} of `{api}` ({apis[api]}) — route through "
                f"h2o3_tpu/compat.py so CPU/old-jax fallbacks survive",
                symbol=mod.rel))

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    for api in apis:
                        if _matches(a.name, api):
                            emit(node, api, "import")
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                for api in apis:
                    if _matches(base, api):
                        emit(node, api, "import")
                        break
                else:
                    for a in node.names:
                        full = f"{base}.{a.name}"
                        for api in apis:
                            if _matches(full, api):
                                emit(node, api, "import")
            elif isinstance(node, ast.Attribute):
                name = _normalize(_dotted(node), mod.imports)
                if name:
                    for api in apis:
                        if _matches(name, api):
                            emit(node, api, "use")
    return findings


# ---------------------------------------------------------------------------
# compile-ledger
# ---------------------------------------------------------------------------

def run_compile_ledger(ctx: Context) -> List[Finding]:
    """Every XLA compile must route through ``obs/compiles.py`` (the
    ledger chokepoint, ``registry.COMPILE_LEDGER_MODULES``): a direct
    ``.lower(...).compile(`` — chained or via a name bound from a
    ``.lower(...)`` call — a direct ``compile_stablehlo`` call, or a
    direct ``note_compile`` call elsewhere is an unrecorded compile that
    silently under-counts /3/Runtime and the compile-seconds series."""
    allowed = set(ctx.reg("COMPILE_LEDGER_MODULES",
                          ("h2o3_tpu/obs/compiles.py",)))
    jit_scope = tuple(ctx.reg("JIT_LEDGER_SCOPE", ()))
    compat = ctx.reg("COMPAT_MODULE", "h2o3_tpu/compat.py")
    findings: List[Finding] = []
    for mod in ctx.project.modules.values():
        if mod.rel in allowed or mod.rel.startswith("h2o3_genmodel/"):
            # the genmodel runners are framework-free by contract (they
            # execute the exporter's exact program through the raw XLA
            # client); the ledger lives with the framework
            continue
        # names (incl. dotted attribute targets like `self._lowered`)
        # bound from a `.lower(...)` call anywhere in the module — the
        # two-step spelling: lowered = fn.lower(...); lowered.compile()
        lowered_names = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) and \
                    isinstance(getattr(node, "value", None), ast.Call) and \
                    isinstance(node.value.func, ast.Attribute) and \
                    node.value.func.attr == "lower":
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    d = _dotted(t)
                    if d:
                        lowered_names.add(d)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "compile":
                direct = (isinstance(fn.value, ast.Call)
                          and isinstance(fn.value.func, ast.Attribute)
                          and fn.value.func.attr == "lower")
                via_name = (_dotted(fn.value) or "") in lowered_names
                if direct or via_name:
                    findings.append(ctx.finding(
                        "compile-ledger", mod, node,
                        "direct `.lower(...).compile(` — every XLA "
                        "compile must route through obs/compiles.py "
                        "(compile_jit/compile_lowered) so it lands a "
                        "ledger row on /3/Runtime", symbol=mod.rel))
            name = _dotted(fn)
            if name and name.split(".")[-1] == "compile_stablehlo" and \
                    mod.rel != compat:
                # the blessed wrapper IS the remediation — a call whose
                # base resolves to the ledger module must not be flagged
                norm = _normalize(name, mod.imports) or name
                via_ledger = (norm.startswith("h2o3_tpu.obs.compiles.")
                              or name.split(".")[-2:-1] == ["compiles"])
                if not via_ledger:
                    findings.append(ctx.finding(
                        "compile-ledger", mod, node,
                        "direct `compile_stablehlo` call — route through "
                        "obs/compiles.py compile_stablehlo(family, text) "
                        "so the compile is ledger-recorded",
                        symbol=mod.rel))
            if name and name.split(".")[-1] == "note_compile":
                findings.append(ctx.finding(
                    "compile-ledger", mod, node,
                    "direct `note_compile` call — the ledger is the one "
                    "writer of the fused-compile counter (it times the "
                    "compile itself, so compile_ms_total cannot drift "
                    "from the per-program rows)", symbol=mod.rel))
        # bare `jax.jit` ban inside the ledgered-jit scopes (ISSUE 17):
        # calls, decorators and bare references all resolve to the same
        # Attribute/Name node, so one walk catches every spelling
        if any(mod.rel.startswith(p) for p in jit_scope):
            seen_jit = set()
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Attribute):
                    name = _normalize(_dotted(node), mod.imports)
                elif isinstance(node, ast.Name):
                    name = mod.imports.get(node.id)
                else:
                    continue
                if name == "jax.jit" and node.lineno not in seen_jit:
                    seen_jit.add(node.lineno)
                    findings.append(ctx.finding(
                        "compile-ledger", mod, node,
                        "bare `jax.jit` in a ledgered-jit scope — use "
                        "obs/compiles.ledgered_jit(family, fn) so the "
                        "compiles this jit triggers land in the ledger "
                        "(family `tree` for models/tree/)",
                        symbol=mod.rel))
    # registry self-check: a renamed chokepoint must not turn this pass
    # into a green no-op
    for rel in allowed:
        if not any(m.rel == rel for m in ctx.project.modules.values()):
            findings.append(Finding(
                "compile-ledger", "h2o3_tpu/analysis/registry.py", 0,
                f"COMPILE_LEDGER_MODULES entry `{rel}` matches no module "
                f"— stale registry path; fix it", symbol=rel, snippet=rel))
    for prefix in jit_scope:
        if not any(m.rel.startswith(prefix)
                   for m in ctx.project.modules.values()):
            findings.append(Finding(
                "compile-ledger", "h2o3_tpu/analysis/registry.py", 0,
                f"JIT_LEDGER_SCOPE prefix `{prefix}` matches no module — "
                f"stale registry path; fix it", symbol=prefix,
                snippet=prefix))
    return findings


# ---------------------------------------------------------------------------
# sync-hygiene
# ---------------------------------------------------------------------------

_SYNC_CALLS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
               "jax.device_get"}


def _is_span_with(node: ast.With, imports) -> bool:
    for item in node.items:
        ce = item.context_expr
        if isinstance(ce, ast.Call):
            name = _normalize(_dotted(ce.func), imports) or ""
            if name.endswith("tracing.span") or name.endswith(".span") \
                    and "tracing" in name:
                return True
            if name == "span" or name.endswith("obs.tracing.span"):
                return True
    return False


def run_sync_hygiene(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.project.modules.values():
        if not mod.rel.startswith("h2o3_tpu/"):
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.With) and
                    _is_span_with(node, mod.imports)):
                continue
            # calls under a NESTED span belong to that span's own scan
            # (the module walk visits every With), so exclude their
            # subtrees here instead of double-attributing them
            nested: set = set()
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.With) and \
                            _is_span_with(sub, mod.imports):
                        for inner in ast.walk(sub):
                            if inner is not sub:
                                nested.add(id(inner))
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if id(sub) in nested or not isinstance(sub, ast.Call):
                        continue
                    name = _normalize(_dotted(sub.func), mod.imports)
                    if name in _SYNC_CALLS:
                        findings.append(ctx.finding(
                            "sync-hygiene", mod, sub,
                            f"`{name}` inside a tracing span forces a "
                            f"device sync under instrumentation — move it "
                            f"out, or baseline it with the audit note if "
                            f"the span deliberately measures the blocking "
                            f"transfer", symbol=mod.rel))
                    elif isinstance(sub.func, ast.Attribute) and \
                            sub.func.attr == "block_until_ready":
                        findings.append(ctx.finding(
                            "sync-hygiene", mod, sub,
                            "`block_until_ready()` inside a tracing span "
                            "— instrumentation must not add device "
                            "syncs", symbol=mod.rel))
                    elif isinstance(sub.func, ast.Name) and \
                            sub.func.id in ("float", "int") and \
                            len(sub.args) == 1 and not sub.keywords and \
                            isinstance(sub.args[0], (ast.Attribute,
                                                     ast.Subscript)):
                        findings.append(ctx.finding(
                            "sync-hygiene", mod, sub,
                            f"`{sub.func.id}(...)` on an array-like "
                            f"inside a tracing span blocks on the device "
                            f"value", symbol=mod.rel))
    # swallowed exceptions on recovery tick paths
    for rel in ctx.reg("SWALLOW_SCOPE", ()):
        mod = next((m for m in ctx.project.modules.values()
                    if m.rel == rel), None)
        if mod is None:
            # registry self-check: a renamed tick module must not
            # silently drop out of the swallow lint
            findings.append(Finding(
                "sync-hygiene", "h2o3_tpu/analysis/registry.py", 0,
                f"SWALLOW_SCOPE entry `{rel}` matches no module — stale "
                f"registry path; fix it", symbol=rel, snippet=rel))
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and \
                    len(node.body) == 1 and \
                    isinstance(node.body[0], ast.Pass):
                findings.append(ctx.finding(
                    "sync-hygiene", mod, node,
                    "swallowed exception (`except: pass`) on a recovery "
                    "tick path — a permanently-failing tick dies "
                    "silently; log it at debug at minimum",
                    symbol=mod.rel))
    return findings
