"""Loader for the native C++ fast CSV parser (built lazily via make).

The reference's ingest hot loop is Java (water/parser/CsvParser.java:16
parseChunk); its only native code arrives via the XGBoost JNI channel
(SURVEY.md §2.10). Here the data-loader IS native: csv_parser.cpp exposes a
C ABI consumed via ctypes, parsing file chunks in parallel threads into
typed column buffers that are handed straight to device_put. Falls back to
the pandas path in ingest/parser.py when the shared lib isn't built."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

_HERE = os.path.dirname(__file__)
_LIB_PATH = os.path.join(_HERE, "libh2o3tpu.so")
_LOCK = threading.Lock()
_LIB = None
_TRIED = False


def _build() -> bool:
    src = os.path.join(_HERE, "csv_parser.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
             "-pthread", "-o", _LIB_PATH, src],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception:
        return False


def get_lib():
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.h2o_parse_csv.restype = ctypes.c_longlong
            lib.h2o_parse_csv.argtypes = [
                ctypes.c_char_p,          # path
                ctypes.c_char,            # sep
                ctypes.c_int,             # has_header
                ctypes.c_int,             # ncols
                ctypes.POINTER(ctypes.c_int),  # col kinds (0=num,1=str)
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),  # out numeric bufs
                ctypes.c_longlong,        # capacity rows
                ctypes.c_int,             # nthreads
            ]
            lib.h2o_count_rows.restype = ctypes.c_longlong
            lib.h2o_count_rows.argtypes = [ctypes.c_char_p]
            _LIB = lib
        except OSError:
            _LIB = None
        return _LIB


def native_parse_csv(path: str, setup) -> Optional[Dict[str, np.ndarray]]:
    """Parse numerics with the native lib; returns None to fall back when the
    lib is unavailable, the file is compressed, or any column is non-numeric
    (string/enum/time columns need host interning anyway)."""
    from h2o3_tpu.core.frame import T_NUM

    if path.endswith((".gz", ".zip")):
        return None
    if any(t != T_NUM for t in setup.column_types):
        return None
    lib = get_lib()
    if lib is None:
        return None
    nrows_cap = lib.h2o_count_rows(path.encode())
    if nrows_cap < 0:
        return None
    ncols = len(setup.column_names)
    bufs = [np.empty(nrows_cap, np.float64) for _ in range(ncols)]
    ptrs = (ctypes.POINTER(ctypes.c_double) * ncols)(
        *[b.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for b in bufs])
    kinds = (ctypes.c_int * ncols)(*([0] * ncols))
    n = lib.h2o_parse_csv(
        path.encode(), setup.separator.encode(), 1 if setup.check_header == 1 else 0,
        ncols, kinds, ptrs, nrows_cap, min(os.cpu_count() or 4, 16))
    if n < 0:
        return None
    return {name: bufs[i][:n] for i, name in enumerate(setup.column_names)}
