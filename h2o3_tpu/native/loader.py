"""Loader for the native C++ fast CSV parser (built lazily via make).

The reference's ingest hot loop is Java (water/parser/CsvParser.java:16
parseChunk); its only native code arrives via the XGBoost JNI channel
(SURVEY.md §2.10). Here the data-loader IS native: csv_parser.cpp exposes a
C ABI consumed via ctypes, parsing file chunks in parallel threads into
typed column buffers that are handed straight to device_put. Falls back to
the pandas path in ingest/parser.py when the shared lib isn't built."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

_HERE = os.path.dirname(__file__)
_LIB_PATH = os.path.join(_HERE, "libh2o3tpu.so")
_LOCK = threading.Lock()
_LIB = None
_TRIED = False


def _build() -> bool:
    srcs = [os.path.join(_HERE, f) for f in ("csv_parser.cpp", "treeshap.cpp")
            if os.path.exists(os.path.join(_HERE, f))]
    if not srcs:
        return False
    try:
        # build to a temp name then rename: an in-place relink would reuse
        # the inode, and glibc dlopen dedupes by dev/inode — a stale mapped
        # handle would be returned by the next CDLL (and truncating a mapped
        # .so can SIGBUS calls into the old mapping)
        tmp = _LIB_PATH + ".build"
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
             "-pthread", "-o", tmp] + srcs,
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _LIB_PATH)
        return True
    except Exception:
        return False


def _wire_treeshap(lib) -> None:
    lib.h2o_treeshap.restype = None
    lib.h2o_treeshap.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_longlong, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int,
    ]


def get_lib():
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            if not hasattr(lib, "h2o_treeshap") and \
                    os.path.exists(os.path.join(_HERE, "treeshap.cpp")):
                # stale .so from before treeshap.cpp existed: rebuild once
                # (the rename in _build gives the new lib a fresh inode, so
                # this CDLL loads it instead of the deduped old mapping)
                if _build():
                    lib = ctypes.CDLL(_LIB_PATH)
            if hasattr(lib, "h2o_treeshap"):
                _wire_treeshap(lib)
            lib.h2o_parse_csv.restype = ctypes.c_longlong
            lib.h2o_parse_csv.argtypes = [
                ctypes.c_char_p,          # path
                ctypes.c_char,            # sep
                ctypes.c_int,             # has_header
                ctypes.c_int,             # ncols
                ctypes.POINTER(ctypes.c_int),  # col kinds (0=num,1=str)
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),  # out numeric bufs
                ctypes.c_longlong,        # capacity rows
                ctypes.c_int,             # nthreads
            ]
            lib.h2o_count_rows.restype = ctypes.c_longlong
            lib.h2o_count_rows.argtypes = [ctypes.c_char_p]
            _LIB = lib
        except (OSError, AttributeError):
            # AttributeError: a checkout missing one of the .cpp sources
            # builds a lib without that symbol — honor the None contract
            # (callers fall back to their pure-Python paths)
            _LIB = None
        return _LIB


def native_treeshap(binned: np.ndarray, forest, nthreads: int = 0
                    ) -> Optional[np.ndarray]:
    """Run the C++ TreeSHAP over a (n, F) int32 binned matrix and a
    CompressedForest; returns (n, F+1) float64 phi (bias column untouched)
    or None when the native lib is unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "h2o_treeshap"):
        return None
    # treeshap.cpp's unique-path buffer is PE m[72]; extend() writes one
    # entry per root-to-leaf level, so forests deeper than ~70 would
    # overflow it — route those to the pure-Python fallback instead
    if getattr(forest, "max_depth", 0) + 2 > 70:
        return None
    n, F = binned.shape
    T, M = forest.feat.shape
    b = np.ascontiguousarray(binned, np.int32)
    feat = np.ascontiguousarray(forest.feat, np.int32)
    thresh = np.ascontiguousarray(forest.thresh_bin, np.int32)
    na_left = np.ascontiguousarray(forest.na_left, np.uint8)
    left = np.ascontiguousarray(forest.left, np.int32)
    right = np.ascontiguousarray(forest.right, np.int32)
    leaf_val = np.ascontiguousarray(forest.leaf_val, np.float32)
    cat_split = np.ascontiguousarray(forest.cat_split, np.int32)
    cat_table = np.ascontiguousarray(forest.cat_table, np.uint8)
    na_bins = np.ascontiguousarray(forest.na_bins, np.int32)
    cover = np.ascontiguousarray(forest.cover, np.float32)
    phi = np.zeros((n, F + 1), np.float64)

    def P(a, ct):
        return a.ctypes.data_as(ctypes.POINTER(ct))

    lib.h2o_treeshap(
        P(b, ctypes.c_int32), n, F,
        P(feat, ctypes.c_int32), P(thresh, ctypes.c_int32),
        P(na_left, ctypes.c_uint8), P(left, ctypes.c_int32),
        P(right, ctypes.c_int32), P(leaf_val, ctypes.c_float),
        P(cat_split, ctypes.c_int32), P(cat_table, ctypes.c_uint8),
        int(cat_table.shape[1]), P(na_bins, ctypes.c_int32),
        P(cover, ctypes.c_float), T, M,
        P(phi, ctypes.c_double),
        nthreads or min(os.cpu_count() or 4, 16))
    return phi


def native_parse_csv(path: str, setup) -> Optional[Dict[str, np.ndarray]]:
    """Parse numerics with the native lib; returns None to fall back when the
    lib is unavailable, the file is compressed, or any column is non-numeric
    (string/enum/time columns need host interning anyway)."""
    from h2o3_tpu.core.frame import T_NUM

    if path.endswith((".gz", ".zip")):
        return None
    if any(t != T_NUM for t in setup.column_types):
        return None
    lib = get_lib()
    if lib is None:
        return None
    nrows_cap = lib.h2o_count_rows(path.encode())
    if nrows_cap < 0:
        return None
    ncols = len(setup.column_names)
    bufs = [np.empty(nrows_cap, np.float64) for _ in range(ncols)]
    ptrs = (ctypes.POINTER(ctypes.c_double) * ncols)(
        *[b.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for b in bufs])
    kinds = (ctypes.c_int * ncols)(*([0] * ncols))
    n = lib.h2o_parse_csv(
        path.encode(), setup.separator.encode(), 1 if setup.check_header == 1 else 0,
        ncols, kinds, ptrs, nrows_cap, min(os.cpu_count() or 4, 16))
    if n < 0:
        return None
    return {name: bufs[i][:n] for i, name in enumerate(setup.column_names)}
