// Native TreeSHAP — the per-row, per-tree path-dependent SHAP walk.
//
// Reference behavior: h2o-genmodel/src/main/java/hex/genmodel/algos/tree/
// TreeSHAP.java (Lundberg algorithm 2 over node covers), surfaced as
// predict_contributions. The recursion is data-dependent control flow a
// TPU cannot tile, and the Python fallback in h2o3_tpu/explain.py pays
// interpreter cost per node; this translation unit runs the identical
// algorithm at native speed, parallelized over rows.
//
// C ABI (ctypes, see native/loader.py): all forest arrays are the flattened
// (T, M) tables of h2o3_tpu/models/tree/compressed.py.

#include <cstring>
#include <cstdint>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

constexpr int MAXP = 72;   // max unique path length (depth<=20 in practice)

struct PE { int d; double z; double o; double w; };

struct Tree {
  const int32_t* feat;
  const int32_t* thresh;
  const uint8_t* na_left;
  const int32_t* left;
  const int32_t* right;
  const float* leaf_val;
  const int32_t* cat_split;
  const float* cover;
};

struct Ctx {
  const int32_t* binned;     // (n, F)
  int F;
  const uint8_t* cat_table;  // (cat_rows, tableB)
  int tableB;
  const int32_t* na_bins;    // (F,)
};

inline void extend(PE* m, int& len, double pz, double po, int pi) {
  m[len].d = pi; m[len].z = pz; m[len].o = po;
  m[len].w = (len == 0) ? 1.0 : 0.0;
  for (int i = len - 1; i >= 0; --i) {
    m[i + 1].w += po * m[i].w * (i + 1) / (double)(len + 1);
    m[i].w = pz * m[i].w * (len - i) / (double)(len + 1);
  }
  ++len;
}

inline void unwind(PE* m, int& len, int i) {
  const int l = len - 1;
  const double one = m[i].o, zero = m[i].z;
  double n = m[l].w;
  for (int j = l - 1; j >= 0; --j) {
    if (one != 0.0) {
      const double tmp = m[j].w;
      m[j].w = n * (l + 1) / ((j + 1) * one);
      n = tmp - m[j].w * zero * (l - j) / (double)(l + 1);
    } else {
      m[j].w = m[j].w * (l + 1) / (zero * (l - j));
    }
  }
  for (int j = i; j < l; ++j) {
    m[j].d = m[j + 1].d; m[j].z = m[j + 1].z; m[j].o = m[j + 1].o;
  }
  --len;
}

inline double unwound_sum(const PE* m, int len, int i) {
  const int l = len - 1;
  const double one = m[i].o, zero = m[i].z;
  double total = 0.0;
  if (one != 0.0) {
    double n = m[l].w;
    for (int j = l - 1; j >= 0; --j) {
      const double tmp = n / ((j + 1) * one);
      total += tmp;
      n = m[j].w - tmp * zero * (l - j);
    }
  } else {
    for (int j = l - 1; j >= 0; --j)
      total += m[j].w / (zero * (l - j));
  }
  return total * (l + 1);
}

void recurse(const Ctx& c, const Tree& t, const int32_t* x, double* phi,
             int node, const PE* parent, int plen,
             double pz, double po, int pi) {
  PE m[MAXP];
  std::memcpy(m, parent, plen * sizeof(PE));
  int len = plen;
  extend(m, len, pz, po, pi);
  const int f = t.feat[node];
  if (f < 0) {                         // leaf
    const double v = t.leaf_val[node];
    for (int i = 1; i < len; ++i)
      phi[m[i].d] += unwound_sum(m, len, i) * (m[i].o - m[i].z) * v;
    return;
  }
  // routing: NA bin, categorical subset, or numeric threshold
  const int b = x[f];
  bool go_left;
  if (b == c.na_bins[f]) {
    go_left = t.na_left[node] != 0;
  } else {
    const int cs = t.cat_split[node];
    if (cs >= 0) {
      const int bb = std::min(b, c.tableB - 1);
      go_left = c.cat_table[(size_t)cs * c.tableB + bb] != 0;
    } else {
      go_left = b <= t.thresh[node];
    }
  }
  const int h = go_left ? t.left[node] : t.right[node];
  const int cold = go_left ? t.right[node] : t.left[node];
  double iz = 1.0, io = 1.0;
  int k = -1;
  for (int i = 1; i < len; ++i)
    if (m[i].d == f) { k = i; break; }
  if (k >= 0) {
    iz = m[k].z; io = m[k].o;
    unwind(m, len, k);
  }
  const double rj = std::max((double)t.cover[node], 1e-12);
  recurse(c, t, x, phi, h, m, len, iz * t.cover[h] / rj, io, f);
  recurse(c, t, x, phi, cold, m, len, iz * t.cover[cold] / rj, 0.0, f);
}

}  // namespace

extern "C" {

// phi must be zero-initialized (n_rows, F+1) float64; contributions for all
// trees accumulate into columns [0, F); callers add the bias afterwards.
void h2o_treeshap(const int32_t* binned, long long n_rows, int F,
                  const int32_t* feat, const int32_t* thresh,
                  const uint8_t* na_left, const int32_t* left,
                  const int32_t* right, const float* leaf_val,
                  const int32_t* cat_split, const uint8_t* cat_table,
                  int tableB, const int32_t* na_bins, const float* cover,
                  int T, int M, double* phi, int nthreads) {
  const Ctx c{binned, F, cat_table, tableB, na_bins};
  nthreads = std::max(1, std::min(nthreads, 64));
  auto worker = [&](long long r0, long long r1) {
    PE root[1];
    for (long long r = r0; r < r1; ++r) {
      const int32_t* x = binned + (size_t)r * F;
      double* ph = phi + (size_t)r * (F + 1);
      for (int ti = 0; ti < T; ++ti) {
        const size_t off = (size_t)ti * M;
        const Tree t{feat + off, thresh + off, na_left + off, left + off,
                     right + off, leaf_val + off, cat_split + off,
                     cover + off};
        recurse(c, t, x, ph, 0, root, 0, 1.0, 1.0, -1);
      }
    }
  };
  if (nthreads == 1 || n_rows < 64) {
    worker(0, n_rows);
    return;
  }
  std::vector<std::thread> threads;
  const long long chunk = (n_rows + nthreads - 1) / nthreads;
  for (int i = 0; i < nthreads; ++i) {
    const long long r0 = i * chunk, r1 = std::min<long long>(r0 + chunk, n_rows);
    if (r0 >= r1) break;
    threads.emplace_back(worker, r0, r1);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
