// Native CSV fast path for h2o3_tpu's data loader.
//
// The reference parses CSV in Java, one 4MB byte-chunk per MRTask map
// (water/parser/CsvParser.java:16 parseChunk; chunking water/fvec/
// FileVec.java:33 DFLT_CHUNK_SIZE). This is the TPU framework's native
// equivalent: mmap the file, split into per-thread byte ranges aligned to
// newline boundaries (same trick as H2O's chunk-boundary row splicing),
// parse doubles with a branch-light inline atof, and write straight into
// caller-provided column buffers. Exposed via a plain C ABI for ctypes.
//
// Numeric-only on purpose: string/enum columns need host interning and go
// through the Python path; the perf-critical 1B-row ingest case is numeric.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <cstdlib>
#include <vector>
#include <thread>
#include <atomic>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Mapped {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;
  bool ok() const { return data != nullptr; }
};

Mapped map_file(const char* path) {
  Mapped m;
  m.fd = open(path, O_RDONLY);
  if (m.fd < 0) return m;
  struct stat st;
  if (fstat(m.fd, &st) != 0 || st.st_size == 0) { close(m.fd); m.fd = -1; return m; }
  void* p = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, m.fd, 0);
  if (p == MAP_FAILED) { close(m.fd); m.fd = -1; return m; }
  m.data = static_cast<const char*>(p);
  m.size = st.st_size;
  return m;
}

void unmap(Mapped& m) {
  if (m.data) munmap(const_cast<char*>(m.data), m.size);
  if (m.fd >= 0) close(m.fd);
}

// Fast double parse over [p, end); returns NaN for empty/invalid tokens.
inline double parse_double(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
  while (end > p && (end[-1] == ' ' || end[-1] == '\r')) --end;
  if (p == end) return NAN;
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') ++p;
  double v = 0.0;
  int digits = 0;
  while (p < end && *p >= '0' && *p <= '9') { v = v * 10.0 + (*p - '0'); ++p; ++digits; }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') { v += (*p - '0') * scale; scale *= 0.1; ++p; ++digits; }
  }
  if (digits == 0) return NAN;
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); ++p; }
    int ex = 0;
    while (p < end && *p >= '0' && *p <= '9') { ex = ex * 10 + (*p - '0'); ++p; }
    v *= pow(10.0, eneg ? -ex : ex);
  }
  if (p != end) {
    // NA tokens and anything non-numeric
    return NAN;
  }
  return neg ? -v : v;
}

// Count newline-terminated rows in a range.
int64_t count_rows_range(const char* p, const char* end) {
  int64_t n = 0;
  for (const char* q = p; q < end; ++q) if (*q == '\n') ++n;
  if (end > p && end[-1] != '\n') ++n;  // last row w/o trailing newline
  return n;
}

struct ThreadResult {
  int64_t rows = 0;
  int64_t start_row = 0;  // filled in by the prefix pass
};

}  // namespace

extern "C" {

int64_t h2o_count_rows(const char* path) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  int nthreads = std::min<int64_t>(std::thread::hardware_concurrency(), 16);
  if (nthreads < 1) nthreads = 1;
  std::vector<int64_t> counts(nthreads, 0);
  std::vector<std::thread> ts;
  size_t step = m.size / nthreads + 1;
  for (int t = 0; t < nthreads; ++t) {
    ts.emplace_back([&, t]() {
      size_t lo = t * step, hi = std::min(m.size, (t + 1) * step);
      if (lo >= m.size) return;
      counts[t] = count_rows_range(m.data + lo, m.data + hi);
    });
  }
  for (auto& th : ts) th.join();
  int64_t total = 0;
  for (auto c : counts) total += c;
  unmap(m);
  return total;
}

// Parse a numeric CSV into per-column double buffers.
// Returns the number of data rows parsed, or -1 on error.
int64_t h2o_parse_csv(const char* path, char sep, int has_header, int ncols,
                      const int* kinds, double** out_cols, int64_t capacity,
                      int nthreads) {
  (void)kinds;
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  const char* base = m.data;
  const char* end = m.data + m.size;

  // skip header row
  const char* data_start = base;
  if (has_header) {
    const char* nl = static_cast<const char*>(memchr(base, '\n', m.size));
    data_start = nl ? nl + 1 : end;
  }
  if (nthreads < 1) nthreads = 1;

  // split into ranges aligned to newlines (H2O chunk-boundary splice rule:
  // a range owns rows whose first byte lies inside it)
  size_t dsize = end - data_start;
  std::vector<const char*> starts(nthreads + 1);
  starts[0] = data_start;
  size_t step = dsize / nthreads + 1;
  for (int t = 1; t < nthreads; ++t) {
    const char* p = data_start + std::min(dsize, t * step);
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    starts[t] = nl ? nl + 1 : end;
  }
  starts[nthreads] = end;

  // pass 1: per-range row counts -> start offsets
  std::vector<ThreadResult> res(nthreads);
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < nthreads; ++t)
      ts.emplace_back([&, t]() {
        res[t].rows = starts[t] < starts[t + 1]
                          ? count_rows_range(starts[t], starts[t + 1]) : 0;
      });
    for (auto& th : ts) th.join();
  }
  int64_t total = 0;
  for (int t = 0; t < nthreads; ++t) { res[t].start_row = total; total += res[t].rows; }
  if (total > capacity) { unmap(m); return -1; }

  // pass 2: parse
  std::atomic<bool> bad{false};
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < nthreads; ++t)
      ts.emplace_back([&, t]() {
        const char* p = starts[t];
        const char* e = starts[t + 1];
        int64_t row = res[t].start_row;
        while (p < e && !bad.load(std::memory_order_relaxed)) {
          const char* line_end = static_cast<const char*>(memchr(p, '\n', e - p));
          if (!line_end) line_end = e;
          const char* tok = p;
          for (int c = 0; c < ncols; ++c) {
            const char* tok_end = static_cast<const char*>(memchr(tok, sep, line_end - tok));
            if (!tok_end || c == ncols - 1) tok_end = line_end;
            out_cols[c][row] = parse_double(tok, tok_end);
            tok = (tok_end < line_end) ? tok_end + 1 : line_end;
          }
          ++row;
          p = line_end + 1;
        }
      });
    for (auto& th : ts) th.join();
  }
  unmap(m);
  return bad.load() ? -1 : total;
}

}  // extern "C"
