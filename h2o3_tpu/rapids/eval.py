"""Rapids evaluator: Env/Session + primitive registry.

Reference: water/rapids/Session.java (refcounted temp frames),
Env.java (scope stack), ast/prims/* (205 prim classes). Prims here
dispatch to the jitted ops layer — each prim is one or a few fused XLA
programs over row-sharded columns.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.core.dkv import DKV
from h2o3_tpu.core.frame import Column, Frame, T_CAT, T_NUM, T_STR
from h2o3_tpu.ops import elementwise as E
from h2o3_tpu.ops import filters as FL
from h2o3_tpu.rapids.parser import (Id, Lambda, NumList, Span, StrLit,
                                    StrList, parse, parse_cached)


class Session:
    """Refcounted temp frames (water/rapids/Session.java).

    Columns are immutable device arrays, so temp frames are cheap COW views;
    the refcount tracks how many OTHER temps alias a temp's columns so a
    client `rm` releases the key immediately but the backing columns only
    die when the last aliasing temp does (Session.java's sanity-checked
    refcnts — here Python's GC owns the buffers, the counts serve the
    `rm`/`end` bookkeeping and introspection)."""

    def __init__(self, session_id: str = "default"):
        self.id = session_id
        self.temps: Dict[str, Frame] = {}
        # keyed by Column.token, NOT id(): id() values are reused after GC,
        # so an id-keyed map can credit a brand-new Column with a dead
        # Column's leftover refcount and corrupt the rm/end bookkeeping
        self.refcnt: Dict[int, int] = {}     # Column.token -> temp refs
        self._planner = None                 # lazy-session DAG (planner.py)

    @property
    def planner(self):
        """The session's deferred-statement DAG planner, created on first
        touch (rapids/planner.SessionPlanner)."""
        if self._planner is None:
            from h2o3_tpu.rapids.planner import SessionPlanner

            self._planner = SessionPlanner(self)
        return self._planner

    def pin_columns(self, cols) -> None:
        """Pin input Columns a deferred statement reads: the refcount
        keeps rm/end bookkeeping honest while a not-yet-flushed DAG node
        still needs them (the node also holds hard references, so the
        buffers cannot be GC'd out from under the flush)."""
        for c in cols:
            self.refcnt[c.token] = self.refcnt.get(c.token, 0) + 1

    def unpin_columns(self, cols) -> None:
        for c in cols:
            n = self.refcnt.get(c.token, 0) - 1
            if n <= 0:
                self.refcnt.pop(c.token, None)
            else:
                self.refcnt[c.token] = n

    def _track(self, fr: Frame, delta: int):
        for c in fr.columns:
            cid = c.token
            n = self.refcnt.get(cid, 0) + delta
            if n <= 0:
                self.refcnt.pop(cid, None)
            else:
                self.refcnt[cid] = n

    def assign(self, key: str, fr: Frame) -> Frame:
        out = Frame(key=key)
        for n in fr.names:
            out.add(n, fr.col(n))
        out.install()
        old = self.temps.get(key)
        if old is not None:
            self._track(old, -1)
        self.temps[key] = out
        self._track(out, +1)
        return out

    def column_refs(self, col: Column) -> int:
        return self.refcnt.get(col.token, 0)

    def remove(self, key: str):
        if self._planner is not None:
            # a pending deferred output for this key becomes a dead temp:
            # the flush will never compute it unless a still-deferred
            # statement reads it
            self._planner.note_removed(key)
        old = self.temps.pop(key, None)
        if old is not None:
            self._track(old, -1)
        DKV.remove(key)

    def end(self):
        if self._planner is not None:
            self._planner.end()      # retire the whole DAG, compute nothing
        for k in list(self.temps):
            self.remove(k)


class Env:
    """Lexical scopes for lambda application (water/rapids/Env.java)."""

    def __init__(self, session: Session, parent: Optional["Env"] = None):
        self.session = session
        self.parent = parent
        self.vars: Dict[str, Any] = {}

    def lookup(self, name: str):
        if name == "_":          # h2o-py placeholder arg (e.g. quantile weights)
            return None
        e: Optional[Env] = self
        while e is not None:
            if name in e.vars:
                return e.vars[name]
            e = e.parent
        got = DKV.get(name)
        if got is not None:
            return got
        raise KeyError(f"unknown identifier {name!r}")


# ---------------------------------------------------------------------------
# value helpers
# ---------------------------------------------------------------------------

def _is_fr(v) -> bool:
    return isinstance(v, Frame)


def _one_col(v) -> Column:
    if isinstance(v, Column):
        return v
    if _is_fr(v):
        if v.ncols != 1:
            raise ValueError("expected a single-column frame")
        return v.col(0)
    raise TypeError(f"expected column, got {type(v)}")


def _colfr(col: Column, name: str = "C1") -> Frame:
    fr = Frame()
    fr.add(name, col)
    return fr


def _scalar(v) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    if _is_fr(v) and v.ncols == 1 and v.nrows == 1:
        return float(v.col(0).to_numpy()[0])
    raise TypeError(f"expected scalar, got {type(v)}")


def _idx_list(v, n: int) -> np.ndarray:
    """NumList/Span/scalar → absolute row/col indices."""
    if isinstance(v, (int, float)):
        return np.asarray([int(v)])
    out: List[int] = []
    for item in v:
        if isinstance(item, Span):
            lo = int(item.lo)
            out.extend(range(lo, lo + int(item.cnt)))
        else:
            out.append(int(item))
    idx = np.asarray(out, np.int64)
    if len(idx) and (idx < 0).all():
        keep = np.setdiff1d(np.arange(n), -idx)   # negative = drop (R style)
        return keep
    return idx


# ---------------------------------------------------------------------------
# primitive registry
# ---------------------------------------------------------------------------

PRIMS: Dict[str, Callable] = {}


def prim(*names):
    def deco(fn):
        for nm in names:
            PRIMS[nm] = fn
        return fn
    return deco


# -- assignment / session (ast/prims/assign) --------------------------------

@prim("tmp=", "assign")
def _assign(env, key, val):
    key = key.name if isinstance(key, Id) else str(key)
    fr = val if _is_fr(val) else _colfr(_one_col(val))
    return env.session.assign(key, fr)


@prim("rm")
def _rm(env, key):
    env.session.remove(key if isinstance(key, str) else key.name)
    return 0.0


# -- structure (ast/prims/mungers) ------------------------------------------

@prim("cols", "cols_py")
def _cols(env, fr, sel):
    names = fr.names
    if isinstance(sel, str):
        return fr.subframe([sel])
    if isinstance(sel, list) and sel and isinstance(sel[0], str):
        return fr.subframe(list(sel))
    idx = _idx_list(sel, fr.ncols)
    return fr.subframe([names[i] for i in idx])


@prim("rows")
def _rows(env, fr, sel):
    if _is_fr(sel):
        return FL.filter_rows(fr, _one_col(sel))
    idx = _idx_list(sel, fr.nrows)
    if len(idx) and np.array_equal(idx, np.arange(idx[0], idx[-1] + 1)):
        return FL.slice_rows(fr, int(idx[0]), int(idx[-1]) + 1)
    return FL.take_rows(fr, idx)


@prim("cbind")
def _cbind(env, *frames):
    out = frames[0] if _is_fr(frames[0]) else _colfr(_one_col(frames[0]))
    for f in frames[1:]:
        out = out.cbind(f if _is_fr(f) else _colfr(_one_col(f)))
    return out


@prim("rbind")
def _rbind(env, *frames):
    return FL.rbind(list(frames))


@prim("colnames=")
def _colnames(env, fr, cols, names):
    idx = _idx_list(cols, fr.ncols)
    new = [names] if isinstance(names, str) else [
        s.s if isinstance(s, StrLit) else str(s) for s in names]
    out = fr.subframe(fr.names)
    for i, nm in zip(idx, new):
        out.rename(out.names[int(i)], nm)
    return out


@prim("sort")
def _sort(env, fr, by, *asc):
    from h2o3_tpu.ops.sort import sort_frame

    def names_of(sel):
        if isinstance(sel, str):
            return [sel]
        if isinstance(sel, StrLit):
            return [sel.s]
        if isinstance(sel, (int, float)):          # bare column index
            return [fr.names[int(sel)]]
        items = list(sel)
        if items and isinstance(items[0], (str, StrLit)):
            return [s.s if isinstance(s, StrLit) else s for s in items]
        return [fr.names[i] for i in _idx_list(sel, fr.ncols)]

    names = names_of(by)
    if asc:
        # h2o-py encodes direction as 1 (asc) / -1 (desc); 0 also = desc
        ascending = [int(_scalar(a)) > 0 for a in
                     (asc[0] if isinstance(asc[0], (list, NumList)) else [asc[0]])]
    else:
        ascending = True
    return sort_frame(fr, names, ascending=ascending)


@prim("merge")
def _merge(env, left, right, all_x, all_y, by_x, by_y, method="auto"):
    from h2o3_tpu.ops.merge import merge

    return merge(left, right, all_x=bool(all_x), all_y=bool(all_y))


@prim("unique")
def _unique(env, fr, include_nas=False):
    from h2o3_tpu.ops.groupby import GroupBy

    return GroupBy(fr, fr.names).count().get_frame().subframe(fr.names)


@prim("table")
def _table(env, fr, *rest):
    from h2o3_tpu.ops.groupby import table

    return table(fr)


@prim("h2o.impute")
def _impute(env, fr, column, method, combine_method, by, *rest):
    from h2o3_tpu.ops.impute import impute

    col = int(_scalar(column)) if not isinstance(column, (list, NumList)) else -1
    method = method.s if isinstance(method, StrLit) else str(method)
    return impute(fr, column=col, method=method.lower())


@prim("na.omit")
def _na_omit(env, fr):
    mask = None
    for c in fr.columns:
        m = E.is_na(c)
        mask = m if mask is None else E.binop("+", mask, m)
    keep = E.binop("==", mask, 0.0)
    return FL.filter_rows(fr, keep)


@prim("is.na")
def _isna_prim(env, v):
    return _colfr(E.is_na(_one_col(v)), "isNA")


@prim("ifelse")
def _ifelse_prim(env, cond, yes, no):
    c = _one_col(cond)
    y = _one_col(yes) if _is_fr(yes) else yes
    n = _one_col(no) if _is_fr(no) else no
    return _colfr(E.ifelse(c, y, n))


@prim("h2o.runif")
def _runif(env, fr, seed):
    rng = np.random.default_rng(int(seed) if seed == seed and seed >= 0 else None)
    return _colfr(Column.from_numpy(rng.random(fr.nrows)), "rnd")


@prim("asfactor", "as.factor")
def _asfactor(env, fr):
    out = Frame()
    for n in (fr.names if _is_fr(fr) else ["C1"]):
        c = fr.col(n)
        if c.is_categorical:
            out.add(n, c)
            continue
        vals = c.to_numpy()
        out.add(n, Column.from_numpy(
            np.asarray([("" if v != v else ("%g" % v)) for v in vals], object),
            ctype=T_CAT))
    return out


@prim("as.numeric", "asnumeric")
def _asnumeric(env, fr):
    out = Frame()
    for n in fr.names:
        c = fr.col(n)
        if c.is_categorical:
            # levels that look numeric convert by value; else by code
            dom = c.domain or []
            try:
                lut = np.asarray([float(x) for x in dom], np.float32)
                codes = c.to_numpy()
                vals = np.where(codes >= 0, lut[np.maximum(codes, 0)], np.nan)
            except ValueError:
                vals = np.where(c.to_numpy() >= 0, c.to_numpy(), np.nan)
            out.add(n, Column.from_numpy(vals.astype(np.float64)))
        else:
            out.add(n, c)
    return out


@prim("as.character", "ascharacter")
def _ascharacter(env, fr):
    out = Frame()
    for n in fr.names:
        c = fr.col(n)
        out.add(n, Column.from_numpy(np.asarray(
            [None if v is None else str(v) for v in c.values()], object)))
    return out


@prim("levels")
def _levels(env, fr):
    """One output column per input column holding its level strings, padded
    with '' to equal length (AstLevels; h2o-py frame.levels() transposes and
    strips the padding client-side)."""
    doms = [list(fr.col(n).domain or []) for n in fr.names]
    depth = max((len(d) for d in doms), default=0) or 1
    out = Frame()
    for n, d in zip(fr.names, doms):
        vals = d + [""] * (depth - len(d))
        out.add(n, Column.from_numpy(np.asarray(vals, object)))
    return out


@prim("append")
def _append(env, fr, col, name):
    out = fr.subframe(fr.names)
    nm = name.s if isinstance(name, StrLit) else str(name)
    out.add(nm, _one_col(col))
    return out


@prim(":=")
def _colassign(env, fr, rhs, col_idx, row_sel):
    """In-place column update → copy-on-write new frame."""
    out = fr.subframe(fr.names)
    idx = _idx_list(col_idx, fr.ncols)
    rhs_cols = (rhs.columns if _is_fr(rhs) else
                [rhs] if isinstance(rhs, Column) else None)
    for k, ci in enumerate(idx):
        nm = fr.names[int(ci)] if int(ci) < fr.ncols else f"C{int(ci)+1}"
        if rhs_cols is not None:
            newc = rhs_cols[k if len(rhs_cols) > 1 else 0]
        else:
            newc = Column.from_numpy(np.full(fr.nrows, float(rhs), np.float64))
        if nm in out:
            out.replace(nm, newc)
        else:
            out.add(nm, newc)
    return out


# -- group by ----------------------------------------------------------------

@prim("GB")
def _gb(env, fr, by, *aggs):
    """(GB fr [by...] agg col na agg col na ...) — triples per aggregate."""
    from h2o3_tpu.ops.groupby import GroupBy

    idx = _idx_list(by, fr.ncols)
    gb = GroupBy(fr, [fr.names[i] for i in idx])
    for i in range(0, len(aggs) - 2, 3):
        agg = aggs[i] if isinstance(aggs[i], str) else (
            aggs[i].name if isinstance(aggs[i], Id) else str(aggs[i]))
        col = aggs[i + 1]
        if agg == "nrow":
            gb.count()
            continue
        cname = col if isinstance(col, str) else fr.names[int(_scalar(col))]
        getattr(gb, agg)(cname)
    return gb.get_frame()


# -- reducers (ast/prims/reducers) ------------------------------------------

def _percol(fr, stat) -> List[float]:
    """Per-column reduction over a frame; non-numeric -> NaN (reducer prims
    in the reference operate frame-wide, ast/prims/reducers)."""
    out = []
    for n in fr.names:
        c = fr.col(n)
        out.append(float(stat(c)) if c.is_numeric or c.ctype == "time"
                   else float("nan"))
    return out


@prim("mean")
def _mean(env, v, *rest):
    """(mean fr) -> scalar (single col); (mean fr skipna axis) -> h2o-py's
    frame form: 1-row frame of per-column means (frame.py:3188)."""
    if _is_fr(v) and rest:
        axis = int(_scalar(rest[1])) if len(rest) > 1 else 0
        out = Frame()
        if axis == 1:
            import jax.numpy as jnp

            num = [v.col(n) for n in v.names if v.col(n).is_numeric]
            if not num:
                raise ValueError("no numeric columns for row-wise mean")
            stack = jnp.stack([c.data for c in num], axis=1)
            mask = ~jnp.isnan(stack)
            s = jnp.where(mask, stack, 0.0).sum(axis=1)
            cnt = mask.sum(axis=1)
            vals = jnp.where(cnt > 0, s / jnp.maximum(cnt, 1), jnp.nan)
            out.add("mean", Column(vals, T_NUM, v.nrows))
            return out
        for n, m in zip(v.names, _percol(v, lambda c: c.rollups.mean)):
            out.add(n, Column.from_numpy(np.asarray([m])))
        return out
    return _one_col(v).rollups.mean


@prim("ls")
def _ls(env):
    """DKV key listing as a 1-column frame (AstLs; h2o.ls())."""
    from h2o3_tpu.models.model import Model

    keys = [k for k in DKV.keys()
            if isinstance(DKV.get(k), (Frame, Model))]
    out = Frame()
    out.add("key", Column.from_numpy(np.asarray(keys or [""], object)))
    return out


@prim("getrow")
def _getrow(env, fr):
    """1xn frame -> scalar list (h2o-py frame.getrow, frame.py:918)."""
    if not _is_fr(fr) or fr.nrows != 1:
        raise ValueError("getrow expects a single-row frame")
    out = []
    for n in fr.names:
        c = fr.col(n)
        if c.is_string:
            out.append(float("nan"))
        else:
            v = c.to_numpy()[0]
            out.append(float(v))
    return out


@prim("sum")
def _sum(env, v, *rest):
    if _is_fr(v) and v.ncols > 1:
        def tot(c):
            r = c.rollups
            return r.mean * (c.nrows - r.na_count)
        return _percol(v, tot)
    c = _one_col(v)
    r = c.rollups
    return r.mean * (c.nrows - r.na_count)


@prim("min")
def _min(env, v, *rest):
    if _is_fr(v) and v.ncols > 1:
        return float(np.nanmin(_percol(v, lambda c: c.rollups.min)))
    return _one_col(v).rollups.min


@prim("max")
def _max(env, v, *rest):
    if _is_fr(v) and v.ncols > 1:
        return float(np.nanmax(_percol(v, lambda c: c.rollups.max)))
    return _one_col(v).rollups.max


@prim("sd")
def _sd(env, v, *rest):
    if _is_fr(v) and v.ncols > 1:
        return _percol(v, lambda c: c.rollups.sigma)
    return _one_col(v).rollups.sigma


@prim("var")
def _var(env, v, *rest):
    if _is_fr(v) and v.ncols > 1:
        return _percol(v, lambda c: c.rollups.sigma ** 2)
    s = _one_col(v).rollups.sigma
    return s * s


@prim("naCnt", "nacnt")
def _nacnt(env, v):
    if _is_fr(v) and v.ncols > 1:
        return [float(v.col(n).rollups.na_count) for n in v.names]
    return float(_one_col(v).rollups.na_count)


@prim("median")
def _median(env, v, *rest):
    from h2o3_tpu.ops.quantile import quantile_column

    if _is_fr(v) and v.ncols > 1:
        return [quantile_column(v.col(n), [0.5])[0] if v.col(n).is_numeric
                else float("nan") for n in v.names]
    return quantile_column(_one_col(v), [0.5])[0]


@prim("quantile")
def _quantile(env, fr, probs, *rest):
    from h2o3_tpu.ops.quantile import quantile_column

    pl = [float(x) for x in (probs if isinstance(probs, (list, NumList)) else [probs])]
    out = Frame()
    out.add("Probs", Column.from_numpy(np.asarray(pl)))
    for n in fr.names:
        c = fr.col(n)
        if c.is_numeric:
            out.add(f"{n}QuantilesQ", Column.from_numpy(
                np.asarray(quantile_column(c, pl))))
    return out


@prim("all")
def _all(env, v):
    c = _one_col(v)
    r = c.rollups
    return 1.0 if r.min == 1.0 and r.max == 1.0 else 0.0


@prim("any")
def _any(env, v):
    return 1.0 if _one_col(v).rollups.max == 1.0 else 0.0


@prim("nrow")
def _nrow(env, fr):
    return float(fr.nrows)


@prim("ncol")
def _ncol(env, fr):
    return float(fr.ncols)


# -- cumulative (ast/prims/repeaters? timeseries) ----------------------------

def _cum(op):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(d):
        x = jnp.where(jnp.isnan(d), {"add": 0.0, "mul": 1.0, "min": jnp.inf,
                                     "max": -jnp.inf}[op], d)
        # jnp ufuncs only grew .accumulate in jax>=0.5; lax has always had
        # the cumulative reductions
        f = {"add": jnp.cumsum, "mul": jnp.cumprod,
             "min": getattr(jnp.minimum, "accumulate", jax.lax.cummin),
             "max": getattr(jnp.maximum, "accumulate", jax.lax.cummax)}[op]
        return f(x).astype(jnp.float32)

    return run


@prim("cumsum")
def _cumsum(env, v, axis=0):
    c = _one_col(v)
    return _colfr(Column.from_device(_cum("add")(c.data), T_NUM, c.nrows))


@prim("cumprod")
def _cumprod(env, v, axis=0):
    c = _one_col(v)
    return _colfr(Column.from_device(_cum("mul")(c.data), T_NUM, c.nrows))


@prim("cummin")
def _cummin(env, v, axis=0):
    c = _one_col(v)
    return _colfr(Column.from_device(_cum("min")(c.data), T_NUM, c.nrows))


@prim("cummax")
def _cummax(env, v, axis=0):
    c = _one_col(v)
    return _colfr(Column.from_device(_cum("max")(c.data), T_NUM, c.nrows))


# -- string ops (host-side; TPUs never see strings) --------------------------

def _strop(fn):
    def impl(env, fr, *args):
        out = Frame()
        for n in fr.names:
            c = fr.col(n)
            if c.is_categorical:
                dom = [fn(x, *args) for x in (c.domain or [])]
                out.add(n, Column(c.data, T_CAT, c.nrows, domain=dom))
            elif c.is_string:
                vals = np.asarray([None if v is None else fn(v, *args)
                                   for v in c.host_data], object)
                out.add(n, Column.from_numpy(vals))
            else:
                out.add(n, c)
        return out
    return impl


PRIMS["toupper"] = _strop(lambda s: s.upper())
PRIMS["tolower"] = _strop(lambda s: s.lower())
PRIMS["trim"] = _strop(lambda s: s.strip())


@prim("replacefirst")
def _replacefirst(env, fr, pat, rep, ignore_case=0.0):
    import re

    p = pat.s if isinstance(pat, StrLit) else str(pat)
    r = rep.s if isinstance(rep, StrLit) else str(rep)
    flags = re.IGNORECASE if ignore_case else 0
    return _strop(lambda s: re.sub(p, r, s, count=1, flags=flags))(env, fr)


@prim("replaceall")
def _replaceall(env, fr, pat, rep, ignore_case=0.0):
    import re

    p = pat.s if isinstance(pat, StrLit) else str(pat)
    r = rep.s if isinstance(rep, StrLit) else str(rep)
    flags = re.IGNORECASE if ignore_case else 0
    return _strop(lambda s: re.sub(p, r, s, flags=flags))(env, fr)


@prim("strlen", "nchar")
def _strlen(env, fr):
    out = Frame()
    for n in fr.names:
        c = fr.col(n)
        if c.is_string:
            vals = np.asarray([np.nan if v is None else len(v) for v in c.host_data])
        elif c.is_categorical:
            lut = np.asarray([len(x) for x in (c.domain or [])] or [0], np.float64)
            codes = c.to_numpy()
            vals = np.where(codes >= 0, lut[np.maximum(codes, 0)], np.nan)
        else:
            vals = np.full(c.nrows, np.nan)
        out.add(n, Column.from_numpy(vals))
    return out


# -- arithmetic / comparison / logic ----------------------------------------

def _str_cmp(col: Column, s: str, op: str) -> Column:
    """(col == 'label') / (col != 'label') for enum/string columns — NA
    compares as NA (AstBinOp string semantics: NA rows drop out of row
    filters), enum compares by code against the interned domain."""
    if col.is_categorical:
        dom = col.domain or []
        idx = dom.index(s) if s in dom else -2       # -2: matches nothing
        codes = col.to_numpy()
        eq = (codes == idx).astype(np.float64)
        if op == "!=":
            eq = 1.0 - eq
        eq[codes < 0] = np.nan
        return Column.from_numpy(eq)
    if col.is_string:
        vals = np.array([np.nan if v is None
                         else float((v == s) if op == "==" else (v != s))
                         for v in col.host_data], np.float64)
        return Column.from_numpy(vals)
    # numeric column vs string: numeric compare when the string parses,
    # else nothing matches (== -> 0 / != -> 1, NA stays NA)
    vals = col.to_numpy()
    try:
        f = float(s)
        eq = (vals == f).astype(np.float64)
    except ValueError:
        eq = np.zeros(len(vals), np.float64)
    if op == "!=":
        eq = 1.0 - eq
    eq[~np.isfinite(vals)] = np.nan
    return Column.from_numpy(eq)


def _binprim(op):
    def impl(env, l, r):
        sl = l.s if isinstance(l, StrLit) else (l if isinstance(l, str) else None)
        sr = r.s if isinstance(r, StrLit) else (r if isinstance(r, str) else None)
        if op in ("==", "!=") and (sl is not None) != (sr is not None):
            col = _one_col(r if sl is not None else l)
            return _colfr(_str_cmp(col, sl if sl is not None else sr, op), op)
        lv = _one_col(l) if _is_fr(l) else l
        rv = _one_col(r) if _is_fr(r) else r
        if isinstance(lv, Column) or isinstance(rv, Column):
            return _colfr(E.binop(op, lv, rv), op)
        return float(E.binop(op, Column.from_numpy(np.asarray([float(lv)])),
                             float(rv)).to_numpy()[0])
    return impl


for _op in ("+", "-", "*", "/", "^", "%", "intDiv", "==", "!=", "<", "<=",
            ">", ">="):
    PRIMS[_op] = _binprim(_op)
PRIMS["%%"] = _binprim("%")
PRIMS["%/%"] = _binprim("intDiv")


def _logical(op):
    def impl(env, l, r):
        lc = _one_col(l) if _is_fr(l) else l
        rc = _one_col(r) if _is_fr(r) else r
        import jax.numpy as jnp

        a = E._as_f32(lc) if isinstance(lc, Column) else jnp.float32(lc)
        b = E._as_f32(rc) if isinstance(rc, Column) else jnp.float32(rc)
        # the same traceable expression the fusion emitter composes
        # (ops/elementwise.logical_expr) — one definition, bitwise parity
        v = E._jit_logical(op)(a, b)
        ref = lc if isinstance(lc, Column) else rc
        return _colfr(Column.from_device(v, T_NUM, ref.nrows), op)
    return impl


PRIMS["&"] = _logical("&")
PRIMS["&&"] = _logical("&")
PRIMS["|"] = _logical("|")
PRIMS["||"] = _logical("|")


def _unprim(op):
    def impl(env, v):
        return _colfr(E.unop(op, _one_col(v)), op)
    return impl


for _op in E._UNOPS:
    PRIMS[_op] = _unprim(_op)


@prim("scale")
def _scale(env, fr, center, scale):
    out = Frame()
    for n in fr.names:
        c = fr.col(n)
        if not c.is_numeric:
            out.add(n, c)
            continue
        r = c.rollups
        mu = r.mean if (center == 1.0 or center is True) else 0.0
        sd = r.sigma if (scale == 1.0 or scale is True) else 1.0
        cc = E.binop("/", E.binop("-", c, mu), sd if sd else 1.0)
        out.add(n, cc)
    return out


# -- frame split / misc ------------------------------------------------------

@prim("h2o.splitframe")
def _splitframe(env, fr, ratios, seed=-1.0):
    rl = [float(x) for x in (ratios if isinstance(ratios, (list, NumList)) else [ratios])]
    parts = FL.split_frame(fr, rl, seed=int(seed) if seed >= 0 else None)
    for i, pr in enumerate(parts):
        env.session.assign(f"{fr.key}_split_{i}", pr)
    return parts[0]


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------

def _eval(ast, env: Env):
    if isinstance(ast, (int, float)):
        return float(ast)
    if isinstance(ast, StrLit):
        return ast.s
    if isinstance(ast, (NumList, StrList)):
        return ast
    if isinstance(ast, Lambda):
        return ast
    if isinstance(ast, Id):
        return env.lookup(ast.name)
    if isinstance(ast, list):
        if not ast:
            return None
        head = ast[0]
        if isinstance(head, Id):
            name = head.name
            if name in ("tmp=", "assign"):
                key = ast[1]
                val = _eval(ast[2], env)
                return PRIMS[name](env, key, val)
            if name == "rm":
                k = ast[1]
                return PRIMS["rm"](env, k.name if isinstance(k, Id) else _eval(k, env))
            fn = PRIMS.get(name)
            if fn is None:
                raise ValueError(f"unknown rapids primitive {name!r}")
            if name in _fusion.ROOT_OPS:
                # offer the MAXIMAL fusible subtree rooted here to the
                # fusion engine: one XLA program instead of one dispatch
                # (and one Column materialization) per prim
                got = _fusion.try_execute(ast, env)
                if got is not _fusion._MISS:
                    return got
            args = [_eval(a, env) for a in ast[1:]]
            if _fusion.PRIM_FUSION.get(name) == _fusion.HOST:
                _fusion.note_host_fallback()   # the exceptional path
            return fn(env, *args)
        if isinstance(head, Lambda):
            args = [_eval(a, env) for a in ast[1:]]
            return _eval_lambda(env, head, args)
        # raw list of expressions: evaluate all, return last
        res = None
        for e in ast:
            res = _eval(e, env)
        return res
    raise TypeError(f"cannot evaluate {ast!r}")


def _eval_lambda(env: Env, lam, args):
    """Apply an AST lambda (AstFunction) to evaluated args; arity is
    checked like the reference (AstFunction.apply errors on mismatch)."""
    if not isinstance(lam, Lambda):
        raise TypeError(f"expected lambda, got {type(lam)}")
    if len(args) != len(lam.args):
        raise ValueError(f"lambda expects {len(lam.args)} argument(s) "
                         f"({' '.join(lam.args)}), got {len(args)}")
    sub = Env(env.session, parent=env)
    for nm, v in zip(lam.args, args):
        sub.vars[nm] = v
    return _eval(lam.body, sub)


def exec_rapids(expr: str, session: Optional[Session] = None):
    """Parse + evaluate one Rapids statement (water/rapids/Rapids.exec).

    With the lazy session engine on (rapids/planner.py,
    H2O_TPU_RAPIDS_LAZY), assignment statements the planner can model
    defer into the session's DAG and return a Frame whose columns
    materialize on first data access; any statement the planner cannot
    defer is an observation point that flushes the DAG first, preserving
    statement order. Fusible chains execute as single XLA programs
    (rapids/fusion.py); parse/plan/execute child spans land on the
    active trace (inert when no trace is active — wall-clock only, no
    device syncs)."""
    from h2o3_tpu.obs import tracing

    session = session or Session()
    env = Env(session)
    _fusion.note_statement()
    progs_before = _fusion.counters()["fused_programs"]
    with tracing.span("parse", chars=len(expr)):
        ast = parse_cached(expr)
    try:
        # StrLit/list at top level (e.g. "frame_id") → lookup
        if isinstance(ast, StrLit):
            return env.lookup(ast.s)
        got = _planner.offer_statement(ast, env)
        if got is not _planner._MISS:
            return got
        with tracing.span("execute"):
            return _eval(ast, env)
    finally:
        _fusion.note_statement_result(progs_before)


# extended prim suites register themselves on import (advmath/time/string/
# search/mungers/matrix/repeaters/timeseries — water/rapids/ast/prims/*)
from h2o3_tpu.rapids import prims_ext as _prims_ext  # noqa: E402,F401
# the statement fusion engine (classification registry + planner); imported
# after the registries are complete so its guard surface sees every prim
from h2o3_tpu.rapids import fusion as _fusion  # noqa: E402
# the lazy-session DAG planner (defer/flush across statements)
from h2o3_tpu.rapids import planner as _planner  # noqa: E402
