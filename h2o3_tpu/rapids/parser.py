"""Rapids string parser (reference: water/rapids/Rapids.java).

Grammar:
  expr   := '(' op arg* ')'            application
          | '{' id* '.' expr '}'       lambda (AstFunction)
          | '[' item* ']'              number/string list; a:b = span(lo,cnt)
          | number | 'str' | "str" | id | TRUE | FALSE | NA | NaN
Parses to plain python: lists (application, head first), Lambda, Span,
float, str wrapped in StrLit, Id for identifiers.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Any, List


@dataclass
class Id:
    name: str


@dataclass
class StrLit:
    s: str


@dataclass
class Span:
    lo: float
    cnt: float


@dataclass
class Lambda:
    args: List[str]
    body: Any


class _Reader:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def peek(self):
        # ',' counts as whitespace, matching the reference tokenizer
        # (water/rapids/Rapids.java skipWS) — h2o-py emits %r-style lists
        # like ['a','b'] in Assembly step ASTs
        while self.i < len(self.s) and (self.s[self.i].isspace()
                                        or self.s[self.i] == ","):
            self.i += 1
        return self.s[self.i] if self.i < len(self.s) else ""

    def next(self):
        c = self.peek()
        self.i += 1
        return c

    def token(self) -> str:
        self.peek()
        j = self.i
        while j < len(self.s) and not self.s[j].isspace() and self.s[j] not in "()[]{},'\"":
            j += 1
        tok = self.s[self.i:j]
        self.i = j
        return tok

    def string(self, quote: str) -> str:
        out = []
        while True:
            if self.i >= len(self.s):
                raise ValueError("unterminated string")
            c = self.s[self.i]
            self.i += 1
            if c == "\\":
                out.append(self.s[self.i])
                self.i += 1
            elif c == quote:
                return "".join(out)
            else:
                out.append(c)


def _atom(tok: str):
    if tok in ("TRUE", "True", "true"):
        return 1.0
    if tok in ("FALSE", "False", "false"):
        return 0.0
    if tok in ("NA", "NaN", "nan"):
        return float("nan")
    try:
        return float(tok)
    except ValueError:
        return Id(tok)


def _parse_one(r: _Reader):
    c = r.peek()
    if c == "(":
        r.next()
        items = []
        while r.peek() != ")":
            if r.peek() == "":
                raise ValueError("unbalanced (")
            items.append(_parse_one(r))
        r.next()
        return items
    if c == "[":
        r.next()
        items: List[Any] = []
        while r.peek() != "]":
            if r.peek() == "":
                raise ValueError("unbalanced [")
            e = _parse_one(r)
            # a:b spans arrive as tokens 'lo:cnt' (atom parse fails) — handle
            if isinstance(e, Id) and ":" in e.name:
                lo, cnt = e.name.split(":")
                items.append(Span(float(lo), float(cnt)))
            else:
                items.append(e)
        r.next()
        if any(isinstance(x, (StrLit, Id)) for x in items):
            return StrList([x.s if isinstance(x, StrLit)
                            else x.name if isinstance(x, Id) else x
                            for x in items])
        return NumList(items)
    if c == "{":
        r.next()
        args: List[str] = []
        while True:
            p = r.peek()
            if p == ".":
                r.next()
                break
            if p == "":
                raise ValueError("unbalanced {")
            t = r.token()
            if t == ".":
                break
            args.append(t)
        body = _parse_one(r)
        if r.peek() != "}":
            raise ValueError("unbalanced {")
        r.next()
        return Lambda(args, body)
    if c in ("'", '"'):
        r.next()
        return StrLit(r.string(c))
    tok = r.token()
    if not tok:
        raise ValueError(f"parse error at {r.i}: {r.s[r.i:r.i+20]!r}")
    return _atom(tok)


class NumList(list):
    """Marker: a bracket list of pure numbers/spans (vs an application)."""


class StrList(list):
    """Marker: a bracket list of strings (already unwrapped to str)."""


def parse(s: str):
    r = _Reader(s)
    ast = _parse_one(r)
    if r.peek() != "":
        raise ValueError(f"trailing input: {r.s[r.i:]!r}")
    return ast


# LRU cap for the statement-parse memo: long-lived serving sessions see an
# unbounded stream of distinct statement strings (literals differ per
# request), so the memo must be bounded or it grows without limit. Read
# once at import (uniform-env contract, like H2O_TPU_HOST_MATRIX_CELLS);
# occupancy is surfaced on the /3/ScoringMetrics `rapids` block.
_PARSE_CACHE_CAP = max(
    int(os.environ.get("H2O_TPU_RAPIDS_PARSE_CACHE", "1024") or 1024), 16)


@functools.lru_cache(maxsize=_PARSE_CACHE_CAP)
def parse_cached(s: str):
    """Memoized :func:`parse` for the statement hot path: h2o-py clients
    re-send the same AST strings constantly (every frame refresh), and the
    evaluator treats parsed ASTs as read-only, so caching by the exact
    expression string is safe. Parse errors are not cached (lru_cache
    does not memoize raises). Bounded by H2O_TPU_RAPIDS_PARSE_CACHE
    entries (LRU eviction)."""
    return parse(s)


def parse_cache_stats() -> dict:
    """Occupancy/effectiveness of the bounded statement-parse memo."""
    info = parse_cached.cache_info()
    return {"size": info.currsize, "cap": info.maxsize,
            "hits": info.hits, "misses": info.misses}
