"""Lazy whole-session Rapids: the cross-statement DAG planner.

Reference: H2O-3's clients already build lazy expression ASTs client-side
and only ship them on an observation (h2o-py ExprNode._eager_frame,
PAPER.md L7) — but the server still executes every shipped statement
eagerly, materializing a result Column per statement. This module makes
the SESSION the compilation unit (ROADMAP item 3):

- **Deferral.** ``(tmp= k expr)`` / ``(assign k expr)`` statements whose
  RHS the fusion engine could plan (fusible elementwise/comparison/
  logic/ifelse/is.na chains over device columns), plus device ``sort``
  statements and contiguous row slices over a deferred sort, are
  recorded as DAG nodes instead of executing. The assigned temp is a
  real Frame whose columns are **lazy** (``Column.file_backed`` with a
  planner loader), so nrows/names/types answer without execution and ANY
  data access — REST frame fetch, CSV export, rollups, a model build —
  is automatically an observation point that flushes the DAG. Statements
  the planner cannot defer flush first and then run eagerly, preserving
  statement order exactly.
- **SSA bindings + pinning.** Every identifier in a deferred RHS is
  resolved at defer time and snapshotted on the node (overwriting or
  ``rm``-ing a temp later cannot change what an already-deferred
  statement reads — the regression the refcount pin guards), and the
  concrete input Columns are pinned in the Session's refcounts until the
  node retires.
- **Flush planning.** At a flush the planner computes liveness: nodes
  whose key was overwritten or removed and that no live node depends on
  are **dead temps** — never computed. A deferred intermediate consumed
  by exactly one live fused statement is **inlined**: its expression
  tree splices into the consumer's fused program as a traced
  intermediate (no Column ever materializes), bitwise-identical by the
  fusion emitter's shared-expression + rewrite-edge-split contract.
  Structurally identical live nodes are **CSE-deduplicated** (one
  program execution, one Column, counted ``cse_hits``). A device sort
  whose only live consumer is a row slice executes as one fused
  sort+selection (``ops/sort.sort_frame(rows=(lo, hi))``): only the
  selected window of the sorted permutation is gathered.
- **Caching.** Fused flush programs ride the PR-9 signature cache + the
  PR-6 persistent compile cache and the PR-12 compile ledger unchanged
  (family ``rapids``) — a warm session flushes with zero XLA compiles.
- **Fallbacks.** Any node whose fused plan fails (ragged layout, evicted
  host column) replays its recorded AST through the eager evaluator over
  its snapshotted bindings — the same statement-order semantics, so lazy
  results are bitwise-identical to eager evaluation by construction.
  Multi-process clouds stay eager: a flush triggered by a
  coordinator-only REST fetch would dispatch collectives the followers
  never join (the PR-5/PR-7 mirrored-program invariant), so
  ``enabled()`` deterministically reports False there.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_tpu.core.frame import Column, Frame, T_CAT, T_NUM
from h2o3_tpu.ops import elementwise as E
from h2o3_tpu.rapids import fusion
from h2o3_tpu.rapids.parser import Id, NumList, Span, StrLit, StrList

_MISS = object()

# ---------------------------------------------------------------------------
# enable / force switches (same contract as fusion.enabled / fusion.force)
# ---------------------------------------------------------------------------

_FORCE: Optional[bool] = None


def enabled() -> bool:
    """Master switch for lazy-session deferral (H2O_TPU_RAPIDS_LAZY,
    default on). Deterministically OFF on multi-process clouds: a flush
    can be triggered by a coordinator-only observation (REST frame
    fetch), and its device programs must not run unmirrored around
    shared collectives."""
    if _FORCE is not None:
        return _FORCE
    import jax

    if jax.process_count() > 1:
        return False
    return os.environ.get("H2O_TPU_RAPIDS_LAZY", "1").lower() not in (
        "0", "false", "off")


class force:
    """Context manager pinning deferral on/off regardless of the env knob
    (bench A/B runs and the equivalence suite)."""

    def __init__(self, on: bool):
        self._on = bool(on)
        self._prev: Optional[bool] = None

    def __enter__(self):
        global _FORCE
        self._prev = _FORCE
        _FORCE = self._on
        return self

    def __exit__(self, *exc):
        global _FORCE
        _FORCE = self._prev
        return False


# ---------------------------------------------------------------------------
# counters (surfaced on the /3/ScoringMetrics `rapids` block + /3/Metrics)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_COUNTS = {
    "deferred_statements": 0,      # statements recorded as DAG nodes
    "flushes": 0,                  # DAG flushes (>= 1 node processed)
    "cse_hits": 0,                 # nodes served from an identical node
    "dead_temps_eliminated": 0,    # nodes never computed (unobservable)
    "inlined_intermediates": 0,    # nodes spliced into consumers' programs
    "fused_sort_selections": 0,    # sort+slice pairs run as one window
    "eager_replays": 0,            # nodes replayed through the evaluator
    "transparent_statements": 0,   # metadata-only munges run over lazy cols
}
_PENDING = 0                       # deferred statements awaiting flush


def _bump(key: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTS[key] += int(n)


def _pending_add(n: int) -> None:
    global _PENDING
    with _LOCK:
        _PENDING += int(n)


def counters() -> dict:
    with _LOCK:
        out = dict(_COUNTS)
        out["deferred_pending"] = _PENDING
        return out


def reset_counters() -> None:
    global _PENDING
    with _LOCK:
        for k in _COUNTS:
            _COUNTS[k] = 0
        _PENDING = 0


class _NotDeferrable(Exception):
    """Internal: this statement must flush + run eagerly."""


# ---------------------------------------------------------------------------
# DAG nodes + lazy frames
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("kind", "key", "ast", "bindings", "deps", "out",
                 "out_cols", "out_names", "output_dead", "state", "seq",
                 "by", "asc", "src_frame", "src", "lo", "hi", "nrows",
                 "pinned")

    def __init__(self, kind: str):
        self.kind = kind               # "expr" | "sort" | "slice"
        self.key: Optional[str] = None
        self.ast = None
        self.bindings: Dict[str, Any] = {}
        self.deps: List["_Node"] = []
        self.out: Optional[Frame] = None
        self.out_cols: List[Column] = []
        self.out_names: List[str] = []
        self.output_dead = False
        self.state = "pending"         # pending -> done
        self.seq = 0
        self.by = None                 # sort: key names
        self.asc = True                # sort: direction(s)
        self.src_frame: Optional[Frame] = None   # sort: input frame
        self.src: Optional["_Node"] = None       # slice: the sort node
        self.lo = 0                    # slice window
        self.hi = 0
        self.nrows = 0
        self.pinned: List[Column] = []


class DeferredFrame(Frame):
    """Pending output of a deferred statement: a normal Frame whose lazy
    Columns materialize (via the owning planner) on first data access —
    which makes every data-touching surface an observation point with no
    call-site changes."""

    def __init__(self, node: _Node, key: Optional[str] = None):
        super().__init__(key=key)
        self._node = node

    def __repr__(self) -> str:
        return (f"<DeferredFrame {self._key} {self.nrows}x{self.ncols} "
                f"{self._node.kind}:{self._node.state}>")


def _lazy_column(planner: "SessionPlanner", node: _Node, ctype: str,
                 nrows: int, domain=None) -> Column:
    holder: Dict[str, Column] = {}

    def _load():
        planner.observe(node)
        col = holder["col"]
        if col._data is None:
            raise RuntimeError(
                f"deferred rapids node #{node.seq} ({node.kind}) failed "
                "to materialize")
        # ensure() bound the device buffer via the data setter; the
        # getter re-checks and never uses this return value
        return None

    col = Column.file_backed(_load, ctype, nrows, domain=domain)
    holder["col"] = col
    return col


# ---------------------------------------------------------------------------
# deferral scanning — mirrors the eager evaluator's accepted shapes so any
# statement the eager path would REJECT (bad arity, unknown column, row
# mismatch) is never deferred: its error surfaces at the original statement
# ---------------------------------------------------------------------------

class _Scan:
    __slots__ = ("bindings", "deps", "_dep_ids", "nrows", "cols")

    def __init__(self):
        self.bindings: Dict[str, Any] = {}
        self.deps: List[_Node] = []
        self._dep_ids: set = set()
        self.nrows: Optional[int] = None
        self.cols: List[Column] = []   # concrete columns (to pin)


class _SnapEnv:
    """Env over a node's SSA binding snapshot (defer-time resolution)."""

    __slots__ = ("b",)

    def __init__(self, bindings: Dict[str, Any]):
        self.b = bindings

    def lookup(self, name: str):
        if name in self.b:
            return self.b[name]
        raise KeyError(name)


# live planners, discoverable by column token: the pipeline splicer
# (h2o3_tpu/pipeline.py) receives only a Frame and must find which
# planner's pending DAG its lazy columns belong to WITHOUT touching the
# columns (a data access would be an observation point and flush the DAG)
_PLANNERS: "weakref.WeakSet" = weakref.WeakSet()


def pending_node_for_token(tok: int):
    """(planner, node) owning a still-pending lazy column, else None."""
    for pl in list(_PLANNERS):
        n = pl.node_for_token(tok)
        if n is not None and n.state == "pending":
            return pl, n
    return None


class SessionPlanner:
    """Per-Session deferred-statement DAG (see module docstring)."""

    def __init__(self, session):
        self.session = session
        self._lock = threading.RLock()
        self._nodes: List[_Node] = []
        self._by_key: Dict[str, _Node] = {}
        self._by_token: Dict[int, _Node] = {}
        self._cse: Dict[tuple, Column] = {}
        self._seq = 0
        self._flushing = False
        _PLANNERS.add(self)

    # -- lookup ------------------------------------------------------------
    def node_for_token(self, tok: int) -> Optional[_Node]:
        return self._by_token.get(tok)

    def node_for_frame(self, fr: Frame) -> Optional[_Node]:
        """The single pending node ALL of fr's columns belong to."""
        node = None
        for c in fr.columns:
            n = self._by_token.get(c.token)
            if n is None or n.state != "pending" or \
                    (node is not None and n is not node):
                return None
            node = n
        return node

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._nodes)

    # -- statement entry ---------------------------------------------------
    def offer(self, ast, env):
        """Returns the statement result when deferred, else _MISS after
        flushing any pending DAG (the statement is an observation point —
        except `rm`, which only retires)."""
        from h2o3_tpu.obs import tracing

        with self._lock:
            if enabled():
                try:
                    got = self._try_defer(ast, env)
                except _NotDeferrable:
                    got = _MISS
                if got is not _MISS:
                    return got
            if self._is_rm(ast):
                return _MISS       # retirement rides Session.remove
            if self._is_assign(ast):
                # the key WILL be rebound; its pending node (if any) is
                # observable only through still-deferred readers
                k = self._assign_key(ast)
                old = self._by_key.pop(k, None)
                if old is not None:
                    old.output_dead = True
            if self._nodes and self._is_transparent(ast):
                # metadata-only munges (cbind / append / colnames= / cols)
                # move Column REFS between frames without reading a single
                # value — running them eagerly over still-lazy columns is
                # NOT an observation. The assembled frame keeps its pending
                # tokens, so a downstream predict can splice the whole
                # feature DAG into one munge→score program
                # (h2o3_tpu/pipeline.py) with zero materializations.
                _bump("transparent_statements")
                return _MISS
            if self._nodes:
                with tracing.span("flush", reason="statement"):
                    self.flush()
            return _MISS

    @staticmethod
    def _is_assign(ast) -> bool:
        return (isinstance(ast, list) and len(ast) == 3
                and isinstance(ast[0], Id)
                and ast[0].name in ("tmp=", "assign"))

    @staticmethod
    def _assign_key(ast) -> str:
        k = ast[1]
        return k.name if isinstance(k, Id) else str(k)

    @staticmethod
    def _is_rm(ast) -> bool:
        return (isinstance(ast, list) and len(ast) == 2
                and isinstance(ast[0], Id) and ast[0].name == "rm")

    # prims verified metadata-only in rapids/eval.py: they assemble frames
    # from Column references (Frame.cbind/subframe/add/rename) and never
    # touch `.data`, so lazy columns pass through them un-observed
    _TRANSPARENT = frozenset({"cbind", "append", "colnames=", "cols",
                              "cols_py"})

    @classmethod
    def _is_transparent(cls, ast) -> bool:
        if cls._is_assign(ast):
            ast = ast[2]
        return (isinstance(ast, list) and bool(ast)
                and isinstance(ast[0], Id)
                and ast[0].name in cls._TRANSPARENT)

    # -- deferral ----------------------------------------------------------
    def _try_defer(self, ast, env):
        if not self._is_assign(ast):
            # bare statements hand their result straight back to the
            # caller — deferring buys nothing and would skew the eager
            # counter contracts; chaining happens through temps
            return _MISS
        key = self._assign_key(ast)
        rhs = ast[2]
        if not (isinstance(rhs, list) and rhs and isinstance(rhs[0], Id)):
            return _MISS
        head = rhs[0].name
        if head == "sort":
            node = self._scan_sort(rhs, env)
        elif head == "rows":
            node = self._scan_slice(rhs, env)
        elif head in fusion.ROOT_OPS:
            node = self._scan_expr_node(rhs, env)
        else:
            return _MISS
        self._seq += 1
        node.seq = self._seq
        node.key = key
        old = self._by_key.get(key)
        if old is not None:
            old.output_dead = True
        self._by_key[key] = node
        self._nodes.append(node)
        for c in node.out_cols:
            self._by_token[c.token] = node
        self.session.pin_columns(node.pinned)
        _bump("deferred_statements")
        _pending_add(1)
        return self.session.assign(key, node.out)

    def _bind_name(self, name: str, env, sc: _Scan):
        if name in sc.bindings:
            return sc.bindings[name]
        try:
            v = env.lookup(name)
        except KeyError:
            raise _NotDeferrable
        sc.bindings[name] = v
        return v

    def _note_col(self, col: Column, sc: _Scan) -> None:
        if col.ctype not in fusion._LEAF_CTYPES:
            raise _NotDeferrable
        if sc.nrows is None:
            sc.nrows = col.nrows
        elif sc.nrows != col.nrows:
            raise _NotDeferrable       # eager would raise a row mismatch
        node = self._by_token.get(col.token)
        if node is not None and node.state == "pending":
            if id(node) not in sc._dep_ids:
                sc._dep_ids.add(id(node))
                sc.deps.append(node)
        else:
            sc.cols.append(col)

    def _scan_expr(self, ast, env, sc: _Scan) -> bool:
        """-> is_col; raises _NotDeferrable on any shape the fusion
        planner (or the eager evaluator) would not accept."""
        if isinstance(ast, bool):
            raise _NotDeferrable
        if isinstance(ast, (int, float)):
            return False
        if isinstance(ast, Id):
            v = self._bind_name(ast.name, env, sc)
            if isinstance(v, Frame):
                if v.ncols != 1:
                    raise _NotDeferrable
                self._note_col(v.col(0), sc)
                return True
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return False
            raise _NotDeferrable
        if not isinstance(ast, list) or not ast or \
                not isinstance(ast[0], Id):
            raise _NotDeferrable
        name = ast[0].name
        if name in ("cols", "cols_py"):
            if len(ast) != 3 or not isinstance(ast[1], Id):
                raise _NotDeferrable
            fr = self._bind_name(ast[1].name, env, sc)
            if not isinstance(fr, Frame):
                raise _NotDeferrable
            cname = _cols_sel_name(fr, ast[2])
            self._note_col(fr.col(cname), sc)
            return True
        if name in fusion._BIN_NAMES:
            if len(ast) != 3:
                raise _NotDeferrable
            l = self._scan_expr(ast[1], env, sc)
            r = self._scan_expr(ast[2], env, sc)
            return l or r
        if name in fusion._LOGICAL_NAMES:
            if len(ast) != 3:
                raise _NotDeferrable
            l = self._scan_expr(ast[1], env, sc)
            r = self._scan_expr(ast[2], env, sc)
            if not (l or r):
                raise _NotDeferrable
            return True
        if name in E._UNOPS:
            if len(ast) != 2 or not self._scan_expr(ast[1], env, sc):
                raise _NotDeferrable
            return True
        if name == "ifelse":
            if len(ast) != 4 or not self._scan_expr(ast[1], env, sc):
                raise _NotDeferrable
            self._scan_expr(ast[2], env, sc)
            self._scan_expr(ast[3], env, sc)
            return True
        if name == "is.na":
            if len(ast) != 2 or not self._scan_expr(ast[1], env, sc):
                raise _NotDeferrable
            return True
        raise _NotDeferrable

    def _scan_expr_node(self, rhs, env) -> _Node:
        sc = _Scan()
        if not self._scan_expr(rhs, env, sc) or sc.nrows is None:
            raise _NotDeferrable
        node = _Node("expr")
        node.ast = rhs
        node.bindings = sc.bindings
        node.deps = sc.deps
        node.nrows = sc.nrows
        node.pinned = sc.cols
        col = _lazy_column(self, node, T_NUM, sc.nrows)
        name = _expr_out_name(rhs)
        df = DeferredFrame(node)
        df.add(name, col)
        node.out = df
        node.out_cols = [col]
        node.out_names = [name]
        return node

    def _scan_sort(self, rhs, env) -> _Node:
        # (sort fr by asc...) — device-only: every column rides a lazy
        # device Column, so host-resident (string) frames stay eager
        if len(rhs) < 3 or not isinstance(rhs[1], Id):
            raise _NotDeferrable
        sc = _Scan()
        fr = self._bind_name(rhs[1].name, env, sc)
        if not isinstance(fr, Frame) or not fr.ncols:
            raise _NotDeferrable
        by = _sort_by_names(fr, rhs[2])
        asc = _sort_ascending(rhs[3:])
        for c in fr.columns:
            self._note_col(c, sc)
        node = _Node("sort")
        node.ast = rhs
        node.bindings = sc.bindings
        node.deps = sc.deps
        node.nrows = fr.nrows
        node.pinned = sc.cols
        node.by = by
        node.asc = asc
        node.src_frame = fr
        df = DeferredFrame(node)
        for nm in fr.names:
            c = fr.col(nm)
            lc = _lazy_column(self, node, c.ctype, fr.nrows,
                              domain=c.domain)
            df.add(nm, lc)
            node.out_cols.append(lc)
            node.out_names.append(nm)
        node.out = df
        return node

    def _scan_slice(self, rhs, env) -> _Node:
        # (rows s [lo:hi]) over a DEFERRED sort — the pair the planner
        # fuses into one windowed sort+selection
        if len(rhs) != 3 or not isinstance(rhs[1], Id):
            raise _NotDeferrable
        sc = _Scan()
        fr = self._bind_name(rhs[1].name, env, sc)
        if not isinstance(fr, Frame) or not fr.ncols:
            raise _NotDeferrable
        src = self.node_for_frame(fr)
        if src is None or src.kind != "sort":
            raise _NotDeferrable
        sel = rhs[2]
        if not isinstance(sel, NumList):
            raise _NotDeferrable
        from h2o3_tpu.rapids.eval import _idx_list

        idx = _idx_list(sel, fr.nrows)
        if not len(idx) or idx[0] < 0 or \
                not np.array_equal(idx, np.arange(idx[0], idx[-1] + 1)):
            raise _NotDeferrable
        n = fr.nrows
        lo = max(0, min(int(idx[0]), n))
        hi = max(lo, min(int(idx[-1]) + 1, n))
        node = _Node("slice")
        node.ast = rhs
        node.bindings = sc.bindings
        node.deps = [src]
        node.src = src
        node.lo = lo
        node.hi = hi
        node.nrows = hi - lo
        df = DeferredFrame(node)
        for nm in fr.names:
            c = fr.col(nm)
            lc = _lazy_column(self, node, c.ctype, node.nrows,
                              domain=c.domain)
            df.add(nm, lc)
            node.out_cols.append(lc)
            node.out_names.append(nm)
        node.out = df
        return node

    # -- session hooks -----------------------------------------------------
    def note_removed(self, key: str) -> None:
        with self._lock:
            n = self._by_key.pop(key, None)
            if n is not None:
                n.output_dead = True

    def end(self) -> None:
        """Session teardown: every pending output is unobservable —
        retire the whole DAG without computing anything."""
        with self._lock:
            nodes = self._nodes
            for n in nodes:
                n.output_dead = True
            dead = [n for n in nodes if n.state == "pending"]
            _bump("dead_temps_eliminated", len(dead))
            self._retire(nodes)

    # -- flush -------------------------------------------------------------
    def flush(self, target: Optional[_Node] = None) -> None:
        """Observation point: plan the deferred DAG (liveness, CSE,
        inlining, sort+selection fusion) and execute what is observable,
        in statement order."""
        with self._lock:
            nodes = list(self._nodes)
            if not nodes:
                return
            _bump("flushes")
            needed = self._needed(nodes, target)
            consumers: Dict[int, set] = {}
            for n in nodes:
                if id(n) not in needed:
                    continue
                for d in n.deps:
                    consumers.setdefault(id(d), set()).add(id(n))
            by_id = {id(n): n for n in nodes}
            inline: set = set()
            slice_fused: set = set()
            for n in nodes:
                if id(n) not in needed or not n.output_dead:
                    continue
                cons = consumers.get(id(n), set())
                if len(cons) != 1:
                    continue
                consumer = by_id.get(next(iter(cons)))
                if consumer is None:
                    continue
                # expr inlining only pays off inside a FUSED consumer
                # program; with fusion off every consumer eager-replays,
                # which needs its deps materialized anyway
                if n.kind == "expr" and consumer.kind == "expr" and \
                        fusion.enabled():
                    inline.add(id(n))
                elif n.kind == "sort" and consumer.kind == "slice":
                    slice_fused.add(id(consumer))
            self._flushing = True
            try:
                for n in nodes:
                    if id(n) not in needed or id(n) in inline:
                        continue
                    if n.kind == "sort" and self._sort_is_fused(
                            n, consumers, by_id, slice_fused):
                        continue
                    self._materialize(n, inline, slice_fused)
            finally:
                self._flushing = False
            _bump("inlined_intermediates", len(inline))
            dead = [n for n in nodes if id(n) not in needed]
            _bump("dead_temps_eliminated", len(dead))
            self._retire(nodes)

    @staticmethod
    def _sort_is_fused(n: _Node, consumers, by_id, slice_fused) -> bool:
        cons = consumers.get(id(n), set())
        return (len(cons) == 1 and next(iter(cons)) in slice_fused)

    @staticmethod
    def _needed(nodes: List[_Node], target: Optional[_Node]) -> set:
        roots = [n for n in nodes if not n.output_dead]
        if target is not None and target.state == "pending":
            roots.append(target)
        needed: set = set()
        stack = list(roots)
        while stack:
            n = stack.pop()
            if id(n) in needed:
                continue
            needed.add(id(n))
            stack.extend(d for d in n.deps if d.state == "pending")
        return needed

    def _retire(self, nodes: List[_Node]) -> None:
        for n in nodes:
            self.session.unpin_columns(n.pinned)
            n.pinned = []
            for c in n.out_cols:
                if self._by_token.get(c.token) is n:
                    self._by_token.pop(c.token)
            if n.key is not None and self._by_key.get(n.key) is n:
                self._by_key.pop(n.key)
        _pending_add(-len(nodes))
        self._nodes = []
        self._cse.clear()

    # -- materialization ---------------------------------------------------
    def observe(self, node: _Node) -> None:
        """A lazy Column of `node` was touched: this is an observation
        point. Flush the CURRENT epoch with full planning (liveness /
        CSE / inlining / sort+selection fusion) when the node belongs to
        it; a retired straggler (dead-eliminated earlier, observed now)
        materializes alone from its recorded recipe."""
        from h2o3_tpu.obs import tracing

        with self._lock:
            if node.state == "done":
                return
            if not self._flushing and any(n is node for n in self._nodes):
                with tracing.span("flush", reason="data_access"):
                    self.flush(target=node)
            if node.state != "done":
                # mid-flush re-entry (an eager replay touching a lazy
                # leaf) or a retired straggler: materialize directly —
                # re-entering flush() here would loop on its inline set
                self.ensure(node)

    def ensure(self, node: _Node) -> None:
        """Idempotent on-demand materialization (lazy-Column loaders and
        cross-epoch stragglers: a dead-eliminated node observed later
        still computes, from its own recorded recipe)."""
        with self._lock:
            if node.state == "done":
                return
            self._materialize(node, frozenset(), frozenset())

    def _materialize(self, node: _Node, inline: set,
                     slice_fused: set) -> None:
        if node.state == "done":
            return
        if node.kind == "slice" and id(node) in slice_fused and \
                node.src is not None and node.src.state == "pending":
            for d in node.src.deps:
                self.ensure(d)
            self._mat_slice_fused(node)
            node.state = "done"
            return
        for d in node.deps:
            if id(d) not in inline:
                self.ensure(d)
        if node.kind == "expr":
            self._mat_expr(node, inline)
        elif node.kind == "sort":
            self._mat_sort(node)
        else:
            self.ensure(node.src)
            self._mat_slice(node)
        node.state = "done"

    def _mat_expr(self, node: _Node, inline: set) -> None:
        col: Optional[Column] = None
        if fusion.enabled():
            plan = self._build_plan(node, inline)
            if plan is not None:
                ck = _cse_key(plan)
                col = self._cse.get(ck)
                if col is not None:
                    _bump("cse_hits")
                else:
                    try:
                        col = fusion.execute_plan(plan)
                    except Exception:   # noqa: BLE001 — eager is the
                        col = None      # contract, never fail a flush
                    if col is not None:
                        self._cse[ck] = col
        if col is None:
            # eager replay touches dep columns directly — every dep must
            # be materialized first, INCLUDING inline-marked ones (whose
            # consumer-side fused plan never happened), or the lazy-leaf
            # loader would re-enter the flush
            for d in node.deps:
                self.ensure(d)
            col = self._eager_col(node)
            _bump("eager_replays")
        node.out_cols[0].data = col.data

    def _build_plan(self, node: _Node, inline: set):
        pl = _LazyPlanner(_SnapEnv(node.bindings), self, inline)
        try:
            root, is_col = pl.build(node.ast)
        except fusion._NotFusible:
            return None
        p = pl.plan
        if not is_col or p.padded is None or p.n_ops == 0:
            return None
        p.root = root
        p.out_name = fusion._out_name(root)
        fusion._split_rewrite_edges(p)
        fusion._finish_signature(p)
        return p

    def _eager_col(self, node: _Node) -> Column:
        from h2o3_tpu.rapids import eval as _ev

        env = _ev.Env(self.session)
        env.vars.update(node.bindings)
        res = _ev._eval(node.ast, env)
        return res if isinstance(res, Column) else _ev._one_col(res)

    def _mat_sort(self, node: _Node) -> None:
        from h2o3_tpu.ops.sort import sort_frame

        res = sort_frame(node.src_frame, node.by, ascending=node.asc)
        self._fill(node, res)

    def _mat_slice(self, node: _Node) -> None:
        from h2o3_tpu.ops.filters import slice_rows

        res = slice_rows(node.src.out, node.lo, node.hi)
        self._fill(node, res)

    def _mat_slice_fused(self, node: _Node) -> None:
        from h2o3_tpu.ops.sort import sort_frame

        src = node.src
        res = sort_frame(src.src_frame, src.by, ascending=src.asc,
                         rows=(node.lo, node.hi))
        _bump("fused_sort_selections")
        self._fill(node, res)

    @staticmethod
    def _fill(node: _Node, res: Frame) -> None:
        for lc, nm in zip(node.out_cols, node.out_names):
            src = res.col(nm)
            if src.data is None:
                raise RuntimeError(
                    f"deferred {node.kind} produced a host column {nm!r}")
            lc.data = src.data

    def stats(self) -> dict:
        with self._lock:
            return {"pending": len(self._nodes)}


# ---------------------------------------------------------------------------
# fused planning over deferred leaves
# ---------------------------------------------------------------------------

class _LazyPlanner(fusion._Planner):
    """fusion._Planner that resolves PENDING deferred outputs: inlined
    deps splice their expression trees (traced intermediates — no Column
    materializes); everything else is ensured and bound as a leaf."""

    def __init__(self, env, planner: SessionPlanner, inline: set):
        super().__init__(env)
        self._lazy = planner
        self._inline = inline

    def _bind_value(self, v):
        if isinstance(v, Frame) and v.ncols == 1:
            col = v.col(0)
            node = self._lazy.node_for_token(col.token)
            if node is not None and node.state == "pending":
                return self._pending(node, col), True
        return super()._bind_value(v)

    def _frame_leaf(self, fr, name):
        col = fr.col(name)
        node = self._lazy.node_for_token(col.token)
        if node is not None and node.state == "pending":
            return self._pending(node, col)
        return super()._frame_leaf(fr, name)

    def _pending(self, node: _Node, col: Column):
        if id(node) in self._inline and node.kind == "expr":
            env0 = self.env
            self.env = _SnapEnv(node.bindings)
            try:
                n, is_col = self.build(node.ast)
            finally:
                self.env = env0
            if not is_col:
                raise fusion._NotFusible
            return n
        self._lazy.ensure(node)
        return self._leaf(col)


def _cse_key(plan) -> tuple:
    """Value-level identity of a fused plan: program signature (structure
    × dtypes × rows bucket) + concrete leaf Column tokens + constant
    VALUES (constants are traced in the program cache, but CSE needs
    value equality)."""
    leaves = tuple(("P",) + _cse_key(l) if isinstance(l, fusion.Plan)
                   else ("C", l.token) for l in plan.leaves)
    return (plan.signature, leaves, tuple(plan.consts))


# ---------------------------------------------------------------------------
# scan helpers
# ---------------------------------------------------------------------------

def _expr_out_name(ast) -> str:
    name = ast[0].name
    if name in fusion._BIN_NAMES or name in fusion._LOGICAL_NAMES:
        return fusion._OP_ALIAS.get(name, name)
    if name in E._UNOPS:
        return name
    if name == "is.na":
        return "isNA"
    return "C1"


def _cols_sel_name(fr: Frame, sel) -> str:
    """Single-column (cols fr sel) selector -> column name; mirrors
    fusion._Planner._leaf_from_cols exactly."""
    if isinstance(sel, StrLit):
        name = sel.s
    elif isinstance(sel, StrList) and len(sel) == 1:
        name = sel[0]
    elif (isinstance(sel, NumList) and len(sel) == 1
          and not isinstance(sel[0], Span)):
        i = int(sel[0])
        if not 0 <= i < fr.ncols:
            raise _NotDeferrable
        name = fr.names[i]
    elif isinstance(sel, (int, float)) and not isinstance(sel, bool):
        i = int(sel)
        if not 0 <= i < fr.ncols:
            raise _NotDeferrable
        name = fr.names[i]
    else:
        raise _NotDeferrable
    if name not in fr:
        raise _NotDeferrable
    return name


def _sort_by_names(fr: Frame, by) -> List[str]:
    """Mirror of the eager sort prim's names_of, restricted to the shapes
    the planner can verify statically (anything else stays eager)."""
    from h2o3_tpu.rapids.eval import _idx_list

    if isinstance(by, str):
        names = [by]
    elif isinstance(by, StrLit):
        names = [by.s]
    elif isinstance(by, (int, float)) and not isinstance(by, bool):
        i = int(by)
        if not 0 <= i < fr.ncols:
            raise _NotDeferrable
        names = [fr.names[i]]
    elif isinstance(by, StrList):
        names = [s.s if isinstance(s, StrLit) else s for s in by]
    elif isinstance(by, NumList):
        try:
            names = [fr.names[i] for i in _idx_list(by, fr.ncols)]
        except IndexError:
            raise _NotDeferrable
    else:
        raise _NotDeferrable
    if not names or any(n not in fr for n in names):
        raise _NotDeferrable
    return names


def _sort_ascending(rest):
    """Mirror of the eager sort prim's direction parsing (only the first
    direction argument is consulted; 1 = asc, <= 0 = desc)."""
    if not rest:
        return True
    a0 = rest[0]
    items = a0 if isinstance(a0, (list, NumList)) else [a0]
    asc = []
    for a in items:
        if not isinstance(a, (int, float)) or isinstance(a, bool):
            raise _NotDeferrable
        asc.append(int(a) > 0)
    return asc


# ---------------------------------------------------------------------------
# eval entry
# ---------------------------------------------------------------------------

def offer_statement(ast, env):
    """exec_rapids hook: defer when possible, flush when the statement is
    an observation point. Cheap no-op for sessions that never deferred
    anything while the knob is off."""
    s = env.session
    if getattr(s, "_planner", None) is None and not enabled():
        return _MISS
    return s.planner.offer(ast, env)


def stats() -> dict:
    """Counters for the /3/ScoringMetrics `rapids` block + /3/Metrics."""
    return counters()
