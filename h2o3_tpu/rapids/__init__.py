"""Rapids — the lazy dataframe-algebra protocol.

Reference: water/rapids/ (23,281 LoC) — clients build ASTs client-side and
POST Lisp-like strings to /99/Rapids (Rapids.java parser, Session.java
refcounted temps, Env.java stack, 205 prim files under ast/prims/).

TPU-native design: the wire grammar is kept verbatim (h2o-py compatibility)
but prims dispatch straight to the jitted ops layer (h2o3_tpu/ops/*) — an
AST '(+ frame 5)' becomes one fused XLA elementwise program over row-sharded
columns instead of a chunk-iterating MRTask.
"""

from h2o3_tpu.rapids.parser import parse
from h2o3_tpu.rapids.eval import Env, Session, exec_rapids  # noqa: F401
