"""Rapids statement fusion engine: one XLA program per statement.

Reference: water/rapids executes each prim as its own MRTask pass over the
chunks; the first jax_graft port kept that shape — one (or a few) XLA
dispatches per prim with a Column materialized between every step, and a
host sync wherever a scalar crossed a prim boundary. This module is the
PR-2-style "compile-once fast path" applied to the whole expression
engine (ROADMAP open item 4a):

- **Classification.** Every registered prim carries one of three
  fusibility classes (closed enumeration, guarded by
  tests/test_consistency.py): ``fusible`` prims can appear INSIDE one
  fused XLA program (elementwise arithmetic/comparison/logic, unary
  math, ifelse, is.na, column selection); ``barrier`` prims are
  device-executed but bound a fused region with their own program
  (group-by, merge, sort, quantile, cumulative ops, the reducers and
  the ``rows`` filter — both consume fused chains as input, structural
  munging); ``host`` prims materialize data on the host and are the
  EXCEPTIONAL path — each execution increments the
  ``barrier_fallbacks`` counter.
- **Planning.** The evaluator offers every fusible application node to
  :func:`try_execute` before falling back to eager evaluation. The
  planner walks the subtree, binds Column leaves (dtype-checked,
  dedup'd by ``Column.token``, all sharing one padded row layout) and
  scalar constants, and renders a structure-only signature — constants
  are traced arguments, so repeated client statements that differ only
  in literals share one compiled program. A successful plan covers the
  MAXIMAL fusible subtree; barrier/host ancestors simply consume its
  result, so chains fuse without any special casing per prim. The one
  carve-out from "one program per statement" is bitwise soundness:
  edges the compiler is known to rewrite across (mul feeding +/- —
  FMA contraction; division/power chains — algebraic reassociation)
  become sub-program boundaries (:func:`_split_rewrite_edges`), each
  segment cached and shared like any other program.
- **Compilation.** Programs are AOT-compiled (``lower().compile()``)
  once per signature × column dtypes × padded-rows bucket and held in
  an in-process cache; the PR-6 persistent compile cache
  (``$H2O_TPU_COMPILE_CACHE_DIR``, artifact/compile_cache.py) serializes
  them across processes and restarts, so a warm server compiles ZERO
  fused programs for statement shapes it has seen before
  (counter-asserted by the fusion test suite).
- **Sharded execution.** Leaves are the columns' row-sharded device
  buffers consumed where they are; the program's output sharding is
  pinned to ``P('rows')`` over the mesh's named row axis
  (core/sharded_frame.ROW_AXIS), so fused statements never stage a
  column on the coordinator — ``gathered_rows`` stays 0 and the rows
  are counted ``packed`` on the same data-plane counters PR 7
  introduced. The eager evaluator remains as the degraded/ragged
  fallback, exactly as the host-packed scorer did.

The emitter composes the SAME traceable expressions the eager jits wrap
(ops/elementwise binop_expr/unop_expr/ifelse_expr/logical_expr/
isna_expr/cat_to_f32_expr), which is what makes fused output bitwise
identical to the eager evaluator by construction: identical per-element
op DAG, identical f32 casts at every node boundary — XLA fusion removes
the intermediate materializations, not the rounding steps.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.core.frame import (Column, Frame, T_CAT, T_INT, T_NUM,
                                 T_TIME)
from h2o3_tpu.ops import elementwise as E
from h2o3_tpu.rapids.parser import (Id, Lambda, NumList, Span, StrLit,
                                    StrList)

# ---------------------------------------------------------------------------
# fusibility classes — closed enumeration (consistency-suite guarded)
# ---------------------------------------------------------------------------

FUSIBLE = "fusible"
BARRIER = "barrier"
HOST = "host"
FUSION_CLASSES = frozenset({FUSIBLE, BARRIER, HOST})

# canonical op aliases (h2o-py emits both spellings)
_OP_ALIAS = {"%%": "%", "%/%": "intDiv", "&&": "&", "||": "|"}

_BIN_NAMES = {"+", "-", "*", "/", "^", "%", "intDiv", "%%", "%/%",
              "==", "!=", "<", "<=", ">", ">="}
_LOGICAL_NAMES = {"&", "&&", "|", "||"}

# fusible = can appear INSIDE one fused program. Reducers and the `rows`
# filter are NOT here: they CONSUME a fused chain but always execute as
# their own program (the rollup reduction / the selection gather), which
# is exactly the barrier definition.
_FUSIBLE_NAMES = (_BIN_NAMES | _LOGICAL_NAMES | set(E._UNOPS)
                  | {"ifelse", "is.na", "cols", "cols_py"})

# device-executed (or pure-metadata) prims that bound a fused region
_BARRIER_NAMES = {
    ",", ":=", "GB", "append", "assign", "cbind", "colnames=",
    "columnsByType", "cor", "cummax", "cummin", "cumprod", "cumsum",
    "distance", "filterNACols", "getTimeZone", "h2o.fillna", "h2o.impute",
    "any.factor", "any.na", "difflag1", "is.character", "is.factor",
    "is.numeric",
    "kurtosis", "median", "merge", "model.reset.threshold", "na.omit",
    "ncol", "nlevels", "none", "nrow", "prod", "prod.na", "quantile",
    "rbind", "rename", "rm", "rows", "scale", "setDomain", "setTimeZone",
    "setproperty", "skewness", "sort", "sumNA", "sumaxis", "table",
    "tmp=", "unique", "which.max", "which.min", "x",
    "mean", "sum", "min", "max", "sd", "var", "all", "any", "naCnt",
    "nacnt",
    # device-resident since the lazy-session PR: segmented-scan ranking
    # and the device diff (ops/window.py) — host loop only as the counted
    # ragged/string fallback
    "rank_within_groupby",
}

# host-materializing prims — the exceptional path (barrier_fallbacks)
_HOST_NAMES = {
    "apply", "as.Date", "as.character", "as.factor", "as.numeric",
    "ascharacter", "asfactor", "asnumeric", "countmatches", "cut", "day",
    "dayOfWeek", "ddply", "dropdup", "entropy", "flatten",
    "getrow", "grep", "grouped_permute", "h2o.mad",
    "h2o.random_stratified_split", "h2o.runif", "h2o.splitframe", "hist",
    "hour", "isax", "kfold_column", "levels", "listTimeZones", "ls",
    "lstrip", "mad", "match", "maxNA", "melt", "millis", "minNA",
    "minute", "mktime", "mode", "modulo_kfold_column", "moment", "month",
    "nchar", "num_valid_substrings", "perfectAUC", "pivot",
    "relevel", "rep_len", "replaceall",
    "replacefirst", "rstrip", "second", "segment_models_as_frame", "seq",
    "seq_len",
    "setLevel", "signif", "strDistance", "stratified_kfold_column",
    "strlen", "strsplit", "substring", "t", "tf-idf", "tokenize",
    "tolower", "topn", "toupper", "trim", "week", "which", "year",
}

PRIM_FUSION: Dict[str, str] = {}
for _n in _FUSIBLE_NAMES:
    PRIM_FUSION[_n] = FUSIBLE
for _n in _BARRIER_NAMES:
    PRIM_FUSION[_n] = BARRIER
for _n in _HOST_NAMES:
    PRIM_FUSION[_n] = HOST


def classify(name: str) -> Optional[str]:
    """Fusibility class of a registered prim (None for unknown names —
    the consistency guard refuses unclassified prims at test time)."""
    return PRIM_FUSION.get(name)


# compute roots the evaluator offers to try_execute (a subset of the
# fusible class: prims the emitter can be the ROOT of a fused program for)
ROOT_OPS = (_BIN_NAMES | _LOGICAL_NAMES | set(E._UNOPS)
            | {"ifelse", "is.na"})


# ---------------------------------------------------------------------------
# counters (surfaced as h2o3_rapids_* on /3/Metrics and under the
# ScoringMetrics `rapids` block)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_COUNTS = {
    "statements": 0,              # exec_rapids calls
    "fused_statements": 0,        # statements that ran >= 1 fused program
    "fused_programs": 0,          # fused program executions
    "fused_programs_compiled": 0,  # actual XLA compiles
    "compile_cache_hits": 0,      # warm reuse (in-memory sig or disk tier)
    "barrier_fallbacks": 0,       # host-class prim executions
    "host_materialized_cells": 0,  # cells staged on host by host prims
    "fused_rows": 0,              # logical rows through fused programs
}


def _bump(key: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTS[key] += int(n)


def note_statement() -> None:
    _bump("statements")


def note_host_fallback() -> None:
    _bump("barrier_fallbacks")


def note_host_cells(cells: int) -> None:
    _bump("host_materialized_cells", cells)


def counters() -> dict:
    with _LOCK:
        return dict(_COUNTS)


def reset_counters() -> None:
    with _LOCK:
        for k in _COUNTS:
            _COUNTS[k] = 0


# ---------------------------------------------------------------------------
# enable / force switches
# ---------------------------------------------------------------------------

_FORCE: Optional[bool] = None


def enabled() -> bool:
    """Master switch (H2O_TPU_RAPIDS_FUSION, default on). Off = the eager
    op-at-a-time evaluator everywhere, kept for A/B bitwise verification
    and emergency rollback — the same demotion contract as
    H2O_TPU_SHARDED_PLANE."""
    if _FORCE is not None:
        return _FORCE
    return os.environ.get("H2O_TPU_RAPIDS_FUSION", "1").lower() not in (
        "0", "false", "off")


class force:
    """Context manager pinning fusion on/off regardless of the env knob
    (bench A/B runs and the equivalence suite)."""

    def __init__(self, on: bool):
        self._on = bool(on)
        self._prev: Optional[bool] = None

    def __enter__(self):
        global _FORCE
        self._prev = _FORCE
        _FORCE = self._on
        return self

    def __exit__(self, *exc):
        global _FORCE
        _FORCE = self._prev
        return False


# ---------------------------------------------------------------------------
# planning
# ---------------------------------------------------------------------------

class _NotFusible(Exception):
    """Internal: this subtree cannot enter a fused program."""


_LEAF_CTYPES = (T_NUM, T_INT, T_CAT, T_TIME)


class Plan:
    """A fused column program: expression tree over Column leaves and
    scalar constants, plus the layout facts the cache key needs."""

    __slots__ = ("root", "leaves", "consts", "leaf_ctypes", "leaf_dtypes",
                 "padded", "nrows", "n_ops", "out_name", "signature")

    def __init__(self):
        self.root = None
        self.leaves: List[Column] = []
        self.consts: List[float] = []
        self.leaf_ctypes: List[str] = []
        self.leaf_dtypes: List[str] = []
        self.padded: Optional[int] = None
        self.nrows: Optional[int] = None
        self.n_ops = 0
        self.out_name = "C1"
        self.signature = ""


class _Planner:
    def __init__(self, env):
        self.env = env
        self.plan = Plan()
        self._leaf_ix: Dict[int, int] = {}     # Column.token -> leaf index

    # -- leaves ------------------------------------------------------------
    def _leaf(self, col: Column) -> tuple:
        if col.ctype not in _LEAF_CTYPES:
            raise _NotFusible
        d = col.data                      # faults evicted columns back in
        if d is None:
            raise _NotFusible             # host-resident (string) column
        p = self.plan
        padded = int(d.shape[0])
        if p.padded is None:
            p.padded, p.nrows = padded, col.nrows
        elif p.padded != padded or p.nrows != col.nrows:
            raise _NotFusible             # ragged layout: eager fallback
        ix = self._leaf_ix.get(col.token)
        if ix is None:
            ix = len(p.leaves)
            self._leaf_ix[col.token] = ix
            p.leaves.append(col)
            p.leaf_ctypes.append(col.ctype)
            p.leaf_dtypes.append(str(d.dtype))
        return ("L", ix)

    def _const(self, v: float) -> tuple:
        p = self.plan
        p.consts.append(float(v))
        return ("K", len(p.consts) - 1)

    def _resolve_frame(self, a) -> Frame:
        if isinstance(a, Id):
            try:
                v = self.env.lookup(a.name)
            except KeyError:
                raise _NotFusible
            if isinstance(v, Frame):
                return v
        raise _NotFusible

    def _leaf_from_cols(self, ast) -> tuple:
        if len(ast) != 3:
            raise _NotFusible
        fr = self._resolve_frame(ast[1])
        sel = ast[2]
        if isinstance(sel, StrLit):
            name = sel.s
        elif isinstance(sel, StrList) and len(sel) == 1:
            name = sel[0]
        elif (isinstance(sel, NumList) and len(sel) == 1
              and not isinstance(sel[0], Span)):
            i = int(sel[0])
            if not 0 <= i < fr.ncols:
                raise _NotFusible
            name = fr.names[i]
        elif isinstance(sel, (int, float)) and not isinstance(sel, bool):
            i = int(sel)
            if not 0 <= i < fr.ncols:
                raise _NotFusible
            name = fr.names[i]
        else:
            raise _NotFusible
        if name not in fr:
            raise _NotFusible
        return self._frame_leaf(fr, name)

    def _bind_value(self, v) -> Tuple[tuple, bool]:
        """Resolved Id value -> plan node. Overridable hook: the lazy
        session planner (rapids/planner.py) splices deferred-temp
        expression trees here instead of materializing their Columns."""
        if isinstance(v, Frame):
            if v.ncols != 1:
                raise _NotFusible
            return self._frame_leaf(v, v.names[0]), True
        if isinstance(v, Column):
            return self._leaf(v), True
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return self._const(float(v)), False
        raise _NotFusible

    def _frame_leaf(self, fr: Frame, name: str) -> tuple:
        """Named-column leaf binding (same overridable hook contract as
        _bind_value — the lazy planner intercepts pending deferred
        outputs before their lazy Columns are touched)."""
        return self._leaf(fr.col(name))

    # -- recursive build ---------------------------------------------------
    def build(self, ast) -> Tuple[tuple, bool]:
        """-> (node, is_column). Mirrors the eager evaluator's value
        rules so fused and eager agree on which shapes are legal; any
        shape the eager path would reject raises _NotFusible and the
        eager path reports the error."""
        if isinstance(ast, bool):
            raise _NotFusible
        if isinstance(ast, (int, float)):
            return self._const(float(ast)), False
        if isinstance(ast, Id):
            try:
                v = self.env.lookup(ast.name)
            except KeyError:
                raise _NotFusible
            return self._bind_value(v)
        if not isinstance(ast, list) or not ast or \
                not isinstance(ast[0], Id):
            raise _NotFusible
        name = ast[0].name
        p = self.plan
        if name in ("cols", "cols_py"):
            return self._leaf_from_cols(ast), True
        if name in _BIN_NAMES:
            if len(ast) != 3:
                raise _NotFusible
            l, lcol = self.build(ast[1])
            r, rcol = self.build(ast[2])
            p.n_ops += 1
            return ("bin", _OP_ALIAS.get(name, name), l, r), lcol or rcol
        if name in _LOGICAL_NAMES:
            if len(ast) != 3:
                raise _NotFusible
            l, lcol = self.build(ast[1])
            r, rcol = self.build(ast[2])
            if not (lcol or rcol):
                raise _NotFusible         # eager needs a Column ref
            p.n_ops += 1
            return ("log", _OP_ALIAS.get(name, name), l, r), True
        if name in E._UNOPS:
            if len(ast) != 2:
                raise _NotFusible
            x, xcol = self.build(ast[1])
            if not xcol:
                raise _NotFusible         # eager _one_col would raise
            p.n_ops += 1
            return ("un", name, x), True
        if name == "ifelse":
            if len(ast) != 4:
                raise _NotFusible
            c, ccol = self.build(ast[1])
            if not ccol:
                raise _NotFusible
            a, _ = self.build(ast[2])
            b, _ = self.build(ast[3])
            p.n_ops += 1
            return ("ifelse", c, a, b), True
        if name == "is.na":
            if len(ast) != 2:
                raise _NotFusible
            x, xcol = self.build(ast[1])
            if not xcol:
                raise _NotFusible
            p.n_ops += 1
            return ("isna", x), True
        raise _NotFusible


def _render(node) -> str:
    k = node[0]
    if k in ("L", "K"):
        return f"{k}{node[1]}"
    if k in ("bin", "log", "un"):
        return "(" + node[1] + " " + " ".join(
            _render(c) for c in node[2:]) + ")"
    return "(" + k + " " + " ".join(_render(c) for c in node[1:]) + ")"


def _out_name(node) -> str:
    """Output column name, matching the eager prims' _colfr naming."""
    k = node[0]
    if k in ("bin", "log", "un"):
        return node[1]
    if k == "isna":
        return "isNA"
    return "C1"                            # ifelse


def plan_expr(ast, env) -> Optional[Plan]:
    """Plan `ast` as a fused program (plus FMA-boundary sub-programs);
    None when not fusible."""
    pl = _Planner(env)
    try:
        root, is_col = pl.build(ast)
    except _NotFusible:
        return None
    p = pl.plan
    if not is_col or p.padded is None or p.n_ops == 0:
        return None
    p.root = root
    p.out_name = _out_name(root)
    _split_rewrite_edges(p)
    _finish_signature(p)
    return p


def _plan_is_scalar(plan: Plan) -> bool:
    """True when the program's output is rank-0 (a const-only subtree:
    no transitive Column leaf)."""
    return all(isinstance(l, Plan) and _plan_is_scalar(l)
               for l in plan.leaves)


# ---------------------------------------------------------------------------
# compilation — in-memory signature cache + PR-6 persistent tier
# ---------------------------------------------------------------------------

class _Program:
    __slots__ = ("exe", "jfn")

    def __init__(self, exe, jfn):
        self.exe = exe
        self.jfn = jfn


_PROGRAMS: Dict[str, _Program] = {}
_PROG_LOCK = threading.Lock()
_PROG_CAP = 256


def clear_programs() -> None:
    """Drop the in-process program cache (tests simulate a cold restart
    against the persistent tier this way)."""
    with _PROG_LOCK:
        _PROGRAMS.clear()


# ops XLA rewrites ACROSS when composed in one program, diverging from
# per-op f32 rounding: division/power/remainder chains get reassociated
# by the algebraic simplifier ((a/b)/c -> a/(b*c), a/exp(b) -> a*exp(-b),
# ...), so such a node always runs as its own segment with compute
# operands materialized
_BOUNDARY_OPS = frozenset({"/", "^", "%", "intDiv"})


def _is_compute(node) -> bool:
    return node[0] not in ("L", "K")


def _is_boundary(node) -> bool:
    return node[0] == "bin" and node[1] in _BOUNDARY_OPS


def _split_rewrite_edges(plan: Plan) -> None:
    """Rewrite the plan so no edge the backend is known to rewrite
    unsoundly (w.r.t. per-op f32 rounding) stays inside one program:

    - a multiply feeding +/- would be contracted into an FMA by codegen
      (the product skips its rounding step);
    - division/power/remainder nodes get algebraically reassociated with
      their neighbors by the HLO simplifier.

    Each such producer becomes its own sub-program whose materialized
    output re-enters the parent as a leaf — a program boundary is the
    one construct the compiler cannot rewrite across (everything cheaper
    — optimization_barrier, bitcast round-trips, output pinning,
    reduce_precision — is simplified away or contracted through before
    codegen; verified empirically). The common long chains of
    add/sub/mul/cmp/ifelse/mask/unary ops stay in one program.
    Sub-programs are full Plans: cached by their own signature, split
    recursively, shared across statements."""

    def walk(node):
        k = node[0]
        if k in ("L", "K"):
            return node
        if k == "bin":
            op = node[1]
            l = walk(node[2])
            r = walk(node[3])
            if op in _BOUNDARY_OPS:
                # a boundary node's compute operands arrive materialized
                l = extract(l) if _is_compute(l) else l
                r = extract(r) if _is_compute(r) else r
            else:
                if op in ("+", "-"):
                    if l[0] == "bin" and l[1] == "*":
                        l = extract(l)
                    if r[0] == "bin" and r[1] == "*":
                        r = extract(r)
                l = extract(l) if _is_boundary(l) else l
                r = extract(r) if _is_boundary(r) else r
            return ("bin", op, l, r)
        kids = [c if isinstance(c, str) else walk(c) for c in node[1:]]
        kids = [c if isinstance(c, str) or not _is_boundary(c)
                else extract(c) for c in kids]
        return (k, *kids)

    def extract(node):
        sub = Plan()
        sub.padded, sub.nrows = plan.padded, plan.nrows
        remap_l: Dict[int, int] = {}
        remap_k: Dict[int, int] = {}

        def rebind(n):
            if n[0] == "L":
                ix = remap_l.get(n[1])
                if ix is None:
                    ix = remap_l[n[1]] = len(sub.leaves)
                    sub.leaves.append(plan.leaves[n[1]])
                    sub.leaf_ctypes.append(plan.leaf_ctypes[n[1]])
                    sub.leaf_dtypes.append(plan.leaf_dtypes[n[1]])
                return ("L", ix)
            if n[0] == "K":
                ix = remap_k.get(n[1])
                if ix is None:
                    ix = remap_k[n[1]] = len(sub.consts)
                    sub.consts.append(plan.consts[n[1]])
                return ("K", ix)
            return (n[0], *[c if isinstance(c, str) else rebind(c)
                            for c in n[1:]])

        sub.root = rebind(node)
        sub.n_ops = _count_ops(sub.root)
        _split_rewrite_edges(sub)
        _finish_signature(sub)
        ix = len(plan.leaves)
        plan.leaves.append(sub)
        plan.leaf_ctypes.append(T_NUM)
        plan.leaf_dtypes.append("float32")
        return ("L", ix)

    plan.root = walk(plan.root)
    _compact_leaves(plan)


def _count_ops(node) -> int:
    if node[0] in ("L", "K"):
        return 0
    return 1 + sum(_count_ops(c) for c in node[1:]
                   if not isinstance(c, str))


def _compact_leaves(plan: Plan) -> None:
    """Drop leaves/consts the (possibly rewritten) tree no longer
    references and renumber the survivors in first-use order."""
    used_l: Dict[int, int] = {}
    used_k: Dict[int, int] = {}

    def renum(n):
        if n[0] == "L":
            ix = used_l.setdefault(n[1], len(used_l))
            return ("L", ix)
        if n[0] == "K":
            ix = used_k.setdefault(n[1], len(used_k))
            return ("K", ix)
        return (n[0], *[c if isinstance(c, str) else renum(c)
                        for c in n[1:]])

    plan.root = renum(plan.root)
    plan.leaves = [plan.leaves[i] for i in used_l]
    plan.leaf_ctypes = [plan.leaf_ctypes[i] for i in used_l]
    plan.leaf_dtypes = [plan.leaf_dtypes[i] for i in used_l]
    plan.consts = [plan.consts[i] for i in used_k]


def _leaf_sig(plan: Plan, i: int) -> str:
    leaf = plan.leaves[i]
    if isinstance(leaf, Plan):
        return "P{" + leaf.signature + "}"
    return f"{plan.leaf_ctypes[i]}/{plan.leaf_dtypes[i]}"


def _finish_signature(plan: Plan) -> None:
    plan.signature = (_render(plan.root)
                      + "|" + ",".join(_leaf_sig(plan, i)
                                       for i in range(len(plan.leaves)))
                      + f"|k{len(plan.consts)}|r{plan.padded}")


def _constrain_rows(v, mesh):
    """Pin the root output to the named row sharding from INSIDE the
    traced program (works identically for jit dispatch and the AOT
    lower/compile path, and leaves the pinned aux outputs — which may be
    rank-0 scalar subtrees — unconstrained)."""
    try:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from h2o3_tpu.core.sharded_frame import ROW_AXIS

        return jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, P(ROW_AXIS)))
    except Exception:   # noqa: BLE001 — constraint is an optimization
        return v


def _emit(plan: Plan, mesh):
    """Build the traceable python callable for this plan. Leaves convert
    through the SAME expressions the eager jits trace (elementwise
    *_expr), giving bitwise parity with op-at-a-time evaluation."""
    n_leaf = len(plan.leaves)
    ctypes = list(plan.leaf_ctypes)
    root = plan.root
    scalar_out = _plan_is_scalar(plan)

    def f(*args):
        def ev(node):
            k = node[0]
            if k == "L":
                d = args[node[1]]
                return (E.cat_to_f32_expr(d) if ctypes[node[1]] == T_CAT
                        else d)
            if k == "K":
                return args[n_leaf + node[1]]
            if k == "bin":
                return E.binop_expr(node[1], ev(node[2]), ev(node[3]))
            if k == "log":
                return E.logical_expr(node[1], ev(node[2]), ev(node[3]))
            if k == "un":
                return E.unop_expr(node[1], ev(node[2]))
            if k == "ifelse":
                return E.ifelse_expr(ev(node[1]), ev(node[2]), ev(node[3]))
            if k == "isna":
                return E.isna_expr(ev(node[1]))
            raise AssertionError(f"bad fused node {k!r}")

        r = ev(root)
        return r if scalar_out else _constrain_rows(r, mesh)

    return f


def _mesh():
    from h2o3_tpu.core.runtime import cluster

    return cluster().mesh


def _program_for(plan: Plan) -> _Program:
    sig = plan.signature
    with _PROG_LOCK:
        prog = _PROGRAMS.get(sig)
    if prog is not None:
        _bump("compile_cache_hits")
        from h2o3_tpu.obs import compiles

        compiles.record_hit("rapids", sig, "memory",
                            program="rapids_statement")
        return prog

    import jax

    from h2o3_tpu.artifact import compile_cache
    from h2o3_tpu.obs import compiles

    mesh = _mesh()
    jfn = jax.jit(_emit(plan, mesh))

    ckey = None
    exe = None
    if compile_cache.enabled():
        sig_hash = hashlib.sha256(sig.encode()).hexdigest()
        ckey = compile_cache.cache_key(sig_hash, plan.padded,
                                       variant="rapids")
        exe = compile_cache.load(ckey)
        if exe is not None:
            _bump("compile_cache_hits")
            compiles.record_hit("rapids", sig, "disk",
                                program="rapids_statement")
    if exe is None:
        structs = []
        for i, leaf in enumerate(plan.leaves):
            if isinstance(leaf, Plan) and _plan_is_scalar(leaf):
                structs.append(jax.ShapeDtypeStruct((), np.float32))
            else:
                structs.append(jax.ShapeDtypeStruct(
                    (plan.padded,), np.dtype(plan.leaf_dtypes[i])))
        structs += [jax.ShapeDtypeStruct((), np.float32)] * len(plan.consts)
        # ledger chokepoint: times the compile, records the row, feeds
        # the legacy note_compile counter with the SAME milliseconds
        exe = compiles.compile_jit("rapids", jfn, structs, signature=sig,
                                   program="rapids_statement")
        _bump("fused_programs_compiled")
        if ckey is not None:
            compile_cache.store(ckey, exe)
    from h2o3_tpu.memory import budget as membudget

    membudget.note_compiled("rapids", int(plan.padded or 0), exe)
    prog = _Program(exe, jfn)
    with _PROG_LOCK:
        if len(_PROGRAMS) >= _PROG_CAP:
            _PROGRAMS.pop(next(iter(_PROGRAMS)))
        _PROGRAMS[sig] = prog
    return prog


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

_CONST_CACHE: Dict[bytes, Any] = {}
_CONST_LOCK = threading.Lock()
_CONST_CAP = 1024


def _const_arg(v: float):
    """Device scalar for a traced constant, cached by its f32 bits — a
    fresh jnp.float32 per dispatch costs a device_put each, which
    dominated warm fused dispatch on profile (constants repeat across a
    session's statements; NaN bits key fine as bytes)."""
    k = np.float32(v).tobytes()
    a = _CONST_CACHE.get(k)
    if a is None:
        import jax.numpy as jnp

        a = jnp.float32(v)
        with _CONST_LOCK:
            if len(_CONST_CACHE) >= _CONST_CAP:
                _CONST_CACHE.pop(next(iter(_CONST_CACHE)))
            _CONST_CACHE[k] = a
    return a


def _run_program(plan: Plan):
    """Dispatch one program, resolving sub-program leaves first (each is
    its own compiled program; outputs stay device-resident between
    segments)."""
    prog = _program_for(plan)
    args = [(_run_program(leaf) if isinstance(leaf, Plan) else leaf.data)
            for leaf in plan.leaves]
    args += [_const_arg(v) for v in plan.consts]
    try:
        out = prog.exe(*args)
    except Exception:   # noqa: BLE001 — AOT layout/placement mismatch
        out = prog.jfn(*args)
    _bump("fused_programs")
    return out


# ---------------------------------------------------------------------------
# chunk-streamed execution (memory planner / OOM ladder)
# ---------------------------------------------------------------------------

def _window_pow2(m: int) -> int:
    """Windowed programs compile at power-of-two row counts, so a ladder
    (or a ragged tail) mints at most log2(padded) program shapes."""
    return 1 << max(int(m) - 1, 0).bit_length()


def _emit_windowed(plan: Plan, mesh, win: int):
    """Wrap the plan's traced body with a runtime row offset: full-length
    Column leaves are pad→dynamic-sliced to `win` rows at traced `pos`
    (no gather, no host round-trip); pre-windowed sub-program leaves and
    scalar leaves pass straight through. Every node in a fused plan is
    elementwise, so the window computes exactly the rows it covers —
    concatenated windows are bitwise the single-dispatch output."""
    inner = _emit(plan, mesh)
    n_leaf = len(plan.leaves)
    full_len = [not isinstance(l, Plan) for l in plan.leaves]

    def f(pos, *args):
        import jax
        import jax.numpy as jnp

        vals = []
        for i in range(n_leaf):
            a = args[i]
            if full_len[i]:
                a = jax.lax.dynamic_slice_in_dim(
                    jnp.pad(a, (0, win)), pos, win)
            vals.append(a)
        return inner(*vals, *args[n_leaf:])

    return f


def _windowed_program_for(plan: Plan, win: int) -> _Program:
    """Compile (or fetch) the pos-parameterized `win`-row twin of this
    plan's program. Shares the signature cache under a ``|w{win}``
    suffix; goes through the same ledger chokepoint with its own program
    tag so the compile ledger tells full and windowed dispatch apart."""
    sig = plan.signature + f"|w{int(win)}"
    with _PROG_LOCK:
        prog = _PROGRAMS.get(sig)
    if prog is not None:
        _bump("compile_cache_hits")
        from h2o3_tpu.obs import compiles

        compiles.record_hit("rapids", sig, "memory",
                            program="rapids_statement_windowed")
        return prog

    import jax

    from h2o3_tpu.memory import budget as membudget
    from h2o3_tpu.obs import compiles

    mesh = _mesh()
    jfn = jax.jit(_emit_windowed(plan, mesh, win))
    structs = [jax.ShapeDtypeStruct((), np.int32)]      # pos
    for i, leaf in enumerate(plan.leaves):
        if isinstance(leaf, Plan):
            structs.append(jax.ShapeDtypeStruct(
                () if _plan_is_scalar(leaf) else (win,), np.float32))
        else:
            structs.append(jax.ShapeDtypeStruct(
                (plan.padded,), np.dtype(plan.leaf_dtypes[i])))
    structs += [jax.ShapeDtypeStruct((), np.float32)] * len(plan.consts)
    exe = compiles.compile_jit("rapids", jfn, structs, signature=sig,
                               program="rapids_statement_windowed")
    _bump("fused_programs_compiled")
    membudget.note_compiled("rapids", int(win), exe)
    prog = _Program(exe, jfn)
    with _PROG_LOCK:
        if len(_PROGRAMS) >= _PROG_CAP:
            _PROGRAMS.pop(next(iter(_PROGRAMS)))
        _PROGRAMS[sig] = prog
    return prog


def _run_windowed(plan: Plan, pos: int, win: int, scalar_cache: Dict):
    """Dispatch one `win`-row window of the plan at row offset `pos`.
    Scalar sub-programs are computed once per statement (cached across
    windows — their value is row-independent); row-shaped sub-programs
    window recursively at the same offset, so no full-length
    intermediate is ever materialized on a chunked run."""
    import jax.numpy as jnp

    prog = _windowed_program_for(plan, win)
    args = []
    for leaf in plan.leaves:
        if isinstance(leaf, Plan):
            if _plan_is_scalar(leaf):
                key = id(leaf)
                if key not in scalar_cache:
                    scalar_cache[key] = _run_program(leaf)
                args.append(scalar_cache[key])
            else:
                args.append(_run_windowed(leaf, pos, win, scalar_cache))
        else:
            args.append(leaf.data)
    args += [_const_arg(v) for v in plan.consts]
    call = (jnp.int32(pos), *args)
    try:
        out = prog.exe(*call)
    except Exception as e:   # noqa: BLE001 — AOT layout/placement mismatch
        from h2o3_tpu.memory import stream as _mstream

        if _mstream.is_oom(e):
            raise           # the ladder owns memory exhaustion
        out = prog.jfn(*call)
    _bump("fused_programs")
    return out


def _run_streamed(plan: Plan):
    """Route the plan through the memory stream driver. The planned-full
    case dispatches the EXACT single-dispatch program (one window, same
    bytes); a budgeted or ladder-halved run streams pow2-sized windowed
    twins and concatenates on device."""
    import jax.numpy as jnp

    from h2o3_tpu.memory import stream

    n_pad = int(plan.padded)
    scalar_cache: Dict[int, Any] = {}

    def window(pos, m):
        if pos == 0 and m == n_pad:
            return _run_program(plan)
        w = _window_pow2(m)
        out = _run_windowed(plan, pos, w, scalar_cache)
        return out[:m] if m != w else out

    pieces = stream.run_windows(
        "rapids", n_pad, window, max_window=n_pad,
        row_bytes=4.0 * (len(plan.leaves) + 2))
    return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)


def execute_plan(plan: Plan) -> Column:
    """Run one fused statement plan over its row-sharded leaves; the
    result stays a device column (no host round-trip, rows counted
    packed)."""
    from h2o3_tpu.core import sharded_frame
    from h2o3_tpu.obs import tracing

    # host-side dispatch wall time only — the fused result stays
    # device-resident, so tracing adds no device sync
    with tracing.span("fused_dispatch", ops=plan.n_ops,
                      rows=int(plan.nrows), leaves=len(plan.leaves)):
        out = _run_streamed(plan)
    _bump("fused_rows", int(plan.nrows))
    sharded_frame.note_packed(int(plan.nrows))
    return Column.from_device(out, T_NUM, plan.nrows)


_MISS = object()


def try_execute(ast, env):
    """Offer an application node to the fusion engine. Returns the fused
    result Frame, or the _MISS sentinel when the subtree is not fusible
    (the caller falls back to the eager evaluator). Planning only reads
    the environment (Id lookups are pure), so a miss has no side
    effects."""
    if not enabled():
        return _MISS
    from h2o3_tpu.obs import tracing

    try:
        with tracing.span("plan", prim=ast[0].name):
            plan = plan_expr(ast, env)
        if plan is None:
            return _MISS
        col = execute_plan(plan)
    except Exception as e:   # noqa: BLE001 — never take a statement down
        from h2o3_tpu.memory import MemoryPressureError

        if isinstance(e, MemoryPressureError):
            raise           # typed pressure surfaces as 503, not a silent
                            # eager retry into the same exhausted device
        return _MISS    # fusion bug; the eager path is the contract
    fr = Frame()
    fr.add(plan.out_name, col)
    return fr


def note_statement_result(fused_programs_before: int) -> None:
    """Statement epilogue: mark the statement fused when at least one
    fused program ran during it."""
    with _LOCK:
        if _COUNTS["fused_programs"] > fused_programs_before:
            _COUNTS["fused_statements"] += 1


def stats() -> dict:
    """Counters + cache occupancy (the /3/ScoringMetrics `rapids` block):
    fusion counters, the lazy-session planner's counters (deferral/CSE/
    dead-temp/inline/sort-fusion), and the bounded statement-parse memo."""
    from h2o3_tpu.rapids import parser as _parser
    from h2o3_tpu.rapids import planner as _planner

    out = counters()
    with _PROG_LOCK:
        out["cached_programs"] = len(_PROGRAMS)
    out["enabled"] = enabled()
    lazy = _planner.counters()
    lazy["enabled"] = _planner.enabled()
    out["lazy"] = lazy
    out["parse_cache"] = _parser.parse_cache_stats()
    return out
